"""Builtin function registry: implementation + type inference + engine support.

Reference parity: the builtin tables in pkg/expression (funcs map) and the
per-engine legality switches (infer_pushdown.go:160 scalarExprSupportedByTiKV,
:266 scalarExprSupportedByFlash). An entry declares which engines may execute
it; the planner refuses to push a fragment containing an unsupported builtin
to that engine (expression.can_push_down).

Implementations receive ``(xp, args, ctx)``:
- ``xp``: numpy or jax.numpy — the ONLY difference between host and TPU
  execution of a scalar builtin;
- ``args``: list of (data, validity) pairs, validity=None meaning all-valid;
- ``ctx``: EvalContext (row count, scale info, string dictionaries host-side).

Returns (data, validity) with MySQL NULL semantics (validity=None allowed).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from tidb_tpu.types import FieldType, TypeKind
from tidb_tpu.types.field_type import bool_type, double_type, merge_types

ALL_ENGINES = frozenset({"host", "tpu"})
HOST_ONLY = frozenset({"host"})


@dataclass
class FuncSpec:
    name: str
    impl: Callable  # (xp, args, ctx) -> (data, validity)
    infer: Callable  # (arg_ftypes) -> FieldType
    engines: frozenset = ALL_ENGINES
    # TPU support may be conditional (e.g. string compares need sorted dicts);
    # checked at DAG-bind time, not plan time
    variadic: bool = False
    arity: int = 2


REGISTRY: dict[str, FuncSpec] = {}


def register(name: str, infer, engines=ALL_ENGINES, variadic=False, arity=2):
    def deco(fn):
        REGISTRY[name] = FuncSpec(name, fn, infer, engines, variadic, arity)
        return fn

    return deco


# -- validity helpers -------------------------------------------------------


def and_valid(xp, *vs):
    """Combine validity masks (None = all valid)."""
    out = None
    for v in vs:
        if v is None:
            continue
        out = v if out is None else (out & v)
    return out


# -- type inference helpers -------------------------------------------------


def infer_bool(args):
    return bool_type()


def infer_double(args):
    return double_type()


def infer_first(args):
    return args[0]


def infer_merge(args):
    t = args[0]
    for a in args[1:]:
        t = merge_types(t, a)
    return t
