"""Field types.

Reference parity: pkg/parser/types/field_type.go (FieldType) and pkg/types.
Redesigned: instead of MySQL's ~30 `mysql.Type*` byte codes we keep a small
enum of logical kinds, each with a fixed physical device representation:

=============  =========================  ===========================
TypeKind       logical                    physical (device)
=============  =========================  ===========================
INT            TINYINT..BIGINT (signed)   int64
UINT           unsigned ints              int64 (two's complement)
FLOAT          FLOAT/DOUBLE               float64 (float32 on request)
DECIMAL        DECIMAL(p,s)               int64 scaled by 10**s
STRING         CHAR/VARCHAR/TEXT/BLOB     int32 dictionary code
DATE           DATE                       int64 days since epoch
DATETIME       DATETIME/TIMESTAMP         int64 microseconds since epoch
DURATION       TIME                       int64 microseconds
JSON           JSON                       host-only (no device rep)
=============  =========================  ===========================

NULL is carried out-of-band in each Column's validity mask (three-valued logic
lives in tidb_tpu.expression); there is no NULL sentinel in the data lanes.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field, replace


class TypeKind(enum.IntEnum):
    INT = 0
    UINT = 1
    FLOAT = 2
    DECIMAL = 3
    STRING = 4
    DATE = 5
    DATETIME = 6
    DURATION = 7
    JSON = 8
    NULLTYPE = 9  # type of literal NULL


# Kinds whose device representation is int64.
_I64_KINDS = frozenset(
    {TypeKind.INT, TypeKind.UINT, TypeKind.DECIMAL, TypeKind.DATE, TypeKind.DATETIME, TypeKind.DURATION}
)


@dataclass(frozen=True)
class FieldType:
    """Logical column type. Immutable; share instances freely."""

    kind: TypeKind
    # display length (MySQL flen); informational
    length: int = -1
    # decimal digits after the point; only DECIMAL uses it for scaling
    scale: int = 0
    nullable: bool = True
    # collation: only binary ("bin") vs case-insensitive ("ci") distinction kept
    collation: str = "bin"
    # CHAR(n) pads; VARCHAR does not — affects comparisons only at the edges
    fixed_char: bool = False
    # JSON documents ride the STRING representation (normalized text) with
    # this marker for display/type functions (ref: types.JSON column flag)
    json: bool = False

    # -- physical mapping -------------------------------------------------
    @property
    def device_dtype(self) -> str:
        if self.kind in _I64_KINDS:
            return "int64"
        if self.kind == TypeKind.FLOAT:
            return "float64"
        if self.kind == TypeKind.STRING:
            return "int32"  # dictionary code
        if self.kind == TypeKind.NULLTYPE:
            return "int64"
        raise TypeError(f"type {self.kind.name} has no device representation")

    @property
    def is_numeric(self) -> bool:
        return self.kind in (TypeKind.INT, TypeKind.UINT, TypeKind.FLOAT, TypeKind.DECIMAL)

    @property
    def is_temporal(self) -> bool:
        return self.kind in (TypeKind.DATE, TypeKind.DATETIME, TypeKind.DURATION)

    @property
    def is_string(self) -> bool:
        return self.kind == TypeKind.STRING

    def not_null(self) -> "FieldType":
        return replace(self, nullable=False)

    def __str__(self) -> str:  # for EXPLAIN / error messages
        base = self.kind.name
        if self.kind == TypeKind.DECIMAL:
            base += f"({self.length},{self.scale})"
        elif self.length >= 0:
            base += f"({self.length})"
        if not self.nullable:
            base += " NOT NULL"
        return base


# -- canonical constructors ------------------------------------------------

def bigint_type(nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.INT, length=20, nullable=nullable)


def bool_type() -> FieldType:
    # MySQL BOOL == TINYINT(1); we evaluate predicates to INT {0,1}
    return FieldType(TypeKind.INT, length=1, nullable=True)


def double_type(nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.FLOAT, nullable=nullable)


def decimal_type(precision: int = 10, scale: int = 0, nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.DECIMAL, length=precision, scale=scale, nullable=nullable)


def string_type(length: int = -1, nullable: bool = True, collation: str = "bin") -> FieldType:
    return FieldType(TypeKind.STRING, length=length, nullable=nullable, collation=collation)


def date_type(nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.DATE, nullable=nullable)


def datetime_type(nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.DATETIME, nullable=nullable)


def duration_type(nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.DURATION, nullable=nullable)


def merge_types(a: FieldType, b: FieldType) -> FieldType:
    """Least common supertype for expression results (ref: pkg/expression
    type inference). DECIMAL ∪ FLOAT → FLOAT; INT ∪ DECIMAL → DECIMAL; any ∪
    STRING → STRING comparisons coerce to FLOAT per MySQL rules (handled in
    expression layer, not here)."""
    if a.kind == TypeKind.NULLTYPE:
        return b
    if b.kind == TypeKind.NULLTYPE:
        return a
    if a.kind == b.kind:
        if a.kind == TypeKind.DECIMAL:
            scale = max(a.scale, b.scale)
            return decimal_type(max(a.length - a.scale, b.length - b.scale) + scale, scale)
        return a
    ranks = {
        TypeKind.INT: 0,
        TypeKind.UINT: 0,
        TypeKind.DATE: 0,
        TypeKind.DATETIME: 0,
        TypeKind.DURATION: 0,
        TypeKind.DECIMAL: 1,
        TypeKind.FLOAT: 2,
        TypeKind.STRING: 3,
        TypeKind.JSON: 3,
    }
    ra, rb = ranks[a.kind], ranks[b.kind]
    hi = a if ra >= rb else b
    if hi.kind == TypeKind.STRING:
        # mixed string/number arithmetic goes through FLOAT in MySQL
        return double_type()
    if hi.kind == TypeKind.DECIMAL:
        lo = b if hi is a else a
        scale = hi.scale
        return decimal_type(max(hi.length - hi.scale, 20) + scale, scale)
    return hi
