"""Type system: MySQL-flavoured field types mapped onto TPU-friendly storage.

Reference parity: pkg/types (datum/field types) + pkg/parser/types. The rebuild
collapses MySQL's zoo of storage classes onto four device-resident physical
representations (int64 / float64 / int32-dictionary-code / bytes), because the
TPU wants fixed-width lanes; the logical MySQL type survives in ``FieldType``
for semantics (display, coercion, NULL-ability, decimal scale).
"""

from tidb_tpu.types.field_type import (
    FieldType,
    TypeKind,
    bigint_type,
    bool_type,
    date_type,
    datetime_type,
    decimal_type,
    double_type,
    duration_type,
    string_type,
)
from tidb_tpu.types.datum import Datum, NULL

__all__ = [
    "FieldType",
    "TypeKind",
    "Datum",
    "NULL",
    "bigint_type",
    "bool_type",
    "date_type",
    "datetime_type",
    "decimal_type",
    "double_type",
    "duration_type",
    "string_type",
]
