"""Datum: one scalar value crossing the host boundary (constants, point rows).

Reference parity: pkg/types/datum.go. Heavily simplified: on the device there
are no datums at all — only columns; Datum exists for literals in plans, keys
in point lookups, and row assembly in the write path.
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass
from typing import Any

from tidb_tpu.types.field_type import FieldType, TypeKind

_EPOCH_DATE = _dt.date(1970, 1, 1)
_EPOCH_DT = _dt.datetime(1970, 1, 1)


class _Null:
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "NULL"

    def __bool__(self):
        return False


NULL = _Null()


@dataclass(frozen=True)
class Datum:
    """A typed scalar. ``value`` holds the *logical* Python value
    (int/float/str/bytes/date/datetime/None)."""

    value: Any
    ftype: FieldType

    @property
    def is_null(self) -> bool:
        return self.value is None

    def physical(self) -> Any:
        """Encode to the device representation (int64/float64) — strings are
        NOT encodable without a dictionary and raise."""
        v = self.value
        if v is None:
            return 0
        k = self.ftype.kind
        if k == TypeKind.UINT:
            v = int(v)
            return v - (1 << 64) if v >= (1 << 63) else v  # two's complement
        if k == TypeKind.INT:
            return int(v)
        if k == TypeKind.FLOAT:
            return float(v)
        if k == TypeKind.DECIMAL:
            return int(round(float(v) * (10 ** self.ftype.scale)))
        if k == TypeKind.DATE:
            if isinstance(v, _dt.date):
                return (v - _EPOCH_DATE).days
            if isinstance(v, str):  # wire form (ISO) from serialized plans
                return date_to_days(v)
            return int(v)
        if k == TypeKind.DATETIME:
            if isinstance(v, _dt.datetime):
                return int((v - _EPOCH_DT).total_seconds() * 1_000_000)
            if isinstance(v, str):
                try:
                    return datetime_to_micros(v)
                except ValueError:
                    return datetime_to_micros(v + " 00:00:00")
            return int(v)
        if k == TypeKind.DURATION:
            return int(v)
        raise TypeError(f"no physical scalar for {self.ftype}")


def date_to_days(v: "str | _dt.date") -> int:
    if isinstance(v, str):
        v = _dt.date.fromisoformat(v)
    return (v - _EPOCH_DATE).days


def days_to_date(days: int) -> _dt.date:
    return _EPOCH_DATE + _dt.timedelta(days=int(days))


def datetime_to_micros(v: "str | _dt.datetime") -> int:
    if isinstance(v, str):
        v = _dt.datetime.fromisoformat(v)
    return int((v - _EPOCH_DT).total_seconds() * 1_000_000)


def micros_to_datetime(us: int) -> _dt.datetime:
    return _EPOCH_DT + _dt.timedelta(microseconds=int(us))
