"""Datum: one scalar value crossing the host boundary (constants, point rows).

Reference parity: pkg/types/datum.go. Heavily simplified: on the device there
are no datums at all — only columns; Datum exists for literals in plans, keys
in point lookups, and row assembly in the write path.
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass
from typing import Any

from tidb_tpu.types.field_type import FieldType, TypeKind

_EPOCH_DATE = _dt.date(1970, 1, 1)
_EPOCH_DT = _dt.datetime(1970, 1, 1)


class _Null:
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "NULL"

    def __bool__(self):
        return False


NULL = _Null()


@dataclass(frozen=True)
class Datum:
    """A typed scalar. ``value`` holds the *logical* Python value
    (int/float/str/bytes/date/datetime/None)."""

    value: Any
    ftype: FieldType

    @property
    def is_null(self) -> bool:
        return self.value is None

    def physical(self) -> Any:
        """Encode to the device representation (int64/float64) — strings are
        NOT encodable without a dictionary and raise."""
        v = self.value
        if v is None:
            return 0
        k = self.ftype.kind
        if k == TypeKind.UINT:
            v = int(v)
            return v - (1 << 64) if v >= (1 << 63) else v  # two's complement
        if k == TypeKind.INT:
            return int(v)
        if k == TypeKind.FLOAT:
            return float(v)
        if k == TypeKind.DECIMAL:
            return int(round(float(v) * (10 ** self.ftype.scale)))
        if k == TypeKind.DATE:
            if isinstance(v, _dt.date):
                return (v - _EPOCH_DATE).days
            if isinstance(v, str):  # wire form (ISO) from serialized plans
                return date_to_days(v)
            return int(v)
        if k == TypeKind.DATETIME:
            if isinstance(v, _dt.datetime):
                return int((v - _EPOCH_DT).total_seconds() * 1_000_000)
            if isinstance(v, str):
                try:
                    return datetime_to_micros(v)
                except ValueError:
                    return datetime_to_micros(v + " 00:00:00")
            return int(v)
        if k == TypeKind.DURATION:
            if isinstance(v, (str, _dt.timedelta)):
                return duration_to_micros(v)
            return int(v)
        raise TypeError(f"no physical scalar for {self.ftype}")


def date_to_days(v: "str | _dt.date") -> int:
    if isinstance(v, str):
        v = _dt.date.fromisoformat(v)
    return (v - _EPOCH_DATE).days


def days_to_date(days: int) -> _dt.date:
    return _EPOCH_DATE + _dt.timedelta(days=int(days))


def datetime_to_micros(v: "str | _dt.datetime") -> int:
    if isinstance(v, str):
        v = _dt.datetime.fromisoformat(v)
    return int((v - _EPOCH_DT).total_seconds() * 1_000_000)


def micros_to_datetime(us: int) -> _dt.datetime:
    return _EPOCH_DT + _dt.timedelta(microseconds=int(us))


def duration_to_micros(v: "str | _dt.timedelta") -> int:
    """MySQL TIME '[-][H]H:MM:SS[.ffffff]' (hours may exceed 23, up to 838)
    → signed microseconds (ref: types/duration.go parsing)."""
    if isinstance(v, _dt.timedelta):
        return int(v.total_seconds() * 1_000_000)
    s = v.strip()
    neg = s.startswith("-")
    if neg:
        s = s[1:]
    frac = 0
    if "." in s:
        s, f = s.split(".", 1)
        frac = int((f + "000000")[:6])
    parts = s.split(":")
    if len(parts) == 3:
        h, m, sec = (int(p) for p in parts)
    elif len(parts) == 2:
        h, m, sec = int(parts[0]), int(parts[1]), 0
    else:
        # bare number: MySQL reads it as [HH]MMSS
        x = int(parts[0])
        h, m, sec = x // 10000, (x // 100) % 100, x % 100
    us = ((h * 3600 + m * 60 + sec) * 1_000_000) + frac
    return -us if neg else us


def micros_to_duration(us: int) -> _dt.timedelta:
    return _dt.timedelta(microseconds=int(us))


def format_physical(x, ftype) -> bytes:
    """MySQL-style text rendering of one physical (non-NULL, non-string)
    value — shared by CAST(... AS CHAR) and GROUP_CONCAT."""
    from tidb_tpu.types.field_type import TypeKind

    k = ftype.kind
    if k == TypeKind.DECIMAL and ftype.scale > 0:
        iv = int(x)
        sign = "-" if iv < 0 else ""
        iv = abs(iv)
        return f"{sign}{iv // 10**ftype.scale}.{iv % 10**ftype.scale:0{ftype.scale}d}".encode()
    if k == TypeKind.FLOAT:
        return repr(float(x)).encode()
    if k == TypeKind.DATE:
        return str(days_to_date(int(x))).encode()
    if k == TypeKind.DATETIME:
        return str(micros_to_datetime(int(x))).encode()
    if k == TypeKind.DURATION:
        us = int(x)
        sign = "-" if us < 0 else ""
        us = abs(us)
        sec, frac = divmod(us, 1_000_000)
        h, rem = divmod(sec, 3600)
        m, s = divmod(rem, 60)
        base = f"{sign}{h:02d}:{m:02d}:{s:02d}"
        return (base + (f".{frac:06d}" if frac else "")).encode()
    return str(int(x)).encode()
