"""Bulk columnar loader — the IMPORT INTO / lightning analog.

Reference parity: pkg/lightning local backend + IMPORT INTO (disttask) —
bypasses per-statement SQL overhead and writes encoded rows straight through
a transaction in batches. Used by bench/bootstrap; the SQL surface for it
(IMPORT INTO) can layer on later.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from tidb_tpu.executor.write import index_entry, to_physical
from tidb_tpu.kv import tablecodec
from tidb_tpu.kv.rowcodec import RowSchema, encode_row
from tidb_tpu.session.session import DB
from tidb_tpu.types import TypeKind


def bulk_load(db: DB, table_name: str, columns: Sequence[Sequence], db_name: str = "test", batch: int = 200_000, handle_base: int | None = None, on_existing: str | None = None) -> int:
    """Load columnar data (one sequence per table column, logical values).
    Handles come from the int PK column when pk_is_handle, else autoid.

    ``handle_base`` pins the autoid handles to a pre-reserved range so a
    re-run writes the SAME keys; ``on_existing`` ('skip' for reserved ranges,
    'verify' for user-keyed PK tables) dedupes the columnar ingest against
    already-stable handles — together they make a restarted import subtask
    idempotent, and 'verify' surfaces duplicate-PK conflicts (ref: lightning
    checkpoint re-import + duplicate detection)."""
    t = db.catalog.table(db_name, table_name)
    ncols = len(t.columns)
    if len(columns) != ncols:
        raise ValueError(f"expected {ncols} columns, got {len(columns)}")
    n = len(columns[0])
    schema = RowSchema(t.storage_schema)

    phys_cols = []
    for c, vals in zip(t.columns, columns):
        k = c.ftype.kind
        if isinstance(vals, np.ndarray) and k in (TypeKind.INT, TypeKind.UINT, TypeKind.DECIMAL, TypeKind.DATE, TypeKind.DATETIME, TypeKind.DURATION):
            phys_cols.append(vals.astype(np.int64))
        elif isinstance(vals, np.ndarray) and k == TypeKind.FLOAT:
            phys_cols.append(vals.astype(np.float64))
        elif isinstance(vals, np.ndarray) and vals.dtype.kind == "S" and k == TypeKind.STRING:
            # fixed-width bytes: C-speed dictionary encode in the ingest path
            # (no NULLs — an S array cannot carry None; JSON stays on the
            # to_physical path for validation + canonical re-serialization)
            phys_cols.append(vals)
        else:
            phys_cols.append([to_physical(v, c.ftype) for v in vals])

    if t.partition is not None:
        return _bulk_load_partitioned(db, t, phys_cols, n, schema, handle_base=handle_base, on_existing=on_existing)

    if not any(idx.state != "delete_only" for idx in t.indexes):
        # columnar stable-layer ingest (TiFlash stable analog): columns go
        # into the store decoded and device-ready — no row encode at all.
        # Indexed tables keep the txn path below so index entries stay
        # transactional with their rows.
        if t.pk_is_handle:
            all_handles = np.ascontiguousarray(np.asarray(phys_cols[t.pk_offset], dtype=np.int64))
        elif handle_base is not None:
            all_handles = np.arange(handle_base, handle_base + n, dtype=np.int64)
        else:
            base = db.catalog.alloc_autoid(t.id, n)
            all_handles = np.arange(base, base + n, dtype=np.int64)
        _ingest_columnar(db, t.id, t, phys_cols, all_handles, n, schema, on_existing=on_existing)
        if t.pk_is_handle and n:
            db.catalog.rebase_autoid(t.id, int(all_handles.max()) + 1)
        return n

    loaded = 0
    i = 0
    while i < n:
        j = min(i + batch, n)
        txn = db.store.begin()
        if t.pk_is_handle:
            handles = phys_cols[t.pk_offset][i:j]
        elif handle_base is not None:
            handles = range(handle_base + i, handle_base + j)
        else:
            base = db.catalog.alloc_autoid(t.id, j - i)
            handles = range(base, base + (j - i))
        existing: dict = {}
        if on_existing == "verify":
            # duplicate-PK conflict surfacing on the txn path too — ONE
            # snapshot scan over the batch's handle span replaces a per-row
            # point get (which would be one RPC per row on a remote store)
            hs = list(handles)
            if hs:
                span = tablecodec.handle_range(t.id, int(min(hs)), int(max(hs)))
                snap = db.store.get_snapshot(db.store.current_ts())
                existing = dict(snap.scan(span))
        for r, h in zip(range(i, j), handles):
            vals = [phys_cols[c][r] for c in range(ncols)]
            rk = tablecodec.record_key(t.id, int(h))
            row = encode_row(schema, vals)
            if on_existing == "verify":
                prev = existing.get(rk)
                if prev is not None:
                    if prev == row:
                        continue  # idempotent re-run: identical row
                    raise ValueError(
                        f"duplicate key conflict on handle {int(h)}: existing row differs"
                    )
            txn.put(rk, row)
            for idx in t.indexes:
                if idx.state == "delete_only":
                    continue  # writes don't maintain delete-only indexes
                ik, iv = index_entry(t, idx, vals, int(h))
                txn.put(ik, iv)
        txn.commit()
        loaded += j - i
        i = j
    if t.pk_is_handle:
        mx = int(np.max(np.asarray(phys_cols[t.pk_offset]))) if n else 0
        db.catalog.rebase_autoid(t.id, mx + 1)
    return loaded


def _ingest_columnar(db: DB, physical_id: int, t, phys_cols, handles: np.ndarray, n: int, schema: RowSchema, on_existing: str | None = None) -> None:
    """Columns → StableBlock via MemStore.ingest_columnar. Strings dictionary-
    encode through np.unique (C-speed inverse) against the shared table
    dictionary, so blocks hand int32 code lanes straight to the device."""
    from tidb_tpu.copr.colcache import cache_for

    cache = cache_for(db.store)
    if physical_id != t.id:
        cache.set_table_alias(physical_id, t.id)
    cols: dict[int, tuple[np.ndarray, np.ndarray]] = {}
    dicts: dict = {}
    string_slots: list[int] = []
    for pos, (c, vals) in enumerate(zip(t.columns, phys_cols)):
        k = c.ftype.kind
        if k in (TypeKind.STRING, TypeKind.JSON):
            string_slots.append(pos)
            dicts[pos] = cache.dictionary(t.id, pos)  # before ingest_lock
        elif isinstance(vals, np.ndarray):
            dt = np.float64 if k == TypeKind.FLOAT else np.int64
            cols[pos] = (vals.astype(dt, copy=False), np.ones(n, dtype=bool))
        else:
            valid = np.fromiter((v is not None for v in vals), dtype=bool, count=n)
            dt = np.float64 if k == TypeKind.FLOAT else np.int64
            data = np.fromiter(
                ((0 if v is None else v) for v in vals), dtype=dt, count=n
            )
            cols[pos] = (data, valid)
    # encode string codes and append the block under one cache lock: a
    # concurrent ensure_sorted_dict compaction between encode and ingest
    # would remap every block EXCEPT this not-yet-visible one
    with cache.ingest_lock():
        for pos in string_slots:
            raw = phys_cols[pos]
            if isinstance(raw, np.ndarray) and raw.dtype.kind == "S":
                valid = np.ones(n, dtype=bool)
                safe = raw
            else:
                arr = np.asarray(raw, dtype=object)
                valid = np.fromiter((v is not None for v in arr), dtype=bool, count=n)
                safe = np.where(valid, arr, b"") if n else arr
            dic = dicts[pos]
            if n:
                uniq, inv = np.unique(safe, return_inverse=True)
                code_of = np.fromiter((dic.encode(bytes(u)) for u in uniq), dtype=np.int32, count=len(uniq))
                data = code_of[inv.reshape(-1)].astype(np.int32, copy=False)
                data = np.where(valid, data, 0).astype(np.int32, copy=False)
            else:
                data = np.empty(0, np.int32)
            cols[pos] = (data, valid)
        db.store.ingest_columnar(physical_id, handles, cols, schema, dicts, on_existing=on_existing)


def _bulk_load_partitioned(db: DB, t, phys_cols, n: int, schema: RowSchema, handle_base: int | None = None, on_existing: str | None = None) -> int:
    """Partition-routed load: rows group by partition id, then each group
    loads through the native ingest (or txn fallback) under its partition's
    physical table id."""
    p = t.partition
    raw = phys_cols[p.col_offset]
    if isinstance(raw, np.ndarray):
        pcol = raw.astype(np.int64, copy=False)
        null_mask = np.zeros(n, dtype=bool)
    else:
        null_mask = np.fromiter((v is None for v in raw), dtype=bool, count=n)
        pcol = np.fromiter((0 if v is None else int(v) for v in raw), dtype=np.int64, count=n)
    if p.type == "hash":
        pidx = pcol % len(p.defs)
    else:
        bounds = np.array(
            [d.less_than if d.less_than is not None else 2**62 for d in p.defs], dtype=np.int64
        )
        pidx = np.searchsorted(bounds, pcol, side="right")
        if int(pidx.max(initial=0)) >= len(p.defs):
            bad = int(pcol[pidx >= len(p.defs)][0])
            from tidb_tpu.catalog.catalog import CatalogError

            raise CatalogError(f"Table has no partition for value {bad}")
    pidx = np.where(null_mask, 0, pidx)  # NULL routes to the first partition

    if t.pk_is_handle:
        handles = np.ascontiguousarray(np.asarray(phys_cols[t.pk_offset], dtype=np.int64))
    elif handle_base is not None:
        handles = np.arange(handle_base, handle_base + n, dtype=np.int64)
    else:
        base = db.catalog.alloc_autoid(t.id, n)
        handles = np.arange(base, base + n, dtype=np.int64)

    from tidb_tpu.executor.write import index_entry

    has_index = any(idx.state != "delete_only" for idx in t.indexes)
    for k, d in enumerate(p.defs):
        sel = np.nonzero(pidx == k)[0]
        if len(sel) == 0:
            continue
        view = t.partition_view(d.id)
        sub_cols = [
            c[sel] if isinstance(c, np.ndarray) else [c[int(i)] for i in sel] for c in phys_cols
        ]
        sub_handles = handles[sel]
        if not has_index:
            _ingest_columnar(db, view.id, t, sub_cols, sub_handles, len(sel), schema, on_existing=on_existing)
            continue
        txn = db.store.begin()
        for j, h in enumerate(sub_handles):
            vals = [sub_cols[c][j] for c in range(len(t.columns))]
            txn.put(tablecodec.record_key(view.id, int(h)), encode_row(schema, vals))
            for idx in t.indexes:
                if idx.state == "delete_only":
                    continue
                ik, iv = index_entry(view, idx, vals, int(h))
                txn.put(ik, iv)
        txn.commit()
    if t.pk_is_handle and n:
        db.catalog.rebase_autoid(t.id, int(handles.max()) + 1)
    return n
