"""Bulk columnar loader — the IMPORT INTO / lightning analog.

Reference parity: pkg/lightning local backend + IMPORT INTO (disttask) —
bypasses per-statement SQL overhead and writes encoded rows straight through
a transaction in batches. Used by bench/bootstrap; the SQL surface for it
(IMPORT INTO) can layer on later.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from tidb_tpu.executor.write import index_entry, to_physical
from tidb_tpu.kv import tablecodec
from tidb_tpu.kv.rowcodec import RowSchema, encode_row
from tidb_tpu.session.session import DB
from tidb_tpu.types import TypeKind


def bulk_load(db: DB, table_name: str, columns: Sequence[Sequence], db_name: str = "test", batch: int = 200_000) -> int:
    """Load columnar data (one sequence per table column, logical values).
    Handles come from the int PK column when pk_is_handle, else autoid."""
    t = db.catalog.table(db_name, table_name)
    ncols = len(t.columns)
    assert len(columns) == ncols, f"expected {ncols} columns"
    n = len(columns[0])
    schema = RowSchema(t.storage_schema)

    phys_cols = []
    for c, vals in zip(t.columns, columns):
        k = c.ftype.kind
        if isinstance(vals, np.ndarray) and k in (TypeKind.INT, TypeKind.UINT, TypeKind.DECIMAL, TypeKind.DATE, TypeKind.DATETIME, TypeKind.DURATION):
            phys_cols.append(vals.astype(np.int64))
        elif isinstance(vals, np.ndarray) and k == TypeKind.FLOAT:
            phys_cols.append(vals.astype(np.float64))
        else:
            phys_cols.append([to_physical(v, c.ftype) for v in vals])

    # native fast path (C++ encode + SST-style ingest; ref: lightning local
    # backend): row+key encoding and the per-key 2PC loop collapse into one
    # C call + one bulk store insert. Indexed tables keep the txn path so
    # index entries stay transactional with their rows.
    from tidb_tpu.native import lib as native_lib

    if native_lib() is not None and not any(idx.state != "delete_only" for idx in t.indexes):
        from tidb_tpu.native.bulk import encode_rows, split_encoded

        if t.pk_is_handle:
            all_handles = np.ascontiguousarray(np.asarray(phys_cols[t.pk_offset], dtype=np.int64))
        else:
            base = db.catalog.alloc_autoid(t.id, n)
            all_handles = np.arange(base, base + n, dtype=np.int64)
        enc = encode_rows(t, phys_cols, all_handles)
        if enc is not None:
            keys_buf, rows_buf, row_starts = enc
            pairs = list(split_encoded(keys_buf, rows_buf, row_starts))
            db.store.ingest([k for k, _ in pairs], [v for _, v in pairs])
            if t.pk_is_handle and n:
                db.catalog.rebase_autoid(t.id, int(all_handles.max()) + 1)
            return n

    loaded = 0
    i = 0
    while i < n:
        j = min(i + batch, n)
        txn = db.store.begin()
        if t.pk_is_handle:
            handles = phys_cols[t.pk_offset][i:j]
        else:
            base = db.catalog.alloc_autoid(t.id, j - i)
            handles = range(base, base + (j - i))
        for r, h in zip(range(i, j), handles):
            vals = [phys_cols[c][r] for c in range(ncols)]
            txn.put(tablecodec.record_key(t.id, int(h)), encode_row(schema, vals))
            for idx in t.indexes:
                if idx.state == "delete_only":
                    continue  # writes don't maintain delete-only indexes
                ik, iv = index_entry(t, idx, vals, int(h))
                txn.put(ik, iv)
        txn.commit()
        loaded += j - i
        i = j
    if t.pk_is_handle:
        mx = int(np.max(np.asarray(phys_cols[t.pk_offset]))) if n else 0
        db.catalog.rebase_autoid(t.id, mx + 1)
    return loaded
