"""Read-path executors (ref: pkg/executor table_reader.go, aggregate/,
sortexec/, join/ — collapsed to chunk-materializing operators)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from tidb_tpu.copr import dagpb
from tidb_tpu.copr.host_engine import _aggregate as host_aggregate  # complete-mode agg
from tidb_tpu.copr.host_engine import _selection as host_selection
from tidb_tpu.copr.host_engine import finalize_agg, sort_perm
from tidb_tpu.expression.expr import AggDesc, ColumnRef, Constant, EvalBatch, eval_to_column
from tidb_tpu.kv import tablecodec
from tidb_tpu.kv.kv import Request, RequestType, StoreType
from tidb_tpu.kv.rowcodec import RowSchema, decode_row
from tidb_tpu.planner.plans import (
    PhysDistinct,
    PhysDual,
    PhysFinalAgg,
    PhysHashJoin,
    PhysIndexJoin,
    PhysMergeJoin,
    PhysIndexLookUp,
    PhysIndexMerge,
    PhysIndexReader,
    PhysLimit,
    PhysMemSource,
    PhysPointGet,
    PhysProjection,
    PhysSelection,
    PhysSetOp,
    PhysSort,
    PhysTableReader,
    PhysWindow,
)
from tidb_tpu.types import TypeKind
from tidb_tpu.types.field_type import bigint_type
from tidb_tpu.utils.chunk import Chunk, Column, Dictionary


class ExecError(Exception):
    pass


class Executor:
    schema: list

    def execute(self) -> Chunk:
        raise NotImplementedError


def build_executor(plan, session) -> Executor:
    """ref: executorBuilder.build (builder.go:164). When the session carries a
    RuntimeStatsColl (EXPLAIN ANALYZE), every built node is instrumented."""
    e = _build_executor(plan, session)
    coll = getattr(session, "runtime_stats", None)
    if coll is not None:
        from tidb_tpu.utils.execdetails import instrument

        instrument(e, plan, coll)
    return e


def _build_executor(plan, session) -> Executor:
    if isinstance(plan, PhysTableReader):
        return TableReaderExec(plan, session)
    if isinstance(plan, PhysSelection):
        return SelectionExec(plan, build_executor(plan.children[0], session), session)
    if isinstance(plan, PhysProjection):
        return ProjectionExec(plan, build_executor(plan.children[0], session), session)
    if isinstance(plan, PhysFinalAgg):
        return FinalAggExec(plan, build_executor(plan.children[0], session))
    if isinstance(plan, PhysSort):
        return SortExec(plan, build_executor(plan.children[0], session))
    if isinstance(plan, PhysLimit):
        return LimitExec(plan, build_executor(plan.children[0], session))
    if isinstance(plan, PhysHashJoin):
        return HashJoinExec(plan, build_executor(plan.children[0], session), build_executor(plan.children[1], session))
    if isinstance(plan, PhysMergeJoin):
        return MergeJoinExec(plan, build_executor(plan.children[0], session), build_executor(plan.children[1], session))
    if isinstance(plan, PhysIndexJoin):
        return IndexJoinExec(plan, build_executor(plan.children[0], session), session)
    if isinstance(plan, PhysDistinct):
        return DistinctExec(build_executor(plan.children[0], session))
    if isinstance(plan, PhysSetOp):
        return SetOpExec(plan, [build_executor(c, session) for c in plan.children])
    if isinstance(plan, PhysWindow):
        return WindowExec(plan, build_executor(plan.children[0], session), session)
    if isinstance(plan, PhysDual):
        return DualExec(plan)
    if isinstance(plan, PhysMemSource):
        return MemSourceExec(plan)
    if isinstance(plan, PhysPointGet):
        return PointGetExec(plan, session)
    if isinstance(plan, PhysIndexReader):
        return IndexReaderExec(plan, session)
    if isinstance(plan, PhysIndexLookUp):
        return IndexLookUpExec(plan, session)
    if isinstance(plan, PhysIndexMerge):
        return IndexMergeExec(plan, session)
    from tidb_tpu.parallel.gather import MPPGatherExec, PhysMPPGather

    if isinstance(plan, PhysMPPGather):
        return MPPGatherExec(plan, session)
    raise ExecError(f"no executor for {type(plan).__name__}")


def _window_pb(w) -> dagpb.ExecutorPB:
    """Serialize a pushed LogicalWindow into the DAG wire form (ref: the
    tipb.Window message TiFlash consumes)."""
    from tidb_tpu.expression.expr import _ft_pb

    if w.frame is not None:
        frame = ("rows",) + tuple(w.frame)
    elif w.whole_partition:
        frame = "whole"
    elif w.rows_frame:
        frame = "rows_cur"
    else:
        frame = "range_cur"
    return dagpb.ExecutorPB(
        dagpb.WINDOW,
        partition_by=[e.to_pb() for e in w.partition_by],
        order_by=[(e.to_pb(), d) for e, d in w.order_by],
        frame=frame,
        win_funcs=[
            {"name": f.name, "args": [a.to_pb() for a in f.args], "ft": _ft_pb(f.ftype)}
            for f in w.funcs
        ],
    )


def _empty_chunk(schema) -> Chunk:
    cols = []
    for oc in schema:
        dt = {TypeKind.FLOAT: np.float64, TypeKind.STRING: np.int32}.get(oc.ftype.kind, np.int64)
        cols.append(Column(np.empty(0, dt), np.empty(0, bool), oc.ftype))
    return Chunk(cols)


@dataclass
class TableReaderExec(Executor):
    plan: PhysTableReader
    session: object
    # index executors run their table phase through a SYNTHETIC reader; the
    # sidecars must land on the visible plan node (the IndexLookUp/IndexMerge
    # row of EXPLAIN ANALYZE), not on the synthetic one nobody renders
    detail_target: object = None

    def __post_init__(self):
        self.schema = self.plan.schema

    def execute(self) -> Chunk:
        from tidb_tpu.utils import failpoint

        # test hook: park a reader mid-statement (cross-node KILL tests);
        # receives the executor so hooks can filter by plan/table
        failpoint.inject("table_reader_begin", self)
        p = self.plan
        if p.table.partition is not None:
            # one request per partition (each is its own physical table —
            # ref: kv.Request.PartitionIDAndRanges); chunks concat like
            # multi-region partials
            from tidb_tpu.copr.colcache import cache_for

            cache = cache_for(self.session.store)
            views = p.partitions if p.partitions is not None else p.table.partition_views()
            for view in views:
                cache.set_table_alias(view.id, p.table.id)
            self.session.check_killed()
            if len(views) > 1:
                # partitions fan out like region tasks (ref: partitioned
                # scans sharing the distsql concurrency budget); numpy/XLA
                # release the GIL so tasks overlap for real
                from concurrent.futures import ThreadPoolExecutor

                budget = int(self.session.vars.get("tidb_distsql_scan_concurrency", 8))
                conc = max(1, min(budget, len(views)))
                # partitions share (not multiply) the scan budget: each
                # per-partition request gets its slice of workers
                self._conc_override = max(1, budget // conc)
                try:
                    with ThreadPoolExecutor(max_workers=conc, thread_name_prefix="part") as pool:
                        results = list(pool.map(lambda v: self._execute_one(v, self._translate_ranges(v)), views))
                finally:
                    self._conc_override = None
                self.session.check_killed()
                chunks = [ch for ch in results if len(ch)]
            else:
                chunks = [ch for ch in (self._execute_one(v, self._translate_ranges(v)) for v in views) if len(ch)]
            if not chunks:
                return _empty_chunk(p.schema)
            return Chunk.concat(chunks) if len(chunks) > 1 else chunks[0]
        t = p.table
        ranges = p.ranges if p.ranges is not None else [tablecodec.record_range(t.id)]
        return self._execute_one(t, ranges)

    def _translate_ranges(self, view) -> list:
        """Planner ranges are handle ranges in logical-table key space —
        re-encode them for the partition's physical id."""
        p = self.plan
        if p.ranges is None:
            return [tablecodec.record_range(view.id)]
        out = []
        for kr in p.ranges:
            lo, hi = tablecodec.range_to_handles(kr, p.table.id)
            if lo < hi:
                out.append(tablecodec.handle_range(view.id, lo, hi - 1))
        return out

    def _execute_one(self, t, ranges) -> Chunk:
        from tidb_tpu.utils import metrics as _m

        p = self.plan
        _m.COP_TASKS.inc(engine=p.store_type.value if hasattr(p.store_type, "value") else str(p.store_type))
        scan = dagpb.ExecutorPB(
            dagpb.TABLE_SCAN,
            table_id=t.id,
            columns=[
                dagpb.ColumnInfoPB(slot, t.columns[slot].ftype)
                if slot >= 0
                else dagpb.ColumnInfoPB(-1, bigint_type(nullable=False), is_handle=True)
                for slot in p.scan_slots
            ],
            storage_schema=t.storage_schema,
        )
        executors = [scan]
        if p.pushed_conditions:
            executors.append(dagpb.ExecutorPB(dagpb.SELECTION, conditions=[c.to_pb() for c in p.pushed_conditions]))
        if p.pushed_window is not None:
            executors.append(_window_pb(p.pushed_window))
        if p.pushed_agg is not None:
            executors.append(
                dagpb.ExecutorPB(
                    dagpb.AGGREGATION,
                    group_by=[g.to_pb() for g in p.pushed_agg.group_by],
                    aggs=[a.to_pb() for a in p.pushed_agg.aggs],
                    agg_mode=dagpb.AGG_PARTIAL if p.pushed_agg_mode == "partial" else dagpb.AGG_COMPLETE,
                    rollup=getattr(p.pushed_agg, "rollup", False),
                )
            )
        if p.pushed_topn is not None:
            by, limit = p.pushed_topn
            executors.append(
                dagpb.ExecutorPB(dagpb.TOPN, order_by=[[e.to_pb(), d] for e, d in by], limit=limit)
            )
        if p.pushed_limit is not None:
            executors.append(dagpb.ExecutorPB(dagpb.LIMIT, limit=p.pushed_limit))
        dag = dagpb.DAGRequest(executors=executors)
        if not ranges:
            return _empty_chunk(p.schema)
        if self.session._txn_dirty():
            # union-scan path (ref: UnionScanExec): scan through the txn's
            # membuffer overlay and replay pushed operators host-side
            return self._union_scan(dag, ranges, t)
        host_tail: list = []
        if p.pushed_window is not None:
            # windows need every partition row in ONE computation; a table
            # spanning multiple regions splits into independent cop tasks, so
            # run the scan prefix remotely and the window (plus anything
            # above it) host-side over the gathered rows
            n_regions = sum(1 for _ in self.session.store.pd.regions_in_ranges(ranges))
            if n_regions > 1:
                widx = next(i for i, ex in enumerate(executors) if ex.tp == dagpb.WINDOW)
                host_tail = executors[widx:]
                dag = dagpb.DAGRequest(executors=executors[:widx])
        req = Request(
            tp=RequestType.DAG,
            data=dag,
            ranges=ranges,
            store_type=p.store_type,
            start_ts=self.session.read_ts(),
            concurrency=getattr(self, "_conc_override", None)
            or int(self.session.vars.get("tidb_distsql_scan_concurrency", 8)),
            keep_order=p.keep_order,
            warn=self.session.append_warning,
            tracer=self.session.tracer,
        )
        client = self.session.store.get_client()
        # gather through a spillable container accounted against the query's
        # memory tracker (ref: copr worker results → memory.Tracker; spill =
        # chunk_in_disk host-RAM offload), checking the kill flag per task
        from tidb_tpu.utils.rowcontainer import RowContainer

        rc = RowContainer(getattr(self.session, "mem_tracker", None), "cop-gather")
        try:
            for res in client.send(req):
                self.session.check_killed()
                # per-task ExecDetails sidecar → the statement aggregate
                # (slow log / statements_summary) and, under EXPLAIN
                # ANALYZE, this reader node's cop_task execution-info line
                if res.details is not None:
                    self.session.record_cop_detail(self.detail_target or p, res.details)
                rc.add(res.chunk)
            out = rc.to_chunk()
        finally:
            rc.close()
        if out is None:
            return _empty_chunk(p.schema)
        if host_tail:
            from tidb_tpu.copr.host_engine import run_operators

            out = run_operators(out, host_tail, [])
        # string columns may carry per-region-identical dictionaries (table-
        # level, shared) — concat requires the same object, which holds here
        return out

    def _union_scan(self, dag, ranges, t=None) -> Chunk:
        from tidb_tpu.copr.host_engine import run_operators
        from tidb_tpu.executor.write import _rows_to_chunk, _scan_visible_rows

        if t is None:
            t = self.plan.table
        handles, rows, _ = _scan_visible_rows(self.session, t)
        # restrict by handle ranges
        keep = []
        bounds = [tablecodec.range_to_handles(kr, t.id) for kr in ranges]
        for i, h in enumerate(handles):
            if any(lo <= h < hi for lo, hi in bounds):
                keep.append(i)
        rows = [rows[i] for i in keep]
        handles = [handles[i] for i in keep]
        full = _rows_to_chunk(self.session, t, rows)
        cols = []
        for slot in self.plan.scan_slots:
            if slot == -1:
                cols.append(Column(np.asarray(handles, np.int64), np.ones(len(handles), bool), bigint_type(nullable=False)))
            else:
                cols.append(full.columns[slot])
        chunk = Chunk(cols)
        out = run_operators(chunk, dag.executors[1:], dag.output_offsets)
        return out if len(out.columns) else _empty_chunk(self.plan.schema)


def _union_scan_fallback(session, table, scan_slots, conditions, schema, target=None) -> Chunk:
    """Dirty-txn path shared by the index executors: index contents may lag
    the membuffer, so read through a membuffer-merged table scan instead
    (ref: UnionScanExec wrapping IndexReader/IndexLookUp). ``target`` keeps
    any cop sidecars attributed to the visible index plan node."""
    reader = PhysTableReader(
        db="",
        table=table,
        store_type=StoreType.HOST,
        pushed_conditions=list(conditions),
        scan_slots=list(scan_slots),
        schema=schema,
    )
    return TableReaderExec(reader, session, detail_target=target).execute()


def _gather_index_chunks(session, plan, req) -> list:
    """One index-side cop fan-out with the TableReaderExec sidecar
    discipline: every task's wire-shipped ExecDetails folds into the
    statement aggregate and — under EXPLAIN ANALYZE — into ``plan``'s own
    ``cop_task:`` execution-info line (the index executors used to drop
    these on the floor; ROADMAP named the gap)."""
    chunks = []
    for res in session.store.get_client().send(req):
        session.check_killed()
        if res.details is not None:
            session.record_cop_detail(plan, res.details)
        if len(res.chunk):
            chunks.append(res.chunk)
    return chunks


def _coalesce_handle_ranges(table_id: int, handles: np.ndarray) -> list:
    """Sorted handles → minimal list of contiguous [lo, hi] key ranges."""
    if len(handles) == 0:
        return []
    hs = np.unique(handles)  # sorts
    breaks = np.nonzero(np.diff(hs) != 1)[0]
    starts = np.concatenate(([0], breaks + 1))
    ends = np.concatenate((breaks, [len(hs) - 1]))
    return [tablecodec.handle_range(table_id, int(hs[s]), int(hs[e])) for s, e in zip(starts, ends)]


@dataclass
class IndexReaderExec(Executor):
    """Covering-index read (ref: IndexReaderExecutor, distsql.go)."""

    plan: PhysIndexReader
    session: object

    def __post_init__(self):
        self.schema = self.plan.schema

    def execute(self) -> Chunk:
        p = self.plan
        if self.session._txn_dirty():
            return _union_scan_fallback(
                self.session, p.table, [oc.slot for oc in p.schema], p.all_conditions, p.schema,
                target=p,
            )
        if not p.ranges:
            return _empty_chunk(p.schema)
        t = p.table
        cols = []
        for pos, slot in enumerate(p.output_slots):
            if slot == -1:
                cols.append(dagpb.ColumnInfoPB(-1, bigint_type(nullable=False), is_handle=True))
            else:
                cols.append(dagpb.ColumnInfoPB(slot, t.columns[slot].ftype))
        scan = dagpb.ExecutorPB(
            dagpb.INDEX_SCAN,
            table_id=t.id,
            index_id=p.index.id,
            index_col_offsets=list(p.index.column_offsets),
            unique=p.index.unique,
            columns=cols,
            storage_schema=t.storage_schema,
        )
        executors = [scan]
        if p.pushed_conditions:
            executors.append(dagpb.ExecutorPB(dagpb.SELECTION, conditions=[c.to_pb() for c in p.pushed_conditions]))
        req = Request(
            tp=RequestType.DAG,
            data=dagpb.DAGRequest(executors=executors),
            ranges=p.ranges,
            store_type=StoreType.HOST,
            start_ts=self.session.read_ts(),
            concurrency=int(self.session.vars.get("tidb_distsql_scan_concurrency", 8)),
            keep_order=True,
            warn=self.session.append_warning,
            tracer=self.session.tracer,
        )
        chunks = _gather_index_chunks(self.session, p, req)
        if not chunks:
            return _empty_chunk(p.schema)
        return Chunk.concat(chunks) if len(chunks) > 1 else chunks[0]


@dataclass
class IndexLookUpExec(Executor):
    """Index scan → handle collection → batched table row fetch
    (ref: IndexLookUpExecutor's index worker + table worker pipeline)."""

    plan: PhysIndexLookUp
    session: object

    def __post_init__(self):
        self.schema = self.plan.schema

    def execute(self) -> Chunk:
        p = self.plan
        if self.session._txn_dirty():
            return _union_scan_fallback(
                self.session, p.table, p.scan_slots, p.all_conditions, p.schema, target=p
            )
        if not p.ranges:
            return _empty_chunk(p.schema)
        t = p.table
        # phase 1: index side — handles only
        scan = dagpb.ExecutorPB(
            dagpb.INDEX_SCAN,
            table_id=t.id,
            index_id=p.index.id,
            index_col_offsets=list(p.index.column_offsets),
            unique=p.index.unique,
            columns=[dagpb.ColumnInfoPB(-1, bigint_type(nullable=False), is_handle=True)],
            storage_schema=t.storage_schema,
        )
        req = Request(
            tp=RequestType.DAG,
            data=dagpb.DAGRequest(executors=[scan]),
            ranges=p.ranges,
            store_type=StoreType.HOST,
            start_ts=self.session.read_ts(),
            concurrency=int(self.session.vars.get("tidb_distsql_scan_concurrency", 8)),
            warn=self.session.append_warning,
            tracer=self.session.tracer,
        )
        handle_chunks = _gather_index_chunks(self.session, p, req)
        if not handle_chunks:
            return _empty_chunk(p.schema)
        handles = np.concatenate([c.columns[0].data for c in handle_chunks])
        # phase 2: table side — fetch rows by coalesced handle ranges with
        # residual filters pushed (ref: buildTableReaderForIndexJoin); its
        # cop sidecars attribute to THIS plan node's execution-info line
        reader = PhysTableReader(
            db=p.db,
            table=t,
            store_type=StoreType.HOST,
            pushed_conditions=list(p.residual_conditions),
            scan_slots=list(p.scan_slots),
            ranges=_coalesce_handle_ranges(t.id, handles),
            schema=p.schema,
        )
        return TableReaderExec(reader, self.session, detail_target=p).execute()


@dataclass
class IndexMergeExec(Executor):
    """Union/intersection of per-path handle sets feeding one table lookup
    (ref: IndexMergeReaderExecutor, executor/index_merge_reader.go:88 —
    partial index/table workers → handle union → table worker). Paths run
    concurrently on the cop pool; the table side re-applies the FULL
    condition list, so over-approximating paths stay correct."""

    plan: "PhysIndexMerge"
    session: object

    def __post_init__(self):
        self.schema = self.plan.schema

    def _path_handles(self, path) -> np.ndarray:
        p = self.plan
        t = p.table
        if path[0] == "table":
            scan = dagpb.ExecutorPB(
                dagpb.TABLE_SCAN,
                table_id=t.id,
                columns=[dagpb.ColumnInfoPB(-1, bigint_type(nullable=False), is_handle=True)],
                storage_schema=t.storage_schema,
            )
            ranges = path[1]
        else:
            idx = path[1]
            scan = dagpb.ExecutorPB(
                dagpb.INDEX_SCAN,
                table_id=t.id,
                index_id=idx.id,
                index_col_offsets=list(idx.column_offsets),
                unique=idx.unique,
                columns=[dagpb.ColumnInfoPB(-1, bigint_type(nullable=False), is_handle=True)],
                storage_schema=t.storage_schema,
            )
            ranges = path[2]
        if not ranges:
            return np.empty(0, np.int64)
        req = Request(
            tp=RequestType.DAG,
            data=dagpb.DAGRequest(executors=[scan]),
            ranges=ranges,
            store_type=StoreType.HOST,
            start_ts=self.session.read_ts(),
            concurrency=int(self.session.vars.get("tidb_distsql_scan_concurrency", 8)),
            warn=self.session.append_warning,
            tracer=self.session.tracer,
        )
        chunks = _gather_index_chunks(self.session, self.plan, req)
        if not chunks:
            return np.empty(0, np.int64)
        return np.concatenate([c.columns[0].data for c in chunks])

    def execute(self) -> Chunk:
        p = self.plan
        if self.session._txn_dirty():
            return _union_scan_fallback(
                self.session, p.table, p.scan_slots, p.all_conditions, p.schema, target=p
            )
        from concurrent.futures import ThreadPoolExecutor

        if len(p.paths) > 1:
            with ThreadPoolExecutor(max_workers=min(4, len(p.paths)), thread_name_prefix="imerge") as pool:
                handle_sets = list(pool.map(self._path_handles, p.paths))
        else:
            handle_sets = [self._path_handles(path) for path in p.paths]
        if p.intersection:
            handles = handle_sets[0]
            for h in handle_sets[1:]:
                handles = np.intersect1d(handles, h)
        else:
            handles = np.unique(np.concatenate(handle_sets)) if handle_sets else np.empty(0, np.int64)
        if not len(handles):
            return _empty_chunk(p.schema)
        reader = PhysTableReader(
            db=p.db,
            table=p.table,
            store_type=StoreType.HOST,
            pushed_conditions=list(p.residual_conditions),
            scan_slots=list(p.scan_slots),
            ranges=_coalesce_handle_ranges(p.table.id, handles),
            schema=p.schema,
        )
        return TableReaderExec(reader, self.session, detail_target=p).execute()


@dataclass
class SelectionExec(Executor):
    plan: PhysSelection
    child: Executor
    session: object = None

    def __post_init__(self):
        self.schema = self.plan.schema

    def execute(self) -> Chunk:
        chunk = self.child.execute()
        warn = self.session.append_warning if self.session is not None else None
        return host_selection(chunk, [c.to_pb() for c in self.plan.conditions], warn=warn)


@dataclass
class ProjectionExec(Executor):
    plan: PhysProjection
    child: Executor
    session: object = None

    def __post_init__(self):
        self.schema = self.plan.schema

    def execute(self) -> Chunk:
        chunk = self.child.execute()
        warn = self.session.append_warning if self.session is not None else None
        batch = EvalBatch.from_chunk(chunk, warn=warn)
        if len(chunk) == 0:
            return _empty_chunk(self.plan.schema)
        return Chunk([eval_to_column(e, batch, np) for e in self.plan.exprs])


@dataclass
class FinalAggExec(Executor):
    plan: PhysFinalAgg
    child: Executor
    session: object = None

    # engage the partial/final worker pipeline past this input size
    PARALLEL_MIN_ROWS = 200_000

    def __post_init__(self):
        self.schema = self.plan.schema

    def execute(self) -> Chunk:
        chunk = self.child.execute()
        aggs = self.plan.aggs
        # rollup partials interleave GROUPING() flags after the keys — the
        # merge identity is (keys, flags) and both pass through
        ngroup = len(self.plan.group_by) * (2 if getattr(self.plan, "rollup", False) else 1)
        if not self.plan.partial_input:
            splittable = not any(a.distinct or a.name == "group_concat" for a in aggs)
            if splittable and len(chunk) >= self.PARALLEL_MIN_ROWS:
                return self._partial_final_pipeline(chunk)
            ex = dagpb.ExecutorPB(
                dagpb.AGGREGATION,
                group_by=[g.to_pb() for g in self.plan.group_by],
                aggs=[a.to_pb() for a in aggs],
                agg_mode=dagpb.AGG_COMPLETE,
            )
            return host_aggregate(chunk, ex)
        return merge_partials(chunk, aggs, ngroup)

    def _partial_final_pipeline(self, chunk: Chunk) -> Chunk:
        """Partial/final worker pipeline (ref: parallel HashAgg,
        aggregate/agg_hash_executor.go:94): slices aggregate to partial
        state concurrently; partials spill through a tracker-registered
        RowContainer (ref: agg_spill.go) before the final merge."""
        from concurrent.futures import ThreadPoolExecutor

        from tidb_tpu.utils.rowcontainer import RowContainer

        p = self.plan
        n = len(chunk)
        conc = 4
        tracker = None
        if self.session is not None:
            from tidb_tpu.session.session import executor_concurrency

            conc = executor_concurrency(self.session.vars, "tidb_hashagg_partial_concurrency")
            tracker = getattr(self.session, "mem_tracker", None)
        per = max((n + conc - 1) // conc, 65536)
        bounds = [(i, min(i + per, n)) for i in range(0, n, per)]
        pex = dagpb.ExecutorPB(
            dagpb.AGGREGATION,
            group_by=[g.to_pb() for g in p.group_by],
            aggs=[a.to_pb() for a in p.aggs],
            agg_mode=dagpb.AGG_PARTIAL,
        )
        rc = RowContainer(tracker, "agg-partials")
        try:
            if len(bounds) > 1:
                with ThreadPoolExecutor(max_workers=min(conc, len(bounds)), thread_name_prefix="agg") as pool:
                    parts = list(pool.map(lambda b: host_aggregate(chunk.slice(*b), pex), bounds))
            else:
                parts = [host_aggregate(chunk.slice(*b), pex) for b in bounds]
            for part in parts:
                rc.add(part)
            merged = rc.to_chunk()
        finally:
            rc.close()
        if merged is None or not len(merged):
            # empty input: fall through to the complete-mode scalar handling
            ex = dagpb.ExecutorPB(
                dagpb.AGGREGATION,
                group_by=[g.to_pb() for g in p.group_by],
                aggs=[a.to_pb() for a in p.aggs],
                agg_mode=dagpb.AGG_COMPLETE,
            )
            return host_aggregate(chunk, ex)
        return merge_partials(merged, p.aggs, len(p.group_by))


def merge_partials(chunk: Chunk, aggs: list[AggDesc], ngroup: int) -> Chunk:
    """Merge per-region partial-state chunks into final values (ref: the
    final-mode HashAgg above a partial cop agg, aggregate/agg_hash_executor)."""
    ncols = chunk.num_cols
    key_cols = chunk.columns[ncols - ngroup :] if ngroup else []
    n = len(chunk)
    # group rows by key columns; ci string keys group by their general_ci
    # WEIGHT class (per-region partials may split 'a'/'A'/'á' — the merge
    # is where they collapse, ref: collate-aware final HashAgg)
    def _key_lane(c) -> np.ndarray:
        from tidb_tpu.utils.collate import canon_codes, is_ci_string

        if is_ci_string(c):
            return canon_codes(c.data, c.validity, c.dictionary)
        return c.data

    if ngroup and n:
        key_lanes = [_key_lane(c) for c in key_cols]
        lanes = []
        for c, kd in zip(key_cols, key_lanes):
            lanes.append(kd)
            lanes.append(~c.validity)
        perm = np.lexsort(tuple(reversed(lanes)))
        boundary = np.zeros(n, dtype=bool)
        boundary[0] = True
        for c, kd in zip(key_cols, key_lanes):
            ds, vs = kd[perm], c.validity[perm]
            boundary[1:] |= ds[1:] != ds[:-1]
            boundary[1:] |= vs[1:] != vs[:-1]
        seg = np.cumsum(boundary) - 1
        ngroups = int(seg[-1]) + 1
    else:
        perm = np.arange(n)
        seg = np.zeros(n, dtype=np.int64)
        ngroups = 1 if (n or not ngroup) else 0
        boundary = np.zeros(n, dtype=bool)
        if n:
            boundary[0] = True

    state_cols: list[Column] = []
    i = 0
    for a in aggs:
        for pk in a.partial_kinds:
            c = chunk.columns[i]
            i += 1
            data, valid = c.data[perm], c.validity[perm]
            if pk in ("count",):
                out = np.bincount(seg, weights=data, minlength=ngroups).astype(np.int64)
                state_cols.append(Column(out, np.ones(ngroups, bool), c.ftype))
            elif pk == "sum":
                w = np.where(valid, data, 0)
                if data.dtype == np.float64:
                    out = np.bincount(seg, weights=w, minlength=ngroups)
                else:
                    out = np.zeros(ngroups, dtype=np.int64)
                    np.add.at(out, seg, w)
                anyv = np.zeros(ngroups, dtype=bool)
                np.logical_or.at(anyv, seg, valid)
                state_cols.append(Column(out.astype(data.dtype), anyv, c.ftype))
            elif pk in ("min", "max"):
                from tidb_tpu.copr.host_engine import (
                    _string_minmax,
                    minmax_sentinel,
                    string_minmax_needs_rank,
                )

                if string_minmax_needs_rank(c.ftype, c.dictionary):
                    # partial states carry dictionary CODES; merging them raw
                    # has the same misordering as the cop-side reduce (ci
                    # weight order / unsorted dictionary — see host_engine)
                    out, cntv = _string_minmax(
                        pk, data, valid, seg, ngroups, c.dictionary,
                        c.ftype.collation == "ci",
                    )
                    state_cols.append(Column(out, cntv > 0, c.ftype, c.dictionary))
                else:
                    sentinel = minmax_sentinel(pk, data.dtype)
                    d = np.where(valid, data, sentinel).astype(data.dtype)
                    out = np.full(ngroups, sentinel, dtype=data.dtype)
                    (np.minimum if pk == "min" else np.maximum).at(out, seg, d)
                    anyv = np.zeros(ngroups, dtype=bool)
                    np.logical_or.at(anyv, seg, valid)
                    state_cols.append(Column(out, anyv, c.ftype, c.dictionary))
            elif pk == "first_row":
                first_idx = np.nonzero(boundary)[0] if n else np.empty(0, np.int64)
                # first VALID row per group preferred
                out = np.zeros(ngroups, dtype=data.dtype)
                anyv = np.zeros(ngroups, dtype=bool)
                # walk groups: take first valid value
                order = np.lexsort((np.arange(n), ~valid, seg)) if n else np.empty(0, np.int64)
                if n:
                    b2 = np.ones(n, dtype=bool)
                    b2[1:] = seg[order][1:] != seg[order][:-1]
                    firsts = order[b2]
                    out[seg[firsts]] = data[firsts]
                    anyv[seg[firsts]] = valid[firsts]
                state_cols.append(Column(out, anyv, c.ftype, c.dictionary))
            elif pk == "sumsq":
                # partial sums of squares (double) merge by addition
                out = np.bincount(seg, weights=np.where(valid, data, 0.0), minlength=ngroups)
                anyv = np.zeros(ngroups, dtype=bool)
                np.logical_or.at(anyv, seg, valid)
                state_cols.append(Column(out, anyv, c.ftype))
            elif pk in ("bit_and", "bit_or", "bit_xor"):
                from tidb_tpu.copr.host_engine import bit_reduce

                out = bit_reduce(pk, data, valid, seg, ngroups)
                state_cols.append(Column(out, np.ones(ngroups, bool), c.ftype))
            elif pk == "group_concat":
                # group_concat never pushes partials (planner gate); merging
                # would need value-order metadata the lanes don't carry
                raise ValueError("group_concat cannot merge as a partial aggregate")
    # key outputs: value at first row of each group
    out_keys: list[Column] = []
    if ngroup and n:
        firsts = np.nonzero(boundary)[0]
        for c in key_cols:
            out_keys.append(Column(c.data[perm][firsts], c.validity[perm][firsts], c.ftype, c.dictionary))
    elif ngroup:
        out_keys = [Column(np.empty(0, c.data.dtype), np.empty(0, bool), c.ftype, c.dictionary) for c in key_cols]
    partial = Chunk(state_cols + out_keys)
    if ngroups == 0 and ngroup == 0:
        # scalar agg over empty input: synthesize the empty-partial row
        pass
    group_fts = [c.ftype for c in key_cols]
    group_dicts = [c.dictionary for c in key_cols]
    return finalize_agg(partial, aggs, group_fts, group_dicts)


@dataclass
class SortExec(Executor):
    plan: PhysSort
    child: Executor

    def __post_init__(self):
        self.schema = self.plan.schema

    def execute(self) -> Chunk:
        chunk = self.child.execute()
        if len(chunk) == 0:
            return chunk
        perm = sort_perm(chunk, [[e.to_pb(), d] for e, d in self.plan.by])
        return chunk.take(perm)


@dataclass
class LimitExec(Executor):
    plan: PhysLimit
    child: Executor

    def __post_init__(self):
        self.schema = self.plan.schema

    def execute(self) -> Chunk:
        chunk = self.child.execute()
        return chunk.slice(min(self.plan.offset, len(chunk)), min(self.plan.offset + self.plan.limit, len(chunk)))


@dataclass
class DistinctExec(Executor):
    child: Executor

    def __post_init__(self):
        self.schema = self.child.schema

    def execute(self) -> Chunk:
        chunk = self.child.execute()
        n = len(chunk)
        if n == 0:
            return chunk

        def key_of(c) -> np.ndarray:
            # codes identify values within one dictionary; ci collations
            # dedupe by general_ci WEIGHT class ('a' ≡ 'A' ≡ 'á')
            from tidb_tpu.utils.collate import canon_codes, is_ci_string

            if is_ci_string(c):
                return canon_codes(c.data, c.validity, c.dictionary)
            return c.data

        keys = [key_of(c) for c in chunk.columns]
        lanes = []
        for c, kd in zip(chunk.columns, keys):
            lanes.append(kd)
            lanes.append(~c.validity)
        perm = np.lexsort(tuple(reversed(lanes)))
        # keep the first row of each distinct key tuple
        diff = np.zeros(n, dtype=bool)
        diff[0] = True
        for c, kd in zip(chunk.columns, keys):
            ds, vs = kd[perm], c.validity[perm]
            diff[1:] |= ds[1:] != ds[:-1]
            diff[1:] |= vs[1:] != vs[:-1]
        return chunk.take(np.sort(perm[diff]))


@dataclass
class WindowExec(Executor):
    """Window functions (ref: pkg/executor WindowExec + pipelined window
    workers, collapsed to a sorted-partition sweep). Supported frames: whole
    partition, RANGE UNBOUNDED..CURRENT (peers share the frame — the MySQL
    default with ORDER BY) and ROWS UNBOUNDED..CURRENT."""

    plan: PhysWindow
    child: Executor
    session: object = None

    def __post_init__(self):
        self.schema = self.plan.schema

    def execute(self) -> Chunk:
        p = self.plan
        chunk = self.child.execute()
        n = len(chunk)
        if n == 0:
            return Chunk(
                list(chunk.columns)
                + [
                    Column(np.empty(0, _np_dtype(f.ftype)), np.empty(0, bool), f.ftype)
                    for f in p.funcs
                ]
            )
        dev = self._try_device(chunk, n)
        if dev is not None:
            return dev
        keys = [[e.to_pb(), False] for e in p.partition_by] + [
            [e.to_pb(), d] for e, d in p.order_by
        ]
        perm = sort_perm(chunk, keys) if keys else np.arange(n)
        batch = EvalBatch.from_chunk(chunk)
        part_start = np.zeros(n, dtype=bool)
        part_start[0] = True
        for e in p.partition_by:
            c = eval_to_column(e, batch, np)
            # mask NULL slots: computed-expression garbage must not split a
            # NULL partition (same rule as the device kernel)
            d, v = np.where(c.validity, c.data, 0)[perm], c.validity[perm]
            part_start[1:] |= (d[1:] != d[:-1]) | (v[1:] != v[:-1])
        # order-key peer groups: ranking functions always use these, whatever
        # the frame says (MySQL ignores frames for ranking)
        peer_start = part_start.copy()
        for e, _ in p.order_by:
            c = eval_to_column(e, batch, np)
            d, v = np.where(c.validity, c.data, 0)[perm], c.validity[perm]
            peer_start[1:] |= (d[1:] != d[:-1]) | (v[1:] != v[:-1])
        pbounds = np.flatnonzero(part_start).tolist() + [n]
        out_cols = []
        for f in p.funcs:
            argcols = [eval_to_column(a, batch, np) for a in f.args]
            sdata, svalid = self._compute(f, argcols, perm, pbounds, peer_start)
            data = np.empty(n, dtype=sdata.dtype)
            valid = np.empty(n, dtype=bool)
            data[perm] = sdata
            valid[perm] = svalid
            dic = (
                argcols[0].dictionary
                if argcols and argcols[0].ftype.kind == TypeKind.STRING
                else None
            )
            out_cols.append(Column(data, valid, f.ftype, dic))
        return Chunk(list(chunk.columns) + out_cols)

    def _try_device(self, chunk: Chunk, n: int):
        """Window evaluation on the device via ops/window_kernel (sorted-batch
        segment program) when the shape qualifies; None → host sweep."""
        from tidb_tpu.ops import window_core as wc
        from tidb_tpu.ops import window_kernel as wk

        p = self.plan
        if self.session is None or n > wk.DEVICE_MAX_ROWS:
            return None
        engines = str(self.session.vars.get("tidb_isolation_read_engines", "tpu,host"))
        if "tpu" not in engines:
            return None
        # phase 1: reject on static structure only (expression ftypes and
        # plan-time constants) — no column evaluation until the shape is
        # known-supported, so fallbacks don't pay O(n) twice
        spec_res = wc.derive_specs(
            p.funcs,
            whole_partition=p.whole_partition,
            rows_frame=p.rows_frame,
            frame=p.frame,
            # dict codes are not ORDER-comparable at this layer (the cop
            # binder legalizes them with sorted dictionaries; here the chunk
            # may carry arbitrary-order codes)
            order_is_string=any(e.ftype.kind == TypeKind.STRING for e, _ in p.order_by),
        )
        if spec_res is None:
            return None
        frame_tag, specs = spec_res

        # measured-cost routing (not a hard row floor): device wins when its
        # fixed dispatch + upload + per-row work undercut the host sweep —
        # but a shape's FIRST compile only pays off on big batches
        from tidb_tpu.utils.chunk import bucket_size as _bs

        spec_key = (len(p.partition_by), tuple(d for _, d in p.order_by), frame_tag, tuple(specs))
        n_lanes_up = len(p.partition_by) + len(p.order_by) + sum(1 for _n, ha, *_ in specs if ha)
        if not wk.device_beats_host(
            n, n_lanes_up, len(p.funcs), wk.is_compiled(spec_key, _bs(n))
        ):
            return None

        # phase 2: evaluate lanes (shape is supported from here on)
        batch = EvalBatch.from_chunk(chunk)

        def lane_of(e):
            c = eval_to_column(e, batch, np)
            return (c.data.astype(np.float64 if c.ftype.kind == TypeKind.FLOAT else np.int64), c.validity)

        # partition keys need only identity → dictionary codes qualify
        part = [lane_of(e) for e in p.partition_by]
        order = [lane_of(e) for e, _ in p.order_by]
        arg_lanes = []
        for f, (name, has_arg, is_f, _, _, _) in zip(p.funcs, specs):
            arg_lanes.append(lane_of(f.args[0]) if has_arg else None)

        from tidb_tpu.utils.chunk import bucket_size

        n_pad = bucket_size(n)
        # integer sort-lane bounds (one cheap numpy pass) enable the packed
        # single-key sort; without them large batches stay on the host sweep
        # (the multi-lane sort compiles/runs pathologically past one block)
        bounds = []
        for d, v in part + order:
            if np.issubdtype(d.dtype, np.floating):
                bounds.append(None)
                continue
            lv = d[v]
            bounds.append((int(lv.min()), int(lv.max())) if lv.size else (0, 0))
        bounds = wc.widen_bounds(bounds)
        if wc.packed_bits(bounds, n_pad) is None:
            if n > wk.MULTILANE_MAX_ROWS:
                return None
            bounds = None

        def pad(pair):
            d, v = pair
            pd = np.zeros(n_pad, dtype=d.dtype)
            pd[:n] = d
            pv = np.zeros(n_pad, dtype=bool)
            pv[:n] = v
            return (pd, pv)

        spec = (len(part), tuple(d for _, d in p.order_by), frame_tag, tuple(specs))
        bkey = tuple(bounds) if bounds is not None else None
        if n < wk.COMPILE_GATE_ROWS and not wk.is_compiled(spec, n_pad, bkey):
            # the compile key includes the widened bounds: a bounds variant
            # of an otherwise-warm shape still costs a 30-120s compile,
            # which a small batch must not buy
            return None
        fn = wk.get_window_fn(spec, n_pad, bkey)
        import jax

        flat = fn(
            tuple(pad(x) for x in part),
            tuple(pad(x) for x in order),
            # only real arg lanes travel: zeros pairs for no-arg funcs would
            # ride the variadic sort as dead payload operands
            tuple(pad(x) for x in arg_lanes if x is not None),
            np.int64(n),
        )
        got = jax.device_get(flat)  # one batched transfer
        out_cols = []
        for i, f in enumerate(p.funcs):
            data = np.asarray(got[2 * i])[:n]
            valid = np.asarray(got[2 * i + 1])[:n].astype(bool)
            dt = _np_dtype(f.ftype)
            out_cols.append(Column(data.astype(dt, copy=False), valid, f.ftype))
        return Chunk(list(chunk.columns) + out_cols)

    def _compute(self, f, argcols, perm, pbounds, peer_start):
        """Returns (data, validity) arrays in sorted-row order."""
        p = self.plan
        n = len(perm)
        dt = _np_dtype(f.ftype)
        out = np.zeros(n, dtype=dt)
        valid = np.ones(n, dtype=bool)
        av = argcols[0].data[perm] if argcols else None
        vv = argcols[0].validity[perm] if argcols else None
        mm_rank = mm_codes = None  # lazily-built MIN/MAX comparison lanes
        for s, e in zip(pbounds, pbounds[1:]):
            m = e - s
            ps = peer_start[s:e]
            starts = np.flatnonzero(ps)
            ends = np.r_[starts[1:], m]
            sizes = ends - starts
            # frame [fs, fe) per row under the supported frames
            fs = np.zeros(m, dtype=np.int64)
            if p.frame is not None:
                skind, sn, ekind, en = p.frame
                idx = np.arange(m, dtype=np.int64)
                if skind == "unbounded":
                    fs = np.zeros(m, dtype=np.int64)
                elif skind == "current":
                    fs = idx
                elif skind == "preceding":
                    fs = np.maximum(idx - sn, 0)
                else:  # following
                    fs = np.minimum(idx + sn, m)
                if ekind == "unbounded":
                    fe = np.full(m, m, dtype=np.int64)
                elif ekind == "current":
                    fe = idx + 1
                elif ekind == "preceding":
                    fe = np.maximum(idx - en + 1, 0)
                else:  # following
                    fe = np.minimum(idx + en + 1, m)
                fe = np.maximum(fe, fs)  # empty frames: fe == fs
            elif p.whole_partition:
                fe = np.full(m, m, dtype=np.int64)
            elif p.rows_frame:
                fe = np.arange(1, m + 1, dtype=np.int64)
            else:  # RANGE ..CURRENT: peers share the frame
                fe = np.repeat(ends, sizes)
            name = f.name
            if name == "row_number":
                out[s:e] = np.arange(1, m + 1)
            elif name == "rank":
                out[s:e] = np.repeat(starts + 1, sizes)
            elif name == "dense_rank":
                out[s:e] = np.repeat(np.arange(1, len(starts) + 1), sizes)
            elif name == "percent_rank":
                r = np.repeat(starts, sizes).astype(np.float64)
                out[s:e] = r / (m - 1) if m > 1 else 0.0
            elif name == "cume_dist":
                out[s:e] = np.repeat(ends, sizes) / float(m)
            elif name == "ntile":
                k = int(av[s])
                q, rem = divmod(m, k)
                bsizes = np.array([q + 1] * rem + [q] * (k - rem), dtype=np.int64)
                out[s:e] = np.repeat(np.arange(1, k + 1), bsizes)[:m]
            elif name in ("lead", "lag"):
                # offset/default are plan-time constants (builder enforces)
                off = int(argcols[1].data[0]) if len(argcols) > 1 else 1
                shift = -off if name == "lead" else off
                src = np.arange(m) - shift
                ok = (src >= 0) & (src < m)
                idx = np.clip(src, 0, m - 1)
                out[s:e] = np.where(ok, av[s:e][idx], 0)
                valid[s:e] = np.where(ok, vv[s:e][idx], False)
                if len(argcols) > 2:  # explicit default
                    dcol = argcols[2]
                    dvalid = bool(dcol.validity[0])
                    if argcols[0].ftype.kind == TypeKind.STRING and dvalid:
                        # re-encode into the argument's dictionary — the
                        # constant's private dictionary codes don't transfer
                        dv = argcols[0].dictionary.encode(dcol.logical_value(0))
                    else:
                        dv = dcol.data[0]
                    out[s:e] = np.where(ok, out[s:e], dv)
                    valid[s:e] = np.where(ok, valid[s:e], dvalid)
            elif name == "first_value":
                nonempty = fe > fs
                fs_c = np.clip(fs, 0, m - 1)
                out[s:e] = np.where(nonempty, av[s:e][fs_c], 0)
                valid[s:e] = np.where(nonempty, vv[s:e][fs_c], False)
            elif name == "last_value":
                nonempty = fe > fs
                fe_c = np.clip(fe - 1, 0, m - 1)
                out[s:e] = np.where(nonempty, av[s:e][fe_c], 0)
                valid[s:e] = np.where(nonempty, vv[s:e][fe_c], False)
            elif name in ("count", "sum", "avg", "min", "max"):
                if name == "count" and not argcols:
                    out[s:e] = fe - fs
                    continue
                pvv = vv[s:e]
                c0 = np.r_[0, np.cumsum(pvv.astype(np.int64))]
                cnt = c0[fe] - c0[fs]
                if name == "count":
                    out[s:e] = cnt
                    continue
                pav = av[s:e]
                if name in ("min", "max"):
                    if mm_rank is None:
                        mm_rank, mm_codes = _cmp_lanes(argcols[0], av)
                    rank = mm_rank[s:e]
                    if rank.dtype == np.float64:
                        fill = np.inf if name == "min" else -np.inf
                    else:
                        fill = np.iinfo(np.int64).max if name == "min" else np.iinfo(np.int64).min
                    lane = np.where(pvv, rank, fill)
                    if p.frame is None:
                        acc = (np.minimum if name == "min" else np.maximum).accumulate(lane)
                        best = acc[np.maximum(fe - 1, 0)]
                    else:
                        best = _sliding_extreme(lane, fs, fe, name == "min", fill)
                    if mm_codes is not None:
                        # all-NULL frames carry the sentinel — mask before the
                        # rank→code fancy index, not after
                        best = np.where(cnt > 0, best, 0)
                        res = mm_codes[best.astype(np.int64)]
                    else:
                        res = best
                    out[s:e] = np.where(cnt > 0, res.astype(dt, copy=False), 0)
                    valid[s:e] = cnt > 0
                    continue
                filled = np.where(pvv, pav, 0)
                s0 = np.r_[
                    0, np.cumsum(filled.astype(np.float64 if dt == np.float64 else np.int64))
                ]
                cum = s0[fe] - s0[fs]
                if name == "sum":
                    out[s:e] = np.where(cnt > 0, cum.astype(dt, copy=False), 0)
                    valid[s:e] = cnt > 0
                else:  # avg
                    safe = np.maximum(cnt, 1)
                    if f.ftype.kind == TypeKind.DECIMAL:
                        scale_up = 10 ** (f.ftype.scale - argcols[0].ftype.scale)
                        out[s:e] = np.where(
                            cnt > 0, np.round(cum * scale_up / safe).astype(np.int64), 0
                        )
                    else:
                        out[s:e] = np.where(cnt > 0, cum / safe, 0.0)
                    valid[s:e] = cnt > 0
            else:
                raise ExecError(f"unsupported window function {name}")
        return out, valid


def _sliding_extreme(lane, fs, fe, is_min: bool, fill):
    """MIN/MAX over sliding [fs, fe) frames via a monotonic deque (frame
    bounds are nondecreasing for ROWS frames → O(n) total)."""
    from collections import deque

    m = len(lane)
    out = np.full(m, fill, dtype=lane.dtype)
    dq: deque = deque()  # indices, lane values monotonic
    lo = 0
    hi = 0
    better = (lambda a, b: a <= b) if is_min else (lambda a, b: a >= b)
    for i in range(m):
        while hi < fe[i]:
            v = lane[hi]
            while dq and better(v, lane[dq[-1]]):
                dq.pop()
            dq.append(hi)
            hi += 1
        while lo < fs[i]:
            if dq and dq[0] == lo:
                dq.popleft()
            lo += 1
        if dq and fe[i] > fs[i]:
            out[i] = lane[dq[0]]
    return out


def _np_dtype(ftype):
    return {TypeKind.FLOAT: np.float64, TypeKind.STRING: np.int32}.get(ftype.kind, np.int64)


def _cmp_lanes(col, data):
    """(comparison lane, rank→code lookup) for cumulative MIN/MAX: plain
    lanes compare directly; unsorted-dictionary strings compare by value
    rank, mapped back to codes afterwards."""
    if col.ftype.kind == TypeKind.STRING and col.dictionary is not None and not col.dictionary.sorted:
        vals = col.dictionary.decode_many(data)
        order = {v: i for i, v in enumerate(sorted(set(vals)))}
        rank = np.fromiter((order[v] for v in vals), dtype=np.int64, count=len(vals))
        code_for_rank = np.zeros(len(order), dtype=np.int64)
        for v, c in zip(vals, data):
            code_for_rank[order[v]] = c
        return rank, code_for_rank
    return data.astype(np.int64, copy=False) if data.dtype != np.float64 else data, None


@dataclass
class SetOpExec(Executor):
    """UNION / INTERSECT / EXCEPT with multiset (ALL) or set semantics
    (ref: UnionExec + set-operation rewrites). Row identity uses logical
    values, so NULLs compare equal as MySQL set ops require."""

    plan: PhysSetOp
    childs: list

    def __post_init__(self):
        self.schema = self.plan.schema

    def execute(self) -> Chunk:
        from collections import Counter

        l, r = (c.execute() for c in self.childs)
        op, all_ = self.plan.op, self.plan.all
        if op == "union" and all_ and self._concat_ok(l, r):
            return Chunk.concat([l, r])
        lrows, rrows = l.rows(), r.rows()
        if op == "union":
            rows = lrows + rrows
            if not all_:
                rows = list(dict.fromkeys(rows))
        elif op == "intersect":
            rc = Counter(rrows)
            rows = []
            if all_:
                for t in lrows:
                    if rc[t] > 0:
                        rows.append(t)
                        rc[t] -= 1
            else:
                seen: set = set()
                for t in lrows:
                    if rc[t] > 0 and t not in seen:
                        rows.append(t)
                        seen.add(t)
        else:  # except
            rc = Counter(rrows)
            rows = []
            if all_:
                for t in lrows:
                    if rc[t] > 0:
                        rc[t] -= 1
                    else:
                        rows.append(t)
            else:
                seen = set()
                for t in lrows:
                    if rc[t] == 0 and t not in seen:
                        rows.append(t)
                        seen.add(t)
        cols = [
            Column.from_values([row[i] for row in rows], oc.ftype)
            for i, oc in enumerate(self.schema)
        ]
        return Chunk(cols)

    @staticmethod
    def _concat_ok(l: Chunk, r: Chunk) -> bool:
        """Physical concat is sound unless string lanes use different
        dictionaries (codes would collide)."""
        for lc, rc in zip(l.columns, r.columns):
            if lc.ftype.kind == TypeKind.STRING and lc.dictionary is not rc.dictionary:
                return False
        return True


@dataclass
class HashJoinExec(Executor):
    plan: PhysHashJoin
    left: Executor
    right: Executor
    session: object = None

    def __post_init__(self):
        self.schema = self.plan.schema

    def _key_array(self, chunk: Chunk, idx: int):
        c = chunk.columns[idx]
        if c.ftype.kind == TypeKind.STRING and c.dictionary is not None:
            # cross-table joins: dictionaries differ → join on bytes
            return np.array([None if not c.validity[i] else c.dictionary.decode(int(c.data[i])) for i in range(len(c))], dtype=object)
        return c.data

    def execute(self) -> Chunk:
        p = self.plan
        lc = self.left.execute()
        rc = self.right.execute()
        if p.kind in ("semi", "anti"):
            return self._semi_anti(lc, rc)
        if p.kind == "cross" and not p.eq_conds:
            li = np.repeat(np.arange(len(lc)), len(rc))
            ri = np.tile(np.arange(len(rc)), len(lc))
            joined = Chunk(
                [c.take(li) for c in lc.columns] + [c.take(ri) for c in rc.columns]
            )
            return self._apply_other(joined)
        # grace-join spill (ref: join/hash_join_spill.go): when the inputs
        # exceed a share of the memory quota, partition both sides by key
        # hash and join partition-by-partition, accumulating output through
        # a tracker-registered spillable container — peak memory is bounded
        # by one partition plus spilled output pages
        tracker = getattr(self.session, "mem_tracker", None) if self.session is not None else None
        quota = tracker.limit if tracker is not None and tracker.limit > 0 else -1
        if quota > 0 and p.eq_conds:
            in_bytes = sum(
                c.data.nbytes + c.validity.nbytes for c in list(lc.columns) + list(rc.columns)
            )
            numeric = not any(
                lc.columns[l].ftype.kind == TypeKind.STRING or rc.columns[r].ftype.kind == TypeKind.STRING
                for l, r in p.eq_conds
            )
            if in_bytes > quota // 4 and numeric:
                return self._partitioned_join(lc, rc, in_bytes, quota, tracker)
        return self._join_pair(lc, rc)

    def _partitioned_join(self, lc: Chunk, rc: Chunk, in_bytes: int, quota: int, tracker) -> Chunk:
        from tidb_tpu.utils.rowcontainer import RowContainer

        p = self.plan
        K = 2
        while K < 64 and in_bytes // K > max(quota // 8, 1):
            K *= 2
        MIX = np.int64(-7046029254386353131)

        def owners(chunk, poss):
            with np.errstate(over="ignore"):
                h = chunk.columns[poss[0]].data.astype(np.int64).copy()
                for pos in poss[1:]:
                    h = h * MIX + chunk.columns[pos].data.astype(np.int64)
            return (np.abs(h) % K).astype(np.int64)

        lown = owners(lc, [l for l, _ in p.eq_conds])
        rown = owners(rc, [r for _, r in p.eq_conds])
        out = RowContainer(tracker, "join-output")
        try:
            for k in range(K):
                lsub = lc.take(np.nonzero(lown == k)[0])
                rsub = rc.take(np.nonzero(rown == k)[0])
                if len(lsub) == 0 and (p.kind != "right" or len(rsub) == 0):
                    continue
                part = self._join_pair(lsub, rsub)
                if len(part):
                    out.add(part)
            merged = out.to_chunk()
        finally:
            out.close()
        return merged if merged is not None else _empty_chunk(self.schema)

    def _join_pair(self, lc: Chunk, rc: Chunk) -> Chunk:
        p = self.plan
        # build on right, probe left (ref: hash_join build/probe)
        rkeys = [self._key_array(rc, r) for _, r in p.eq_conds]
        rvalid = [rc.columns[r].validity for _, r in p.eq_conds]
        lkeys = [self._key_array(lc, l) for l, _ in p.eq_conds]
        lvalid = [lc.columns[l].validity for l, _ in p.eq_conds]
        vec = self._vector_match(lkeys, lvalid, rkeys, rvalid)
        if vec is not None:
            li, ri, rmatched, lmatched = vec
            lmiss = list(np.nonzero(~lmatched)[0])
        else:
            table: dict = {}
            for j in range(len(rc)):
                if all(v[j] for v in rvalid):
                    k = tuple(ka[j] for ka in rkeys)
                    table.setdefault(k, []).append(j)
            li_list: list[int] = []
            ri_list: list[int] = []
            lmiss = []
            rmatched = np.zeros(len(rc), dtype=bool)
            for i in range(len(lc)):
                if all(v[i] for v in lvalid):
                    k = tuple(ka[i] for ka in lkeys)
                    hits = table.get(k)
                    if hits:
                        for j in hits:
                            li_list.append(i)
                            ri_list.append(j)
                            rmatched[j] = True
                        continue
                lmiss.append(i)
            li = np.asarray(li_list, dtype=np.int64)
            ri = np.asarray(ri_list, dtype=np.int64)
        cols = [c.take(li) for c in lc.columns] + [c.take(ri) for c in rc.columns]
        joined = Chunk(cols)
        joined = self._apply_other(joined)
        if p.kind == "left" and lmiss:
            lm = np.asarray(lmiss, dtype=np.int64)
            null_right = [
                Column(np.zeros(len(lm), c.data.dtype), np.zeros(len(lm), bool), c.ftype, c.dictionary)
                for c in rc.columns
            ]
            miss = Chunk([c.take(lm) for c in lc.columns] + null_right)
            joined = Chunk.concat([joined, miss]) if len(joined) else miss
        elif p.kind == "right":
            rmiss = np.nonzero(~rmatched)[0]
            if len(rmiss):
                null_left = [
                    Column(np.zeros(len(rmiss), c.data.dtype), np.zeros(len(rmiss), bool), c.ftype, c.dictionary)
                    for c in lc.columns
                ]
                miss = Chunk(null_left + [c.take(rmiss) for c in rc.columns])
                joined = Chunk.concat([joined, miss]) if len(joined) else miss
        return joined

    @staticmethod
    def _vector_match(lkeys, lvalid, rkeys, rvalid):
        """Vectorized equi-match for numeric keys: mix key lanes, sort the
        build side, expand probe matches via searchsorted + cumsum (the host
        analog of the MPP expansion join) with exact per-component
        verification. Returns (li, ri, rmatched, lmatched) or None when any
        key lane is non-numeric (object dtype → generic dict path).
        Replaces a per-row Python build/probe loop that cost ~15s/M rows."""
        if any(k.dtype == object for k in lkeys + rkeys):
            return None
        MIX = np.int64(-7046029254386353131)
        with np.errstate(over="ignore"):
            lk = lkeys[0].astype(np.int64).copy()
            rk = rkeys[0].astype(np.int64).copy()
            for a in lkeys[1:]:
                lk = lk * MIX + a.astype(np.int64)
            for a in rkeys[1:]:
                rk = rk * MIX + a.astype(np.int64)
        lval = np.ones(len(lk), dtype=bool)
        for v in lvalid:
            lval &= v
        rval = np.ones(len(rk), dtype=bool)
        for v in rvalid:
            rval &= v
        rperm = np.argsort(np.where(rval, rk, np.iinfo(np.int64).max), kind="stable")
        rk_s = np.where(rval, rk, np.iinfo(np.int64).max)[rperm]
        pk = np.where(lval, lk, np.iinfo(np.int64).max - 1)
        lo = np.searchsorted(rk_s, pk, side="left")
        hi = np.searchsorted(rk_s, pk, side="right")
        cnt = np.where(lval, hi - lo, 0)
        total = int(cnt.sum())
        li = np.repeat(np.arange(len(lk)), cnt)
        base = np.repeat(np.cumsum(cnt) - cnt, cnt)
        ri_s = np.repeat(lo, cnt) + (np.arange(total) - base)
        ri = rperm[ri_s]
        # exact verification: a mix collision must not fabricate a match, and
        # a legal probe key equal to the int64 sentinel must not range over
        # NULL build slots (mirrors _local_expand_join's rvalid mask)
        live = rval[ri]
        for la, ra in zip(lkeys, rkeys):
            live &= la[li] == ra[ri]
        li, ri = li[live], ri[live]
        rmatched = np.zeros(len(rk), dtype=bool)
        rmatched[ri] = True
        lmatched = np.zeros(len(lk), dtype=bool)
        lmatched[li] = True
        return li, ri, rmatched, lmatched

    def _semi_anti(self, lc: Chunk, rc: Chunk) -> Chunk:
        """[NOT] EXISTS / [NOT] IN rewrites (ref: semi-join executors). The
        output is the matching (semi) or non-matching (anti) LEFT rows."""
        p = self.plan
        if p.kind == "anti" and p.null_aware:
            return self._null_aware_anti(lc, rc)
        if p.other_conds:
            return self._semi_anti_other(lc, rc)
        rkeys = [self._key_array(rc, r) for _, r in p.eq_conds]
        rvalid = [rc.columns[r].validity for _, r in p.eq_conds]
        table: set = set()
        for j in range(len(rc)):
            if all(v[j] for v in rvalid):
                table.add(tuple(ka[j] for ka in rkeys))
        lkeys = [self._key_array(lc, l) for l, _ in p.eq_conds]
        lvalid = [lc.columns[l].validity for l, _ in p.eq_conds]
        keep: list[int] = []
        for i in range(len(lc)):
            key_valid = all(v[i] for v in lvalid)
            matched = key_valid and tuple(ka[i] for ka in lkeys) in table
            if (p.kind == "semi") == matched:
                keep.append(i)
        return Chunk([c.take(np.asarray(keep, dtype=np.int64)) for c in lc.columns])

    def _semi_anti_other(self, lc: Chunk, rc: Chunk) -> Chunk:
        """Semi/anti with non-equality join conditions (ref: the reference's
        Apply → semi join with otherConds): expand candidate pairs on the eq
        keys (all pairs when none — the nested-loop Apply shape), filter the
        joined rows through other_conds, then EXISTS-reduce per left row."""
        p = self.plan
        n_l, n_r = len(lc), len(rc)
        matched = np.zeros(n_l, dtype=bool)

        def probe_pairs(li: np.ndarray, ri: np.ndarray) -> None:
            if not len(li):
                return
            joined = Chunk([c.take(li) for c in lc.columns] + [c.take(ri) for c in rc.columns])
            from tidb_tpu.expression.expr import EvalBatch, eval_to_column, expr_from_pb

            batch = EvalBatch.from_chunk(joined)
            keep = np.ones(len(joined), dtype=bool)
            for c in p.other_conds:
                col = eval_to_column(expr_from_pb(c.to_pb()), batch, np)
                keep &= (col.data != 0) & col.validity
            matched[li[keep]] = True

        # cap the materialized pair batch — the nested loop is O(n_l*n_r)
        # time either way, but memory stays bounded (ref: Apply executor's
        # chunked probing)
        PAIR_BATCH = 1 << 20
        if p.eq_conds:
            rkeys = [self._key_array(rc, r) for _, r in p.eq_conds]
            rvalid = [rc.columns[r].validity for _, r in p.eq_conds]
            table: dict = {}
            for j in range(n_r):
                if all(v[j] for v in rvalid):
                    table.setdefault(tuple(ka[j] for ka in rkeys), []).append(j)
            lkeys = [self._key_array(lc, l) for l, _ in p.eq_conds]
            lvalid = [lc.columns[l].validity for l, _ in p.eq_conds]
            li_list, ri_list = [], []
            for i in range(n_l):
                if all(v[i] for v in lvalid):
                    for j in table.get(tuple(ka[i] for ka in lkeys), ()):
                        li_list.append(i)
                        ri_list.append(j)
                if len(li_list) >= PAIR_BATCH:
                    probe_pairs(np.asarray(li_list, dtype=np.int64), np.asarray(ri_list, dtype=np.int64))
                    li_list, ri_list = [], []
            probe_pairs(np.asarray(li_list, dtype=np.int64), np.asarray(ri_list, dtype=np.int64))
        elif n_r:  # pure non-eq correlation: blocked nested loop
            rows_per_block = max(PAIR_BATCH // n_r, 1)
            for i0 in range(0, n_l, rows_per_block):
                i1 = min(i0 + rows_per_block, n_l)
                li = np.repeat(np.arange(i0, i1, dtype=np.int64), n_r)
                ri = np.tile(np.arange(n_r, dtype=np.int64), i1 - i0)
                probe_pairs(li, ri)
        want = matched if p.kind == "semi" else ~matched
        sel = np.nonzero(want)[0]
        return Chunk([c.take(sel) for c in lc.columns])

    def _null_aware_anti(self, lc: Chunk, rc: Chunk) -> Chunk:
        """NOT IN semantics per correlation group (ref: null-aware anti join,
        hash_join null-aware variants). By construction (builder rewrite) the
        FIRST eq pair is the IN operand; the rest are correlation keys.

        For each left row with correlation group G (right rows whose
        correlation keys match): NOT IN is TRUE iff G is empty, or (operand
        is non-NULL, no NULL among G's IN-column values, and operand ∉ G).
        """
        p = self.plan
        (in_l, in_r), corr = p.eq_conds[0], p.eq_conds[1:]
        rin = self._key_array(rc, in_r)
        rin_valid = rc.columns[in_r].validity
        rcorr = [self._key_array(rc, r) for _, r in corr]
        rcorr_valid = [rc.columns[r].validity for _, r in corr]
        groups: dict = {}  # corr key → [set of in-values, has_null]
        for j in range(len(rc)):
            if not all(v[j] for v in rcorr_valid):
                continue  # NULL correlation key never matches any left row
            g = groups.setdefault(tuple(ka[j] for ka in rcorr), [set(), False])
            if rin_valid[j]:
                g[0].add(rin[j])
            else:
                g[1] = True
        lin = self._key_array(lc, in_l)
        lin_valid = lc.columns[in_l].validity
        lcorr = [self._key_array(lc, l) for l, _ in corr]
        lcorr_valid = [lc.columns[l].validity for l, _ in corr]
        keep: list[int] = []
        for i in range(len(lc)):
            if all(v[i] for v in lcorr_valid):
                g = groups.get(tuple(ka[i] for ka in lcorr))
            else:
                g = None  # NULL correlation key → empty group
            if g is None:
                keep.append(i)  # NOT IN (empty) is TRUE even for NULL operand
                continue
            vals, has_null = g
            if not lin_valid[i] or has_null or lin[i] in vals:
                continue  # NULL operand / NULL in list / match → not TRUE
            keep.append(i)
        return Chunk([c.take(np.asarray(keep, dtype=np.int64)) for c in lc.columns])

    def _apply_other(self, joined: Chunk) -> Chunk:
        if not self.plan.other_conds or len(joined) == 0:
            return joined
        return host_selection(joined, [c.to_pb() for c in self.plan.other_conds])


@dataclass
class MergeJoinExec(Executor):
    """Sort-merge join over handle-ordered reader inputs (ref: executor/join/
    merge_join.go): both children stream ascending on the single join key, so
    matching is two searchsorted sweeps + a cumsum expansion — no hash table."""

    plan: "PhysMergeJoin"
    left: Executor
    right: Executor

    def __post_init__(self):
        self.schema = self.plan.schema

    def execute(self) -> Chunk:
        p = self.plan
        lc = self.left.execute()
        rc = self.right.execute()
        l_pos, r_pos = p.eq_conds[0]
        lk = lc.columns[l_pos]
        rk = rc.columns[r_pos]
        # planner guarantees ascending keys (pk-as-handle readers); NULL keys
        # never match an inner join
        lo = np.searchsorted(rk.data, lk.data, side="left")
        hi = np.searchsorted(rk.data, lk.data, side="right")
        cnt = np.where(lk.validity, hi - lo, 0)
        total = int(cnt.sum())
        li = np.repeat(np.arange(len(lc)), cnt)
        base = np.repeat(np.cumsum(cnt) - cnt, cnt)
        ri = np.repeat(lo, cnt) + (np.arange(total) - base)
        joined = Chunk([c.take(li) for c in lc.columns] + [c.take(ri) for c in rc.columns])
        keep = np.ones(len(joined), dtype=bool)
        if p.other_conds and len(joined):
            from tidb_tpu.expression.expr import EvalBatch, eval_to_column, expr_from_pb

            batch = EvalBatch.from_chunk(joined)
            for c in p.other_conds:
                col = eval_to_column(expr_from_pb(c.to_pb()), batch, np)
                keep &= (col.data != 0) & col.validity
            joined = joined.take(np.nonzero(keep)[0])
        if p.kind == "left":
            matched = np.zeros(len(lc), dtype=bool)
            matched[li[keep]] = True
            miss = np.nonzero(~matched)[0]
            if len(miss):
                null_right = [
                    Column(np.zeros(len(miss), c.data.dtype), np.zeros(len(miss), bool), c.ftype, c.dictionary)
                    for c in rc.columns
                ]
                extra = Chunk([c.take(miss) for c in lc.columns] + null_right)
                joined = Chunk.concat([joined, extra]) if len(joined) else extra
        return joined


@dataclass
class _ChunkSource(Executor):
    """Executor over an already-materialized chunk (index-join inner feed)."""

    chunk: Chunk

    def __post_init__(self):
        self.schema = []

    def execute(self) -> Chunk:
        return self.chunk


# past this many distinct PK probes a coalesced range scan beats point gets
_INNER_POINT_BATCH_MAX = 4096


def _inner_point_rows(session, inner_tpl, t, handles) -> Chunk:
    """Index-join inner PK probes as BATCHED point reads through the
    cross-session point-get batcher (copr/client.PointGetBatcher): one store
    dispatch for the probe set, membuffer-overlaid inside a transaction
    (Txn.batch_get), residual pushed conditions re-applied host-side."""
    from tidb_tpu.copr.client import batched_point_get
    from tidb_tpu.copr.host_engine import run_operators
    from tidb_tpu.executor.write import _rows_to_chunk
    from tidb_tpu.kv.rowcodec import RowSchema, decode_row
    from tidb_tpu.kv.txn import retry_locked

    keys = [tablecodec.record_key(t.id, int(h)) for h in handles]
    txn = session._txn
    if txn is not None:
        raws = txn.batch_get(keys)
    else:
        read_ts = session.read_ts()
        raws = retry_locked(
            session.store, lambda: batched_point_get(session.store, read_ts, keys)
        )
    schema = RowSchema(t.storage_schema)
    rows = [decode_row(schema, raw) for raw in raws if raw is not None]
    live_handles = [h for h, raw in zip(handles, raws) if raw is not None]
    full = _rows_to_chunk(session, t, rows)
    cols = []
    for slot in inner_tpl.scan_slots:
        if slot == -1:
            cols.append(
                Column(
                    np.asarray(live_handles, np.int64),
                    np.ones(len(live_handles), bool),
                    bigint_type(nullable=False),
                )
            )
        else:
            cols.append(full.columns[slot])
    chunk = Chunk(cols)
    if inner_tpl.pushed_conditions:
        sel = dagpb.ExecutorPB(
            dagpb.SELECTION, conditions=[c.to_pb() for c in inner_tpl.pushed_conditions]
        )
        chunk = run_operators(chunk, [sel], [])
    return chunk if len(chunk.columns) else _empty_chunk(inner_tpl.schema)


@dataclass
class IndexJoinExec(Executor):
    """Index nested-loop join (ref: index_lookup_join.go): outer rows drive
    point reads into the inner table via PK or a secondary index, so only
    matching inner rows are fetched; the in-memory match reuses the hash
    join over the (small) fetched set."""

    plan: "PhysIndexJoin"
    outer: Executor
    session: object

    def __post_init__(self):
        self.schema = self.plan.schema

    def execute(self) -> Chunk:
        from tidb_tpu.kv.kv import KeyRange
        from tidb_tpu.planner.plans import PhysIndexLookUp
        from tidb_tpu.planner.ranger import _encode_datum, prefix_next

        p = self.plan
        oc = self.outer.execute()
        inner_tpl = p.children[1]
        t = inner_tpl.table
        # distinct non-NULL outer key tuples → point ranges
        keys: set = set()
        kcols = [oc.columns[l] for l, _ in p.eq_conds]
        for i in range(len(oc)):
            if all(c.validity[i] for c in kcols):
                keys.add(tuple(int(c.data[i]) for c in kcols))
        if p.inner_index is None:
            handles = sorted(k[0] for k in keys)
            if handles and len(handles) <= _INNER_POINT_BATCH_MAX:
                # PK probes through the cross-session point-get batcher: ONE
                # batched store dispatch for the whole probe set (concurrent
                # sessions' probes coalesce too) instead of a cop fan-out —
                # the index-lookup inner per-key gap PERF.md named
                ic = _inner_point_rows(self.session, inner_tpl, t, handles)
            elif handles:
                ranges = [
                    KeyRange(tablecodec.record_key(t.id, h), tablecodec.record_key(t.id, h + 1))
                    for h in handles
                ]
                inner_plan = PhysTableReader(
                    db=inner_tpl.db,
                    table=t,
                    # point lookups are the row-store role (ref: index joins
                    # read through TiKV, never the columnar engine)
                    store_type=StoreType.HOST,
                    pushed_conditions=list(inner_tpl.pushed_conditions),
                    scan_slots=list(inner_tpl.scan_slots),
                    ranges=ranges,
                    schema=inner_tpl.schema,
                )
                ic = TableReaderExec(inner_plan, self.session).execute()
            else:
                ic = _empty_chunk(inner_tpl.schema)
        else:
            idx = p.inner_index
            p0 = tablecodec.index_prefix(t.id, idx.id)
            key_fts = [t.columns[off].ftype for off in idx.column_offsets[: len(p.eq_conds)]]
            ranges = []
            for k in sorted(keys):
                enc = p0 + b"".join(_encode_datum(v, ft) for v, ft in zip(k, key_fts))
                ranges.append(KeyRange(enc, prefix_next(enc)))
            lookup = PhysIndexLookUp(
                db=inner_tpl.db,
                table=t,
                index=idx,
                ranges=ranges,
                scan_slots=list(inner_tpl.scan_slots),
                residual_conditions=list(inner_tpl.pushed_conditions),
                all_conditions=list(inner_tpl.pushed_conditions),
                schema=inner_tpl.schema,
            )
            ic = IndexLookUpExec(lookup, self.session).execute() if ranges else _empty_chunk(inner_tpl.schema)
        # match in memory over the fetched inner subset
        hj = PhysHashJoin(
            kind=p.kind,
            eq_conds=p.eq_conds,
            other_conds=p.other_conds,
            schema=p.schema,
        )
        return HashJoinExec(hj, _ChunkSource(oc), _ChunkSource(ic)).execute()


@dataclass
class DualExec(Executor):
    plan: PhysDual

    def __post_init__(self):
        self.schema = self.plan.schema

    def execute(self) -> Chunk:
        # one dummy row so projections above evaluate constants once
        c = Column(np.zeros(1, np.int64), np.ones(1, bool), bigint_type(nullable=False))
        return Chunk([c])


@dataclass
class MemSourceExec(Executor):
    """Materialized in-memory rowset (recursive-CTE results, memtables)."""

    plan: object  # PhysMemSource

    def __post_init__(self):
        self.schema = self.plan.schema

    def execute(self) -> Chunk:
        rows = self.plan.rows
        return Chunk(
            [
                Column.from_values([r[i] for r in rows], oc.ftype)
                for i, oc in enumerate(self.plan.schema)
            ]
        )


@dataclass
class PointGetExec(Executor):
    plan: PhysPointGet
    session: object

    def __post_init__(self):
        self.schema = self.plan.schema

    def execute(self) -> Chunk:
        t = self.plan.table
        txn = self.session.txn_for_read()
        rk = tablecodec.record_key(t.id, self.plan.handle)
        if txn.membuf.contains(rk):
            raw = txn.membuf.get(rk)
        else:
            # honors current-read overrides (FOR UPDATE at for_update_ts)
            raw = txn._retry_locked(lambda: self.session.store.get_snapshot(self.session.read_ts()).get(rk))
        slots = getattr(self.plan, "scan_slots", list(range(len(t.columns))))
        if raw is None:
            return _empty_chunk(self.plan.schema)
        vals = decode_row(RowSchema(t.storage_schema), raw)
        cols = []
        from tidb_tpu.copr.colcache import cache_for

        cache = cache_for(self.session.store)
        for pos, slot in enumerate(slots):
            ci = t.columns[slot]
            v = vals[slot]
            if ci.ftype.kind == TypeKind.STRING:
                dic = cache.dictionary(t.id, slot)
                data = np.array([0 if v is None else dic.encode(v)], dtype=np.int32)
                cols.append(Column(data, np.array([v is not None]), ci.ftype, dic))
            else:
                dt = np.float64 if ci.ftype.kind == TypeKind.FLOAT else np.int64
                data = np.array([0 if v is None else v], dtype=dt)
                cols.append(Column(data, np.array([v is not None]), ci.ftype))
        return Chunk(cols)
