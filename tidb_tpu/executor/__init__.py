"""Executor layer.

Reference parity: pkg/executor — the Volcano engine (exec.Executor
Open/Next/Close, builder.go dispatch). Round-1 shape: operators materialize
chunks (streaming iteration is a later round; the coprocessor layer below
already streams per-region). Read path in executors.py, DML in write.py.
"""

from tidb_tpu.executor.executors import build_executor, ExecError

__all__ = ["build_executor", "ExecError"]
