"""DML executors: INSERT / UPDATE / DELETE with index maintenance.

Reference parity: pkg/executor/insert.go, update.go, delete.go +
pkg/table/tables (AddRecord/UpdateRecord/RemoveRecord) + index KV layout
(tablecodec). All writes stage into the session txn's membuffer; constraint
checks read through the txn (so uncommitted rows conflict correctly).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from tidb_tpu.catalog.schema import IndexInfo, TableInfo
from tidb_tpu.expression.expr import EvalBatch, eval_to_column
from tidb_tpu.kv import tablecodec
from tidb_tpu.kv.rowcodec import RowSchema, decode_row, encode_row
from tidb_tpu.parser import ast
from tidb_tpu.planner.builder import BuildCtx, Builder, _literal
from tidb_tpu.planner.plans import OutCol, PlanError
from tidb_tpu.types import TypeKind
from tidb_tpu.types.datum import date_to_days, datetime_to_micros
from tidb_tpu.utils import codec
from tidb_tpu.utils.chunk import Chunk, Column


class WriteError(Exception):
    pass


class DupKeyError(WriteError):
    def __init__(self, key_desc: str):
        super().__init__(f"Duplicate entry for key '{key_desc}'")


# -- value coercion: literal → physical slot value ---------------------------


def _strict(session) -> bool:
    return "STRICT" in str(session.vars.get("sql_mode", "")).upper()


def _warn_of(session):
    return session.append_warning


def to_physical(v, ftype, warn=None, strict: bool = True, col: str = "") -> object:
    """Logical → storage value. Non-strict mode coerces MySQL-style —
    leading-numeric string prefixes, clamped garbage — and reports through
    ``warn`` (ref: types truncation + stmtctx.AppendWarning: 1265/1366);
    strict mode raises like MySQL's STRICT_TRANS_TABLES."""
    if v is None:
        return None
    k = ftype.kind
    if k in (TypeKind.INT, TypeKind.UINT) and isinstance(v, str):
        import re as _re
        from decimal import ROUND_HALF_UP, Decimal

        num = _re.match(r"\s*([+-]?(?:\d+(?:\.\d*)?|\.\d+)(?:[eE][+-]?\d+)?)\s*$", v)
        if num is not None:
            # clean numeric string: MySQL rounds half away from zero, no
            # warning ('12.5' → 13)
            v = int(Decimal(num.group(1)).to_integral_value(rounding=ROUND_HALF_UP))
        else:
            m = _re.match(r"\s*[+-]?\d+", v)
            if m is not None:
                # numeric prefix + trailing garbage → 1265 Data truncated
                msg = f"Data truncated for column '{col}'"
                code = 1265
            else:
                msg = f"Incorrect integer value: '{v}' for column '{col}'"
                code = 1366
            if strict:
                raise WriteError(msg)
            if warn is not None:
                warn("Warning", code, msg)
            v = int(m.group()) if m else 0
    if k == TypeKind.FLOAT and isinstance(v, str):
        try:
            v = float(v)
        except ValueError:
            msg = f"Incorrect DOUBLE value: '{v}' for column '{col}'"
            if strict:
                raise WriteError(msg)
            if warn is not None:
                warn("Warning", 1366, msg)
            v = 0.0
    if k == TypeKind.DECIMAL:
        from decimal import ROUND_HALF_UP, Decimal, InvalidOperation

        if isinstance(v, (str, Decimal)):
            # exact decimal path: MySQL rounds half AWAY from zero on the
            # decimal digits, which binary floats misrepresent (1.005)
            try:
                d = v if isinstance(v, Decimal) else Decimal(v.strip())
            except InvalidOperation:
                msg = f"Incorrect DECIMAL value: '{v}' for column '{col}'"
                if strict:
                    raise WriteError(msg)
                if warn is not None:
                    warn("Warning", 1366, msg)
                return 0
            scaled = d.scaleb(ftype.scale)
            q = int(scaled.to_integral_value(rounding=ROUND_HALF_UP))
            if warn is not None and scaled != q:
                warn("Note", 1265, f"Data truncated for column '{col}'")
            return q
        try:
            exact = float(v) * (10**ftype.scale)
        except (TypeError, ValueError):
            msg = f"Incorrect DECIMAL value: '{v}' for column '{col}'"
            if strict:
                raise WriteError(msg)
            if warn is not None:
                warn("Warning", 1366, msg)
            return 0
        q = int(round(exact))
        if warn is not None and abs(exact - q) > 1e-9:
            # fractional digits beyond the column scale were rounded away
            warn("Note", 1265, f"Data truncated for column '{col}'")
        return q
    if k == TypeKind.STRING:
        if isinstance(v, str):
            v = v.encode("utf-8")
        elif not isinstance(v, bytes):
            v = str(v).encode("utf-8")
        if ftype.length is not None and ftype.length >= 0 and not ftype.json:
            chars = v.decode("utf-8", "surrogateescape")
            if len(chars) > ftype.length:
                # VARCHAR(n) overflow: strict errors (MySQL 1406) unless only
                # trailing spaces overflow (truncated with a note even in
                # strict mode); non-strict truncates at a character boundary
                only_spaces = chars[ftype.length:].strip(" ") == ""
                if strict and not only_spaces:
                    raise WriteError(f"Data too long for column '{col}'")
                if warn is not None:
                    if only_spaces:
                        warn("Note", 1265, f"Data truncated for column '{col}'")
                    else:
                        warn("Warning", 1265, f"Data truncated for column '{col}'")
                v = chars[: ftype.length].encode("utf-8", "surrogateescape")
        if ftype.json:
            import json as _json

            try:
                v = _json.dumps(
                    _json.loads(v.decode("utf-8")), separators=(", ", ": "), ensure_ascii=False
                ).encode()
            except Exception:
                raise WriteError(f"Invalid JSON text: {v[:60]!r}")
        return v
    if k == TypeKind.DATE:
        if isinstance(v, (int, np.integer)):
            return int(v)
        return date_to_days(v if isinstance(v, str) else v)
    if k == TypeKind.DATETIME:
        if isinstance(v, (int, np.integer)):
            return int(v)
        try:
            return datetime_to_micros(v)
        except ValueError:
            return datetime_to_micros(str(v) + " 00:00:00")
    if k == TypeKind.FLOAT:
        return float(v)
    if k == TypeKind.UINT:
        v = int(v)
        return v - (1 << 64) if v >= 1 << 63 else v
    if k == TypeKind.DURATION and not isinstance(v, (int, np.integer)):
        from tidb_tpu.types.datum import duration_to_micros

        return duration_to_micros(v)
    return int(v)


def index_entry(t: TableInfo, idx: IndexInfo, vals: list, handle: int) -> tuple[bytes, bytes]:
    """Encode one index KV pair. Unique: key has no handle suffix, value
    carries the handle; non-unique: handle in key. NULL-containing unique
    entries get the handle suffix too (MySQL: NULLs don't conflict)."""
    enc = bytearray()
    has_null = False
    for off in idx.column_offsets:
        v = vals[off]
        ft = t.columns[off].ftype
        if v is None:
            has_null = True
            enc += codec.encode_key_nil()
        elif ft.kind == TypeKind.STRING:
            enc += codec.encode_key_bytes(v if isinstance(v, bytes) else str(v).encode())
        elif ft.kind == TypeKind.FLOAT:
            enc += codec.encode_key_float(float(v))
        else:
            enc += codec.encode_key_int(int(v))
    if idx.unique and not has_null:
        return tablecodec.index_key(t.id, idx.id, bytes(enc)), codec.encode_int_raw(handle)
    return tablecodec.index_key(t.id, idx.id, bytes(enc), handle), b"0"


# -- foreign keys (ref: planner/core/foreign_key.go:78 FK check/cascade plan
# nodes + the executor's FK check / FK cascade execs). Checks read through
# the txn membuffer, so same-statement and same-txn rows count. -------------
_FK_MAX_DEPTH = 15  # MySQL cascade depth limit


def _fk_on(session) -> bool:
    try:
        return bool(int(session.vars.get("foreign_key_checks", 1)))
    except (TypeError, ValueError):
        return True


def _encode_fk_key(t: TableInfo, offsets: list[int], key_vals: list) -> bytes:
    """Memcomparable encoding of (non-NULL) FK key values, matching
    index_entry's datum layout."""
    enc = bytearray()
    for off, v in zip(offsets, key_vals):
        ft = t.columns[off].ftype
        if ft.kind == TypeKind.STRING:
            enc += codec.encode_key_bytes(v if isinstance(v, bytes) else str(v).encode())
        elif ft.kind == TypeKind.FLOAT:
            enc += codec.encode_key_float(float(v))
        else:
            enc += codec.encode_key_int(int(v))
    return bytes(enc)


def _fk_resolve(session, fk):
    """(parent TableInfo, ref column offsets) or None when the parent is
    gone (dropped with checks off)."""
    parent = session.catalog.try_table(fk.ref_db, fk.ref_table)
    if parent is None:
        return None
    ref_offs = []
    for n in fk.ref_col_names:
        c = parent.column(n)
        if c is None:
            return None
        ref_offs.append(c.offset)
    return parent, ref_offs


def _fk_parent_exists(session, parent: TableInfo, ref_offs: list[int], key_vals: list) -> bool:
    if parent.pk_is_handle and ref_offs == [parent.pk_offset]:
        return _txn_read(session, tablecodec.record_key(parent.id, int(key_vals[0]))) is not None
    idx = next(
        (
            i
            for i in parent.indexes
            if i.state == "public" and (i.unique or i.primary) and list(i.column_offsets) == list(ref_offs)
        ),
        None,
    )
    if idx is None:  # parent index dropped with checks off: fail open
        return True
    ik = tablecodec.index_key(parent.id, idx.id, _encode_fk_key(parent, ref_offs, key_vals))
    return _txn_read(session, ik) is not None


def _fk_check_child(session, t: TableInfo, vals: list) -> None:
    """INSERT/UPDATE on a child: every non-NULL FK key needs a parent row."""
    if not t.foreign_keys or not _fk_on(session):
        return
    for fk in t.foreign_keys:
        key = [vals[o] for o in fk.col_offsets]
        if any(k is None for k in key):
            continue  # SQL: NULL keys are exempt from the check
        res = _fk_resolve(session, fk)
        if res is None:
            continue
        parent, ref_offs = res
        if not _fk_parent_exists(session, parent, ref_offs, key):
            raise WriteError(
                f"Cannot add or update a child row: a foreign key constraint fails ({fk.name})"
            )


def _fk_child_rows(session, ct: TableInfo, fk, key_vals: list) -> list:
    """[(handle, vals)] of child rows whose FK equals key_vals, read through
    the membuffer via the FK's supporting index (auto-created at DDL time)."""
    from tidb_tpu.kv.kv import KeyRange
    from tidb_tpu.planner.ranger import prefix_next

    txn = session.txn()
    schema = RowSchema(ct.storage_schema)
    if ct.pk_is_handle and fk.col_offsets == [ct.pk_offset]:
        h = int(key_vals[0])
        raw = _txn_read(session, tablecodec.record_key(ct.id, h))
        return [(h, decode_row(schema, raw))] if raw is not None else []
    idx = next(
        (
            i
            for i in ct.indexes
            if i.state == "public"
            and list(i.column_offsets[: len(fk.col_offsets)]) == list(fk.col_offsets)
        ),
        None,
    )
    out = []
    if idx is not None:
        prefix = tablecodec.index_key(ct.id, idx.id, _encode_fk_key(ct, fk.col_offsets, key_vals))
        for k, v in txn.scan(KeyRange(prefix, prefix_next(prefix))):
            # unique non-NULL entries carry the handle in an 8-byte value; a
            # longer key alone does NOT imply a key-tail handle — a unique
            # index extending the FK prefix appends more column datums instead
            if len(v) == 8:
                h = codec.decode_int_raw(v)
            else:  # non-unique / NULL-containing unique: handle rides the key tail
                h = codec.decode_int_raw(k[-8:])
            raw = _txn_read(session, tablecodec.record_key(ct.id, h))
            if raw is not None:
                out.append((h, decode_row(schema, raw)))
        return out
    # no usable index (dropped with checks off): full visible scan
    for k, v in txn.scan(tablecodec.record_range(ct.id)):
        _, h = tablecodec.decode_record_key(k)
        vals = decode_row(schema, v)
        if [vals[o] for o in fk.col_offsets] == list(key_vals):
            out.append((h, vals))
    return out


def _fk_on_parent_delete(session, t: TableInfo, vals: list, depth: int = 0) -> None:
    """DELETE of a (potential) parent row: RESTRICT / CASCADE / SET NULL
    over every referencing child (ref: FK cascade exec)."""
    if not _fk_on(session):
        return
    refs = session.catalog.referencing_fks_by_id(t.id)
    if not refs:
        return
    if depth >= _FK_MAX_DEPTH:
        raise WriteError("foreign key cascade depth exceeded")
    for ct, fk, parent in refs:
        ref_offs = [parent.column(n).offset for n in fk.ref_col_names]
        key = [vals[o] for o in ref_offs]
        if any(k is None for k in key):
            continue
        rows = _fk_child_rows(session, ct, fk, key)
        # a row referencing itself doesn't restrict its own delete
        rows = [(h, cv) for h, cv in rows if not (ct.id == t.id and cv == vals)]
        if not rows:
            continue
        if fk.on_delete in ("restrict", "no_action"):
            raise WriteError(
                f"Cannot delete or update a parent row: a foreign key constraint fails ({fk.name})"
            )
        for h, cvals in rows:
            if fk.on_delete == "cascade":
                _delete_row(session, ct, cvals, h, fk_depth=depth + 1)
            else:  # set_null
                nv = list(cvals)
                for o in fk.col_offsets:
                    nv[o] = None
                _fk_rewrite_child(session, ct, cvals, h, nv, depth + 1)


def _fk_on_parent_update(session, t: TableInfo, old_vals: list, new_vals: list, depth: int = 0) -> None:
    """Referenced key changed on an UPDATE: apply each child FK's ON UPDATE
    action. Runs AFTER the parent's new row is staged, so cascaded child
    rewrites pass their own child-side checks."""
    if not _fk_on(session):
        return
    refs = session.catalog.referencing_fks_by_id(t.id)
    if not refs:
        return
    if depth >= _FK_MAX_DEPTH:
        raise WriteError("foreign key cascade depth exceeded")
    for ct, fk, parent in refs:
        ref_offs = [parent.column(n).offset for n in fk.ref_col_names]
        okey = [old_vals[o] for o in ref_offs]
        nkey = [new_vals[o] for o in ref_offs]
        if okey == nkey or any(k is None for k in okey):
            continue
        rows = _fk_child_rows(session, ct, fk, okey)
        if not rows:
            continue
        if fk.on_update in ("restrict", "no_action"):
            raise WriteError(
                f"Cannot delete or update a parent row: a foreign key constraint fails ({fk.name})"
            )
        for h, cvals in rows:
            nv = list(cvals)
            for o, newv in zip(fk.col_offsets, nkey if fk.on_update == "cascade" else [None] * len(nkey)):
                nv[o] = newv
            _fk_rewrite_child(session, ct, cvals, h, nv, depth + 1)


def _fk_rewrite_child(session, ct: TableInfo, old_vals: list, handle: int, new_vals: list, depth: int) -> None:
    """In-place child row rewrite for cascaded SET NULL / UPDATE: stage the
    rewrite, then propagate to grandchildren (their cascades read the child's
    new key from the membuffer; a RESTRICT aborts the whole statement and the
    stage rolls back)."""
    _delete_row(session, ct, old_vals, handle, fk_depth=None)
    _write_row(session, ct, new_vals, handle)
    _fk_on_parent_update(session, ct, old_vals, new_vals, depth)


def _txn_read(session, key: bytes):
    """Read through the membuffer; in an explicit pessimistic txn the base
    snapshot is for_update_ts (current read), else start_ts. Constraint
    checks must see rows committed after txn start once the key is locked."""
    txn = session.txn()
    if txn.membuf.contains(key):
        return txn.membuf.get(key)
    if session._explicit and txn.pessimistic:
        return session.store.get_snapshot(txn.for_update_ts).get(key)
    return txn.get(key)


def _write_row(session, t: TableInfo, vals: list, handle: int, on_dup=None) -> int:
    """Stage one row + its index entries; returns rows affected. ``on_dup``
    is "replace" | "ignore" | ("update", assignments, db, alias) | None."""
    txn = session.txn()
    schema = RowSchema(t.storage_schema)
    rk = tablecodec.record_key(t.id, handle)
    session.lock_for_write([rk])  # pessimistic stmt-time lock (no-op otherwise)
    existing = _txn_read(session, rk)
    if existing is not None:
        if on_dup == "replace":
            _delete_row(session, t, decode_row(schema, existing), handle)
        elif on_dup == "ignore":
            return 0
        elif isinstance(on_dup, tuple) and on_dup[0] == "update":
            return _apply_on_dup_update(session, t, decode_row(schema, existing), handle, vals, on_dup)
        else:
            raise DupKeyError(f"PRIMARY ({handle})")
    # unique index conflict checks (delete-only indexes don't take writes,
    # so they can't conflict either — ref: F1 state semantics)
    for idx in t.indexes:
        if not idx.unique or idx.state == "delete_only":
            continue
        ik, _ = index_entry(t, idx, vals, handle)
        if any(vals[o] is None for o in idx.column_offsets):
            continue  # NULL never conflicts
        hit = _txn_read(session, ik)
        if hit is not None:
            if on_dup == "replace":
                old_handle = codec.decode_int_raw(hit)
                old_raw = _txn_read(session, tablecodec.record_key(t.id, old_handle))
                if old_raw is not None:
                    _delete_row(session, t, decode_row(schema, old_raw), old_handle)
            elif on_dup == "ignore":
                return 0
            elif isinstance(on_dup, tuple) and on_dup[0] == "update":
                old_handle = codec.decode_int_raw(hit)
                old_raw = _txn_read(session, tablecodec.record_key(t.id, old_handle))
                if old_raw is not None:
                    return _apply_on_dup_update(
                        session, t, decode_row(schema, old_raw), old_handle, vals, on_dup
                    )
            else:
                raise DupKeyError(idx.name)
    _fk_check_child(session, t, vals)
    txn.put(rk, encode_row(schema, vals))
    for idx in t.indexes:
        if idx.state == "delete_only":
            continue  # writes don't maintain delete-only indexes
        ik, iv = index_entry(t, idx, vals, handle)
        txn.put(ik, iv)
    return 1


def _delete_row(session, t: TableInfo, vals: list, handle: int, fk_depth: "int | None" = 0) -> None:
    """``fk_depth``: referential-action recursion depth; None = plain
    storage delete with no FK handling (update paths manage keys themselves)."""
    txn = session.txn()
    session.lock_for_write([tablecodec.record_key(t.id, handle)])
    txn.delete(tablecodec.record_key(t.id, handle))
    for idx in t.indexes:
        ik, _ = index_entry(t, idx, vals, handle)
        txn.delete(ik)
    if fk_depth is not None:
        _fk_on_parent_delete(session, t, vals, fk_depth)


def execute_insert(session, stmt: ast.Insert) -> int:
    db = stmt.table.db or session.current_db
    t = session.catalog.table(db, stmt.table.name)
    cols = t.columns
    if stmt.columns:
        name_to_off = {}
        for cn in stmt.columns:
            c = t.column(cn)
            if c is None:
                raise WriteError(f"Unknown column '{cn}'")
            name_to_off[cn.lower()] = c.offset
        targets = [name_to_off[c.lower()] for c in stmt.columns]
    else:
        targets = list(range(len(cols)))

    rows_values: list[list] = []
    if stmt.select is not None:
        rows = session._run_select_ast(stmt.select)
        for r in rows:
            rows_values.append(list(r))
    else:
        builder = Builder(session.catalog, db, subquery_runner=session._subquery_runner, warn=session.append_warning)
        for row in stmt.values:
            if len(row) != len(targets):
                raise WriteError("Column count doesn't match value count")
            vals = []
            for node in row:
                e = builder.resolve(node, BuildCtx([]))
                from tidb_tpu.expression.expr import Constant

                if not isinstance(e, Constant):
                    raise WriteError("non-constant INSERT value")
                vals.append(e.value if e.ftype.kind != TypeKind.DATE or isinstance(e.value, (int, np.integer)) else e.value)
            rows_values.append(vals)

    affected = 0
    first_auto_id = None  # first generated AUTO_INCREMENT id this statement
    alias = stmt.table.alias or stmt.table.name
    if stmt.on_dup_update:
        on_dup = ("update", stmt.on_dup_update, db, alias)
    else:
        on_dup = "replace" if stmt.replace else ("ignore" if stmt.ignore else None)
    for vals in rows_values:
        full: list = [None] * len(cols)
        for off, v in zip(targets, vals):
            full[off] = (
                to_physical(v, cols[off].ftype, warn=_warn_of(session), strict=_strict(session), col=cols[off].name)
                if not isinstance(v, (bytes,)) or cols[off].ftype.kind == TypeKind.STRING
                else v
            )
        # defaults + auto increment
        handle = None
        for c in cols:
            if full[c.offset] is None and c.offset not in targets:
                if c.auto_increment:
                    nid = session.catalog.alloc_autoid(t.id)
                    full[c.offset] = nid
                    if first_auto_id is None:
                        first_auto_id = int(nid)
                elif c.default is not None and c.default != "CURRENT_TIMESTAMP":
                    full[c.offset] = to_physical(c.default, c.ftype)
                elif c.default == "CURRENT_TIMESTAMP":
                    import datetime

                    full[c.offset] = to_physical(datetime.datetime.now(), c.ftype)
                elif not c.ftype.nullable:
                    raise WriteError(f"Field '{c.name}' doesn't have a default value")
        if t.pk_is_handle:
            pkv = full[t.pk_offset]
            if pkv is None and cols[t.pk_offset].auto_increment:
                pkv = session.catalog.alloc_autoid(t.id)
                full[t.pk_offset] = pkv
                if first_auto_id is None:
                    first_auto_id = int(pkv)
            if pkv is None:
                raise WriteError("primary key cannot be NULL")
            handle = int(pkv)
            if cols[t.pk_offset].auto_increment:
                session.catalog.rebase_autoid(t.id, handle + 1)
        else:
            handle = session.catalog.alloc_autoid(t.id)
        # partitioned tables: route the row to its partition's physical id
        # (ref: table/tables partitionedTable.AddRecord locating the
        # partition before the write)
        wt = t.partition_view(t.partition_id_for(full)) if t.partition is not None else t
        affected += _write_row(session, wt, full, handle, on_dup)
    # OK-packet id is statement-local (0 when nothing was generated);
    # LAST_INSERT_ID() stays sticky across non-generating statements
    # (ref: session vars LastInsertID vs mysql_insert_id())
    session._stmt_insert_id = first_auto_id or 0
    if first_auto_id is not None:
        session.last_insert_id = first_auto_id
    return affected


def _apply_on_dup_update(session, t: TableInfo, old_vals: list, handle: int, cand_vals: list, on_dup: tuple) -> int:
    """ON DUPLICATE KEY UPDATE against the conflicting row (ref:
    executor/insert.go onDuplicateUpdate): assignments see the existing row;
    VALUES(col) reads the would-be inserted value. Affected rows follow
    MySQL: 2 when the row changes, 0 when it is set to its current values."""
    _, assignments, db, alias = on_dup
    from tidb_tpu.planner.pointget import _to_logical

    def subst_values(node):
        # VALUES(col) → literal of the candidate row's value
        if isinstance(node, ast.FuncCall) and node.name == "values" and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.ColumnName):
                c = t.column(arg.name)
                if c is None:
                    raise WriteError(f"Unknown column '{arg.name}' in VALUES()")
                return ast.Literal(_to_logical(cand_vals[c.offset], c.ftype))
        import dataclasses

        if dataclasses.is_dataclass(node) and isinstance(node, ast.Node):
            return type(node)(
                **{
                    f.name: (
                        subst_values(v)
                        if isinstance(v := getattr(node, f.name), ast.Node)
                        else ([subst_values(x) if isinstance(x, ast.Node) else x for x in v] if isinstance(v, list) else v)
                    )
                    for f in dataclasses.fields(node)
                }
            )
        return node

    chunk = _rows_to_chunk(session, t, [old_vals])
    builder = Builder(session.catalog, db, subquery_runner=session._subquery_runner, warn=session.append_warning)
    schema = [OutCol(c.name, c.ftype, table=alias, slot=c.offset) for c in t.columns]
    batch = EvalBatch.from_chunk(chunk, warn=_warn_of(session))
    new_vals = list(old_vals)
    for colname, expr_ast in assignments:
        cname = colname if isinstance(colname, str) else colname.name
        c = t.column(cname)
        if c is None:
            raise WriteError(f"Unknown column '{cname}'")
        e = builder.resolve(subst_values(expr_ast), BuildCtx(schema))
        out = eval_to_column(e, batch, np)
        new_vals[c.offset] = to_physical(
            out.logical_value(0), c.ftype, warn=_warn_of(session), strict=_strict(session), col=c.name
        )
    if new_vals == old_vals:
        return 0
    new_handle = handle
    if t.pk_is_handle and new_vals[t.pk_offset] != old_vals[t.pk_offset]:
        new_handle = int(new_vals[t.pk_offset])
    _delete_row(session, t, old_vals, handle, fk_depth=None)
    _write_row(session, t, new_vals, new_handle)
    _fk_on_parent_update(session, t, old_vals, new_vals)
    return 2


def _scan_visible_rows(session, t: TableInfo):
    """All rows visible to the txn (membuffer overlaid) → (handles, rows,
    row_tables). The base snapshot follows session.read_ts() so FOR UPDATE
    current reads apply inside dirty transactions too. ``row_tables[i]`` is
    the physical table (partition view) each row lives in."""
    txn = session.txn()
    schema = RowSchema(t.storage_schema)
    handles, rows, row_tables = [], [], []
    for view in t.partition_views():
        for k, v in txn.scan(tablecodec.record_range(view.id), read_ts=session.read_ts()):
            handles.append(tablecodec.decode_record_key(k)[1])
            rows.append(decode_row(schema, v))
            row_tables.append(view)
    return handles, rows, row_tables


def _rows_to_chunk(session, t: TableInfo, rows: list[list]) -> Chunk:
    from tidb_tpu.copr.colcache import cache_for

    cache = cache_for(session.store)
    cols = []
    n = len(rows)
    for c in t.columns:
        k = c.ftype.kind
        if k == TypeKind.STRING:
            dic = cache.dictionary(t.id, c.offset)
            data = np.zeros(n, np.int32)
            valid = np.ones(n, bool)
            for i, r in enumerate(rows):
                if r[c.offset] is None:
                    valid[i] = False
                else:
                    data[i] = dic.encode(r[c.offset])
            cols.append(Column(data, valid, c.ftype, dic))
        else:
            dt = np.float64 if k == TypeKind.FLOAT else np.int64
            data = np.zeros(n, dt)
            valid = np.ones(n, bool)
            for i, r in enumerate(rows):
                if r[c.offset] is None:
                    valid[i] = False
                else:
                    data[i] = r[c.offset]
            cols.append(Column(data, valid, c.ftype, None))
    return Chunk(cols)


def _where_mask(session, t: TableInfo, chunk: Chunk, where, db: str, alias: str) -> np.ndarray:
    if where is None:
        return np.ones(len(chunk), dtype=bool)
    builder = Builder(session.catalog, db, subquery_runner=session._subquery_runner, warn=session.append_warning)
    schema = [OutCol(c.name, c.ftype, table=alias, slot=c.offset) for c in t.columns]
    cond = builder.resolve(where, BuildCtx(schema))
    col = eval_to_column(cond, EvalBatch.from_chunk(chunk, warn=_warn_of(session)), np)
    return (col.data != 0) & col.validity


def _pessimistic_current_read(session, t: TableInfo, handles, rows, chunk, idxs, where, db, alias, row_tables=None):
    """Lock the matched rows, then re-read them at for_update_ts and re-apply
    the WHERE filter — the "current read" that makes pessimistic UPDATE/DELETE
    see the latest committed values instead of the start_ts snapshot
    (ref: sessiontxn/isolation pessimistic provider's for-update read).
    Returns (idxs, rows, chunk), possibly updated in place."""
    txn = session._txn
    if not (session._explicit and txn is not None and txn.pessimistic) or len(idxs) == 0:
        return idxs, rows, chunk
    def _tid(i) -> int:
        return row_tables[int(i)].id if row_tables is not None else t.id

    keys = [tablecodec.record_key(_tid(i), handles[int(i)]) for i in idxs]
    session.lock_for_write(keys)
    snap = session.store.get_snapshot(txn.for_update_ts)
    schema = RowSchema(t.storage_schema)
    changed = False
    live = []
    for i in idxs:
        rk = tablecodec.record_key(_tid(i), handles[int(i)])
        if txn.membuf.contains(rk):
            raw = txn.membuf.get(rk)
        else:
            raw = snap.get(rk)
        if raw is None:  # deleted underneath us after the lock
            changed = True
            continue
        fresh = decode_row(schema, raw)
        if fresh != rows[int(i)]:
            rows[int(i)] = fresh
            changed = True
        live.append(i)
    idxs = np.asarray(live, dtype=np.int64)
    if changed:
        chunk = _rows_to_chunk(session, t, rows)
        mask = _where_mask(session, t, chunk, where, db, alias)
        idxs = np.asarray([i for i in idxs if mask[int(i)]], dtype=np.int64)
    return idxs, rows, chunk


def execute_update(session, stmt: ast.Update) -> int:
    db = stmt.table.db or session.current_db
    t = session.catalog.table(db, stmt.table.name)
    alias = stmt.table.alias or stmt.table.name
    handles, rows, row_tables = _scan_visible_rows(session, t)
    if not rows:
        return 0
    chunk = _rows_to_chunk(session, t, rows)
    mask = _where_mask(session, t, chunk, stmt.where, db, alias)
    idxs = np.nonzero(mask)[0]
    if stmt.order_by:
        from tidb_tpu.copr.host_engine import sort_perm

        builder = Builder(session.catalog, db, subquery_runner=session._subquery_runner, warn=session.append_warning)
        schema = [OutCol(c.name, c.ftype, table=alias, slot=c.offset) for c in t.columns]
        by = [[builder.resolve(oi.expr, BuildCtx(schema)).to_pb(), oi.desc] for oi in stmt.order_by]
        sub = chunk.take(idxs)
        idxs = idxs[sort_perm(sub, by)]
    if stmt.limit is not None:
        idxs = idxs[: stmt.limit]
    idxs, rows, chunk = _pessimistic_current_read(
        session, t, handles, rows, chunk, idxs, stmt.where, db, alias, row_tables
    )

    # evaluate assignment expressions over the full chunk (row values)
    builder = Builder(session.catalog, db, subquery_runner=session._subquery_runner, warn=session.append_warning)
    schema = [OutCol(c.name, c.ftype, table=alias, slot=c.offset) for c in t.columns]
    batch = EvalBatch.from_chunk(chunk, warn=_warn_of(session))
    new_cols = {}
    for colname, expr_ast in stmt.assignments:
        c = t.column(colname.name)
        if c is None:
            raise WriteError(f"Unknown column '{colname.name}'")
        e = builder.resolve(expr_ast, BuildCtx(schema))
        out = eval_to_column(e, batch, np)
        new_cols[c.offset] = out

    affected = 0
    rowschema = RowSchema(t.storage_schema)
    for i in idxs:
        old_vals = rows[i]
        new_vals = list(old_vals)
        for off, out in new_cols.items():
            lv = out.logical_value(int(i))
            new_vals[off] = to_physical(
                lv, t.columns[off].ftype, warn=_warn_of(session), strict=_strict(session), col=t.columns[off].name
            )
        if new_vals == old_vals:
            continue
        handle = handles[i]
        new_handle = handle
        if t.pk_is_handle and new_vals[t.pk_offset] != old_vals[t.pk_offset]:
            new_handle = int(new_vals[t.pk_offset])
        old_t = row_tables[i]
        new_t = t.partition_view(t.partition_id_for(new_vals)) if t.partition is not None else t
        _delete_row(session, old_t, old_vals, handle, fk_depth=None)
        _write_row(session, new_t, new_vals, new_handle)
        _fk_on_parent_update(session, t, old_vals, new_vals)
        affected += 1
    return affected


def execute_delete(session, stmt: ast.Delete) -> int:
    db = stmt.table.db or session.current_db
    t = session.catalog.table(db, stmt.table.name)
    alias = stmt.table.alias or stmt.table.name
    handles, rows, row_tables = _scan_visible_rows(session, t)
    if not rows:
        return 0
    chunk = _rows_to_chunk(session, t, rows)
    mask = _where_mask(session, t, chunk, stmt.where, db, alias)
    idxs = np.nonzero(mask)[0]
    if stmt.order_by:
        from tidb_tpu.copr.host_engine import sort_perm

        builder = Builder(session.catalog, db, subquery_runner=session._subquery_runner, warn=session.append_warning)
        schema = [OutCol(c.name, c.ftype, table=alias, slot=c.offset) for c in t.columns]
        by = [[builder.resolve(oi.expr, BuildCtx(schema)).to_pb(), oi.desc] for oi in stmt.order_by]
        sub = chunk.take(idxs)
        idxs = idxs[sort_perm(sub, by)]
    if stmt.limit is not None:
        idxs = idxs[: stmt.limit]
    idxs, rows, chunk = _pessimistic_current_read(
        session, t, handles, rows, chunk, idxs, stmt.where, db, alias, row_tables
    )
    for i in idxs:
        _delete_row(session, row_tables[int(i)], rows[int(i)], handles[int(i)])
    return int(len(idxs))
