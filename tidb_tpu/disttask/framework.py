"""Distributed task framework.

Reference parity (pkg/disttask/framework):
- task / subtask state machines persisted in system tables
  (mysql.tidb_global_task, mysql.tidb_background_subtask — framework/storage)
  so SQL can inspect them and pending work resumes after interruption;
- a Scheduler that asks the task type's SchedulerExt to plan subtasks per
  step and advances the task when all subtasks of a step finish
  (scheduler/scheduler.go:61);
- TaskExecutor worker threads ("nodes") claiming pending subtasks and
  running the registered StepExecutor (taskexecutor/interface.go:70);
- cancellation propagates to running subtasks; failed subtasks fail the
  task and remaining subtasks are cancelled (proto/task.go transitions).
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional


class TaskState:
    PENDING = "pending"
    RUNNING = "running"
    SUCCEED = "succeed"
    FAILED = "failed"
    CANCELLING = "cancelling"
    CANCELLED = "cancelled"


class SubtaskState:
    PENDING = "pending"
    RUNNING = "running"
    SUCCEED = "succeed"
    FAILED = "failed"
    CANCELED = "canceled"


@dataclass
class Subtask:
    id: int
    task_id: int
    step: int
    state: str
    exec_id: str
    meta: dict
    summary: dict
    lease: int = 0  # epoch ms the claim expires; 0 = unclaimed


@dataclass
class Task:
    id: int
    type: str
    state: str
    step: int
    concurrency: int
    meta: dict
    error: str = ""


class SchedulerExt:
    """Per-task-type planning hooks (ref: scheduler.Extension)."""

    #: step numbers, in order; the task succeeds after the last one
    steps: list[int] = [1]

    def plan_subtasks(self, task: Task, step: int, manager: "DistTaskManager") -> list[dict]:
        """→ subtask metas for this step. Metas must be self-contained JSON:
        they travel through the shared system tables to OTHER processes'
        executor nodes (ref: subtask meta bytes crossing nodes)."""
        raise NotImplementedError

    def on_done(self, task: Task, manager: "DistTaskManager") -> None:
        """Called once when the task reaches succeed."""


class StepExecutor:
    """Runs one subtask (ref: execute.StepExecutor.RunSubtask)."""

    def run_subtask(self, task: Task, subtask: Subtask, manager: "DistTaskManager") -> dict:
        """→ summary dict persisted on the subtask."""
        raise NotImplementedError


_REGISTRY: dict[str, tuple[SchedulerExt, StepExecutor]] = {}


def register_task_type(name: str, ext: SchedulerExt, executor: StepExecutor) -> None:
    # registration happens at setup time, before any scheduler thread runs
    _REGISTRY[name] = (ext, executor)  # graftcheck: off=shared-mutation


class DistTaskManager:
    """Owner-side scheduler + executor pool in one process (the reference
    splits these across nodes; the contracts are the same)."""

    def __init__(self, db, n_workers: int = 4, node_prefix: str = "exec", lease_ms: int = 10_000):
        self.db = db
        self.n_workers = n_workers
        self.node_prefix = node_prefix
        self.lease_ms = lease_ms
        self._mu = threading.Lock()
        self._cancel_flags: dict[int, threading.Event] = {}
        self._ensure_tables()

    # -- storage (system tables; ref: framework/storage) --------------------
    def _ensure_tables(self) -> None:
        s = self._session()
        for ddl in (
            "CREATE DATABASE IF NOT EXISTS mysql",
            "CREATE TABLE IF NOT EXISTS mysql.tidb_global_task (id BIGINT PRIMARY KEY, "
            "task_type VARCHAR(64), state VARCHAR(32), step BIGINT, concurrency BIGINT, "
            "meta TEXT, error TEXT)",
            "CREATE TABLE IF NOT EXISTS mysql.tidb_background_subtask (id BIGINT PRIMARY KEY, "
            "task_id BIGINT, step BIGINT, state VARCHAR(32), exec_id VARCHAR(64), "
            "meta TEXT, summary TEXT, lease BIGINT)",
        ):
            # managers in several processes bootstrap concurrently; the
            # catalog's optimistic versioning reloads and asks for a retry
            for attempt in range(5):
                try:
                    s.execute(ddl)
                    break
                except Exception as e:
                    if "retry the statement" not in str(e) or attempt == 4:
                        raise
                    time.sleep(0.05 * (attempt + 1))

    def _session(self):
        s = self.db.session()
        s.user, s.host = "root", "%"
        return s

    def _q(self, sql: str):
        return self._session().query(sql)

    def _x(self, sql: str):
        return self._session().execute(sql)

    @staticmethod
    def _esc(v: str) -> str:
        return v.replace("\\", "\\\\").replace("'", "\\'")

    def _next_id(self, table: str) -> int:
        r = self._q(f"SELECT MAX(id) FROM mysql.{table}")
        return (r[0][0] or 0) + 1

    # -- task API ------------------------------------------------------------
    def submit_task(self, task_type: str, meta: dict, concurrency: int = 4) -> int:
        if task_type not in _REGISTRY:
            raise ValueError(f"unknown task type {task_type!r}")
        with self._mu:
            tid = self._next_id("tidb_global_task")
            self._x(
                "INSERT INTO mysql.tidb_global_task VALUES "
                f"({tid}, '{task_type}', '{TaskState.PENDING}', 0, {concurrency}, "
                f"'{self._esc(json.dumps(meta))}', '')"
            )
        return tid

    def get_task(self, task_id: int) -> Optional[Task]:
        r = self._q(f"SELECT * FROM mysql.tidb_global_task WHERE id = {task_id}")
        if not r:
            return None
        tid, tp, state, step, conc, meta, err = r[0]
        return Task(tid, tp, state, step, conc, json.loads(meta), err or "")

    def subtasks(self, task_id: int, step: Optional[int] = None) -> list[Subtask]:
        cond = f"task_id = {task_id}" + (f" AND step = {step}" if step is not None else "")
        out = []
        for sid, tid, st, state, ex, meta, summary, lease in self._q(
            f"SELECT * FROM mysql.tidb_background_subtask WHERE {cond} ORDER BY id"
        ):
            out.append(
                Subtask(sid, tid, st, state, ex, json.loads(meta), json.loads(summary or "{}"), lease or 0)
            )
        return out

    def cancel_task(self, task_id: int) -> None:
        self._set_task_state(task_id, TaskState.CANCELLING)
        with self._mu:
            ev = self._cancel_flags.get(task_id)
        if ev is not None:
            ev.set()

    def is_cancelling(self, task_id: int) -> bool:
        with self._mu:
            ev = self._cancel_flags.get(task_id)
        return ev is not None and ev.is_set()

    def _set_task_state(self, task_id: int, state: str, error: str = "") -> None:
        self._x(
            f"UPDATE mysql.tidb_global_task SET state = '{state}', error = '{self._esc(error)}' "
            f"WHERE id = {task_id}"
        )

    def _set_subtask(self, sid: int, state: str, summary: Optional[dict] = None) -> None:
        extra = f", summary = '{self._esc(json.dumps(summary))}'" if summary is not None else ""
        self._x(
            f"UPDATE mysql.tidb_background_subtask SET state = '{state}'{extra} WHERE id = {sid}"
        )

    # -- scheduler + executor (ref: scheduleLoop + taskExecutor pool) --------
    def run_task(self, task_id: int) -> Task:
        """Drive one task to a terminal state (synchronous scheduler loop;
        the caller is the 'owner node')."""
        task = self.get_task(task_id)
        if task is None:
            raise ValueError(f"unknown task {task_id}")
        ext, _ = _REGISTRY[task.type]
        cancel = threading.Event()
        with self._mu:
            self._cancel_flags[task_id] = cancel
        try:
            self._set_task_state(task_id, TaskState.RUNNING)
            for step in ext.steps:
                task = self.get_task(task_id)
                existing = self.subtasks(task_id, step)
                if not existing:
                    metas = ext.plan_subtasks(task, step, self)
                    with self._mu:
                        base = self._next_id("tidb_background_subtask")
                        for i, m in enumerate(metas):
                            self._x(
                                "INSERT INTO mysql.tidb_background_subtask VALUES "
                                f"({base + i}, {task_id}, {step}, '{SubtaskState.PENDING}', '', "
                                f"'{self._esc(json.dumps(m))}', '{{}}', 0)"
                            )
                self._x(
                    f"UPDATE mysql.tidb_global_task SET step = {step} WHERE id = {task_id}"
                )
                ok, err = self._run_step(task_id, step, cancel)
                if not ok:
                    if err and err != "cancelled":
                        self._set_task_state(task_id, TaskState.FAILED, err)
                    else:
                        self._set_task_state(task_id, TaskState.CANCELLED, "cancelled by user")
                    return self.get_task(task_id)
            task = self.get_task(task_id)
            ext.on_done(task, self)
            self._set_task_state(task_id, TaskState.SUCCEED)
            return self.get_task(task_id)
        finally:
            with self._mu:
                self._cancel_flags.pop(task_id, None)

    # -- cross-process subtask claiming (ref: taskexecutor manager claiming
    # subtasks from shared storage; scheduler balanceSubtasks re-queueing
    # subtasks whose node died) ---------------------------------------------
    def claim_subtask(self, exec_id: str, lease_ms: int = 10_000, task_id: Optional[int] = None):
        """Atomically claim one pending subtask of a running task. The claim
        is an optimistic conditional UPDATE — two nodes racing the same row
        hit a write conflict and one loses cleanly. Returns (Task, Subtask)
        or None."""
        cond = f"AND t.id = {task_id}" if task_id is not None else ""
        # only claim task types REGISTERED in this process — a node must not
        # take work it cannot execute (ref: executors advertising task types)
        known = ", ".join(f"'{self._esc(k)}'" for k in _REGISTRY) or "''"
        rows = self._q(
            "SELECT s.id, s.task_id FROM mysql.tidb_background_subtask s, "
            "mysql.tidb_global_task t WHERE s.task_id = t.id AND "
            f"t.state = '{TaskState.RUNNING}' AND s.state = '{SubtaskState.PENDING}' "
            f"AND t.task_type IN ({known}) {cond} "
            "ORDER BY s.id LIMIT 4"
        )
        now_ms = int(time.time() * 1000)
        for sid, tid in rows:
            try:
                res = self._x(
                    f"UPDATE mysql.tidb_background_subtask SET state = '{SubtaskState.RUNNING}', "
                    f"exec_id = '{self._esc(exec_id)}', lease = {now_ms + lease_ms} "
                    f"WHERE id = {sid} AND state = '{SubtaskState.PENDING}'"
                )
            # write conflict: another node won the claim — the protocol, not
            # a failure (optimistic claim via conditional UPDATE)
            except Exception:  # graftcheck: off=except-swallow
                continue
            if getattr(res, "affected", 0) != 1:
                continue
            task = self.get_task(tid)
            st = next(s for s in self.subtasks(tid) if s.id == sid)
            return task, st
        return None

    def run_claimed(self, task: Task, st: Subtask) -> None:
        """Execute a claimed subtask and persist its terminal state.

        While the subtask runs, a heartbeat thread RENEWS the claim lease —
        a slow-but-alive node must not lose its claim to the scheduler's
        expiry sweep (ref: subtask heartbeat/balance). The terminal write is
        FENCED on still owning the claim: if the lease was lost anyway and
        the subtask re-queued, the stale worker's state write is discarded.
        Data side effects survive the fence, so executors must be idempotent
        under re-runs — the import executor writes deterministic handle
        ranges reserved at plan time (see tools/importer plan_subtasks)."""
        reg = _REGISTRY.get(task.type)
        if reg is None:  # claim filter should prevent this; never kill the node loop
            self._fenced_set(st, SubtaskState.FAILED, {"error": f"task type {task.type!r} not registered"})
            return
        _, executor = reg
        hb_stop = threading.Event()

        def heartbeat():
            while not hb_stop.wait(self.lease_ms / 3000.0):
                try:
                    self._x(
                        f"UPDATE mysql.tidb_background_subtask SET lease = "
                        f"{int(time.time() * 1000) + self.lease_ms} WHERE id = {st.id} "
                        f"AND state = '{SubtaskState.RUNNING}' AND exec_id = '{self._esc(st.exec_id)}'"
                    )
                # store briefly unreachable; the next beat retries the lease
                except Exception:  # graftcheck: off=except-swallow
                    pass

        hb = threading.Thread(target=heartbeat, daemon=True, name=f"disttask-hb-{st.id}")
        hb.start()
        try:
            summary = executor.run_subtask(task, st, self)
            self._fenced_set(st, SubtaskState.SUCCEED, summary or {})
        except Exception as e:
            self._fenced_set(st, SubtaskState.FAILED, {"error": str(e)})
        finally:
            hb_stop.set()
            hb.join()

    def _fenced_set(self, st: Subtask, state: str, summary: dict) -> bool:
        """Terminal subtask write, conditional on the claim still being
        ours — a re-queued claim makes the stale execution a no-op."""
        try:
            res = self._x(
                f"UPDATE mysql.tidb_background_subtask SET state = '{state}', "
                f"summary = '{self._esc(json.dumps(summary))}' WHERE id = {st.id} "
                f"AND state = '{SubtaskState.RUNNING}' AND exec_id = '{self._esc(st.exec_id)}'"
            )
            return getattr(res, "affected", 0) == 1
        except Exception:
            return False

    def _requeue_expired(self, task_id: int, step: int) -> int:
        """Running subtasks whose claim lease expired (node died mid-run)
        go back to pending for another node to pick up."""
        now_ms = int(time.time() * 1000)
        n = 0
        for st in self.subtasks(task_id, step):
            if st.state == SubtaskState.RUNNING and 0 < st.lease < now_ms:
                try:
                    res = self._x(
                        f"UPDATE mysql.tidb_background_subtask SET state = '{SubtaskState.PENDING}', "
                        f"exec_id = '', lease = 0 WHERE id = {st.id} AND state = '{SubtaskState.RUNNING}' "
                        f"AND lease = {st.lease}"
                    )
                    n += getattr(res, "affected", 0)
                # reclaim is best-effort: a missed subtask is retried by the
                # next expiry sweep (lease still expired)
                except Exception:  # graftcheck: off=except-swallow
                    pass
        return n

    def _run_step(self, task_id: int, step: int, cancel: threading.Event) -> tuple[bool, str]:
        """Drive one step to completion. Local worker threads AND executor
        nodes in other processes (TaskExecutorNode over the same store)
        claim subtasks from the shared tables; the owner loop re-queues
        expired claims and waits until every subtask is terminal."""
        task = self.get_task(task_id)
        stop_workers = threading.Event()

        def worker(node_id: int):
            from tidb_tpu.utils import failpoint

            exec_id = f"{self.node_prefix}-{node_id}"
            failpoint.inject("disttask_local_worker_start", exec_id)
            idle = 0
            while not cancel.is_set() and not stop_workers.is_set():
                got = self.claim_subtask(exec_id, lease_ms=self.lease_ms, task_id=task_id)
                if got is None:
                    idle += 1
                    if idle > 2:
                        return  # no pending work left for this step
                    time.sleep(0.05)
                    continue
                idle = 0
                self.run_claimed(*got)

        n = min(max(task.concurrency, 1), self.n_workers)
        threads = [
            threading.Thread(target=worker, args=(i,), daemon=True, name=f"disttask-w{i}")
            for i in range(n)
        ]
        for t in threads:
            t.start()
        err = ""
        while True:
            sts = self.subtasks(task_id, step)
            failed = [s for s in sts if s.state == SubtaskState.FAILED]
            if failed:
                err = failed[0].summary.get("error", "subtask failed")
                cancel.set()
                break
            if cancel.is_set():
                break
            if all(s.state == SubtaskState.SUCCEED for s in sts):
                break
            # a remote node may have died mid-claim: expired leases re-queue,
            # and idle local workers restart to pick them up
            if self._requeue_expired(task_id, step) and all(not t.is_alive() for t in threads):
                threads = [
                    threading.Thread(target=worker, args=(i,), daemon=True, name=f"disttask-w{i}")
                    for i in range(n)
                ]
                for t in threads:
                    t.start()
            time.sleep(0.05)
        stop_workers.set()
        for t in threads:
            t.join()
        if err or cancel.is_set():
            for st in self.subtasks(task_id, step):
                if st.state == SubtaskState.PENDING:
                    self._set_subtask(st.id, SubtaskState.CANCELED)
            return False, err or "cancelled"
        return True, ""

    def start_executor_node(self, node_id: str, poll_s: float = 0.1) -> "TaskExecutorNode":
        node = TaskExecutorNode(self, node_id, poll_s=poll_s)
        node.start()
        return node

    def resume_pending(self) -> list[int]:
        """Re-drive tasks left non-terminal (crash recovery — ref: disttask
        resuming from system-table state after restart)."""
        out = []
        for (tid,) in self._q(
            "SELECT id FROM mysql.tidb_global_task WHERE state = 'pending' OR state = 'running'"
        ):
            self.run_task(tid)
            out.append(tid)
        return out


class TaskExecutorNode:
    """A subtask-executing node — typically running in ANOTHER process
    attached to the same store (the storage-server process, a worker pod)
    (ref: taskexecutor.Manager, taskexecutor/manager.go — nodes poll shared
    storage for claimable subtasks; no dispatch RPC exists, the tables ARE
    the dispatch)."""

    def __init__(self, manager: DistTaskManager, node_id: str, poll_s: float = 0.1):
        self.manager = manager
        self.node_id = node_id
        self.poll_s = poll_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True, name=f"disttask-{node_id}")
        self.executed = 0

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                got = self.manager.claim_subtask(self.node_id, lease_ms=self.manager.lease_ms)
            except Exception:
                got = None  # store briefly unreachable: keep polling
            if got is None:
                time.sleep(self.poll_s)
                continue
            self.manager.run_claimed(*got)
            self.executed += 1
