"""Distributed task framework.

Reference parity (pkg/disttask/framework):
- task / subtask state machines persisted in system tables
  (mysql.tidb_global_task, mysql.tidb_background_subtask — framework/storage)
  so SQL can inspect them and pending work resumes after interruption;
- a Scheduler that asks the task type's SchedulerExt to plan subtasks per
  step and advances the task when all subtasks of a step finish
  (scheduler/scheduler.go:61);
- TaskExecutor worker threads ("nodes") claiming pending subtasks and
  running the registered StepExecutor (taskexecutor/interface.go:70);
- cancellation propagates to running subtasks; failed subtasks fail the
  task and remaining subtasks are cancelled (proto/task.go transitions).
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional


class TaskState:
    PENDING = "pending"
    RUNNING = "running"
    SUCCEED = "succeed"
    FAILED = "failed"
    CANCELLING = "cancelling"
    CANCELLED = "cancelled"


class SubtaskState:
    PENDING = "pending"
    RUNNING = "running"
    SUCCEED = "succeed"
    FAILED = "failed"
    CANCELED = "canceled"


@dataclass
class Subtask:
    id: int
    task_id: int
    step: int
    state: str
    exec_id: str
    meta: dict
    summary: dict


@dataclass
class Task:
    id: int
    type: str
    state: str
    step: int
    concurrency: int
    meta: dict
    error: str = ""


class SchedulerExt:
    """Per-task-type planning hooks (ref: scheduler.Extension)."""

    #: step numbers, in order; the task succeeds after the last one
    steps: list[int] = [1]

    def plan_subtasks(self, task: Task, step: int) -> list[dict]:
        """→ subtask metas for this step."""
        raise NotImplementedError

    def on_done(self, task: Task, manager: "DistTaskManager") -> None:
        """Called once when the task reaches succeed."""


class StepExecutor:
    """Runs one subtask (ref: execute.StepExecutor.RunSubtask)."""

    def run_subtask(self, task: Task, subtask: Subtask, manager: "DistTaskManager") -> dict:
        """→ summary dict persisted on the subtask."""
        raise NotImplementedError


_REGISTRY: dict[str, tuple[SchedulerExt, StepExecutor]] = {}


def register_task_type(name: str, ext: SchedulerExt, executor: StepExecutor) -> None:
    _REGISTRY[name] = (ext, executor)


class DistTaskManager:
    """Owner-side scheduler + executor pool in one process (the reference
    splits these across nodes; the contracts are the same)."""

    def __init__(self, db, n_workers: int = 4, node_prefix: str = "exec"):
        self.db = db
        self.n_workers = n_workers
        self.node_prefix = node_prefix
        self._mu = threading.Lock()
        self._cancel_flags: dict[int, threading.Event] = {}
        self._ensure_tables()

    # -- storage (system tables; ref: framework/storage) --------------------
    def _ensure_tables(self) -> None:
        s = self._session()
        s.execute("CREATE DATABASE IF NOT EXISTS mysql")
        s.execute(
            "CREATE TABLE IF NOT EXISTS mysql.tidb_global_task (id BIGINT PRIMARY KEY, "
            "task_type VARCHAR(64), state VARCHAR(32), step BIGINT, concurrency BIGINT, "
            "meta TEXT, error TEXT)"
        )
        s.execute(
            "CREATE TABLE IF NOT EXISTS mysql.tidb_background_subtask (id BIGINT PRIMARY KEY, "
            "task_id BIGINT, step BIGINT, state VARCHAR(32), exec_id VARCHAR(64), "
            "meta TEXT, summary TEXT)"
        )

    def _session(self):
        s = self.db.session()
        s.user, s.host = "root", "%"
        return s

    def _q(self, sql: str):
        return self._session().query(sql)

    def _x(self, sql: str):
        return self._session().execute(sql)

    @staticmethod
    def _esc(v: str) -> str:
        return v.replace("\\", "\\\\").replace("'", "\\'")

    def _next_id(self, table: str) -> int:
        r = self._q(f"SELECT MAX(id) FROM mysql.{table}")
        return (r[0][0] or 0) + 1

    # -- task API ------------------------------------------------------------
    def submit_task(self, task_type: str, meta: dict, concurrency: int = 4) -> int:
        if task_type not in _REGISTRY:
            raise ValueError(f"unknown task type {task_type!r}")
        with self._mu:
            tid = self._next_id("tidb_global_task")
            self._x(
                "INSERT INTO mysql.tidb_global_task VALUES "
                f"({tid}, '{task_type}', '{TaskState.PENDING}', 0, {concurrency}, "
                f"'{self._esc(json.dumps(meta))}', '')"
            )
        return tid

    def get_task(self, task_id: int) -> Optional[Task]:
        r = self._q(f"SELECT * FROM mysql.tidb_global_task WHERE id = {task_id}")
        if not r:
            return None
        tid, tp, state, step, conc, meta, err = r[0]
        return Task(tid, tp, state, step, conc, json.loads(meta), err or "")

    def subtasks(self, task_id: int, step: Optional[int] = None) -> list[Subtask]:
        cond = f"task_id = {task_id}" + (f" AND step = {step}" if step is not None else "")
        out = []
        for sid, tid, st, state, ex, meta, summary in self._q(
            f"SELECT * FROM mysql.tidb_background_subtask WHERE {cond} ORDER BY id"
        ):
            out.append(Subtask(sid, tid, st, state, ex, json.loads(meta), json.loads(summary or "{}")))
        return out

    def cancel_task(self, task_id: int) -> None:
        self._set_task_state(task_id, TaskState.CANCELLING)
        with self._mu:
            ev = self._cancel_flags.get(task_id)
        if ev is not None:
            ev.set()

    def is_cancelling(self, task_id: int) -> bool:
        with self._mu:
            ev = self._cancel_flags.get(task_id)
        return ev is not None and ev.is_set()

    def _set_task_state(self, task_id: int, state: str, error: str = "") -> None:
        self._x(
            f"UPDATE mysql.tidb_global_task SET state = '{state}', error = '{self._esc(error)}' "
            f"WHERE id = {task_id}"
        )

    def _set_subtask(self, sid: int, state: str, summary: Optional[dict] = None) -> None:
        extra = f", summary = '{self._esc(json.dumps(summary))}'" if summary is not None else ""
        self._x(
            f"UPDATE mysql.tidb_background_subtask SET state = '{state}'{extra} WHERE id = {sid}"
        )

    # -- scheduler + executor (ref: scheduleLoop + taskExecutor pool) --------
    def run_task(self, task_id: int) -> Task:
        """Drive one task to a terminal state (synchronous scheduler loop;
        the caller is the 'owner node')."""
        task = self.get_task(task_id)
        if task is None:
            raise ValueError(f"unknown task {task_id}")
        ext, _ = _REGISTRY[task.type]
        cancel = threading.Event()
        with self._mu:
            self._cancel_flags[task_id] = cancel
        try:
            self._set_task_state(task_id, TaskState.RUNNING)
            for step in ext.steps:
                task = self.get_task(task_id)
                existing = self.subtasks(task_id, step)
                if not existing:
                    metas = ext.plan_subtasks(task, step)
                    with self._mu:
                        base = self._next_id("tidb_background_subtask")
                        for i, m in enumerate(metas):
                            self._x(
                                "INSERT INTO mysql.tidb_background_subtask VALUES "
                                f"({base + i}, {task_id}, {step}, '{SubtaskState.PENDING}', '', "
                                f"'{self._esc(json.dumps(m))}', '{{}}')"
                            )
                self._x(
                    f"UPDATE mysql.tidb_global_task SET step = {step} WHERE id = {task_id}"
                )
                ok, err = self._run_step(task_id, step, cancel)
                if not ok:
                    if err and err != "cancelled":
                        self._set_task_state(task_id, TaskState.FAILED, err)
                    else:
                        self._set_task_state(task_id, TaskState.CANCELLED, "cancelled by user")
                    return self.get_task(task_id)
            task = self.get_task(task_id)
            ext.on_done(task, self)
            self._set_task_state(task_id, TaskState.SUCCEED)
            return self.get_task(task_id)
        finally:
            with self._mu:
                self._cancel_flags.pop(task_id, None)

    def _run_step(self, task_id: int, step: int, cancel: threading.Event) -> tuple[bool, str]:
        task = self.get_task(task_id)
        _, executor = _REGISTRY[task.type]
        pending = [st for st in self.subtasks(task_id, step) if st.state == SubtaskState.PENDING]
        qlock = threading.Lock()
        errors: list[str] = []

        def worker(node_id: int):
            exec_id = f"{self.node_prefix}-{node_id}"
            while not cancel.is_set():
                with qlock:
                    if not pending:
                        return
                    st = pending.pop(0)
                self._x(
                    f"UPDATE mysql.tidb_background_subtask SET state = '{SubtaskState.RUNNING}', "
                    f"exec_id = '{exec_id}' WHERE id = {st.id}"
                )
                try:
                    summary = executor.run_subtask(task, st, self)
                    self._set_subtask(st.id, SubtaskState.SUCCEED, summary or {})
                except Exception as e:
                    self._set_subtask(st.id, SubtaskState.FAILED, {"error": str(e)})
                    errors.append(str(e))
                    cancel.set()  # fail fast; remaining subtasks cancel
                    return

        n = min(max(task.concurrency, 1), self.n_workers)
        threads = [threading.Thread(target=worker, args=(i,), daemon=True) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            for st in self.subtasks(task_id, step):
                if st.state == SubtaskState.PENDING:
                    self._set_subtask(st.id, SubtaskState.CANCELED)
            return False, errors[0]
        if cancel.is_set():
            for st in self.subtasks(task_id, step):
                if st.state == SubtaskState.PENDING:
                    self._set_subtask(st.id, SubtaskState.CANCELED)
            return False, "cancelled"
        return True, ""

    def resume_pending(self) -> list[int]:
        """Re-drive tasks left non-terminal (crash recovery — ref: disttask
        resuming from system-table state after restart)."""
        out = []
        for (tid,) in self._q(
            "SELECT id FROM mysql.tidb_global_task WHERE state = 'pending' OR state = 'running'"
        ):
            self.run_task(tid)
            out.append(tid)
        return out
