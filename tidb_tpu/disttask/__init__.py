"""Distributed task framework (ref: pkg/disttask/framework — scheduler.go:61
dispatching subtasks, taskexecutor/interface.go:70 running them, proto/task.go
state machine, framework/storage system-table persistence)."""

from tidb_tpu.disttask.framework import (
    DistTaskManager,
    StepExecutor,
    SchedulerExt,
    TaskState,
    SubtaskState,
    register_task_type,
)

__all__ = [
    "DistTaskManager",
    "StepExecutor",
    "SchedulerExt",
    "TaskState",
    "SubtaskState",
    "register_task_type",
]
