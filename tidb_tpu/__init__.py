"""tidb_tpu — a TPU-native distributed SQL framework.

A from-scratch rebuild of the capability surface of TiDB (reference:
/root/reference, MySQL-compatible HTAP SQL layer in Go) designed TPU-first:

- Columnar ``Chunk``/``Column`` batches (Arrow-style, fixed-width + dictionary
  encoded strings) map 1:1 onto device arrays (ref: pkg/util/chunk).
- Pushed-down coprocessor DAG fragments (TableScan/Selection/HashAgg/StreamAgg/
  TopN/Limit — ref: pkg/store/mockstore/unistore/cophandler/closure_exec.go)
  execute as jitted XLA kernels over padded static-shape column batches.
- MPP exchange (Hash/Broadcast/PassThrough — ref: pkg/planner/core/fragment.go,
  unistore cophandler/mpp_exec.go) maps onto ``jax.lax`` collectives
  (all_to_all / all_gather / psum) over a ``jax.sharding.Mesh``.
- A Volcano SQL engine (parser → planner → executor) sits on top, with the
  planner's engine-isolation hook (ref: pkg/planner/core/planbuilder.go
  filterPathByIsolationRead) routing eligible plans to the ``tpu`` engine.

Quick start::

    import tidb_tpu
    db = tidb_tpu.open()            # embedded store, in-process
    db.execute("CREATE TABLE t (a BIGINT, b DOUBLE)")
    db.execute("INSERT INTO t VALUES (1, 2.5), (2, 3.5)")
    rows = db.query("SELECT a, SUM(b) FROM t GROUP BY a")
"""

__version__ = "0.1.0"

__all__ = ["open", "__version__"]


def open(*args, **kwargs):  # noqa: A001  (deliberate: db handle factory)
    from tidb_tpu.session.session import open_db

    return open_db(*args, **kwargs)
