"""Generic timer framework (ref: pkg/timer — the runtime TTL and other
background jobs schedule on): named timers with intervals, driven either by
a daemon thread (production) or explicit tick() (tests)."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional


@dataclass
class Timer:
    name: str
    interval_s: float
    fn: Callable[[], object]
    last_run: float = 0.0
    runs: int = 0
    last_error: Optional[str] = None


class TimerRuntime:
    def __init__(self):
        self._mu = threading.Lock()
        self._timers: dict[str, Timer] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def register(self, name: str, interval_s: float, fn: Callable[[], object]) -> None:
        with self._mu:
            self._timers[name] = Timer(name, interval_s, fn)

    def unregister(self, name: str) -> None:
        with self._mu:
            self._timers.pop(name, None)

    def timers(self) -> list[Timer]:
        with self._mu:
            return list(self._timers.values())

    def tick(self, now: Optional[float] = None, force: bool = False) -> list[str]:
        """Run every timer whose interval elapsed; returns the names run."""
        now = time.monotonic() if now is None else now
        ran = []
        for t in self.timers():
            if force or now - t.last_run >= t.interval_s:
                t.last_run = now
                t.runs += 1
                try:
                    t.fn()
                    t.last_error = None
                except Exception as e:  # background jobs never kill the loop
                    t.last_error = str(e)
                ran.append(t.name)
        return ran

    def start(self, resolution_s: float = 0.5) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(resolution_s):
                self.tick()

        self._thread = threading.Thread(target=loop, daemon=True, name="timer-runtime")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
