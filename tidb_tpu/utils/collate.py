"""Collation weight framework (ref: pkg/util/collate/collate.go — the
Collator/WeightString surface; general_ci weights per
pkg/util/collate/general_ci.go).

utf8mb4_general_ci assigns every codepoint a single weight: the uppercase of
its base letter — accents strip ('é' ≡ 'E'), case folds ('a' ≡ 'A'), and
sharp s maps to 'S' (general_ci is a per-character collation, unlike
unicode_ci's full UCA where 'ß' ≡ 'ss'). Comparing weight strings gives both
equality classes and ordering, so one transform serves =, <, GROUP BY,
ORDER BY, FIELD, and LIKE.

The transform is pure per-codepoint → cached in a translation table; the
device path keeps using dictionary codes, re-ranked through these weights by
the host when a ci comparison forces it.
"""

from __future__ import annotations

import unicodedata
from functools import lru_cache


@lru_cache(maxsize=None)
def _weight_char(ch: str) -> str:
    # decompose, strip combining marks (accent folding), uppercase
    base = "".join(c for c in unicodedata.normalize("NFD", ch) if not unicodedata.combining(c))
    if not base:
        base = ch
    up = base.upper()
    # Python upper() expands ß→SS; general_ci is single-weight per char
    if ch in ("ß", "ẞ"):
        return "S"
    return up[:1] if len(up) > 1 else up


def weight_str(s: str, collation: str = "ci") -> str:
    """Weight string under the collation ('ci' = general_ci semantics;
    anything else is binary identity)."""
    if collation != "ci":
        return s
    return "".join(_weight_char(c) for c in s)


def weight_bytes(b: bytes, collation: str = "ci") -> bytes:
    if collation != "ci":
        return b
    return weight_str(b.decode("utf-8", "surrogateescape")).encode("utf-8", "surrogateescape")


def equal(a: bytes, b: bytes, collation: str = "ci") -> bool:
    return weight_bytes(a, collation) == weight_bytes(b, collation)


def canon_codes(data, validity, dictionary):
    """Map dictionary codes to a per-weight-class representative CODE so
    equality on the result is general_ci equality ('a' ≡ 'A' ≡ 'á').
    Invalid rows may carry garbage codes (computed expressions) — they are
    masked to 0 before decoding and are meaningless afterwards anyway
    (callers carry validity in a separate lane). The shared implementation
    for GROUP BY, DISTINCT, distinct-agg, and partial-merge keys."""
    import numpy as np

    safe = np.where(np.asarray(validity, dtype=bool), data, 0)
    vals = dictionary.decode_many(safe)
    rep: dict[bytes, int] = {}
    out = np.empty(len(vals), dtype=np.int64)
    for i, v in enumerate(vals):
        out[i] = rep.setdefault(weight_bytes(v), int(safe[i]))
    return out


def is_ci_string(col) -> bool:
    """Does this chunk Column need weight-class canonicalization?"""
    from tidb_tpu.types import TypeKind

    return (
        col.ftype.kind == TypeKind.STRING
        and col.ftype.collation == "ci"
        and col.dictionary is not None
    )
