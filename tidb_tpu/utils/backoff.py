"""Typed retry/backoff layer — the Backoffer every distributed seam shares.

Reference parity: tikv/client-go ``internal/retry/backoff.go`` — one
``Backoffer`` per request carries a TOTAL sleep budget; each retriable
condition backs off under a typed config (``BoTiKVRPC``, ``BoRegionMiss``,
``BoTxnLock``, ...) with exponential growth and equal jitter; exhausting the
budget surfaces the LAST error, not a generic timeout. Surfaced in
``pkg/store/copr/coprocessor.go`` (region-error re-splitting) and
``pkg/store/copr/mpp_probe.go`` (store liveness).

Every retry loop in :mod:`tidb_tpu.kv.remote`, :mod:`tidb_tpu.kv.sharded`,
:mod:`tidb_tpu.copr.client`, and :mod:`tidb_tpu.parallel.gather` runs under
a Backoffer from this module — there is deliberately no second retry
mechanism. Tests drive determinism two ways: a seeded RNG makes the jitter
sequence reproducible, and the ``sleep`` hook lets a test capture sleeps
instead of paying them.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional

from tidb_tpu.utils import eventlog as _ev


class BackoffConfig:
    """One retriable condition: exponential growth from ``base_ms`` capped at
    ``cap_ms`` (ref: backoff.go NewConfig — name, base, cap, jitter kind)."""

    __slots__ = ("name", "base_ms", "cap_ms", "jitter")

    def __init__(self, name: str, base_ms: float, cap_ms: float, jitter: str = "equal"):
        if jitter not in ("equal", "full", "none"):
            raise ValueError(f"unknown jitter mode {jitter!r}")
        self.name = name
        self.base_ms = base_ms
        self.cap_ms = cap_ms
        self.jitter = jitter

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"BackoffConfig({self.name}, base={self.base_ms}ms, cap={self.cap_ms}ms)"


# the typed conditions (ref: backoff.go BoTiKVRPC / BoRegionMiss / BoTiKVServerBusy /
# BoTxnLock / BoMaxTsNotSynced). Bases are small: the stores are local
# processes, so the first retry should land within a scheduler quantum.
boRPC = BackoffConfig("rpc", base_ms=10, cap_ms=400)  # wire hiccup / reconnect
boRegionMiss = BackoffConfig("regionMiss", base_ms=2, cap_ms=200)  # stale routing
boStoreDown = BackoffConfig("storeDown", base_ms=50, cap_ms=1000)  # owner loss
boTxnLock = BackoffConfig("txnLock", base_ms=1, cap_ms=100)  # foreign lock alive
boMPP = BackoffConfig("mpp", base_ms=1, cap_ms=50)  # mesh re-plan is local


RETRIABLE = "retriable"
FATAL = "fatal"
AMBIGUOUS = "ambiguous"


def classify(err: BaseException) -> str:
    """Error taxonomy (see RESILIENCE.md):

    - ``retriable`` — transient distributed failure: dropped frames, resets,
      timeouts, stale region routing. Safe to retry under a Backoffer.
    - ``ambiguous`` — the request MAY have executed (commit sent, reply
      lost). Never blind-retried; surfaces as UndeterminedError.
    - ``fatal`` — statement/data verdicts (conflicts, aborts, kills, OOM)
      and programming errors. Retrying would change semantics or never help.
    """
    from tidb_tpu.kv.kv import KVError, RegionError, UndeterminedError

    if isinstance(err, UndeterminedError):
        return AMBIGUOUS
    if isinstance(err, RegionError):
        return RETRIABLE
    if isinstance(err, KVError):
        return FATAL  # conflicts/locks/aborts have their own resolution paths
    try:
        from tidb_tpu.utils.memory import QueryKilledError, QueryOOMError

        if isinstance(err, (QueryKilledError, QueryOOMError)):
            return FATAL
    except ImportError:  # pragma: no cover
        pass
    if isinstance(err, (ConnectionError, TimeoutError, OSError)):
        return RETRIABLE
    if getattr(err, "retriable", False):
        return RETRIABLE
    return FATAL


class BackoffExhausted(Exception):
    """The Backoffer's total budget ran out. Carries the last underlying
    error so callers can surface the CAUSE, not the mechanism (ref:
    backoff.go returning the longest-sleeping config's error)."""

    def __init__(self, config: BackoffConfig, attempts: int, slept_ms: float, last: Optional[BaseException]):
        self.config = config
        self.attempts = attempts
        self.slept_ms = slept_ms
        self.last = last
        super().__init__(
            f"backoff budget exhausted after {attempts} attempts / {slept_ms:.0f}ms slept"
            + (f"; last error: {last}" if last is not None else "")
        )


class Backoffer:
    """Per-request retry budget (ref: backoff.go Backoffer).

    One instance travels with one logical request (a cop fan-out, a 2PC
    round, an MPP gather); every transient failure along the way calls
    :meth:`backoff` with its typed config. Sleeps grow exponentially per
    config, total sleep is capped by ``budget_ms``, and the jitter stream is
    deterministic under a fixed ``seed`` — chaos tests schedule exact fault
    sequences and still assert exact retry behavior.

    Thread-safe: cop worker pools share one Backoffer per request.
    """

    def __init__(
        self,
        budget_ms: float = 5000,
        seed: Optional[int] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.budget_ms = budget_ms
        # RNG construction is LAZY: one Backoffer travels with every cop
        # request, and seeding a Mersenne state per request was measurable
        # on the warm query path — a request that never backs off never pays
        # it. Determinism is unchanged: Random(seed) built at first backoff
        # replays the same jitter stream as one built here.
        self._seed = seed
        self._rng: Optional[random.Random] = None
        self._sleep = sleep
        self._mu = threading.Lock()
        self._attempts: dict[str, int] = {}
        self._slept_ms = 0.0
        self._errors: list[BaseException] = []

    # -- introspection ------------------------------------------------------
    def attempts(self, config: Optional[BackoffConfig] = None) -> int:
        with self._mu:
            if config is None:
                return sum(self._attempts.values())
            return self._attempts.get(config.name, 0)

    @property
    def slept_ms(self) -> float:
        with self._mu:
            return self._slept_ms

    def remaining_ms(self) -> float:
        with self._mu:
            return max(0.0, self.budget_ms - self._slept_ms)

    def errors(self) -> list[BaseException]:
        with self._mu:
            return list(self._errors)

    # -- the verb -----------------------------------------------------------
    def backoff(self, config: BackoffConfig, err: Optional[BaseException] = None) -> float:
        """Sleep once under ``config`` and record the attempt; returns the
        slept milliseconds. Raises :class:`BackoffExhausted` when the sleep
        would cross the budget, and re-raises ``err`` immediately when it
        classifies as fatal/ambiguous (belt-and-braces: a caller should not
        have asked to retry it)."""
        if err is not None and classify(err) != RETRIABLE:
            raise err
        with self._mu:
            if err is not None and len(self._errors) < 16:
                self._errors.append(err)
            if self._rng is None:
                self._rng = random.Random(self._seed)
            n = self._attempts.get(config.name, 0)
            raw = min(config.cap_ms, config.base_ms * (2 ** n))
            if config.jitter == "equal":
                sleep_ms = raw / 2 + self._rng.random() * raw / 2
            elif config.jitter == "full":
                sleep_ms = self._rng.random() * raw
            else:
                sleep_ms = raw
            if self._slept_ms + sleep_ms > self.budget_ms:
                exhausted = BackoffExhausted(
                    config, sum(self._attempts.values()), self._slept_ms, err
                )
                lg = _ev.on(_ev.ERROR)
                if lg is not None:
                    lg.emit(
                        _ev.ERROR,
                        "backoff",
                        "exhausted",
                        config=config.name,
                        attempts=exhausted.attempts,
                        slept_ms=round(exhausted.slept_ms, 2),
                        last=str(err) if err is not None else None,
                    )
                raise exhausted
            self._attempts[config.name] = n + 1
            self._slept_ms += sleep_ms
        from tidb_tpu.utils import metrics as _metrics

        # regionMiss sleeps are the re-route signal (stale placement → refresh
        # → retry) and log at info; everything else is debug-only churn
        lvl = _ev.INFO if config.name == "regionMiss" else _ev.DEBUG
        lg = _ev.on(lvl)
        if lg is not None:
            lg.emit(
                lvl,
                "backoff",
                "region_miss" if config.name == "regionMiss" else "sleep",
                config=config.name,
                attempt=n + 1,
                sleep_ms=round(sleep_ms, 2),
            )
        _metrics.BACKOFF_TOTAL.inc(config=config.name)
        self._sleep(sleep_ms / 1000.0)
        return sleep_ms
