"""Disk-spillable chunk container (ref: util/chunk/row_container.go +
chunk_in_disk.go): executors accumulate result chunks here; when the query's
memory tracker trips its quota, the container's registered spill action
serializes every held chunk to a temp file with the wire codec and frees the
host memory. Readers stream the chunks back transparently.
"""

from __future__ import annotations

import os
import struct
import tempfile
import threading
from typing import Iterator, Optional

from tidb_tpu.utils.chunk import Chunk, decode_chunk, encode_chunk
from tidb_tpu.utils.memory import Tracker, chunk_bytes


class RowContainer:
    def __init__(self, tracker: Optional[Tracker] = None, label: str = "rowcontainer"):
        self.tracker = tracker
        self.label = label
        # spill actions fire from WHATEVER thread trips the shared tracker's
        # quota — all state transitions are serialized on this lock
        self._mu = threading.RLock()
        self._chunks: list[Chunk] = []
        self._mem_bytes = 0
        self._file = None  # spill file (append-mode)
        self._n_disk_chunks = 0
        self.spilled = False
        self._closed = False
        # original per-column dictionary objects: decode creates fresh
        # Dictionary instances, but Column.concat requires identity; codes
        # stay valid because dictionaries are append-only
        self._col_dicts: list = []
        if tracker is not None:
            tracker.register_spill(self.spill)

    def add(self, chunk: Chunk) -> None:
        if not len(chunk):
            return
        with self._mu:
            if self._closed:
                return
            if not self._col_dicts:
                self._col_dicts = [getattr(c, "dictionary", None) for c in chunk.columns]
            if self.spilled:
                self._write(chunk)
                return
            self._chunks.append(chunk)
            n = chunk_bytes(chunk)
            self._mem_bytes += n
        if self.tracker is not None:
            self.tracker.consume(n)  # may fire spill (incl. this container's)

    def spill(self) -> int:
        """Move all in-memory chunks to disk; returns bytes freed."""
        with self._mu:
            if self._closed or (self.spilled and not self._chunks):
                return 0
            if self._file is None:
                fd, path = tempfile.mkstemp(prefix="tidbtpu-spill-")
                os.close(fd)
                self._file = open(path, "w+b")
                os.unlink(path)  # anonymous: space reclaims on close
            for ch in self._chunks:
                self._write(ch)
            self._chunks.clear()
            freed = self._mem_bytes
            self._mem_bytes = 0
            self.spilled = True
        if self.tracker is not None and freed:
            self.tracker.release(freed)
        return freed

    def _write(self, chunk: Chunk) -> None:
        buf = encode_chunk(chunk)
        self._file.write(struct.pack("<Q", len(buf)))
        self._file.write(buf)
        self._n_disk_chunks += 1

    def chunks(self) -> Iterator[Chunk]:
        with self._mu:
            out: list[Chunk] = []
            if self._file is not None:
                self._file.seek(0)
                for _ in range(self._n_disk_chunks):
                    (ln,) = struct.unpack("<Q", self._file.read(8))
                    ch = decode_chunk(self._file.read(ln))
                    for col, dic in zip(ch.columns, self._col_dicts):
                        if dic is not None:
                            col.dictionary = dic
                    out.append(ch)
                self._file.seek(0, 2)  # back to append position
            out.extend(self._chunks)
        yield from out

    def to_chunk(self, schema_cols=None) -> Optional[Chunk]:
        """Concatenate everything (None when empty)."""
        all_chunks = list(self.chunks())
        if not all_chunks:
            return None
        return Chunk.concat(all_chunks) if len(all_chunks) > 1 else all_chunks[0]

    def close(self) -> None:
        with self._mu:
            self._closed = True
            if self._file is not None:
                self._file.close()
                self._file = None
            freed, self._mem_bytes = self._mem_bytes, 0
            self._chunks.clear()
        if self.tracker is not None:
            self.tracker.unregister_spill(self.spill)
            if freed:
                self.tracker.release(freed)
