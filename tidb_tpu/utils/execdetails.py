"""Per-operator runtime execution statistics for EXPLAIN ANALYZE.

ref: pkg/util/execdetails (RuntimeStatsColl attached to each executor; the
reference records loops/rows/time per plan-node id and renders them in the
`execution info` column of EXPLAIN ANALYZE). Here executors materialize one
chunk per execute() call, so stats are inclusive wall time + produced rows,
keyed by plan-node object identity.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class OpStats:
    rows: int = 0
    time_ms: float = 0.0
    loops: int = 0

    def render(self) -> str:
        return f"actRows:{self.rows}, loops:{self.loops}, time:{self.time_ms:.2f}ms"


@dataclass
class RuntimeStatsColl:
    """Collects OpStats keyed by id(plan_node)."""

    stats: dict = field(default_factory=dict)

    def get(self, plan) -> OpStats:
        s = self.stats.get(id(plan))
        if s is None:
            s = self.stats[id(plan)] = OpStats()
        return s

    def record(self, plan, rows: int, dt_ms: float) -> None:
        s = self.get(plan)
        s.rows += rows
        s.time_ms += dt_ms
        s.loops += 1

    def render(self, plan) -> str:
        s = self.stats.get(id(plan))
        return s.render() if s is not None else ""


def instrument(executor, plan, coll: RuntimeStatsColl):
    """Wrap executor.execute to record inclusive wall time + output rows."""
    inner = executor.execute

    def timed():
        t0 = time.perf_counter()
        chunk = inner()
        dt = (time.perf_counter() - t0) * 1000.0
        coll.record(plan, len(chunk) if chunk is not None else 0, dt)
        return chunk

    executor.execute = timed
    return executor
