"""Per-operator runtime execution statistics for EXPLAIN ANALYZE, plus the
distributed exec-details pipeline.

ref: pkg/util/execdetails — RuntimeStatsColl attached to each executor, AND
the ``ExecDetails``/``TimeDetail``/``ScanDetail`` sidecar every coprocessor
response carries back to the caller, rendered as the ``cop_task: {num, max,
avg, ...}`` execution-info line of EXPLAIN ANALYZE. Here:

- :class:`CopExecDetails` is the per-task sidecar (one per cop region task,
  always on): wall split into queue/wire/store-side processing, device vs
  host compute, jit compile, H2D/D2H bytes, device-cache hits, engine used
  with degrade reason, retries + cumulative backoff sleep, re-split count.
  It travels the wire in compact dict form (``to_pb``/``merge_pb``).
- :class:`CopTasksSummary` aggregates sidecars per statement (slow log,
  statements_summary) and per plan node (EXPLAIN ANALYZE render).
- :class:`MPPExecDetails` is the analogous per-gather record.
- The thread-local *collection context* (:func:`collecting`) is how engines
  attribute into the active task's sidecar without plumbing it through
  every call: ``current_cop()`` is one thread-local read, so the whole
  layer is a no-op-cheap guard when nothing is collecting.

Executors here materialize one chunk per execute() call, so OpStats are
inclusive wall time + produced rows, keyed by plan-node object identity.
"""

from __future__ import annotations

import hashlib
import math
import threading
import time
from contextlib import contextmanager, nullcontext
from dataclasses import dataclass, field


@dataclass
class OpStats:
    rows: int = 0
    time_ms: float = 0.0
    loops: int = 0

    def render(self) -> str:
        return f"actRows:{self.rows}, loops:{self.loops}, time:{self.time_ms:.2f}ms"


# -- per-task sidecar --------------------------------------------------------


class CopExecDetails:
    """One cop task's execution details. Plain __slots__, not a dataclass:
    one is allocated on the always-on path of every cop task."""

    __slots__ = (
        "region_id", "store", "queue_ms", "wire_ms", "proc_ms", "device_ms",
        "host_ms", "compile_ms", "h2d_bytes", "d2h_bytes", "dev_cache_hits",
        "dev_cache_misses", "engine", "degraded", "retries", "backoff_ms",
        "resplits", "delta_rows", "merges", "keys_scanned", "bytes_scanned",
    )

    def __init__(self, region_id: int = -1, store: str = ""):
        self.region_id = region_id
        self.store = store  # "" = embedded (local) store
        self.queue_ms = 0.0  # send-queue wait before a worker picked it up
        self.wire_ms = 0.0  # RPC wall minus store-side processing (remote)
        self.proc_ms = 0.0  # store-side processing wall
        self.device_ms = 0.0  # device-path wall (dispatch + transfer back)
        self.host_ms = 0.0  # host-engine wall
        self.compile_ms = 0.0  # first-call jit compile (kernel-cache miss)
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.dev_cache_hits = 0  # device-resident column LRU
        self.dev_cache_misses = 0
        self.engine = ""  # "tpu" | "host" — the engine that answered
        self.degraded = ""  # degrade reason when the task fell off the TPU
        self.retries = 0
        self.backoff_ms = 0.0  # cumulative Backoffer sleep charged to this task
        self.resplits = 0  # region re-splits (epoch changes)
        self.delta_rows = 0  # columnar delta-overlay rows this scan read through
        self.merges = 0  # delta→base merges this task triggered (query-path)
        self.keys_scanned = 0  # store-side MVCC keys this task read (RU input)
        self.bytes_scanned = 0  # store-side bytes those keys carried

    def to_pb(self) -> dict:
        """Compact wire form (zeros omitted — the sidecar rides every cop
        response header)."""
        out: dict = {"p": round(self.proc_ms, 3)}
        if self.engine:
            out["e"] = self.engine
        if self.device_ms:
            out["dv"] = round(self.device_ms, 3)
        if self.host_ms:
            out["h"] = round(self.host_ms, 3)
        if self.compile_ms:
            out["c"] = round(self.compile_ms, 3)
        if self.h2d_bytes:
            out["h2d"] = self.h2d_bytes
        if self.d2h_bytes:
            out["d2h"] = self.d2h_bytes
        if self.dev_cache_hits:
            out["dch"] = self.dev_cache_hits
        if self.dev_cache_misses:
            out["dcm"] = self.dev_cache_misses
        if self.degraded:
            out["dg"] = self.degraded
        if self.retries:
            out["rt"] = self.retries
        if self.backoff_ms:
            out["bo"] = round(self.backoff_ms, 3)
        if self.resplits:
            out["rs"] = self.resplits
        if self.delta_rows:
            out["dlr"] = self.delta_rows
        if self.merges:
            out["mg"] = self.merges
        if self.keys_scanned:
            out["sk"] = self.keys_scanned
        if self.bytes_scanned:
            out["sb"] = self.bytes_scanned
        return out

    def merge_pb(self, pb: dict) -> None:
        """Fold a store-shipped sidecar into this (caller-side) detail —
        additive, so a re-split/degraded task accumulates every attempt."""
        self.proc_ms += float(pb.get("p", 0.0))
        if pb.get("e"):
            self.engine = pb["e"]
        self.device_ms += float(pb.get("dv", 0.0))
        self.host_ms += float(pb.get("h", 0.0))
        self.compile_ms += float(pb.get("c", 0.0))
        self.h2d_bytes += int(pb.get("h2d", 0))
        self.d2h_bytes += int(pb.get("d2h", 0))
        self.dev_cache_hits += int(pb.get("dch", 0))
        self.dev_cache_misses += int(pb.get("dcm", 0))
        if pb.get("dg") and not self.degraded:
            self.degraded = pb["dg"]
        self.retries += int(pb.get("rt", 0))
        self.backoff_ms += float(pb.get("bo", 0.0))
        self.resplits += int(pb.get("rs", 0))
        self.delta_rows += int(pb.get("dlr", 0))
        self.merges += int(pb.get("mg", 0))
        self.keys_scanned += int(pb.get("sk", 0))
        self.bytes_scanned += int(pb.get("sb", 0))


class CopTasksSummary:
    """Aggregate of CopExecDetails across one statement or one plan node —
    renders the TiDB-style ``cop_task: {...}`` execution-info line."""

    __slots__ = (
        "procs", "queue_ms", "wire_ms", "device_ms", "host_ms", "compile_ms",
        "h2d_bytes", "d2h_bytes", "dev_cache_hits", "dev_cache_misses",
        "engines", "degraded", "retries", "backoff_ms", "resplits",
        "delta_rows", "merges", "keys_scanned", "bytes_scanned",
        "max_proc_ms", "max_task_store", "max_task_region",
    )

    def __init__(self):
        self.procs: list[float] = []
        self.queue_ms = 0.0
        self.wire_ms = 0.0
        self.device_ms = 0.0
        self.host_ms = 0.0
        self.compile_ms = 0.0
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.dev_cache_hits = 0
        self.dev_cache_misses = 0
        self.engines: dict[str, int] = {}
        self.degraded: dict[str, int] = {}
        self.retries = 0
        self.backoff_ms = 0.0
        self.resplits = 0
        self.delta_rows = 0
        self.merges = 0
        self.keys_scanned = 0
        self.bytes_scanned = 0
        self.max_proc_ms = 0.0
        self.max_task_store = ""
        self.max_task_region = -1

    @property
    def num(self) -> int:
        return len(self.procs)

    def add(self, d: CopExecDetails) -> None:
        self.procs.append(d.proc_ms)
        self.queue_ms += d.queue_ms
        self.wire_ms += d.wire_ms
        self.device_ms += d.device_ms
        self.host_ms += d.host_ms
        self.compile_ms += d.compile_ms
        self.h2d_bytes += d.h2d_bytes
        self.d2h_bytes += d.d2h_bytes
        self.dev_cache_hits += d.dev_cache_hits
        self.dev_cache_misses += d.dev_cache_misses
        eng = d.engine or "?"
        self.engines[eng] = self.engines.get(eng, 0) + 1
        if d.degraded:
            self.degraded[d.degraded] = self.degraded.get(d.degraded, 0) + 1
        self.retries += d.retries
        self.backoff_ms += d.backoff_ms
        self.resplits += d.resplits
        self.delta_rows += d.delta_rows
        self.merges += d.merges
        self.keys_scanned += d.keys_scanned
        self.bytes_scanned += d.bytes_scanned
        if d.proc_ms >= self.max_proc_ms:
            self.max_proc_ms = d.proc_ms
            self.max_task_store = d.store or "local"
            self.max_task_region = d.region_id

    def p95_ms(self) -> float:
        xs = sorted(self.procs)
        return xs[max(0, math.ceil(0.95 * len(xs)) - 1)] if xs else 0.0

    def render(self) -> str:
        if not self.procs:
            return ""
        n = len(self.procs)
        avg = sum(self.procs) / n
        eng = " ".join(f"{e}×{c}" for e, c in sorted(self.engines.items()))
        parts = [
            f"num: {n}",
            f"max: {self.max_proc_ms:.1f}ms",
            f"avg: {avg:.1f}ms",
            f"p95: {self.p95_ms():.1f}ms",
            f"engine: {eng}",
            f"backoff: {self.backoff_ms:.0f}ms",
            f"resplits: {self.resplits}",
        ]
        if self.queue_ms:
            parts.append(f"queue: {self.queue_ms / n:.1f}ms")  # avg send-queue wait
        if self.wire_ms:
            parts.append(f"wire: {self.wire_ms / n:.1f}ms")  # avg RPC minus store proc
        if self.compile_ms:
            parts.append(f"compile: {self.compile_ms:.1f}ms")
        if self.device_ms:
            parts.append(f"device: {self.device_ms:.1f}ms")
        if self.host_ms:
            parts.append(f"host: {self.host_ms:.1f}ms")
        if self.h2d_bytes or self.d2h_bytes:
            parts.append(f"h2d: {self.h2d_bytes}B, d2h: {self.d2h_bytes}B")
        if self.dev_cache_hits or self.dev_cache_misses:
            parts.append(f"dev_cache: {self.dev_cache_hits}/{self.dev_cache_hits + self.dev_cache_misses}")
        if self.keys_scanned:
            parts.append(f"scan: {self.keys_scanned} keys/{self.bytes_scanned}B")
        if self.delta_rows:
            parts.append(f"delta_rows: {self.delta_rows}")  # scan paid the delta path
        if self.merges:
            parts.append(f"merges: {self.merges}")
        if self.degraded:
            parts.append(
                "degraded: " + " ".join(f"{k}×{v}" for k, v in sorted(self.degraded.items()))
            )
        return "cop_task: {" + ", ".join(parts) + "}"


class MPPExecDetails:
    """One MPP gather's execution details (the cop sidecar's analog for the
    fragment pipeline). ``shards`` is the per-shard straggler breakdown the
    fragment program's shard probes record: one ``[shard_id, compute_ms,
    rows, exchange_bytes]`` row per mesh shard, so EXPLAIN ANALYZE can name
    WHICH device inside the collective was slow."""

    __slots__ = ("n_fragments", "ndev", "wall_ms", "rows", "retries", "store", "shards", "compiles",
                 "stages", "stage_bytes")

    def __init__(self, n_fragments=0, ndev=0, wall_ms=0.0, rows=0, retries=0, store="", shards=None,
                 compiles=0, stages=1, stage_bytes=None):
        self.n_fragments = n_fragments
        self.ndev = ndev
        self.wall_ms = wall_ms
        self.rows = rows
        self.retries = retries
        self.store = store  # "" = executed on the local mesh
        self.shards = shards or []  # [[shard_id, ms, rows, xchg_bytes], ...]
        # fragment programs BUILT for this gather (0 = every attempt rode the
        # program cache) — the MPP analog of the cop sidecar's jit flag
        self.compiles = compiles
        # staged fragment pipeline: how many on-mesh stages ONE program ran
        # (1 + device-staged subplan build sides), and each device stage's
        # inter-stage exchanged bytes (all on ICI — zero host bytes)
        self.stages = stages
        self.stage_bytes = stage_bytes or []

    def shard_summary(self) -> "tuple | None":
        """(max_ms, min_ms, p95_ms, slowest_shard_id) or None."""
        if not self.shards:
            return None
        ms = sorted(float(s[1]) for s in self.shards)
        p95 = ms[max(0, math.ceil(0.95 * len(ms)) - 1)]
        slowest = max(self.shards, key=lambda s: float(s[1]))
        return ms[-1], ms[0], p95, int(slowest[0])

    def render(self) -> str:
        parts = [
            f"fragments: {self.n_fragments}",
            f"stages: {self.stages}",
            f"ndev: {self.ndev}",
            f"wall: {self.wall_ms:.1f}ms",
            f"rows: {self.rows}",
        ]
        if self.stage_bytes:
            parts.append(
                "stage_bytes: [" + ", ".join(str(int(b)) for b in self.stage_bytes) + "]"
            )
        ss = self.shard_summary()
        if ss is not None:
            mx, mn, p95, slowest = ss
            parts.append(f"shards: {len(self.shards)}")
            parts.append(f"shard max/min/p95: {mx:.1f}/{mn:.1f}/{p95:.1f}ms")
            parts.append(f"slowest: shard {slowest}")
        if self.compiles:
            parts.append(f"compile: {self.compiles}")
        if self.retries:
            parts.append(f"retries: {self.retries}")
        if self.store:
            parts.append(f"store: {self.store}")
        return "mpp_task: {" + ", ".join(parts) + "}"


# -- thread-local collection context ----------------------------------------

_TLS = threading.local()


def current_cop() -> "CopExecDetails | None":
    """The cop-task sidecar THIS thread is filling, if any — engines
    attribute device/host/compile time and transfer bytes through it."""
    return getattr(_TLS, "detail", None)


def current_tracer():
    """The Tracer the active task records spans into (remote server side or
    an embedded traced statement); None when tracing is off."""
    return getattr(_TLS, "tracer", None)


@contextmanager
def collecting(detail: "CopExecDetails | None", tracer=None):
    prev_d = getattr(_TLS, "detail", None)
    prev_t = getattr(_TLS, "tracer", None)
    _TLS.detail, _TLS.tracer = detail, tracer
    try:
        yield detail
    finally:
        _TLS.detail, _TLS.tracer = prev_d, prev_t


def trace_span(name: str):
    """A span on the active task's tracer — nullcontext when tracing is off
    (the zero-cost-when-off rule)."""
    tr = current_tracer()
    return tr.span(name) if tr is not None else nullcontext()


# -- plan digest -------------------------------------------------------------


def plan_digest(plan) -> str:
    """Stable digest of a physical plan's EXPLAIN shape (ref: plan digest in
    util/plancodec), memoized on the plan object so cached plans pay the
    explain walk exactly once."""
    d = getattr(plan, "_plan_digest", None)
    if d is None:
        from tidb_tpu.planner.plans import explain_plan

        try:
            text = explain_plan(plan)
        except Exception:
            text = type(plan).__name__
        d = hashlib.sha256(text.encode()).hexdigest()[:16]
        try:
            plan._plan_digest = d
        except AttributeError:
            pass  # __slots__ plan nodes can't memoize; recompute next time
    return d


# -- per-node collection (EXPLAIN ANALYZE) -----------------------------------


@dataclass
class RuntimeStatsColl:
    """Collects OpStats (+ cop/MPP task summaries) keyed by id(plan_node)."""

    stats: dict = field(default_factory=dict)
    cop: dict = field(default_factory=dict)
    mpp: dict = field(default_factory=dict)

    def get(self, plan) -> OpStats:
        s = self.stats.get(id(plan))
        if s is None:
            s = self.stats[id(plan)] = OpStats()
        return s

    def record(self, plan, rows: int, dt_ms: float) -> None:
        s = self.get(plan)
        s.rows += rows
        s.time_ms += dt_ms
        s.loops += 1

    def record_cop(self, plan, detail: CopExecDetails) -> None:
        s = self.cop.get(id(plan))
        if s is None:
            s = self.cop[id(plan)] = CopTasksSummary()
        s.add(detail)

    def record_mpp(self, plan, detail: MPPExecDetails) -> None:
        self.mpp.setdefault(id(plan), []).append(detail)

    def render(self, plan) -> str:
        parts = []
        s = self.stats.get(id(plan))
        if s is not None:
            parts.append(s.render())
        c = self.cop.get(id(plan))
        if c is not None and c.num:
            parts.append(c.render())
        for m in self.mpp.get(id(plan), ()):
            parts.append(m.render())
        return ", ".join(parts)


def instrument(executor, plan, coll: RuntimeStatsColl):
    """Wrap executor.execute to record inclusive wall time + output rows."""
    inner = executor.execute

    def timed():
        t0 = time.perf_counter()
        chunk = inner()
        dt = (time.perf_counter() - t0) * 1000.0
        coll.record(plan, len(chunk) if chunk is not None else 0, dt)
        return chunk

    executor.execute = timed
    return executor
