"""Failpoint-style fault injection (ref: pingcap/failpoint; 238 reference
files call failpoint.Inject — tests enable named points to force region
splits, slow responses, crashes mid-DDL, ...).

Unlike the reference's build-time code rewriting, points here are plain
runtime hooks: production code calls ``inject("name", *args)`` which is a
no-op unless a test enabled the point with a value or callable.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

_mu = threading.Lock()
_active: dict[str, object] = {}


def enable(name: str, action: object = True) -> None:
    with _mu:
        _active[name] = action


def disable(name: str) -> None:
    with _mu:
        _active.pop(name, None)


def inject(name: str, *args):
    """Returns None when the point is disabled; the action's value (or its
    return value, if callable) when enabled. Callables may raise to simulate
    crashes."""
    with _mu:
        action = _active.get(name)
    if action is None:
        return None
    # every enabled firing is an event BEFORE the action runs (callables may
    # raise to simulate crashes — the chaos.* record must precede the damage
    # so recovery chains in cluster_log show cause, then effect)
    from tidb_tpu.utils import eventlog as _ev

    lg = _ev.on(_ev.WARN)
    if lg is not None:
        lg.emit(_ev.WARN, "chaos", name, failpoint=name)
    if callable(action):
        return action(*args)
    return action


@contextmanager
def enabled(name: str, action: object = True):
    enable(name, action)
    try:
        yield
    finally:
        disable(name)
