"""Structured, leveled, bounded in-process event log (ref: the diagnostics
substrate under pkg/executor cluster_log + log.SearchLogRequest — here a
process-singleton ring instead of grepping log files).

Every load-bearing state transition (election deposed, placement cutover,
boRegionMiss re-route, MPP re-dispatch, engine degrade, chaos failpoint
firing) records one event: ``(ts, level, component, event, fields, trace_id)``.
Events are tuples in per-level bounded deques — append is GIL-atomic, so the
recorder needs NO lock and NO thread (thread_hygiene stays green by design).

Zero-cost discipline (same shape as ``Request.tracer=None``): call sites gate
on :func:`on`, which returns ``None`` when the level is below the configured
floor — the disabled path constructs no fields dict, no tuple, nothing::

    lg = eventlog.on(eventlog.INFO)
    if lg is not None:
        lg.emit(eventlog.INFO, "placement", "migrate_begin", table=tid)

Search (``information_schema.tidb_log`` / the ``log_search`` wire verb)
filters by time range, minimum level, component, and regex server-side, and
caps the shipped rows — rings never cross the wire whole.
"""

from __future__ import annotations

import re
import time
from collections import deque
from typing import Optional

DEBUG, INFO, WARN, ERROR = 0, 1, 2, 3
OFF = 4  # config floor only — no event carries this level

_NAMES = ("debug", "info", "warn", "error")


def level_name(level: int) -> str:
    return _NAMES[level] if 0 <= level < len(_NAMES) else "off"


def level_from_name(name: str) -> int:
    s = str(name).strip().lower()
    if s in ("off", "none", "disable", "disabled"):
        return OFF
    if s in ("warning",):  # accept the Prometheus/MySQL spelling
        return WARN
    try:
        return _NAMES.index(s)
    except ValueError:
        return INFO


class EventLog:
    """Per-level bounded rings of event tuples. Threadless and lockless:
    ``deque.append`` on a bounded deque is atomic under the GIL, and search
    snapshots each ring with ``list()`` (also atomic) before filtering."""

    __slots__ = ("rings",)

    def __init__(self, debug_cap: int, info_cap: int, warn_cap: int, error_cap: int):
        self.rings = (
            deque(maxlen=max(1, int(debug_cap))),
            deque(maxlen=max(1, int(info_cap))),
            deque(maxlen=max(1, int(warn_cap))),
            deque(maxlen=max(1, int(error_cap))),
        )

    def emit(
        self,
        level: int,
        component: str,
        event: str,
        trace_id: Optional[str] = None,
        **fields,
    ) -> None:
        """Record one event. ``fields`` must stay JSON-able — they ride the
        ``log_search`` wire verb and the diag bundle verbatim."""
        self.rings[level].append((time.time(), level, component, event, fields, trace_id))

    def __len__(self) -> int:
        return sum(len(r) for r in self.rings)

    def clear(self) -> None:
        for r in self.rings:
            r.clear()

    def search(
        self,
        since: Optional[float] = None,
        until: Optional[float] = None,
        min_level: int = DEBUG,
        component: Optional[str] = None,
        pattern: Optional[str] = None,
        limit: int = 256,
    ) -> list:
        """Filtered slice, oldest-first, capped at the NEWEST ``limit`` rows
        (a diagnostics read wants the tail of the incident window). ``pattern``
        is a regex matched against ``component.event`` plus every stringified
        field value — the grep-a-log-line analog."""
        rx = re.compile(pattern) if pattern else None
        out = []
        for lvl in range(max(min_level, DEBUG), len(self.rings)):
            for ev in list(self.rings[lvl]):
                ts = ev[0]
                if since is not None and ts < since:
                    continue
                if until is not None and ts > until:
                    continue
                if component is not None and ev[2] != component:
                    continue
                if rx is not None:
                    hay = f"{ev[2]}.{ev[3]} " + " ".join(
                        f"{k}={v}" for k, v in ev[4].items()
                    )
                    if not rx.search(hay):
                        continue
                out.append(ev)
        out.sort(key=lambda e: e[0])
        if limit is not None and limit >= 0 and len(out) > limit:
            out = out[-limit:]
        return out

    def for_trace(self, trace_id: str) -> list:
        """Every retained event carrying ``trace_id``, oldest-first — the
        ``/traces?id=`` ↔ ``/logs`` pivot (slow-log EVENTS / FIRST_ERROR
        cross-links read this)."""
        if not trace_id:
            return []
        out = [
            ev
            for ring in self.rings
            for ev in list(ring)
            if ev[5] == trace_id
        ]
        out.sort(key=lambda e: e[0])
        return out


# process singleton, built lazily from config.current() so a `--config` file's
# [observability] section takes effect without threading Config through every
# instrumented seam. _min_level is cached beside it: `on()` is on hot paths
# (every backoff sleep, every cop dispatch) and must stay two loads + a compare.
_log: Optional[EventLog] = None
_min_level: Optional[int] = None


def _build() -> None:
    global _log, _min_level
    from tidb_tpu import config

    cfg = config.current()
    _min_level = level_from_name(getattr(cfg, "eventlog_level", "info"))
    _log = EventLog(
        getattr(cfg, "eventlog_debug_capacity", 512),
        getattr(cfg, "eventlog_capacity", 2048),
        getattr(cfg, "eventlog_error_capacity", 1024),
        getattr(cfg, "eventlog_error_capacity", 1024),
    )


def on(level: int) -> Optional[EventLog]:
    """The zero-cost gate: the log if ``level`` clears the configured floor,
    else ``None``. Call sites branch on the result so the disabled path
    allocates nothing (tracer=None discipline)."""
    if _min_level is None:
        _build()
    if level < _min_level:
        return None
    return _log


def get() -> EventLog:
    """The singleton regardless of level floor — search/diagnostics reads go
    through here (an OFF log is simply empty)."""
    if _log is None:
        _build()
    return _log


def min_level() -> int:
    if _min_level is None:
        _build()
    return _min_level


def set_level(name) -> None:
    """Re-floor the recorder in place (bench lanes flip info<->off; tests
    drive debug). Accepts a level name or an int level."""
    global _min_level
    if _log is None:
        _build()
    _min_level = name if isinstance(name, int) else level_from_name(name)


def reset() -> None:
    """Drop the singleton so the next touch rebuilds from config — test
    isolation hook (mirrors metricshist's recorder reset idiom)."""
    global _log, _min_level
    _log = None
    _min_level = None
