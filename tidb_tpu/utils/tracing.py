"""Tracing (ref: pkg/util/tracing dual spans + the TRACE statement,
executor/trace.go): a per-statement span collector; instrumentation sites
open spans through Session.span() which no-ops when tracing is off."""

from __future__ import annotations

import time
from contextlib import contextmanager
from dataclasses import dataclass


@dataclass
class Span:
    name: str
    start_s: float  # relative to trace start
    duration_s: float
    depth: int


class Tracer:
    def __init__(self):
        self._t0 = time.perf_counter()
        self._depth = 0
        self.spans: list[Span] = []

    @contextmanager
    def span(self, name: str):
        start = time.perf_counter()
        idx = len(self.spans)
        self.spans.insert(idx, Span(name, start - self._t0, 0.0, self._depth))
        self._depth += 1
        try:
            yield
        finally:
            self._depth -= 1
            self.spans[idx].duration_s = time.perf_counter() - start

    def rows(self) -> list[tuple]:
        out = []
        for s in self.spans:
            label = ("  " * s.depth) + ("└─" if s.depth else "") + s.name
            out.append((label, f"{s.start_s * 1e3:.3f}ms", f"{s.duration_s * 1e3:.3f}ms"))
        return out
