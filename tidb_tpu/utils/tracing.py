"""Tracing (ref: pkg/util/tracing dual spans + the TRACE statement,
executor/trace.go): a per-statement span collector; instrumentation sites
open spans through Session.span() which no-ops when tracing is off.

Distributed half (ref: Dapper-style trace-context propagation): the trace id
travels inside cop/MPP RPC headers (:class:`TraceContext`), the remote
``StoreServer`` records spans into its own :class:`Tracer` under that
context, and the finished spans ship home in the response where the caller
grafts them into the statement trace with :meth:`Tracer.merge_remote` — so
TRACE shows the full cross-process tree, each remote span tagged with the
store that recorded it.

Thread-safety: shared-cop-pool workers open spans on ONE statement tracer
concurrently. Depth/nesting state is per-thread (a span stack in a
``threading.local``); the span list itself appends under a lock with a
monotonically increasing sequence number, and :meth:`rows` orders by
``(start, seq)`` — a deterministic rule independent of interleaving.
Cross-thread nesting (a worker's task span under the requester's
``execute`` span) is explicit via ``span(name, parent=...)``.

Always-on sampled tracing (ref: Dapper §4 — probabilistic sampling makes a
continuous latency breakdown affordable at serving rates): a per-statement
coin in ``Session.execute`` creates a ``Tracer`` for a small fraction of
statements; its ``sampled`` flag rides the :class:`TraceContext` through
every cop/MPP RPC so remote stores record spans ONLY for sampled
statements. Finished sampled traces land in the :class:`TraceReservoir` —
a bounded ring of recent traces plus a *tail-keep* section that pins any
trace whose statement crossed the slow-log threshold, so the interesting
outliers survive ring rotation (the slow log cross-links them by trace id).
Unsampled statements never construct a tracer: the ``Request.tracer is
None`` zero-cost rule is untouched.
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import OrderedDict, deque
from contextlib import contextmanager
from dataclasses import dataclass, field


@dataclass
class Span:
    name: str
    start_s: float  # relative to trace start
    duration_s: float
    depth: int
    seq: int = 0
    # "" = recorded in this process; else the remote store that recorded it
    node: str = ""


@dataclass(frozen=True)
class TraceContext:
    """The wire form of an active trace: what a cop/MPP RPC carries outward
    so the remote side can record spans under the same trace."""

    trace_id: str
    sampled: bool = True

    def to_pb(self) -> dict:
        return {"tid": self.trace_id, "sampled": int(self.sampled)}

    @staticmethod
    def from_pb(pb) -> "TraceContext | None":
        if not pb:
            return None
        return TraceContext(str(pb.get("tid", "")), bool(pb.get("sampled", 1)))


class Tracer:
    def __init__(self, trace_id: "str | None" = None, sampled: bool = True):
        self._t0 = time.perf_counter()
        self.trace_id = trace_id or uuid.uuid4().hex[:16]
        # rides the wire context: remote sides record spans only when set
        self.sampled = sampled
        self._mu = threading.Lock()
        self._tls = threading.local()
        self._seq = 0
        self.spans: list[Span] = []

    # -- span recording -----------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current(self) -> "Span | None":
        """The innermost open span of THIS thread (cross-thread parents are
        captured here and passed to workers via ``span(parent=...)``)."""
        st = self._stack()
        return st[-1] if st else None

    @contextmanager
    def span(self, name: str, parent: "Span | None" = None):
        st = self._stack()
        if parent is None and st:
            parent = st[-1]
        depth = parent.depth + 1 if parent is not None else 0
        start = time.perf_counter()
        sp = Span(name, start - self._t0, 0.0, depth)
        with self._mu:
            sp.seq = self._seq
            self._seq += 1
            self.spans.append(sp)
        st.append(sp)
        try:
            yield sp
        finally:
            st.pop()
            sp.duration_s = time.perf_counter() - start

    # -- wire ----------------------------------------------------------------
    def context(self) -> TraceContext:
        return TraceContext(self.trace_id, self.sampled)

    def to_pb(self) -> list[list]:
        """Finished spans in wire form: [name, start_s, duration_s, depth],
        ordered by the same deterministic (start, seq) rule as rows()."""
        with self._mu:
            spans = sorted(self.spans, key=lambda s: (s.start_s, s.seq))
        return [[s.name, round(s.start_s, 6), round(s.duration_s, 6), s.depth] for s in spans]

    def merge_remote(self, pb_spans, base_s: float, node: str, depth: int = 0) -> None:
        """Graft spans recorded by a remote process into this trace: remote
        starts are relative to the REMOTE trace start (its RPC handling), so
        they rebase onto ``base_s`` — the local time the RPC span opened —
        and indent ``depth`` levels under it. Clock skew never enters: only
        the remote's own relative timings travel."""
        if not pb_spans:
            return
        with self._mu:
            for name, start_s, dur_s, sd in pb_spans:
                sp = Span(
                    str(name), base_s + float(start_s), float(dur_s), depth + int(sd), node=node
                )
                sp.seq = self._seq
                self._seq += 1
                self.spans.append(sp)

    def dump(self) -> list[list]:
        """Structured spans for the trace reservoir / JSON surfaces:
        [name, start_ms, duration_ms, depth, node], (start, seq)-ordered."""
        with self._mu:
            spans = sorted(self.spans, key=lambda s: (s.start_s, s.seq))
        return [
            [s.name, round(s.start_s * 1e3, 3), round(s.duration_s * 1e3, 3), s.depth, s.node]
            for s in spans
        ]

    # -- rendering -----------------------------------------------------------
    def rows(self) -> list[tuple]:
        with self._mu:
            spans = sorted(self.spans, key=lambda s: (s.start_s, s.seq))
        out = []
        for s in spans:
            label = ("  " * s.depth) + ("└─" if s.depth else "") + s.name
            if s.node:
                label += f" @{s.node}"
            out.append((label, f"{s.start_s * 1e3:.3f}ms", f"{s.duration_s * 1e3:.3f}ms"))
        return out


def effective(tracer) -> "Tracer | None":
    """The tracer a recording seam should actually use: None when tracing is
    off OR the context is explicitly unsampled (``TraceContext.sampled=0``).
    The single home of the zero-cost gating rule — every span-recording seam
    (cop clients, MPP dispatch) routes through this, so an unsampled tracer
    behaves byte-identically to no tracer at all."""
    if tracer is None or not getattr(tracer, "sampled", True):
        return None
    return tracer


def clamp_rate(rate: float, qps: float, clamp_qps: float) -> float:
    """Adaptive sampling clamp (Dapper's follow-up idiom: sample generously
    when idle, shed tracing under pressure): above ``clamp_qps`` the
    effective rate scales down proportionally, so the expected number of
    sampled statements per second stays ~``rate * clamp_qps`` no matter how
    hard the instance is driven — and recovers to the configured rate the
    moment load falls back under the threshold. ``clamp_qps <= 0`` disables
    the clamp. The single home of the rule: the session's sampling coin and
    any future remote-side clamp must both route here."""
    if clamp_qps <= 0 or qps <= clamp_qps:
        return rate
    return rate * (clamp_qps / qps)


# -- trace reservoir ---------------------------------------------------------


@dataclass
class TraceEntry:
    """One finished sampled statement in the reservoir."""

    trace_id: str
    time: float  # unix seconds the statement finished
    sql: str
    digest: str
    duration_s: float
    slow: bool  # crossed the slow-log threshold → tail-keep pinned
    spans: list = field(default_factory=list)  # Tracer.dump() rows


class TraceReservoir:
    """Bounded store of recent sampled traces (ref: Dapper's sampled-trace
    collection; GWP's always-on-with-a-budget discipline). Two sections:

    - a ring of the N most recent sampled traces (FIFO eviction);
    - *tail-keep*: traces of statements over the slow-log threshold are
      additionally pinned in their own (smaller) ring, so a latency outlier
      survives long after ordinary ring rotation would have dropped it —
      regardless of how many fast sampled statements follow.

    No background threads: deposits happen on the statement's own thread,
    reads under one lock. Surfaced via ``GET /traces`` and
    ``information_schema.trace_reservoir``; the slow log cross-links entries
    by ``trace_id``."""

    def __init__(self, capacity: int = 64, slow_capacity: int = 32):
        self._mu = threading.Lock()
        self._ring: deque = deque(maxlen=max(int(capacity), 1))
        self._slow: "OrderedDict[str, TraceEntry]" = OrderedDict()
        self.slow_capacity = max(int(slow_capacity), 1)

    def add(self, entry: TraceEntry) -> None:
        with self._mu:
            self._ring.append(entry)
            if entry.slow:
                self._slow[entry.trace_id] = entry
                while len(self._slow) > self.slow_capacity:
                    self._slow.popitem(last=False)

    def get(self, trace_id: str) -> "TraceEntry | None":
        with self._mu:
            hit = self._slow.get(trace_id)
            if hit is not None:
                return hit
            for e in self._ring:
                if e.trace_id == trace_id:
                    return e
        return None

    def traces(self) -> list[TraceEntry]:
        """Every retained trace, oldest first: tail-keep entries that have
        already rotated out of the ring, then the ring itself."""
        with self._mu:
            ring_ids = {e.trace_id for e in self._ring}
            pinned = [e for tid, e in self._slow.items() if tid not in ring_ids]
            return sorted(pinned + list(self._ring), key=lambda e: e.time)

    def __len__(self) -> int:
        with self._mu:
            ring_ids = {e.trace_id for e in self._ring}
            return len(self._ring) + sum(1 for t in self._slow if t not in ring_ids)

    def clear(self) -> None:
        with self._mu:
            self._ring.clear()
            self._slow.clear()
