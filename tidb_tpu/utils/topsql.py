"""Top-SQL + continuous CPU profiling (ref: util/topsql — per-SQL-digest CPU
attribution reported to the dashboard; util/cpuprofile — the shared
continuous profile window).

tpu-native redesign: the reference samples Go pprof labels; here a sampler
thread walks ``sys._current_frames()`` on an interval and attributes each
sample to whatever SQL digest the sampled thread REGISTERED at statement
start (``attach``/``detach``).  Two aggregations come out of one sampler:

- per-digest CPU samples over a ring of 1-second windows (Top-SQL);
- collapsed-stack counts over the same ring (continuous profiling; the
  /status/profile endpoint renders them flamegraph-style: "a;b;c count").
"""

from __future__ import annotations

import sys
import threading
import time
from collections import defaultdict


class TopSQLCollector:
    """One process-wide sampler (started lazily, stopped at close)."""

    def __init__(self, interval_s: float = 0.02, window_s: int = 1, keep_windows: int = 120):
        self.interval_s = interval_s
        self.window_s = window_s
        self.keep = keep_windows
        self._mu = threading.Lock()
        # thread ident → stack of (sql_digest, plan_digest, sample_sql,
        # trace_id): nested internal statements (privilege checks,
        # infoschema helpers) push/pop; samples attribute to the TOP entry
        self._attached: dict[int, list[tuple[str, str, str, str]]] = {}
        # ring: window start ts → digest → samples
        self._windows: dict[int, dict[str, int]] = {}
        self._samples_of: dict[str, str] = {}  # digest → sample sql text
        self._plan_of: dict[str, str] = {}  # digest → plan digest
        # digest → the last trace-sampled statement's reservoir trace id:
        # the Top-SQL ↔ trace-reservoir pivot (GET /traces?id=...)
        self._trace_of: dict[str, str] = {}
        # digest → cumulative metered request units (workload attribution:
        # hot-by-CPU vs hot-by-RU can rank differently — scan-heavy
        # statements burn RUs in the store, not in this process's frames)
        self._ru_of: dict[str, float] = defaultdict(float)
        # collapsed python stacks: "mod.fn;mod.fn;..." → samples
        self._stacks: dict[int, dict[str, int]] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.enabled = True

    # -- statement attribution (called by the session) ----------------------
    def attach(self, sql_digest: str, plan_digest: str, sample_sql: str, trace_id: str = "") -> None:
        self._ensure_running()
        tid = threading.get_ident()
        with self._mu:
            self._attached.setdefault(tid, []).append(
                (sql_digest, plan_digest, sample_sql[:256], trace_id)
            )

    def note_ru(self, sql_digest: str, ru: float) -> None:
        """Accumulate a finished statement's metered RUs on its digest."""
        with self._mu:
            self._ru_of[sql_digest] += ru

    def detach(self) -> None:
        tid = threading.get_ident()
        with self._mu:
            stack = self._attached.get(tid)
            if stack:
                stack.pop()
            if not stack:
                self._attached.pop(tid, None)

    # -- sampler ------------------------------------------------------------
    def _ensure_running(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(target=self._run, daemon=True, name="topsql-sampler")
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            if not self.enabled:
                continue
            with self._mu:
                attached = dict(self._attached)
            if not attached:
                continue  # idle: no stop-the-world frame walks
            now_w = int(time.time()) // self.window_s * self.window_s
            # collect OUTSIDE the lock and drop frame references promptly —
            # held frames pin their locals (sockets, buffers) alive
            hits: list[tuple[str, str, str, str, str]] = []
            frames = sys._current_frames()
            try:
                for tid, stack_entries in attached.items():
                    if not stack_entries:
                        continue
                    dg, pdg, sample, trace_id = stack_entries[-1]
                    f = frames.get(tid)
                    if f is None:
                        continue
                    parts = []
                    g = f
                    depth = 0
                    while g is not None and depth < 48:
                        co = g.f_code
                        parts.append(f"{co.co_filename.rsplit('/', 1)[-1]}:{co.co_name}")
                        g = g.f_back
                        depth += 1
                    del g, f
                    hits.append((dg, pdg, sample, trace_id, ";".join(reversed(parts))))
            finally:
                del frames
            with self._mu:
                win = self._windows.setdefault(now_w, defaultdict(int))
                swin = self._stacks.setdefault(now_w, defaultdict(int))
                for dg, pdg, sample, trace_id, stack in hits:
                    win[dg] += 1
                    self._samples_of[dg] = sample
                    self._plan_of[dg] = pdg
                    if trace_id:  # keep the last SAMPLED statement's pivot
                        self._trace_of[dg] = trace_id
                    swin[stack] += 1
                # expire old windows — and prune digest metadata no retained
                # window references, or a long-lived server accumulates one
                # sample/plan entry per distinct SQL digest forever
                if len(self._windows) > self.keep:
                    for k in sorted(self._windows)[: len(self._windows) - self.keep]:
                        self._windows.pop(k, None)
                        self._stacks.pop(k, None)
                    live = {dg for counts in self._windows.values() for dg in counts}
                    for dg in list(self._samples_of):
                        if dg not in live:
                            self._samples_of.pop(dg, None)
                            self._plan_of.pop(dg, None)
                            self._trace_of.pop(dg, None)
                            self._ru_of.pop(dg, None)

    # -- reports ------------------------------------------------------------
    def top_sql(self, last_s: int = 60, limit: int = 30) -> list[tuple]:
        """[(digest, plan_digest, sample_sql, cpu_seconds, samples,
        trace_id, ru)] over the trailing ``last_s`` seconds, hottest first.
        ``trace_id`` cross-links to the trace reservoir when a sampled
        statement contributed samples; ``ru`` is the digest's cumulative
        metered request units (lifetime — RUs land once per statement, not
        per sample, so they don't window)."""
        cutoff = int(time.time()) - last_s
        agg: dict[str, int] = defaultdict(int)
        with self._mu:
            for w, counts in self._windows.items():
                if w >= cutoff:
                    for dg, n in counts.items():
                        agg[dg] += n
            rows = [
                (
                    dg,
                    self._plan_of.get(dg, ""),
                    self._samples_of.get(dg, ""),
                    round(n * self.interval_s, 4),
                    n,
                    self._trace_of.get(dg, ""),
                    round(self._ru_of.get(dg, 0.0), 3),
                )
                for dg, n in agg.items()
            ]
        rows.sort(key=lambda r: -r[4])
        return rows[:limit]

    def profile(self, last_s: int = 60, limit: int = 100) -> list[tuple[str, int]]:
        """Collapsed-stack lines over the trailing window (flamegraph
        input format: 'frame;frame;frame count')."""
        cutoff = int(time.time()) - last_s
        agg: dict[str, int] = defaultdict(int)
        with self._mu:
            for w, stacks in self._stacks.items():
                if w >= cutoff:
                    for s, n in stacks.items():
                        agg[s] += n
        rows = sorted(agg.items(), key=lambda kv: -kv[1])
        return rows[:limit]


_GLOBAL: TopSQLCollector | None = None
_GLOBAL_MU = threading.Lock()


def collector() -> TopSQLCollector:
    global _GLOBAL
    with _GLOBAL_MU:
        if _GLOBAL is None:
            _GLOBAL = TopSQLCollector()
        return _GLOBAL
