"""Columnar batch format — the host↔device boundary.

Reference parity: pkg/util/chunk (Column: column.go:74, Chunk: chunk.go:35,
wire codec: codec.go:42/101). Redesigned for TPU:

- A ``Column`` is a fixed-width numpy array + a validity mask. No offsets/
  varlen region: strings are dictionary-encoded to int32 codes against a
  ``Dictionary`` (append-only, optionally rank-compacted so codes become
  order-preserving — the planner only pushes string ORDER BY/range predicates
  to the device when ``Dictionary.sorted`` is True).
- A ``Chunk`` is a list of equal-length Columns. Chunks convert losslessly to
  a dict of device arrays (``to_device_cols``) padded to bucketed power-of-two
  lengths so XLA sees few distinct shapes (ref design note: SURVEY.md §7
  "Dynamic shapes vs XLA").
- The wire codec is a simple length-prefixed raw-buffer framing (spiritual
  analog of chunk/codec.go's little-endian column serialization).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Iterable, Sequence

import numpy as np

from tidb_tpu.types import FieldType, TypeKind
from tidb_tpu.types.datum import (
    NULL,
    date_to_days,
    datetime_to_micros,
    days_to_date,
    duration_to_micros,
    micros_to_datetime,
    micros_to_duration,
)

# ---------------------------------------------------------------------------
# Dictionary (string encoding)
# ---------------------------------------------------------------------------


class Dictionary:
    """Append-only bytes→code dictionary.

    Codes are dense int32 starting at 0. After ``compact()`` the dictionary is
    sorted and codes are order-preserving (rank == code), enabling device-side
    string comparisons; appends after compaction clear ``sorted`` again.
    """

    __slots__ = ("_values", "_index", "sorted", "ci_sorted", "_mu")

    def __init__(self, values: Sequence[bytes] = ()):  # noqa: D107
        import threading

        self._values: list[bytes] = list(values)
        self._index: dict[bytes, int] = {v: i for i, v in enumerate(self._values)}
        self.sorted = self._values == sorted(self._values) if self._values else True
        # codes order-preserving under the general_ci WEIGHT order (set by
        # compact(ci=True) — the device ci MIN/MAX legalization); any append
        # may land out of weight order, so it clears like ``sorted``
        self.ci_sorted = not self._values
        # encode() appends; concurrent cop/partition worker threads share
        # table-level dictionaries, so the mutation is locked
        self._mu = threading.Lock()

    def __len__(self) -> int:
        return len(self._values)

    def encode(self, value: "bytes | str") -> int:
        if isinstance(value, str):
            value = value.encode("utf-8")
        code = self._index.get(value)
        if code is not None:
            return code
        with self._mu:
            code = self._index.get(value)
            if code is None:
                code = len(self._values)
                self._values.append(value)
                self._index[value] = code
                if self.sorted and code > 0 and self._values[code - 1] > value:
                    self.sorted = False
                # a single element dict stays sorted; ci weight order is not
                # checked here (weight_bytes costs) — any multi-value append
                # conservatively drops the ci-order proof
                if code > 0:
                    self.ci_sorted = False
        return code

    def try_encode(self, value: "bytes | str") -> int:
        """Encode without inserting; returns -1 if absent (predicate constants
        referencing values not present in the column can never match)."""
        if isinstance(value, str):
            value = value.encode("utf-8")
        return self._index.get(value, -1)

    def decode(self, code: int) -> bytes:
        return self._values[code]

    def decode_many(self, codes: np.ndarray) -> list[bytes]:
        vals = self._values
        return [vals[int(c)] for c in codes]

    def values_array(self) -> list[bytes]:
        return list(self._values)

    def compact(self, ci: bool = False) -> np.ndarray:
        """Sort values; return the old-code→new-code remap array. ``ci``
        sorts by (general_ci weight, bytes) instead of raw bytes — codes
        become order-preserving under the COLLATION's order, which legalizes
        device-side MIN/MAX on ci columns (the host _string_minmax recipe,
        applied once to the dictionary instead of per reduction)."""
        if ci:
            from tidb_tpu.utils.collate import weight_bytes

            order = sorted(
                range(len(self._values)),
                key=lambda i: (weight_bytes(self._values[i]), self._values[i]),
            )
        else:
            order = sorted(range(len(self._values)), key=lambda i: self._values[i])
        remap = np.empty(len(order), dtype=np.int32)
        for new, old in enumerate(order):
            remap[old] = new
        self._values = [self._values[i] for i in order]
        self._index = {v: i for i, v in enumerate(self._values)}
        self.sorted = self._values == sorted(self._values)
        self.ci_sorted = ci or len(self._values) <= 1
        return remap

    # rank lookup for range predicates on sorted dictionaries
    def rank_lower(self, value: "bytes | str") -> int:
        import bisect

        if isinstance(value, str):
            value = value.encode("utf-8")
        return bisect.bisect_left(self._values, value)


# ---------------------------------------------------------------------------
# Column
# ---------------------------------------------------------------------------

_DTYPE_FOR_KIND = {
    TypeKind.INT: np.int64,
    TypeKind.UINT: np.int64,
    TypeKind.DECIMAL: np.int64,
    TypeKind.DATE: np.int64,
    TypeKind.DATETIME: np.int64,
    TypeKind.DURATION: np.int64,
    TypeKind.NULLTYPE: np.int64,
    TypeKind.FLOAT: np.float64,
    TypeKind.STRING: np.int32,
}


@dataclass
class Column:
    """Fixed-width data lane + validity mask (+ dictionary for strings)."""

    data: np.ndarray
    validity: np.ndarray  # bool, True = not NULL
    ftype: FieldType
    dictionary: Dictionary | None = None

    def __post_init__(self):
        if self.data.shape != self.validity.shape:
            raise ValueError(
                f"data/validity length mismatch: {self.data.shape} vs {self.validity.shape}"
            )

    def __len__(self) -> int:
        return len(self.data)

    @property
    def null_count(self) -> int:
        return int(len(self.validity) - self.validity.sum())

    # -- constructors -----------------------------------------------------
    @staticmethod
    def empty(ftype: FieldType, dictionary: Dictionary | None = None) -> "Column":
        dt = _DTYPE_FOR_KIND[ftype.kind]
        return Column(np.empty(0, dtype=dt), np.empty(0, dtype=bool), ftype, dictionary)

    @staticmethod
    def from_values(values: Iterable, ftype: FieldType, dictionary: Dictionary | None = None) -> "Column":
        """Build from logical Python values (None → NULL). Strings encode into
        ``dictionary`` (created on the fly if absent)."""
        values = list(values)
        n = len(values)
        dt = _DTYPE_FOR_KIND[ftype.kind]
        data = np.zeros(n, dtype=dt)
        validity = np.ones(n, dtype=bool)
        k = ftype.kind
        if k == TypeKind.STRING:
            if dictionary is None:
                dictionary = Dictionary()
            for i, v in enumerate(values):
                if v is None or v is NULL:
                    validity[i] = False
                else:
                    data[i] = dictionary.encode(v)
        else:
            for i, v in enumerate(values):
                if v is None or v is NULL:
                    validity[i] = False
                elif k == TypeKind.DECIMAL:
                    data[i] = int(round(float(v) * (10**ftype.scale)))
                elif k == TypeKind.DATE and not isinstance(v, (int, np.integer)):
                    data[i] = date_to_days(v)
                elif k == TypeKind.DATETIME and not isinstance(v, (int, np.integer)):
                    data[i] = datetime_to_micros(v)
                elif k == TypeKind.DURATION and not isinstance(v, (int, np.integer)):
                    data[i] = duration_to_micros(v)
                elif k == TypeKind.UINT and v >= (1 << 63):
                    data[i] = int(v) - (1 << 64)  # two's complement wrap
                else:
                    data[i] = v
        return Column(data, validity, ftype, dictionary)

    # -- access -----------------------------------------------------------
    def logical_value(self, i: int):
        """Decode row i back to a logical Python value."""
        if not self.validity[i]:
            return None
        v = self.data[i]
        k = self.ftype.kind
        if k == TypeKind.STRING:
            return self.dictionary.decode(int(v)).decode("utf-8", "replace")
        if k == TypeKind.DECIMAL:
            s = self.ftype.scale
            iv = int(v)
            if s == 0:
                return iv
            from decimal import Decimal

            # scaleb keeps the declared scale (5.00, not 5) like MySQL
            return Decimal(iv).scaleb(-s)
        if k == TypeKind.DATE:
            return days_to_date(int(v))
        if k == TypeKind.DATETIME:
            return micros_to_datetime(int(v))
        if k == TypeKind.DURATION:
            return micros_to_duration(int(v))
        if k == TypeKind.FLOAT:
            return float(v)
        if k == TypeKind.UINT and v < 0:
            return int(v) + (1 << 64)  # undo two's complement wrap
        return int(v)

    def to_list(self) -> list:
        return [self.logical_value(i) for i in range(len(self))]

    # -- transforms -------------------------------------------------------
    def take(self, idx: np.ndarray) -> "Column":
        return Column(self.data[idx], self.validity[idx], self.ftype, self.dictionary)

    def slice(self, start: int, stop: int) -> "Column":
        return Column(self.data[start:stop], self.validity[start:stop], self.ftype, self.dictionary)

    def pad_to(self, n: int) -> "Column":
        """Pad with NULL rows up to length n (device batching)."""
        cur = len(self)
        if cur == n:
            return self
        if n < cur:
            raise ValueError(f"pad_to({n}) would truncate a {cur}-row column")
        data = np.zeros(n, dtype=self.data.dtype)
        data[:cur] = self.data
        validity = np.zeros(n, dtype=bool)
        validity[:cur] = self.validity
        return Column(data, validity, self.ftype, self.dictionary)

    @staticmethod
    def concat(cols: Sequence["Column"]) -> "Column":
        if not cols:
            raise ValueError("Column.concat of an empty sequence")
        first = cols[0]
        # dictionaries must be shared (same object) to concat raw codes
        for c in cols[1:]:
            if c.dictionary is not first.dictionary:
                raise ValueError("concat across dictionaries requires re-encode")
        return Column(
            np.concatenate([c.data for c in cols]),
            np.concatenate([c.validity for c in cols]),
            first.ftype,
            first.dictionary,
        )


# ---------------------------------------------------------------------------
# Chunk
# ---------------------------------------------------------------------------


@dataclass
class Chunk:
    """Equal-length list of Columns; the unit flowing through the Volcano tree
    and across the wire (ref: chunk.Chunk, chunk.go:35)."""

    columns: list[Column] = field(default_factory=list)

    def __len__(self) -> int:
        return len(self.columns[0]) if self.columns else 0

    @property
    def num_cols(self) -> int:
        return len(self.columns)

    def row(self, i: int) -> tuple:
        return tuple(c.logical_value(i) for c in self.columns)

    def rows(self) -> list[tuple]:
        return [self.row(i) for i in range(len(self))]

    def take(self, idx: np.ndarray) -> "Chunk":
        return Chunk([c.take(idx) for c in self.columns])

    def slice(self, start: int, stop: int) -> "Chunk":
        return Chunk([c.slice(start, stop) for c in self.columns])

    @staticmethod
    def concat(chunks: Sequence["Chunk"]) -> "Chunk":
        if not chunks:
            raise ValueError("Chunk.concat of an empty sequence")
        ncols = chunks[0].num_cols
        return Chunk([Column.concat([ch.columns[i] for ch in chunks]) for i in range(ncols)])


# ---------------------------------------------------------------------------
# Padding buckets — keep XLA shape cache small
# ---------------------------------------------------------------------------

_MIN_BUCKET = 1024


def bucket_size(n: int) -> int:
    """Smallest power-of-two ≥ n (min 1024). All device kernels take padded
    batches of bucketed length + a row-count scalar, so recompilation happens
    O(log max_rows) times per DAG shape rather than per batch."""
    b = _MIN_BUCKET
    while b < n:
        b <<= 1
    return b


# ---------------------------------------------------------------------------
# Wire codec (length-prefixed raw buffers)
# ---------------------------------------------------------------------------

_MAGIC = b"TCHK"
_KIND_CODE = {k: i for i, k in enumerate(TypeKind)}
_CODE_KIND = {i: k for k, i in _KIND_CODE.items()}


def encode_chunk(chunk: Chunk) -> bytes:
    """Serialize (dictionary values travel with the column — fine for results;
    storage-side columns share table-level dictionaries and skip this)."""
    out = [_MAGIC, struct.pack("<ii", chunk.num_cols, len(chunk))]
    for col in chunk.columns:
        ft = col.ftype
        out.append(struct.pack("<bhhb", _KIND_CODE[ft.kind], ft.length, ft.scale, int(ft.nullable)))
        vbytes = np.packbits(col.validity).tobytes()
        out.append(struct.pack("<i", len(vbytes)))
        out.append(vbytes)
        dbytes = np.ascontiguousarray(col.data).tobytes()
        out.append(struct.pack("<i", len(dbytes)))
        out.append(dbytes)
        if ft.kind == TypeKind.STRING:
            vals = col.dictionary.values_array() if col.dictionary else []
            out.append(struct.pack("<i", len(vals)))
            for v in vals:
                out.append(struct.pack("<i", len(v)))
                out.append(v)
    return b"".join(out)


def decode_chunk(buf: bytes) -> Chunk:
    if buf[:4] != _MAGIC:
        raise ValueError("bad chunk magic (corrupt or truncated frame)")
    off = 4
    ncols, nrows = struct.unpack_from("<ii", buf, off)
    off += 8
    cols = []
    for _ in range(ncols):
        kc, length, scale, nullable = struct.unpack_from("<bhhb", buf, off)
        off += 6
        ft = FieldType(_CODE_KIND[kc], length=length, scale=scale, nullable=bool(nullable))
        (vlen,) = struct.unpack_from("<i", buf, off)
        off += 4
        validity = np.unpackbits(np.frombuffer(buf, dtype=np.uint8, count=vlen, offset=off))[:nrows].astype(bool)
        off += vlen
        (dlen,) = struct.unpack_from("<i", buf, off)
        off += 4
        data = np.frombuffer(buf, dtype=_DTYPE_FOR_KIND[ft.kind], count=nrows, offset=off).copy()
        off += dlen
        dictionary = None
        if ft.kind == TypeKind.STRING:
            (nvals,) = struct.unpack_from("<i", buf, off)
            off += 4
            vals = []
            for _ in range(nvals):
                (ln,) = struct.unpack_from("<i", buf, off)
                off += 4
                vals.append(buf[off : off + ln])
                off += ln
            dictionary = Dictionary(vals)
        cols.append(Column(data, validity, ft, dictionary))
    return Chunk(cols)
