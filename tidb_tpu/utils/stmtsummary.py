"""Statement summary + slow query log (ref: util/stmtsummary — per-digest
aggregates surfaced via information_schema.statements_summary; and the slow
query log surfaced via information_schema.slow_query)."""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from dataclasses import asdict, dataclass, field, fields


# digest memo: normalizing re-tokenizes the whole statement (a full lexer
# pass — as costly as a parse), and the hot path needs it per statement for
# stmt-summary/bindings/Top-SQL; warm statements take a dict hit instead
_DIGEST_MEMO: "OrderedDict[str, str]" = OrderedDict()
_DIGEST_MEMO_CAP = 512
_DIGEST_MU = threading.Lock()


def digest(sql: str) -> str:
    """Normalized SQL digest: literals → '?', whitespace folded, lowercased
    keywords (ref: parser/digester.go). Memoized per statement text."""
    with _DIGEST_MU:
        hit = _DIGEST_MEMO.get(sql)
        if hit is not None:
            _DIGEST_MEMO.move_to_end(sql)
            return hit
    d = _digest_uncached(sql)
    with _DIGEST_MU:
        _DIGEST_MEMO[sql] = d
        while len(_DIGEST_MEMO) > _DIGEST_MEMO_CAP:
            _DIGEST_MEMO.popitem(last=False)
    return d


def _digest_uncached(sql: str) -> str:
    import hashlib

    from tidb_tpu.parser.lexer import tokenize

    try:
        toks = tokenize(sql)
    except Exception:
        return hashlib.sha256(sql.encode()).hexdigest()[:16] + "|" + sql[:64]
    parts = []
    for t in toks:
        if t.kind in ("int", "float", "str"):
            parts.append("?")
        elif t.kind == "eof":
            break
        elif t.kind == "ident":
            parts.append(t.value.lower())
        else:
            parts.append(str(t.value))
    norm = " ".join(parts)
    return hashlib.sha256(norm.encode()).hexdigest()[:16] + "|" + norm[:256]


@dataclass
class StmtStats:
    digest: str
    sample: str
    exec_count: int = 0
    sum_latency: float = 0.0
    max_latency: float = 0.0
    sum_rows: int = 0
    last_seen: float = field(default_factory=time.time)
    # distributed exec-details (ref: statements_summary SUM_BACKOFF_TIME /
    # SUM_COP_TASK_NUM columns), fed from the wire-shipped sidecars
    plan_digest: str = ""
    sum_backoff: float = 0.0  # seconds
    sum_cop_tasks: int = 0
    # peak per-statement memory (utils/memory.Tracker root max_consumed) —
    # the statements_summary MAX_MEM column (OOM forensics without a repro)
    max_mem: int = 0
    # workload attribution: request units this digest consumed and the
    # resource group its sessions ran under (statements_summary SUM_RU /
    # RESOURCE_GROUP; metering only)
    sum_ru: float = 0.0
    resource_group: str = ""

    @property
    def avg_latency(self) -> float:
        return self.sum_latency / self.exec_count if self.exec_count else 0.0

    def to_pb(self) -> dict:
        """Wire form for the sys_snapshot introspection verb (the fleet-wide
        cluster_statements_summary rows travel as these dicts)."""
        d = asdict(self)
        d["avg_latency"] = self.avg_latency
        return d

    @classmethod
    def from_pb(cls, pb: dict) -> "StmtStats":
        """Inverse of ``to_pb`` (derived/unknown keys ignored, missing keys
        default) — the cluster_* memtables rebuild real records from wire
        dicts so the dataclass is the ONE home of the field set."""
        names = {f.name for f in fields(cls)}
        d = {k: v for k, v in pb.items() if k in names}
        d.setdefault("digest", "")
        d.setdefault("sample", "")
        return cls(**d)


@dataclass
class SlowEntry:
    """One slow-log ring record (ref: the slow query log's structured
    fields — Plan_digest, Cop_time, Backoff_time, the max-task store)."""

    time: float
    sql: str
    latency_s: float
    rows: int
    user: str
    digest: str = ""
    plan_digest: str = ""
    cop_tasks: int = 0
    cop_proc_max_ms: float = 0.0
    backoff_ms: float = 0.0
    resplits: int = 0
    max_task_store: str = ""
    cop_summary: str = ""
    # when the statement was trace-sampled, the reservoir key an operator
    # pivots to for the full span tree (GET /traces?id=<trace_id>)
    trace_id: str = ""
    # the statement's memory-tracker peak (bytes) — slow_query.MEM_MAX
    mem_max: int = 0
    # event-log cross-links, captured at record time when the statement was
    # trace-sampled: how many events carried its trace_id, and the first
    # ERROR-level one (component.event) — the "what went wrong first" pivot
    events: int = 0
    first_error: str = ""
    # workload attribution: the statement's metered request units and its
    # session's resource group (slow_query RU / RESOURCE_GROUP)
    ru: float = 0.0
    resource_group: str = ""

    def __iter__(self):
        # legacy 5-tuple shape for pre-structured consumers
        return iter((self.time, self.sql, self.latency_s, self.rows, self.user))

    def to_pb(self) -> dict:
        """Wire form for the sys_snapshot verb (cluster_slow_query rows)."""
        return asdict(self)

    @classmethod
    def from_pb(cls, pb: dict) -> "SlowEntry":
        """Inverse of ``to_pb`` (see StmtStats.from_pb)."""
        names = {f.name for f in fields(cls)}
        d = {k: v for k, v in pb.items() if k in names}
        for req, dflt in (("time", 0.0), ("sql", ""), ("latency_s", 0.0),
                          ("rows", 0), ("user", "")):
            d.setdefault(req, dflt)
        return cls(**d)


class StmtSummary:
    def __init__(self, capacity: int = 200, slow_capacity: int = 512):
        self._mu = threading.Lock()
        self._stats: OrderedDict[str, StmtStats] = OrderedDict()
        self.capacity = capacity
        # slow log ring of SlowEntry records
        self._slow: deque = deque(maxlen=slow_capacity)

    def record(
        self,
        sql: str,
        latency_s: float,
        rows: int,
        user: str,
        slow_threshold_s: float,
        digest_val: "str | None" = None,
        plan_digest: str = "",
        cop=None,
        trace_id: str = "",
        mem_max: int = 0,
        ru: float = 0.0,
        resource_group: str = "",
    ) -> None:
        # the session computes one digest per statement and threads it here
        # (plus Top-SQL/bindings) instead of re-normalizing per consumer;
        # ``cop`` is the statement's CopTasksSummary (or None)
        d = digest_val if digest_val is not None else digest(sql)
        with self._mu:
            st = self._stats.get(d)
            if st is None:
                st = StmtStats(d, sql[:256])
                self._stats[d] = st
                while len(self._stats) > self.capacity:
                    self._stats.popitem(last=False)
            st.exec_count += 1
            st.sum_latency += latency_s
            st.max_latency = max(st.max_latency, latency_s)
            st.sum_rows += rows
            st.last_seen = time.time()
            st.max_mem = max(st.max_mem, int(mem_max))
            st.sum_ru += ru
            if resource_group:
                st.resource_group = resource_group
            if plan_digest:
                st.plan_digest = plan_digest
            if cop is not None and cop.num:
                st.sum_backoff += cop.backoff_ms / 1000.0
                st.sum_cop_tasks += cop.num
            self._stats.move_to_end(d)
            if latency_s >= slow_threshold_s:
                e = SlowEntry(
                    time.time(), sql[:512], latency_s, rows, user,
                    digest=d.partition("|")[0], plan_digest=plan_digest,
                    trace_id=trace_id, mem_max=int(mem_max),
                    ru=ru, resource_group=resource_group,
                )
                if cop is not None and cop.num:
                    e.cop_tasks = cop.num
                    e.cop_proc_max_ms = cop.max_proc_ms
                    e.backoff_ms = cop.backoff_ms
                    e.resplits = cop.resplits
                    e.max_task_store = cop.max_task_store
                    e.cop_summary = cop.render()
                if trace_id:
                    # slow statements are rare — a ring scan here is fine,
                    # and the cross-link makes the entry self-diagnosing
                    from tidb_tpu.utils import eventlog as _evlog

                    evs = _evlog.get().for_trace(trace_id)
                    e.events = len(evs)
                    for ev in evs:
                        if ev[1] >= _evlog.ERROR:
                            e.first_error = f"{ev[2]}.{ev[3]}"
                            break
                self._slow.append(e)

    def stats(self) -> list[StmtStats]:
        with self._mu:
            return list(self._stats.values())

    def slow_queries(self) -> list[SlowEntry]:
        with self._mu:
            return list(self._slow)

    def clear(self) -> None:
        with self._mu:
            self._stats.clear()
            self._slow.clear()
