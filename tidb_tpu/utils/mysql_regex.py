"""MySQL/ICU regular-expression dialect → Python ``re`` translation.

Reference parity: pkg/expression/builtin_regexp.go (ICU under the hood since
MySQL 8.0). The dialect differences that matter in practice:

- POSIX bracket classes inside character classes: ``[[:alpha:]]``,
  ``[[:digit:]]``, ``[[:space:]]``, ... (ICU and the old Henry Spencer
  engine both accept these; Python ``re`` does not).
- Word-boundary markers ``[[:<:]]`` / ``[[:>:]]`` (legacy MySQL syntax,
  still accepted by MySQL 8 which rewrites them to ``\\b{w}``).

Everything else Python ``re`` shares with ICU closely enough for the
supported surface (alternation, groups, greedy/lazy quantifiers, anchors,
escapes); genuinely ICU-only syntax still raises MySQL error 3685 through
``re.error`` at compile time.
"""

from __future__ import annotations

import re as _re

# Python equivalents of the POSIX classes, for use INSIDE a character class
_CLASS_MAP = {
    "alnum": r"0-9A-Za-z",
    "alpha": r"A-Za-z",
    "blank": r" \t",
    "cntrl": r"\x00-\x1f\x7f",
    "digit": r"0-9",
    "graph": r"\x21-\x7e",
    "lower": r"a-z",
    "print": r"\x20-\x7e",
    "punct": r"!-/:-@\[-`{-~",
    "space": r"\s",
    "upper": r"A-Z",
    "xdigit": r"0-9A-Fa-f",
    "word": r"0-9A-Za-z_",
}


def translate(pattern: str) -> str:
    """MySQL regexp dialect → Python re pattern."""
    out = []
    i = 0
    n = len(pattern)
    while i < n:
        ch = pattern[i]
        if ch == "\\" and i + 1 < n:
            out.append(pattern[i : i + 2])
            i += 2
            continue
        if pattern.startswith("[[:<:]]", i):
            out.append(r"\b(?=\w)")
            i += 7
            continue
        if pattern.startswith("[[:>:]]", i):
            out.append(r"\b(?<=\w)")
            i += 7
            continue
        if ch == "[":
            # character class: scan to its closing ], expanding [:name:]
            j = i + 1
            cls = ["["]
            if j < n and pattern[j] == "^":
                cls.append("^")
                j += 1
            if j < n and pattern[j] == "]":  # leading ] is a literal
                cls.append(r"\]")
                j += 1
            while j < n and pattern[j] != "]":
                if pattern[j] == "[" and pattern.startswith("[:", j):
                    k = pattern.find(":]", j + 2)
                    if k == -1:
                        raise ValueError("Invalid regular expression: unterminated [: :]")
                    name = pattern[j + 2 : k]
                    body = _CLASS_MAP.get(name)
                    if body is None:
                        raise ValueError(f"Invalid regular expression: unknown class [:{name}:]")
                    cls.append(body)
                    j = k + 2
                elif pattern[j] == "\\" and j + 1 < n:
                    cls.append(pattern[j : j + 2])
                    j += 2
                else:
                    cls.append(pattern[j])
                    j += 1
            if j >= n:
                raise ValueError("Invalid regular expression: unterminated [")
            cls.append("]")
            out.append("".join(cls))
            i = j + 1
            continue
        out.append(ch)
        i += 1
    return "".join(out)


def compile(pattern: str, flags: int = 0):
    """Translate + compile; re.error maps to MySQL's 3685 at the caller."""
    return _re.compile(translate(pattern), flags)
