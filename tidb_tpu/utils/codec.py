"""Memcomparable key codec.

Reference parity: pkg/util/codec (EncodeInt/EncodeBytes/...). The algorithm is
the standard order-preserving encoding used by TiKV-family stores, implemented
here from its published semantics:

- ints: 8-byte big-endian with the sign bit flipped (so byte order == numeric
  order across negatives);
- floats: IEEE bits; positive values flip the sign bit, negative values flip
  all bits;
- bytes: chunked into 8-byte zero-padded groups, each followed by a marker
  byte: 0xFF when the group is full and more data follows, else
  0xFF - pad_count. memcmp order == byte-string order, and encodings are
  prefix-free.
- every encoded datum is prefixed by a flag byte so heterogeneous tuples sort
  type-major (NIL < bytes < int < uint < float is NOT the MySQL order, so we
  use the reference's flag values: NIL=0, BYTES=1, INT=3, UINT=4, FLOAT=5).
"""

from __future__ import annotations

import struct

SIGN_MASK = 0x8000000000000000

NIL_FLAG = 0x00
BYTES_FLAG = 0x01
INT_FLAG = 0x03
UINT_FLAG = 0x04
FLOAT_FLAG = 0x05

_ENC_GROUP_SIZE = 8
_ENC_MARKER = 0xFF
_ENC_PAD = 0x00


def encode_int_raw(v: int) -> bytes:
    """8-byte big-endian, sign bit flipped (no flag)."""
    return struct.pack(">Q", (v ^ SIGN_MASK) & 0xFFFFFFFFFFFFFFFF)


def decode_int_raw(b: bytes, off: int = 0) -> int:
    (u,) = struct.unpack_from(">Q", b, off)
    u ^= SIGN_MASK
    if u >= SIGN_MASK:
        u -= 1 << 64
    return u


def decode_uint_raw(b: bytes, off: int = 0) -> int:
    (u,) = struct.unpack_from(">Q", b, off)
    return u


def encode_bytes_raw(data: bytes) -> bytes:
    """Group encoding: emit 8 data bytes (zero-padded) + marker byte
    (0xFF if full group and not last; else 247+len_of_valid)."""
    out = bytearray()
    i = 0
    n = len(data)
    while True:
        group = data[i : i + _ENC_GROUP_SIZE]
        pad = _ENC_GROUP_SIZE - len(group)
        out += group
        out += bytes([_ENC_PAD]) * pad
        if pad == 0:
            out.append(_ENC_MARKER)
        else:
            out.append(_ENC_MARKER - pad)
            break
        i += _ENC_GROUP_SIZE
        if i == n:
            # exactly consumed; need a terminating empty group
            out += bytes([_ENC_PAD]) * _ENC_GROUP_SIZE
            out.append(_ENC_MARKER - _ENC_GROUP_SIZE)
            break
    return bytes(out)


def decode_bytes_raw(b: bytes, off: int = 0) -> tuple[bytes, int]:
    """Returns (data, new_offset)."""
    out = bytearray()
    while True:
        group = b[off : off + _ENC_GROUP_SIZE]
        marker = b[off + _ENC_GROUP_SIZE]
        off += _ENC_GROUP_SIZE + 1
        if marker == _ENC_MARKER:
            out += group
        else:
            pad = _ENC_MARKER - marker
            out += group[: _ENC_GROUP_SIZE - pad]
            return bytes(out), off


def _float_to_ordered_u64(f: float) -> int:
    (u,) = struct.unpack(">Q", struct.pack(">d", f))
    if u & SIGN_MASK:
        u = (~u) & 0xFFFFFFFFFFFFFFFF
    else:
        u |= SIGN_MASK
    return u


def _ordered_u64_to_float(u: int) -> float:
    if u & SIGN_MASK:
        u &= ~SIGN_MASK & 0xFFFFFFFFFFFFFFFF
    else:
        u = (~u) & 0xFFFFFFFFFFFFFFFF
    return struct.unpack(">d", struct.pack(">Q", u))[0]


# -- flagged datum encoding (index key values) ------------------------------


def encode_key_int(v: int) -> bytes:
    return bytes([INT_FLAG]) + encode_int_raw(v)


def encode_key_float(v: float) -> bytes:
    return bytes([FLOAT_FLAG]) + struct.pack(">Q", _float_to_ordered_u64(v))


def encode_key_bytes(v: bytes) -> bytes:
    return bytes([BYTES_FLAG]) + encode_bytes_raw(v)


def encode_key_nil() -> bytes:
    return bytes([NIL_FLAG])


def decode_key_one(b: bytes, off: int = 0):
    """Decode one flagged datum → (value, new_offset). NULL → None."""
    flag = b[off]
    off += 1
    if flag == NIL_FLAG:
        return None, off
    if flag == INT_FLAG:
        return decode_int_raw(b, off), off + 8
    if flag == UINT_FLAG:
        return decode_uint_raw(b, off), off + 8
    if flag == FLOAT_FLAG:
        (u,) = struct.unpack_from(">Q", b, off)
        return _ordered_u64_to_float(u), off + 8
    if flag == BYTES_FLAG:
        return decode_bytes_raw(b, off)
    raise ValueError(f"unknown datum flag {flag:#x}")


