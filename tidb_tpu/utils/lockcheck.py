"""Runtime lock-order cycle detection: the would-deadlock detector.

Deadlocks are the worst CI failure mode this repo has paid for: the PR 1
``_MESH_EXEC_LOCK`` hang (two concurrent shard_map programs starving the
XLA CPU client's collective rendezvous) walled the whole tier-1 suite at
test_disttask for ~700 seconds with zero diagnostics, and only reproduced
on 2-core hosts. A lock-ORDER inversion has the same shape — it needs the
unlucky interleaving to actually deadlock, so tests pass for months until
one CI host loses the race and hangs forever.

This module makes the inversion itself the error, deterministically: an
opt-in instrumented wrapper around ``threading.Lock``/``RLock`` records the
per-thread set of held locks and the global acquisition-order graph (edge
A→B = "B was acquired while A was held", per lock INSTANCE so two
instances of one class never alias). The moment an acquisition would close
a cycle — even single-threaded, even if the other order ran minutes
earlier — the acquire raises :class:`LockOrderError` naming both creation
sites and the path, instead of some future run hanging.

Opt-in: ``TIDB_TPU_LOCKCHECK=1`` + :func:`install` (tests/conftest.py does
both for tier-1, so every suite run is a deadlock-freedom proof over the
lock orders it actually exercised). ``install()`` patches the
``threading.Lock``/``RLock`` factories, so only locks created AFTER it are
instrumented — stdlib locks bound at interpreter start stay plain, and
:func:`uninstall` restores the originals. The overhead budget is enforced,
not hoped for: the ``graftcheck_runtime_overhead_ms`` benchdaily lane
fails if the instrumented warm-query path costs more than 5% over plain
(ref: TiKV's deadlock detector and abseil's ABSL_ANNOTATE deadlock check,
both of which run in test builds by default).

The static half of this check lives in ``tidb_tpu.tools.check`` (rule
GC-LOCK-ORDER builds the same graph from the AST); this runtime half
catches what static resolution can't see — locks reached through dynamic
dispatch, callbacks, and cross-process server threads.
"""

from __future__ import annotations

import os
import sys
import threading
import weakref

__all__ = [
    "LockOrderError",
    "Lock",
    "RLock",
    "install",
    "uninstall",
    "installed",
    "enabled",
    "reset",
]

ENV_KNOB = "TIDB_TPU_LOCKCHECK"


class LockOrderError(RuntimeError):
    """An acquisition closed a lock-order cycle: with the right thread
    interleaving this program CAN deadlock. ``cycle`` carries the creation
    sites along the closed path, first element = the lock being acquired."""

    def __init__(self, msg: str, cycle: list):
        super().__init__(msg)
        self.cycle = cycle


# the detector's own structures use the ORIGINAL lock type (bound at import,
# before install() can patch the factories) — the detector must never
# instrument itself
_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock

_graph_mu = _ORIG_LOCK()
# lock id → set of lock ids acquired while it was held (the order graph)
_succ: dict[int, set] = {}
# (outer id, inner id) → True for edges already recorded (lock-free fast path)
_edges: dict = {}
# lock id → creation site ("file:line") for error messages
_sites: dict[int, str] = {}
_tls = threading.local()


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _site(depth: int) -> str:
    try:
        f = sys._getframe(depth)
        return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"
    except Exception:
        return "?"


# dead-lock ids queued by GC finalizers. The finalizer must NOT take
# _graph_mu: finalizers run at arbitrary allocation points — including
# inside _path's list building while THIS thread already holds the mutex —
# and a plain lock self-deadlocks (first suite run hung exactly there).
# list.append is GIL-atomic, so the queue needs no lock; the next locked
# operation drains it.
_dead: list = []


def _forget(lid: int) -> None:
    """GC hook (weakref.finalize on every wrapper): queue the dead lock's
    id so a recycled id() can never alias it into someone else's edge."""
    _dead.append(lid)  # GIL-atomic, lock-free by design  # graftcheck: off=shared-mutation


def _purge_locked(lid: int) -> None:
    """Remove one node and its edges. Caller holds _graph_mu (the lock is
    taken one frame up, so the suppressions below document what the static
    rule cannot see)."""
    _succ.pop(lid, None)  # graftcheck: off=shared-mutation (under _graph_mu)
    _sites.pop(lid, None)  # graftcheck: off=shared-mutation (under _graph_mu)
    for s in _succ.values():
        s.discard(lid)
    for k in [k for k in _edges if lid in k]:
        _edges.pop(k, None)  # graftcheck: off=shared-mutation (under _graph_mu)


def _drain_dead_locked() -> None:
    """Drop queued dead nodes from the graph. Caller holds _graph_mu."""
    while _dead:
        _purge_locked(_dead.pop())  # graftcheck: off=shared-mutation (under caller's _graph_mu)


def _path(frm: int, to: int) -> "list | None":
    """DFS over _succ: ids along a path frm→…→to, or None. Caller holds
    _graph_mu."""
    stack = [(frm, [frm])]
    seen = {frm}
    while stack:
        node, path = stack.pop()
        if node == to:
            return path
        for nxt in _succ.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _note_acquire(lk: "_CheckedLock") -> None:
    held = _held()
    me = id(lk)
    for h in held:
        if h is lk:  # RLock re-entry: no new ordering information
            held.append(lk)
            return
    for h in held:
        a = id(h)
        if a == me or (a, me) in _edges:
            continue
        with _graph_mu:
            _drain_dead_locked()
            # adding a→me closes a cycle iff me already reaches a
            cyc = _path(me, a)
            if cyc is not None:
                sites = [_sites.get(i, "?") for i in cyc]
                raise LockOrderError(
                    "lock-order cycle: acquiring lock created at "
                    f"{_sites.get(me, '?')} while holding lock created at "
                    f"{_sites.get(a, '?')}, but the reverse order "
                    f"{' -> '.join(sites)} was already observed — with the "
                    "right thread interleaving this deadlocks",
                    cycle=sites + [_sites.get(a, "?")],
                )
            _succ.setdefault(a, set()).add(me)
            _edges[(a, me)] = True
    held.append(lk)


def _note_release(lk: "_CheckedLock", all_levels: bool = False) -> int:
    """Remove lk from the held list (innermost entry, or every recursion
    level). Returns how many entries were removed — Condition.wait's
    release/restore cycle must re-append exactly that many."""
    held = getattr(_tls, "held", None)
    if not held:
        return 0
    removed = 0
    for i in range(len(held) - 1, -1, -1):
        if held[i] is lk:
            del held[i]
            removed += 1
            if not all_levels:
                break
    return removed


class _CheckedLock:
    """Wraps one lock (plain or reentrant). Implements enough of the
    internal Condition protocol (_is_owned/_release_save/_acquire_restore)
    that ``threading.Condition``/``Event``/``Queue`` built on a checked lock
    keep exact stdlib semantics."""

    def __init__(self, inner, site: str):
        self._inner = inner
        self._site = site
        me = id(self)
        with _graph_mu:
            # id() reuse: if this object recycled a dead wrapper's address,
            # that wrapper's stale edges must die NOW — a leftover A→B edge
            # attributed to our fresh id manufactures false cycles (first
            # seen as a phantom DDLWorker _mu/_run_mu inversion when a new
            # worker's locks landed on its predecessor's freed slots). The
            # finalizer ran at free time, so a recycled id is necessarily
            # still in _sites (not yet drained) or queued in _dead — an O(1)
            # membership guard keeps the O(graph) purge off the common
            # fresh-id construction path.
            if me in _sites or me in _dead:
                _purge_locked(me)
                try:
                    _dead.remove(me)  # graftcheck: off=shared-mutation (under _graph_mu)
                except ValueError:
                    pass
            _sites[me] = site
        weakref.finalize(self, _forget, me)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            try:
                _note_acquire(self)
            except LockOrderError:
                self._inner.release()  # fail the acquire, don't leak the hold
                raise
        return got

    def release(self) -> None:
        self._inner.release()
        _note_release(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # -- Condition wait() protocol ------------------------------------------
    def _is_owned(self) -> bool:
        io = getattr(self._inner, "_is_owned", None)
        if io is not None:
            return io()
        # plain lock: the stdlib probe — if we can grab it, we didn't own it
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _release_save(self):
        # Condition.wait fully releases a re-entrantly held RLock; carry the
        # recursion depth in our saved state so restore re-appends exactly
        # that many held entries — re-appending one would leave the thread
        # holding the lock with an EMPTY held record, silently blinding the
        # detector to every ordering edge through this lock afterwards
        n = _note_release(self, all_levels=True)
        rs = getattr(self._inner, "_release_save", None)
        inner_state = rs() if rs is not None else self._inner.release()
        return (inner_state, max(n, 1))

    def _acquire_restore(self, state) -> None:
        inner_state, n = state
        ar = getattr(self._inner, "_acquire_restore", None)
        if ar is not None:
            ar(inner_state)
        else:
            self._inner.acquire()
        for _ in range(n):
            _note_acquire(self)

    def __getattr__(self, name: str):
        # stdlib internals poke lock-protocol attrs we don't wrap
        # (_at_fork_reinit, _recursion_count, ...) — delegate verbatim
        return getattr(self._inner, name)

    def __repr__(self) -> str:
        return f"<lockcheck {self._inner!r} @ {self._site}>"


def Lock() -> _CheckedLock:
    """Instrumented ``threading.Lock`` (what the patched factory returns)."""
    return _CheckedLock(_ORIG_LOCK(), _site(2))


def RLock() -> _CheckedLock:
    return _CheckedLock(_ORIG_RLOCK(), _site(2))


def enabled() -> bool:
    return os.environ.get(ENV_KNOB, "") == "1"


_installed = False


def installed() -> bool:
    return _installed


def install(force: bool = False) -> bool:
    """Patch the ``threading.Lock``/``RLock`` factories so every lock
    created from here on is order-checked. No-op unless ``force`` or the
    ``TIDB_TPU_LOCKCHECK=1`` env knob is set. Returns whether installed.
    ``threading.Condition()`` (and Event/Queue on top of it) picks the
    checked factory up automatically at construction time."""
    global _installed
    if _installed:
        return True
    if not (force or enabled()):
        return False
    threading.Lock = Lock  # type: ignore[assignment]
    threading.RLock = RLock  # type: ignore[assignment]
    _installed = True
    return True


def uninstall() -> None:
    global _installed
    if not _installed:
        return
    threading.Lock = _ORIG_LOCK  # type: ignore[assignment]
    threading.RLock = _ORIG_RLOCK  # type: ignore[assignment]
    _installed = False


def reset() -> None:
    """Drop every recorded edge (tests: isolate one scenario's graph from
    the suite-wide history; existing locks stay instrumented)."""
    with _graph_mu:
        _drain_dead_locked()
        _succ.clear()
        _edges.clear()
