"""In-process metrics history: a bounded ring time-series recorder.

Reference parity: the ``metrics_schema`` time-series views TiDB fronts a
Prometheus with — except here there is no external scraper: one daemon
thread samples ``utils/metrics.REGISTRY`` every
``[observability] metrics-history-interval-s`` seconds into per-series
rings bounded by ``metrics-history-retention`` (Monarch's in-process
collection idiom). "What did ``qps`` / ``mpp_shard_seconds`` look like
five minutes ago" becomes one query against
``information_schema.metrics_history`` (or ``GET /metrics/history``), and
the fleet-wide variant rides the ``sys_snapshot`` introspection verb
(``information_schema.cluster_metrics_history``).

Footprint discipline: counters/gauges record one point per label set per
tick (plus a ``__total__`` roll-up per metric — the rate/QPS read);
histograms record ``<name>_sum`` and ``<name>_count``. Series count is
capped; each ring holds ``retention/interval`` points of two floats. The
recorder is refcounted — the server boot paths and ``DB.start_background``
start it, and the thread (named ``metrics-history``, covered by the test
suite's thread-hygiene guard) dies when the last holder stops it.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from tidb_tpu.utils import metrics as _metrics

# process birth (the uptime anchor for sys_snapshot reports)
PROC_START = time.time()

# the label-string key of the per-metric roll-up series (sum over every
# label combination — what rate()/QPS reads want)
TOTAL = "__total__"


class MetricsHistory:
    """Bounded per-series rings of (unix_ts, value) samples."""

    def __init__(
        self,
        interval_s: float = 5.0,
        retention_s: float = 600.0,
        registry=None,
        max_series: int = 512,
    ):
        self.interval_s = float(interval_s)
        self.retention_s = float(retention_s)
        self._registry = registry if registry is not None else _metrics.REGISTRY
        self._mu = threading.Lock()
        self._series: dict[tuple[str, str], deque] = {}
        self._max_series = max(int(max_series), 8)
        self.dropped_series = 0
        self._refs = 0
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None

    # -- sampling ------------------------------------------------------------
    def _maxlen(self) -> int:
        iv = max(self.interval_s, 0.05)
        return max(int(self.retention_s / iv) + 1, 2)

    def sample_now(self, now: Optional[float] = None) -> None:
        """One synchronous sample of the whole registry (the recorder thread
        calls this per tick; tests call it directly for determinism)."""
        snap = self._registry.snapshot()
        t = time.time() if now is None else float(now)
        with self._mu:
            for name, m in snap.items():
                if m["kind"] == "histogram":
                    self._append((name + "_sum", ""), t, float(m["sum"]))
                    self._append((name + "_count", ""), t, float(m["count"]))
                    continue
                lnames = m["labels"]
                total = 0.0
                for key, v in m["values"]:
                    total += v
                    lbl = ",".join(f"{k}={val}" for k, val in zip(lnames, key))
                    self._append((name, lbl), t, float(v))
                if lnames:
                    # roll-up series: the one a rate/QPS read wants
                    self._append((name, TOTAL), t, float(total))
            _metrics.METRICS_HISTORY_POINTS.set(
                sum(len(d) for d in self._series.values())
            )

    def _append(self, key: tuple[str, str], t: float, v: float) -> None:
        ml = self._maxlen()
        d = self._series.get(key)
        if d is None:
            if len(self._series) >= self._max_series:
                self.dropped_series += 1
                return
            d = self._series[key] = deque(maxlen=ml)
        elif d.maxlen != ml:
            # interval/retention changed on a live recorder (benchdaily's
            # hostile-tick lane does this): re-bound the ring, or a series
            # born under a fast tick keeps a huge maxlen forever
            d = self._series[key] = deque(d, maxlen=ml)
        d.append((t, v))

    # -- reads ---------------------------------------------------------------
    def series(self, name: Optional[str] = None, since: Optional[float] = None):
        """→ [(name, labels, unix_ts, value)] sorted by (name, labels, ts)."""
        with self._mu:
            out = []
            for (n, lbl), d in sorted(self._series.items()):
                if name is not None and n != name:
                    continue
                for t, v in d:
                    if since is not None and t < since:
                        continue
                    out.append((n, lbl, t, v))
            return out

    def rate(self, name: str, labels: str = TOTAL, window_s: float = 60.0) -> float:
        """Recent per-second rate of a CUMULATIVE series (counter roll-up or
        a histogram's ``_count``): delta over the newest sample reaching back
        ``window_s`` (or the oldest retained). 0.0 when under two samples."""
        with self._mu:
            d = self._series.get((name, labels))
            if d is None and labels == TOTAL:
                # unlabeled counters record under "" (no roll-up needed)
                d = self._series.get((name, ""))
            if d is None or len(d) < 2:
                return 0.0
            t1, v1 = d[-1]
            t0, v0 = d[0]
            for t, v in reversed(d):
                if t1 - t >= window_s:
                    t0, v0 = t, v
                    break
            if t1 <= t0:
                return 0.0
            return max(v1 - v0, 0.0) / (t1 - t0)

    def points(self) -> int:
        with self._mu:
            return sum(len(d) for d in self._series.values())

    def clear(self) -> None:
        with self._mu:
            self._series.clear()

    # -- lifecycle (refcounted: server boot + DB.start_background share one
    # process recorder; the thread dies with the LAST stop()) ---------------
    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> None:
        with self._mu:
            self._refs += 1
            if self.running or self.interval_s <= 0:
                return
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="metrics-history"
            )
            self._thread.start()

    def _loop(self) -> None:
        stop = self._stop
        self.sample_now()  # short-lived processes still get one point
        while not stop.wait(max(self.interval_s, 0.05)):
            self.sample_now()

    def stop(self) -> None:
        with self._mu:
            self._refs = max(self._refs - 1, 0)
            if self._refs > 0 or self._thread is None:
                return
            stop, thread = self._stop, self._thread
            self._stop = self._thread = None
        if stop is not None:
            stop.set()
        if thread is not None:
            thread.join(timeout=5)


# -- process-global recorder --------------------------------------------------
_REC: Optional[MetricsHistory] = None
_REC_MU = threading.Lock()


def recorder() -> MetricsHistory:
    """The process recorder, built from ``[observability]`` config on first
    use. One per process: every starter (StoreServer, DB.start_background,
    the bootable server) shares it refcounted."""
    global _REC
    with _REC_MU:
        if _REC is None:
            from tidb_tpu import config as _config

            cfg = _config.current()
            _REC = MetricsHistory(
                interval_s=cfg.metrics_history_interval_s,
                retention_s=cfg.metrics_history_retention_s,
            )
        return _REC
