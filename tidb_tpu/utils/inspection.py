"""Rule-driven fleet inspection (ref: TiDB's ``information_schema.
inspection_result`` diagnosis framework, executor/inspection_result.go).

A small registry of pure rules reads three local substrates — the
``StoreHealthRegistry`` cache over ``sys_snapshot`` sweeps, the live
metrics registry (+ the metricshist rate reader), and the structured event
log — and turns them into ``(rule, item, status, value, reference,
detail)`` rows. ``status`` is one of ``ok | warning | critical``; every
critical row is echoed into the event log (component ``inspection``) so
the finding itself lands in ``cluster_log`` with a timestamp.

Rules never sweep the wire themselves: they read whatever the health
registry last cached (plus this process's own metrics), so a SELECT from
``information_schema.inspection_result`` stays cheap and deterministic —
run ``db.health.sweep()`` first when fleet freshness matters. Every input
arrives through :class:`InspectionContext`, so tests drive each rule to
warning/critical with synthetic values and zero cluster setup.

Threadless by construction (thread-hygiene): building a context and
evaluating rules spawns nothing and takes no locks beyond the substrates'
own snapshot reads.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Optional

from tidb_tpu.utils import eventlog as _ev

OK, WARNING, CRITICAL = "ok", "warning", "critical"


@dataclass
class InspectionContext:
    """Everything the rules read, decoupled from a live DB. ``from_db``
    fills it from the process's real substrates; tests construct it
    directly with synthetic values."""

    # instance → cached health entry ({"ok","error","shard","ts",...})
    health: dict = field(default_factory=dict)
    # instance → is_stale verdict (registry's freshness clock)
    stale: dict = field(default_factory=dict)
    # instance → seconds since last good report (None = never)
    staleness_s: dict = field(default_factory=dict)
    # per-shard placement weights (None = not a sharded fleet)
    weights: Optional[list] = None
    skew_ratio: float = 2.0
    # combined plan-cache outcome counts: {"hit": n, "miss": n}
    plan_cache: dict = field(default_factory=dict)
    # instance → device-cache resident bytes (local process under its
    # instance name when there is no fleet)
    cache_bytes: dict = field(default_factory=dict)
    hbm_budget: int = 0
    # Histogram.snapshot() of MPP_SHARD_SECONDS (or None)
    mpp_shards: Optional[dict] = None
    # recent backoff sleeps per second (metricshist rate)
    backoff_rate: float = 0.0
    # committed rows pending in delta overlays / the compactor threshold
    delta_rows: float = 0.0
    delta_merge_rows: int = 2048
    # (instance, region_id, table_id) → keys touched over the retained
    # traffic window (reads + writes), from cached heatmap report sections
    region_traffic: dict = field(default_factory=dict)

    @classmethod
    def from_db(cls, db) -> "InspectionContext":
        from tidb_tpu import config as _config
        from tidb_tpu.utils import metrics as _m
        from tidb_tpu.utils.metricshist import recorder

        cfg = _config.current()
        ctx = cls(
            skew_ratio=cfg.balancer_skew_ratio,
            delta_merge_rows=cfg.device_delta_merge_rows,
            hbm_budget=int(
                float(os.environ.get("TIDB_TPU_HBM_GB", "12")) * (1 << 30)
            ),
            mpp_shards=_m.MPP_SHARD_SECONDS.snapshot(),
            backoff_rate=recorder().rate("tidb_tpu_backoff_total"),
            delta_rows=float(_m.DEVICE_DELTA_ROWS.get()),
        )
        # plan-cache outcomes: session fast lane + instance cache combined
        for ctr in (_m.PLAN_CACHE, _m.INSTANCE_PLAN_CACHE):
            for key, v in ctr.snapshot()["values"]:
                k = key[0] if key else ""
                if k in ("hit", "miss"):
                    ctx.plan_cache[k] = ctx.plan_cache.get(k, 0) + v
        health = getattr(db, "health", None)
        if health is not None:
            ctx.health = health.reports()
            for inst, ent in ctx.health.items():
                ctx.stale[inst] = health.is_stale(inst)
                ctx.staleness_s[inst] = health.staleness_s(inst)
                rep = ent.get("report") or {}
                if "device_cache_bytes" in rep:
                    ctx.cache_bytes[inst] = rep["device_cache_bytes"]
                for hent in rep.get("heatmap", ()):
                    n = sum(b[1] + b[3] for b in hent["buckets"])
                    k = (inst, hent["region_id"], hent["table_id"])
                    ctx.region_traffic[k] = ctx.region_traffic.get(k, 0) + n
        if not ctx.cache_bytes:
            # no fleet cache — read this process's own device cache
            store = getattr(db, "store", None)
            from tidb_tpu.kv.memstore import MemStore

            if isinstance(store, MemStore):
                from tidb_tpu.copr.colcache import cache_for
                from tidb_tpu.kv.sharded import ShardedStore

                ctx.cache_bytes[ShardedStore.instance_name(store)] = (
                    cache_for(store).resident_bytes()
                )
        store = getattr(db, "store", None)
        if hasattr(store, "placement_cache") and len(getattr(store, "stores", ())) >= 2:
            from tidb_tpu.kv.placement import _shard_weights

            try:
                ctx.weights, _ = _shard_weights(db, store)
            # weights ride a health sweep; a dead fleet member must not
            # abort the whole inspection — the skew rule just reports ok
            except Exception:  # graftcheck: off=except-swallow
                ctx.weights = None
        return ctx


# -- registry ---------------------------------------------------------------

# (name, type, comment, fn) in registration order
_RULES: list = []


def rule(name: str, rtype: str, comment: str):
    def deco(fn: Callable):
        _RULES.append((name, rtype, comment, fn))
        return fn

    return deco


def rules_catalog() -> list:
    """→ [(name, type, comment)] — information_schema.inspection_rules."""
    return [(n, t, c) for n, t, c, _fn in _RULES]


def inspect(db=None, ctx: Optional[InspectionContext] = None, echo: bool = True) -> list:
    """Evaluate every rule → [(rule, item, status, value, reference,
    detail)], criticals echoed into the event log. ``echo=False`` keeps the
    evaluation side-effect free — the diag bundle uses it so two bundles of
    the same state stay byte-identical (an echo would land in the second
    bundle's log dump)."""
    if ctx is None:
        ctx = InspectionContext.from_db(db)
    rows = []
    for name, _rtype, _comment, fn in _RULES:
        for item, status, value, reference, detail in fn(ctx):
            rows.append((name, item, status, value, reference, detail))
            if status == CRITICAL and echo:
                lg = _ev.on(_ev.ERROR)
                if lg is not None:
                    lg.emit(
                        _ev.ERROR, "inspection", name,
                        item=item, value=value, detail=detail,
                    )
    return rows


# -- rules ------------------------------------------------------------------


@rule(
    "store-liveness", "fleet",
    "Per-store reachability from the health registry: a failed sweep is "
    "critical, a good-but-old report is a warning",
)
def _store_liveness(ctx: InspectionContext):
    out = []
    for inst, ent in sorted(ctx.health.items()):
        if not ent.get("ok", False):
            out.append((
                inst, CRITICAL, "down", "ok",
                f"last sweep failed: {ent.get('error', '')[:160]}",
            ))
        elif ctx.stale.get(inst, False):
            age = ctx.staleness_s.get(inst)
            out.append((
                inst, WARNING,
                f"stale {age:.0f}s" if age is not None else "never seen",
                "fresh report < 60s old",
                "no fresh sys_snapshot report",
            ))
        else:
            out.append((inst, OK, "up", "ok", ""))
    if not out:
        out.append(("fleet", OK, "no stores swept", "ok", ""))
    return out


@rule(
    "store-skew", "balance",
    "Hot/cold placement-weight ratio vs [cluster] balancer-skew-ratio — "
    "past the threshold the balancer should be moving tables",
)
def _store_skew(ctx: InspectionContext):
    w = ctx.weights
    if not w or len(w) < 2:
        return [("placement", OK, "n/a", f"<= {ctx.skew_ratio:g}", "not a sharded fleet")]
    hot = max(range(len(w)), key=lambda i: w[i])
    cold = min(range(len(w)), key=lambda i: w[i])
    ratio = w[hot] / max(w[cold], 1.0)
    status = OK
    if ratio > 2 * ctx.skew_ratio:
        status = CRITICAL
    elif ratio > ctx.skew_ratio:
        status = WARNING
    return [(
        f"shard-{hot}", status, f"{ratio:.2f}", f"<= {ctx.skew_ratio:g}",
        f"weights {[round(x, 1) for x in w]} (hot shard {hot}, cold shard {cold})",
    )]


@rule(
    "plan-cache", "performance",
    "Plan-cache miss ratio (session fast lane + instance cache) — a high "
    "ratio means queries keep paying parse/optimize walls",
)
def _plan_cache(ctx: InspectionContext):
    hit = ctx.plan_cache.get("hit", 0)
    miss = ctx.plan_cache.get("miss", 0)
    total = hit + miss
    if total < 20:
        return [("plan-cache", OK, f"{total} lookups", "miss ratio <= 0.5",
                 "too few lookups to judge")]
    ratio = miss / total
    status = OK
    if ratio >= 0.9:
        status = CRITICAL
    elif ratio > 0.5:
        status = WARNING
    return [("plan-cache", status, f"{ratio:.2f}", "miss ratio <= 0.5",
             f"{miss} misses / {total} lookups")]


@rule(
    "hbm-pressure", "capacity",
    "Device-cache resident bytes vs the HBM LRU budget (TIDB_TPU_HBM_GB) — "
    "near the ceiling the LRU starts evicting hot columns",
)
def _hbm_pressure(ctx: InspectionContext):
    if not ctx.hbm_budget:
        return [("hbm", OK, "n/a", "<= 80% of budget", "no HBM budget configured")]
    out = []
    for inst, nbytes in sorted(ctx.cache_bytes.items()):
        frac = nbytes / ctx.hbm_budget
        status = OK
        if frac >= 0.95:
            status = CRITICAL
        elif frac >= 0.8:
            status = WARNING
        out.append((
            inst, status, f"{frac:.1%}", "<= 80% of budget",
            f"{nbytes} bytes resident of {ctx.hbm_budget} budget",
        ))
    if not out:
        out.append(("hbm", OK, "0%", "<= 80% of budget", "no device cache"))
    return out


def _quantile(buckets, q: float) -> float:
    """Upper-bound quantile estimate from cumulative histogram buckets
    (``Histogram.snapshot()["buckets"]``). +Inf resolves to the last
    finite bound — good enough for a skew RATIO."""
    total = buckets[-1][1] if buckets else 0
    if total <= 0:
        return 0.0
    target = q * total
    last_finite = 0.0
    for bound, cum in buckets:
        if bound == "+Inf":
            break
        last_finite = float(bound)
        if cum >= target:
            return float(bound)
    return last_finite


@rule(
    "mpp-straggler", "performance",
    "Per-shard MPP fragment wall p95/median skew — a high ratio means one "
    "slow shard gates every gather's barrier",
)
def _mpp_straggler(ctx: InspectionContext):
    snap = ctx.mpp_shards
    if not snap or snap.get("count", 0) < 8:
        return [("mpp", OK, "n/a", "p95/median <= 4",
                 "under 8 shard observations")]
    p50 = _quantile(snap["buckets"], 0.50)
    p95 = _quantile(snap["buckets"], 0.95)
    if p50 <= 0:
        return [("mpp", OK, "n/a", "p95/median <= 4", "median bucket at zero")]
    ratio = p95 / p50
    status = OK
    if ratio >= 16:
        status = CRITICAL
    elif ratio > 4:
        status = WARNING
    return [("mpp", status, f"{ratio:.1f}", "p95/median <= 4",
             f"p95={p95:g}s median={p50:g}s over {snap['count']} shards")]


@rule(
    "hot-region", "balance",
    "Single-region traffic skew from the stores' keyspace heatmap rings — "
    "one region taking a sustained multiple of the others' traffic wants a "
    "split or a balancer move",
)
def _hot_region(ctx: InspectionContext):
    tr = ctx.region_traffic
    if len(tr) < 2:
        return [("regions", OK, "n/a", "hottest/mean-of-rest <= 4",
                 "under 2 regions with traffic")]
    (hk, hot) = max(tr.items(), key=lambda kv: kv[1])
    rest = [v for k, v in tr.items() if k != hk]
    mean_rest = sum(rest) / len(rest)
    if hot <= 0 or mean_rest <= 0:
        return [("regions", OK, "n/a", "hottest/mean-of-rest <= 4",
                 "no traffic in the retained window")]
    ratio = hot / mean_rest
    status = OK
    if ratio > 16:
        status = CRITICAL
    elif ratio > 4:
        status = WARNING
    inst, rid, tid = hk
    return [(
        f"region-{rid}", status, f"{ratio:.1f}", "hottest/mean-of-rest <= 4",
        f"{hot} keys on {inst} table {tid} vs mean {mean_rest:.0f} "
        f"over {len(rest)} other regions",
    )]


@rule(
    "backoff-storm", "resilience",
    "Recent backoff sleeps per second (metrics history rate) — a storm "
    "means the fleet is thrashing on retries instead of serving",
)
def _backoff_storm(ctx: InspectionContext):
    rate = ctx.backoff_rate
    status = OK
    if rate >= 50:
        status = CRITICAL
    elif rate >= 5:
        status = WARNING
    return [("backoff", status, f"{rate:.1f}/s", "< 5/s",
             "tidb_tpu_backoff_total rate over the history window")]


@rule(
    "delta-backlog", "capacity",
    "Committed rows pending in columnar delta overlays vs the compactor "
    "threshold — a backlog means reads pay overlay cost every scan",
)
def _delta_backlog(ctx: InspectionContext):
    pending = ctx.delta_rows
    ref = max(ctx.delta_merge_rows, 1)
    status = OK
    if pending >= 4 * ref:
        status = CRITICAL
    elif pending >= ref:
        status = WARNING
    return [("delta", status, f"{pending:g} rows", f"< {ref} rows",
             f"compactor threshold device-delta-merge-rows={ref}")]
