"""Memory tracking with OOM actions (ref: pkg/util/memory/tracker.go:77).

A Tracker tree mirrors the executor tree: children consume() bytes, the
deltas propagate to the root (the per-query tracker holding the quota from
``tidb_mem_quota_query``). On quota excess the tracker fires its registered
actions in priority order — spill callbacks first (ref: SpillDiskAction),
then cancel (ref: PanicOnExceed, the tidb_mem_oom_action=CANCEL default).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional


class QueryOOMError(RuntimeError):
    """Out Of Memory Quota! (ref: memory usage exceeds quota cancel message)"""


class QueryKilledError(RuntimeError):
    """Query interrupted (ref: sqlkiller / max_execution_time)."""


class Tracker:
    def __init__(self, label: str, limit: int = -1, parent: Optional["Tracker"] = None):
        self.label = label
        self.limit = limit  # bytes; -1 = unlimited
        self.parent = parent
        self._mu = threading.Lock()
        self.consumed = 0
        self.max_consumed = 0
        # spill actions, tried largest-win first before cancelling
        self._spill_actions: list[Callable[[], int]] = []

    def child(self, label: str, limit: int = -1) -> "Tracker":
        return Tracker(label, limit, parent=self)

    def register_spill(self, action: Callable[[], int]) -> None:
        """``action() -> bytes freed``; fired on quota excess (root-first)."""
        self._spill_actions.append(action)

    def unregister_spill(self, action: Callable[[], int]) -> None:
        if action in self._spill_actions:
            self._spill_actions.remove(action)

    def consume(self, n: int) -> None:
        t: Optional[Tracker] = self
        while t is not None:
            with t._mu:
                t.consumed += n
                t.max_consumed = max(t.max_consumed, t.consumed)
                over = t.limit >= 0 and t.consumed > t.limit
            if over:
                t._on_exceed()
            t = t.parent

    def release(self, n: int) -> None:
        self.consume(-n)

    def _on_exceed(self) -> None:
        # spill until under the limit; each action reports bytes it freed
        for action in list(self._spill_actions):
            if self.consumed <= self.limit:
                return
            action()
        if self.consumed > self.limit:
            raise QueryOOMError(
                f"Out Of Memory Quota! [{self.label}] consumed={self.consumed} limit={self.limit}"
            )


def chunk_bytes(chunk) -> int:
    """Approximate host memory a Chunk pins (column data + validity)."""
    total = 0
    for c in chunk.columns:
        data = getattr(c, "data", None)
        if data is not None and hasattr(data, "nbytes"):
            total += data.nbytes
        v = getattr(c, "validity", None)
        if v is not None and hasattr(v, "nbytes"):
            total += v.nbytes
    return total
