"""Prometheus-style metrics registry (ref: pkg/metrics — one registry,
per-subsystem counters/histograms, served on the status port's /metrics;
here rendered via ``render()`` and wired into the wire server)."""

from __future__ import annotations

import threading
from typing import Optional

_DEFAULT_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 30)


def _esc(v) -> str:
    """Escape a label VALUE for the Prometheus exposition format (the spec's
    label-value escaping): backslash, double quote, and newline would
    otherwise emit unparseable text — e.g. a degrade-reason label carrying a
    quoted error message."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class Counter:
    def __init__(self, name: str, help_: str, labels: tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.labels = labels
        self._mu = threading.Lock()
        self._vals: dict[tuple, float] = {}

    def inc(self, n: float = 1, **labels) -> None:
        key = tuple(labels.get(k, "") for k in self.labels)
        with self._mu:
            self._vals[key] = self._vals.get(key, 0) + n

    def get(self, **labels) -> float:
        key = tuple(labels.get(k, "") for k in self.labels)
        with self._mu:
            return self._vals.get(key, 0)

    def total(self) -> float:
        """Sum over every label combination — the load-signal read (QPS
        estimation sums statement types; per-type splits ride snapshot())."""
        with self._mu:
            return sum(self._vals.values())

    def snapshot(self) -> dict:
        """JSON-able state for the sys_snapshot report / metrics history."""
        with self._mu:
            return {
                "kind": "counter",
                "labels": list(self.labels),
                "values": [[list(k), v] for k, v in sorted(self._vals.items())],
            }

    def render(self) -> str:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._mu:
            for key, v in sorted(self._vals.items()):
                lbl = ",".join(f'{k}="{_esc(val)}"' for k, val in zip(self.labels, key))
                out.append(f"{self.name}{{{lbl}}} {v:g}" if lbl else f"{self.name} {v:g}")
        return "\n".join(out)


class Gauge:
    """A settable level (ref: prometheus Gauge) — election terms, pool sizes."""

    def __init__(self, name: str, help_: str, labels: tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.labels = labels
        self._mu = threading.Lock()
        self._vals: dict[tuple, float] = {}

    def set(self, v: float, **labels) -> None:
        key = tuple(labels.get(k, "") for k in self.labels)
        with self._mu:
            self._vals[key] = v

    def inc(self, n: float = 1, **labels) -> None:
        """Atomic add — a get()+set() pair from concurrent threads loses
        updates (each call takes the lock separately)."""
        key = tuple(labels.get(k, "") for k in self.labels)
        with self._mu:
            self._vals[key] = self._vals.get(key, 0) + n

    def get(self, **labels) -> float:
        key = tuple(labels.get(k, "") for k in self.labels)
        with self._mu:
            return self._vals.get(key, 0)

    def total(self) -> float:
        with self._mu:
            return sum(self._vals.values())

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "kind": "gauge",
                "labels": list(self.labels),
                "values": [[list(k), v] for k, v in sorted(self._vals.items())],
            }

    def render(self) -> str:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        with self._mu:
            for key, v in sorted(self._vals.items()):
                lbl = ",".join(f'{k}="{_esc(val)}"' for k, val in zip(self.labels, key))
                out.append(f"{self.name}{{{lbl}}} {v:g}" if lbl else f"{self.name} {v:g}")
        return "\n".join(out)


class Histogram:
    def __init__(self, name: str, help_: str, buckets=_DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.buckets = tuple(buckets)
        self._mu = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._n = 0

    def observe(self, v: float) -> None:
        with self._mu:
            self._sum += v
            self._n += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        return self._n

    def snapshot(self) -> dict:
        with self._mu:
            cum = 0
            buckets = []
            for b, c in zip(self.buckets, self._counts):
                cum += c
                buckets.append([b, cum])
            # the overflow bucket, exactly like render()'s +Inf line — without
            # it a wire consumer reconstructing the distribution loses every
            # observation above the top bound ("+Inf" keeps the dict JSON-able)
            buckets.append(["+Inf", cum + self._counts[-1]])
            return {"kind": "histogram", "sum": self._sum, "count": self._n, "buckets": buckets}

    def render(self) -> str:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._mu:
            cum = 0
            for b, c in zip(self.buckets, self._counts):
                cum += c
                out.append(f'{self.name}_bucket{{le="{b:g}"}} {cum}')
            out.append(f'{self.name}_bucket{{le="+Inf"}} {self._n}')
            out.append(f"{self.name}_sum {self._sum:g}")
            out.append(f"{self.name}_count {self._n}")
        return "\n".join(out)


class Registry:
    def __init__(self):
        self._mu = threading.Lock()
        self._metrics: dict[str, object] = {}

    def counter(self, name: str, help_: str = "", labels: tuple[str, ...] = ()) -> Counter:
        with self._mu:
            m = self._metrics.get(name)
            if m is None:
                m = Counter(name, help_, labels)
                self._metrics[name] = m
            return m  # type: ignore[return-value]

    def gauge(self, name: str, help_: str = "", labels: tuple[str, ...] = ()) -> Gauge:
        with self._mu:
            m = self._metrics.get(name)
            if m is None:
                m = Gauge(name, help_, labels)
                self._metrics[name] = m
            return m  # type: ignore[return-value]

    def histogram(self, name: str, help_: str = "", buckets=_DEFAULT_BUCKETS) -> Histogram:
        with self._mu:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, help_, buckets)
                self._metrics[name] = m
            return m  # type: ignore[return-value]

    def render(self) -> str:
        with self._mu:
            ms = list(self._metrics.values())
        return "\n".join(m.render() for m in ms) + "\n"

    def snapshot(self) -> dict:
        """One JSON-able dict of every metric's current state — what the
        ``sys_snapshot`` introspection verb ships fleet-wide and the metrics
        history recorder samples per tick."""
        with self._mu:
            ms = list(self._metrics.items())
        return {name: m.snapshot() for name, m in ms}


# process-global registry (ref: metrics.go package-level collectors)
REGISTRY = Registry()

STMT_TOTAL = REGISTRY.counter(
    "tidb_tpu_executor_statement_total", "Executed statements", ("type",)
)
QUERY_DURATION = REGISTRY.histogram(
    "tidb_tpu_server_handle_query_duration_seconds", "Statement latency"
)
COP_TASKS = REGISTRY.counter("tidb_tpu_copr_task_total", "Coprocessor tasks", ("engine",))
# extension hook failures (hooks may not break queries, but a misbehaving
# plugin must be visible — see extension.ExtensionRegistry._hook_error)
EXT_HOOK_ERRORS = REGISTRY.counter(
    "tidb_tpu_extension_hook_error_total", "Extension callback failures", ("ext", "hook")
)
# session plan reuse (statement fast lane + value-agnostic prepared plans)
PLAN_CACHE = REGISTRY.counter(
    "tidb_tpu_session_plan_cache_total",
    "Plan-cache lookups by outcome (hit = parser/builder/optimizer skipped)",
    ("result",),
)
# resilience layer (utils/backoff.py + the retrying seams; see RESILIENCE.md)
BACKOFF_TOTAL = REGISTRY.counter(
    "tidb_tpu_backoff_total", "Backoffer sleeps by typed config", ("config",)
)
COP_DEGRADED = REGISTRY.counter(
    "tidb_tpu_copr_degraded_task_total",
    "Cop tasks that fell back from the TPU engine to the host engine",
    ("reason",),
)
STORE_FAILOVER = REGISTRY.counter(
    "tidb_tpu_store_failover_total",
    "Sharded-fleet reads/authority calls served by a non-primary replica",
    ("kind",),
)
# quorum-replicated owner election (kv/election.py — the PD/etcd analog)
ELECTION_CAMPAIGN = REGISTRY.counter(
    "tidb_tpu_election_campaign_total",
    "Owner-election campaign attempts by outcome (won/renewed/lost/fenced/repair)",
    ("key", "outcome"),
)
ELECTION_FAILOVER = REGISTRY.counter(
    "tidb_tpu_election_failover_total",
    "Ownership changes: a different node won an election key",
    ("key",),
)
ELECTION_TERM = REGISTRY.gauge(
    "tidb_tpu_election_term",
    "Current fencing token (term) per election key, as observed by this node",
    ("key",),
)
# distributed exec-details pipeline (utils/execdetails + the cop engines):
# device-time attribution exported process-wide; the per-query split rides
# the ExecDetails sidecars into EXPLAIN ANALYZE / the slow log
COP_COMPILE_SECONDS = REGISTRY.histogram(
    "tidb_tpu_copr_compile_seconds",
    "DAG-kernel jit compile wall (first dispatch per kernel-cache key)",
)
COP_DEVICE_SECONDS = REGISTRY.histogram(
    "tidb_tpu_copr_device_seconds",
    "Device-path wall per cop task (dispatch + on-chip + transfer back)",
)
DEVICE_CACHE = REGISTRY.counter(
    "tidb_tpu_device_cache_total",
    "Device-resident column LRU lookups (hit = no H2D transfer paid)",
    ("result",),
)
# delta+merge device column cache (copr/colcache.py delta overlays + the
# session-level compactor): freshness without re-uploading base blocks
DEVICE_DELTA_ROWS = REGISTRY.gauge(
    "tidb_tpu_device_delta_rows",
    "Committed rows pending in columnar delta overlays (not yet merged)",
)
DEVICE_MERGE_SECONDS = REGISTRY.histogram(
    "tidb_tpu_device_merge_seconds",
    "Delta→base merge wall (rebuild + dirty-block accounting) per region",
)
DEVICE_TRANSFER = REGISTRY.counter(
    "tidb_tpu_device_transfer_bytes_total",
    "Host<->device bytes moved by the cop engines",
    ("dir",),
)
SERVER_CONNS = REGISTRY.gauge(
    "tidb_tpu_server_connections", "Open wire-protocol client connections"
)
# always-on sampled tracing (utils/tracing.TraceReservoir + Session.execute)
TRACE_SAMPLED = REGISTRY.counter(
    "tidb_tpu_trace_sampled_total",
    "Statements whose trace was sampled into the reservoir (slow = tail-keep pinned)",
    ("kind",),
)
# per-shard MPP fragment attribution (parallel/gather._shard_probe): one
# observation per mesh shard per gather — the straggler distribution
MPP_SHARD_SECONDS = REGISTRY.histogram(
    "tidb_tpu_mpp_shard_seconds",
    "Per-shard MPP fragment completion wall (launch to shard-local finish)",
)
# MPP compiled-program reuse (parallel/gather._MPP_FN_CACHE): hit = a gather
# rode an already-built jitted fragment program, miss = it had to build one
# (the multi-second XLA wall) — power-of-two cap bucketing keeps this warm
# across same-shape queries of different sizes
MPP_PROGRAM_CACHE = REGISTRY.counter(
    "tidb_tpu_mpp_program_cache_total",
    "MPP fragment-program cache lookups by outcome",
    ("result",),
)
# cross-store × cross-chip hybrid gathers: a straddling gather (tables on
# multiple store shards) ran on the coordinator's mesh with per-owner wire
# reads instead of degrading to the host join
MPP_HYBRID = REGISTRY.counter(
    "tidb_tpu_mpp_hybrid_total",
    "MPP gathers executed on the hybrid shards-x-devices path",
)
# bytes of INTERMEDIATE fragment results that crossed the host boundary
# (a subplan build side materialized through the Volcano executor and
# re-uploaded) — the staged on-mesh pipeline exists to keep this at ZERO;
# the scaling bench lane and the stage-chain tests assert on it
MPP_HOST_INTERMEDIATE = REGISTRY.counter(
    "tidb_tpu_mpp_intermediate_host_bytes_total",
    "Bytes of intermediate MPP fragment results moved through the host",
)
# instance-level serving architecture (planner/instcache + the point-get
# batcher in copr/client): cross-session cache outcomes, and how many
# concurrent point reads each batched store dispatch coalesced (count =
# dispatches issued, sum = keys served — count << sum proves batching)
INSTANCE_PLAN_CACHE = REGISTRY.counter(
    "tidb_tpu_instance_plan_cache_total",
    "Instance (cross-session) cache lookups: hit/miss = plan templates, "
    "ast_hit/ast_miss = statement ASTs",
    ("result",),
)
POINTGET_BATCH = REGISTRY.histogram(
    "tidb_tpu_pointget_batch_size",
    "Point-get keys coalesced per batched store dispatch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128),
)
# cluster observability plane (the sys_snapshot verb + StoreHealthRegistry
# sweeps in session.py, and the utils/metricshist.py in-process recorder)
CLUSTER_SNAPSHOT_SECONDS = REGISTRY.histogram(
    "tidb_tpu_cluster_snapshot_seconds",
    "Full-fleet sys_snapshot sweep wall (all shards, dead-store tolerant)",
)
METRICS_HISTORY_POINTS = REGISTRY.gauge(
    "tidb_tpu_metrics_history_points",
    "Samples currently retained by the in-process metrics history recorder",
)

# elastic data placement (kv/placement.py: the PD-analog placement driver —
# epoch-versioned movable ownership, region migration, the balancer sweep)
PLACEMENT_EPOCH = REGISTRY.gauge(
    "tidb_tpu_placement_epoch",
    "Current placement epoch per table binding (monotone; never regresses)",
    ("table",),
)
PLACEMENT_REFRESH = REGISTRY.counter(
    "tidb_tpu_placement_refresh_total",
    "Placement map re-resolves (the boRegionMiss re-route signal)",
    ("outcome",),
)
PLACEMENT_REROUTE = REGISTRY.counter(
    "tidb_tpu_placement_reroute_total",
    "Data verbs re-routed to a new owner after a placement epoch change",
    ("verb",),
)
REGION_MIGRATE = REGISTRY.counter(
    "tidb_tpu_region_migrate_total",
    "Region (table) migrations between stores",
    ("outcome",),
)
REGION_MIGRATE_SECONDS = REGISTRY.histogram(
    "tidb_tpu_region_migrate_seconds",
    "Wall clock of one region migration (copy + catch-up + fenced cutover)",
)
BALANCER_MOVES = REGISTRY.counter(
    "tidb_tpu_balancer_move_total",
    "Region moves initiated by the load balancer sweep",
    ("reason",),
)
META_CATCHUP = REGISTRY.counter(
    "tidb_tpu_meta_catchup_total",
    "Returning-replica anti-entropy replays (meta + election + placement)",
)
# workload attribution (resourcegroup/groups.py): per-group request units
# and statement counts — the metering substrate admission control (ROADMAP
# item 3) will act on. Labeled by resource group so metricshist keeps a
# per-tenant consumption history.
RU_CONSUMED = REGISTRY.counter(
    "tidb_tpu_resource_group_ru_total",
    "Request units consumed per resource group (RRU + WRU, metering only)",
    ("group",),
)
RU_STATEMENTS = REGISTRY.counter(
    "tidb_tpu_resource_group_statement_total",
    "Statements attributed per resource group",
    ("group",),
)
