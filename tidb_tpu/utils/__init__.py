"""Host-side utilities: columnar batches, codecs, memory tracking, misc."""
