"""Host-side utilities: columnar batches, codecs, memory tracking, misc."""

_SYSVAR_ON = ("on", "true", "yes", "1")
_SYSVAR_OFF = ("off", "false", "no", "0")


def sysvar_int(vars: dict, knob: str, default: int) -> int:
    """Coerce a session sysvar to int, MySQL-style: SET stores raw strings,
    users write ON/OFF as freely as numbers, and a bad value must never
    crash planning — fall back to the default (ref: variable/sysvar.go
    TypeBool/TypeInt validation, which normalizes before the optimizer
    ever sees the value)."""
    v = vars.get(knob, default)
    if isinstance(v, str):
        s = v.strip().lower()
        if s in _SYSVAR_ON:
            return 1
        if s in _SYSVAR_OFF:
            return 0
    try:
        return int(v)
    except (TypeError, ValueError):
        try:
            return int(float(v))
        except (TypeError, ValueError, OverflowError):  # '1e400' → inf
            return default
