"""Recursive-descent SQL parser (ref: pkg/parser/parser.y, hand-rolled).

Precedence (low→high), mirroring MySQL:
OR/|| → XOR → AND/&& → NOT → comparison (=, <>, <, <=, >, >=, IS, IN,
BETWEEN, LIKE) → | → & → << >> → + - → * / DIV MOD % → unary -+!~ → primary.
"""

from __future__ import annotations

from typing import Optional

from tidb_tpu.parser import ast
from tidb_tpu.parser.lexer import Token, tokenize


class ParseError(Exception):
    def __init__(self, msg: str, tok: Token):
        super().__init__(f"{msg} near {tok.value!r} (offset {tok.pos})")
        self.tok = tok


RESERVED = frozenset(
    """SELECT INSERT UPDATE DELETE REPLACE FROM WHERE GROUP HAVING ORDER LIMIT
    OFFSET BY AND OR XOR NOT AS ON JOIN LEFT RIGHT INNER CROSS OUTER UNION SET
    INTO VALUES CREATE DROP ALTER TABLE INDEX DATABASE USE SHOW EXPLAIN BETWEEN
    LIKE IN IS NULL CASE WHEN THEN ELSE END CAST DISTINCT ASC DESC PRIMARY KEY
    UNIQUE DEFAULT EXISTS COMMIT ROLLBACK BEGIN TRUNCATE ANALYZE""".split()
)


class Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.toks = tokenize(sql)
        self.i = 0
        self.param_count = 0  # `?` markers seen so far (prepared statements)

    # -- token helpers ------------------------------------------------------
    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "ident" and t.value.upper() in kws

    def eat_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        if not self.eat_kw(kw):
            raise ParseError(f"expected {kw}", self.peek())

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "op" and t.value in ops

    def eat_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.eat_op(op):
            raise ParseError(f"expected {op!r}", self.peek())

    def ident(self) -> str:
        t = self.peek()
        if t.kind in ("ident", "qident"):
            self.next()
            return t.value
        raise ParseError("expected identifier", t)

    # -- entry --------------------------------------------------------------
    def parse_statement(self) -> ast.Node:
        t = self.peek()
        if t.kind == "op" and t.value == "(":
            return self.parse_select_stmt()
        if t.kind != "ident":
            raise ParseError("expected statement", t)
        kw = t.value.upper()
        fn = {
            "SELECT": self.parse_select_stmt,
            "WITH": self.parse_select_stmt,
            "INSERT": self.parse_insert,
            "REPLACE": self.parse_insert,
            "UPDATE": self.parse_update,
            "DELETE": self.parse_delete,
            "CREATE": self.parse_create,
            "DROP": self.parse_drop,
            "ALTER": self.parse_alter,
            "TRUNCATE": self.parse_truncate,
            "EXPLAIN": self.parse_explain,
            "DESC": self.parse_explain,
            "DESCRIBE": self.parse_explain,
            "RENAME": self.parse_rename,
            "DO": self.parse_do,
            "CHECKSUM": self.parse_checksum,
            "TABLE": self.parse_table_stmt,
            "SET": self.parse_set,
            "SHOW": self.parse_show,
            "USE": self.parse_use,
            "BEGIN": self.parse_begin,
            "START": self.parse_begin,
            "COMMIT": lambda: (self.next(), ast.Commit())[1],
            "ROLLBACK": lambda: (self.next(), ast.Rollback())[1],
            "ANALYZE": self.parse_analyze,
            "LOAD": self.parse_load_data,
            "PREPARE": self.parse_prepare,
            "EXECUTE": self.parse_execute_stmt,
            "DEALLOCATE": self.parse_deallocate,
            "IMPORT": self.parse_import,
            "BACKUP": self.parse_backup,
            "RESTORE": self.parse_restore,
            "KILL": self.parse_kill,
            "GRANT": self.parse_grant,
            "REVOKE": self.parse_grant,
            "TRACE": lambda: (self.next(), ast.Trace(self.parse_statement()))[1],
            "ADMIN": self.parse_admin,
            "RECOVER": self.parse_recover,
            "FLASHBACK": self.parse_recover,
            "PLAN": self.parse_plan_replayer,
        }.get(kw)
        if fn is None:
            raise ParseError("unsupported statement", t)
        return fn()

    # -- SELECT --------------------------------------------------------------
    def parse_select_stmt(self) -> ast.Node:
        """SELECT optionally chained with UNION/INTERSECT/EXCEPT (ref:
        ast.SetOprStmt; INTERSECT binds tighter per MySQL 8). A trailing
        ORDER BY/LIMIT binds to the whole compound."""
        if self.at_kw("WITH"):
            return self.parse_with()
        node, paren = self._setop_operand()
        # whether the top node came from explicit parentheses (an explicitly
        # grouped SetOp must not be re-associated by INTERSECT precedence)
        node_paren = paren
        last, last_paren = node, paren
        while self.at_kw("UNION", "EXCEPT", "INTERSECT"):
            if (
                not last_paren
                and isinstance(last, ast.Select)
                and (last.order_by or last.limit is not None)
            ):
                raise ParseError(
                    "ORDER BY/LIMIT in a non-final set operand needs parentheses", self.peek()
                )
            op = self.next().value.lower()
            all_ = self.eat_kw("ALL")
            if not all_:
                self.eat_kw("DISTINCT")
            last, last_paren = self._setop_operand()
            if (
                op == "intersect"
                and isinstance(node, ast.SetOp)
                and node.op != "intersect"
                and not node_paren
            ):
                node.right = ast.SetOp(node.right, last, op, all=all_)
            else:
                node = ast.SetOp(node, last, op, all=all_)
                node_paren = False
        if not isinstance(node, ast.SetOp):
            if paren and (self.at_kw("ORDER") or self.at_kw("LIMIT")):
                # (SELECT ... LIMIT 10) ORDER BY/LIMIT — the outer clauses
                # apply to the derived result, after the inner ones
                outer = ast.Select(
                    items=[ast.SelectItem(ast.Wildcard())],
                    from_=ast.SubquerySource(node, "__paren__"),
                )
                if self.at_kw("ORDER"):
                    self.next()
                    self.expect_kw("BY")
                    outer.order_by = self.parse_order_items()
                self._parse_limit(outer)
                return outer
            return node
        if not last_paren and isinstance(last, ast.Select):
            # parse_select consumed the trailing ORDER BY/LIMIT — it belongs
            # to the compound statement
            node.order_by, last.order_by = last.order_by, []
            node.limit, node.offset, last.limit, last.offset = last.limit, last.offset, None, 0
        if self.at_kw("ORDER"):
            self.next()
            self.expect_kw("BY")
            node.order_by = self.parse_order_items()
        self._parse_limit(node)
        return node

    def _parse_limit(self, node) -> None:
        """LIMIT n | LIMIT off, n | LIMIT n OFFSET off — sets node.limit/offset."""
        if not self.eat_kw("LIMIT"):
            return
        a = self._limit_value()
        if self.eat_op(","):
            node.offset = a
            node.limit = self._limit_value()
        else:
            node.limit = a
            if self.eat_kw("OFFSET"):
                node.offset = self._limit_value()

    def _limit_value(self) -> int:
        """MySQL's u64 LIMIT/OFFSET literals (18446744073709551615 = "no
        limit") clamp to int64 max HERE, at the parse boundary — a user
        literal must never reach a jitted computation unclamped (ref:
        ast/misc.go Limit uint64)."""
        return min(int(self.next().value), 2**63 - 1)

    def _paren_select_ahead(self) -> bool:
        """True when the upcoming '('... run of parens wraps a SELECT/WITH (as
        opposed to a parenthesized join or scalar expression)."""
        j = 0
        while self.peek(j).kind == "op" and self.peek(j).value == "(":
            j += 1
        t = self.peek(j)
        return j > 0 and t.kind == "ident" and t.value.upper() in ("SELECT", "WITH")

    def parse_with(self) -> ast.Node:
        """WITH [RECURSIVE] name [(col, ...)] AS (query), ... SELECT ...
        (ref: parser.y WithClause → ast.CommonTableExpression list)."""
        self.expect_kw("WITH")
        recursive = self.eat_kw("RECURSIVE")
        ctes: list[ast.CTEDef] = []
        while True:
            name = self.ident()
            cols: list[str] = []
            if self.at_op("("):
                self.next()
                cols.append(self.ident())
                while self.eat_op(","):
                    cols.append(self.ident())
                self.expect_op(")")
            self.expect_kw("AS")
            self.expect_op("(")
            q = self.parse_select_stmt()
            self.expect_op(")")
            ctes.append(ast.CTEDef(name.lower(), [c.lower() for c in cols], q, recursive))
            if not self.eat_op(","):
                break
        stmt = self.parse_select_stmt()
        stmt.ctes = ctes + list(getattr(stmt, "ctes", []))
        return stmt

    def _setop_operand(self) -> tuple:
        if self._paren_select_ahead():
            self.next()
            inner = self.parse_select_stmt()
            self.expect_op(")")
            return inner, True
        return self.parse_select(), False

    def parse_select(self) -> ast.Select:
        self.expect_kw("SELECT")
        hints = []
        if self.peek().kind == "hint":
            hints = _parse_hints(self.next().value)
        distinct = self.eat_kw("DISTINCT")
        self.eat_kw("ALL")
        items = [self.parse_select_item()]
        while self.eat_op(","):
            items.append(self.parse_select_item())
        sel = ast.Select(items=items, distinct=distinct, hints=hints)
        if self.eat_kw("FROM"):
            sel.from_ = self.parse_table_refs()
        if self.eat_kw("WHERE"):
            sel.where = self.parse_expr()
        if self.at_kw("GROUP"):
            self.next()
            self.expect_kw("BY")
            sel.group_by.append(self.parse_expr())
            while self.eat_op(","):
                sel.group_by.append(self.parse_expr())
            if self.at_kw("WITH"):
                self.next()
                self.expect_kw("ROLLUP")
                sel.rollup = True
        if self.eat_kw("HAVING"):
            sel.having = self.parse_expr()
        if self.at_kw("ORDER"):
            self.next()
            self.expect_kw("BY")
            sel.order_by = self.parse_order_items()
        self._parse_limit(sel)
        if self.eat_kw("FOR"):
            self.expect_kw("UPDATE")
            sel.for_update = True
        return sel

    def parse_select_item(self) -> ast.SelectItem:
        if self.at_op("*"):
            self.next()
            return ast.SelectItem(ast.Wildcard())
        # t.* lookahead
        if self.peek().kind in ("ident", "qident") and self.peek(1).kind == "op" and self.peek(1).value == "." and self.peek(2).value == "*":
            tbl = self.ident()
            self.next()
            self.next()
            return ast.SelectItem(ast.Wildcard(table=tbl))
        e = self.parse_expr()
        alias = ""
        if self.eat_kw("AS"):
            alias = self.ident()
        elif self.peek().kind in ("ident", "qident") and not self.at_kw(
            "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "UNION", "INTERSECT", "EXCEPT", "INTO", "JOIN", "ON",
            "LEFT", "RIGHT", "INNER", "CROSS", "AS", "SET",
        ):
            alias = self.ident()
        return ast.SelectItem(e, alias)

    def parse_order_items(self) -> list[ast.OrderItem]:
        out = [self._order_item()]
        while self.eat_op(","):
            out.append(self._order_item())
        return out

    def _order_item(self) -> ast.OrderItem:
        e = self.parse_expr()
        desc = False
        if self.eat_kw("DESC"):
            desc = True
        else:
            self.eat_kw("ASC")
        return ast.OrderItem(e, desc)

    def parse_table_refs(self) -> ast.Node:
        left = self.parse_table_factor()
        while True:
            if self.eat_op(","):
                right = self.parse_table_factor()
                left = ast.Join(left, right, kind="cross")
            elif self.at_kw("JOIN", "INNER", "LEFT", "RIGHT", "CROSS"):
                kind = "inner"
                if self.eat_kw("LEFT"):
                    kind = "left"
                    self.eat_kw("OUTER")
                elif self.eat_kw("RIGHT"):
                    kind = "right"
                    self.eat_kw("OUTER")
                elif self.eat_kw("CROSS"):
                    kind = "cross"
                else:
                    self.eat_kw("INNER")
                self.expect_kw("JOIN")
                right = self.parse_table_factor()
                on = None
                if self.eat_kw("ON"):
                    on = self.parse_expr()
                left = ast.Join(left, right, kind=kind, on=on)
            else:
                return left

    def parse_table_factor(self) -> ast.Node:
        if self.at_op("("):
            # subquery or parenthesized join
            if self._paren_select_ahead():
                self.next()
                sel = self.parse_select_stmt()
                self.expect_op(")")
                alias = ""
                self.eat_kw("AS")
                if self.peek().kind in ("ident", "qident"):
                    alias = self.ident()
                return ast.SubquerySource(sel, alias)
            self.next()
            inner = self.parse_table_refs()
            self.expect_op(")")
            return inner
        name = self.ident()
        db = ""
        if self.eat_op("."):
            db, name = name, self.ident()
        partitions = None
        if self.at_kw("PARTITION") and self.peek(1).kind == "op" and self.peek(1).value == "(":
            # t PARTITION (p0, p1) — explicit partition selection
            self.next()
            self.expect_op("(")
            partitions = [self.ident().lower()]
            while self.eat_op(","):
                partitions.append(self.ident().lower())
            self.expect_op(")")
        as_of = None
        alias = ""
        if self.at_kw("AS") and self.peek(1).value.upper() == "OF":
            # stale read: t AS OF TIMESTAMP expr (ref: ast.TableName.AsOf)
            self.next()
            self.next()
            self.expect_kw("TIMESTAMP")
            as_of = self.parse_expr()
        if self.eat_kw("AS"):
            alias = self.ident()
        elif self.peek().kind in ("ident", "qident") and not self.at_kw(
            "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "JOIN", "ON", "LEFT", "RIGHT",
            "INNER", "CROSS", "SET", "UNION", "INTERSECT", "EXCEPT", "USING", "FOR",
            "USE", "IGNORE", "FORCE",  # index hints, reserved in MySQL
        ):
            alias = self.ident()
        hints = None
        while self.at_kw("USE", "IGNORE", "FORCE") and self.peek(1).value.upper() in ("INDEX", "KEY"):
            kind = self.next().value.lower()
            self.next()  # INDEX | KEY
            if self.eat_kw("FOR"):
                # FOR JOIN | FOR ORDER BY | FOR GROUP BY — scope qualifiers
                # are accepted and applied globally (single-scan planner)
                if not self.eat_kw("JOIN"):
                    self.next()
                    self.expect_kw("BY")
            self.expect_op("(")
            names = []
            if not self.at_op(")"):
                names.append("primary" if self.eat_kw("PRIMARY") else self.ident().lower())
                while self.eat_op(","):
                    names.append("primary" if self.eat_kw("PRIMARY") else self.ident().lower())
            self.expect_op(")")
            hints = (hints or []) + [(kind, names)]
        return ast.TableRef(name, db=db, alias=alias, as_of=as_of, index_hints=hints, partitions=partitions)

    # -- expressions ---------------------------------------------------------
    def parse_expr(self) -> ast.Node:
        return self._or_expr()

    def _or_expr(self) -> ast.Node:
        left = self._xor_expr()
        while self.at_kw("OR") or self.at_op("||"):
            self.next()
            left = ast.BinaryOp("or", left, self._xor_expr())
        return left

    def _xor_expr(self) -> ast.Node:
        left = self._and_expr()
        while self.at_kw("XOR"):
            self.next()
            left = ast.BinaryOp("xor", left, self._and_expr())
        return left

    def _and_expr(self) -> ast.Node:
        left = self._not_expr()
        while self.at_kw("AND") or self.at_op("&&"):
            self.next()
            left = ast.BinaryOp("and", left, self._not_expr())
        return left

    def _not_expr(self) -> ast.Node:
        if self.at_kw("NOT") or self.at_op("!"):
            self.next()
            return ast.UnaryOp("not", self._not_expr())
        return self._comparison()

    _CMP = {"=": "eq", "<=>": "nulleq", "<>": "ne", "!=": "ne", "<": "lt", "<=": "le", ">": "gt", ">=": "ge"}

    def _comparison(self) -> ast.Node:
        left = self._bitor()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in self._CMP:
                self.next()
                if self.at_kw("ANY", "SOME", "ALL"):
                    left = self._quantified_cmp(self._CMP[t.value], left)
                    continue
                left = ast.BinaryOp(self._CMP[t.value], left, self._bitor())
                continue
            if self.at_kw("IS"):
                self.next()
                neg = self.eat_kw("NOT")
                if self.at_kw("TRUE", "FALSE", "UNKNOWN"):
                    kind = self.next().value.upper()
                    # IS TRUE ⇔ IFNULL(x,0) <> 0; IS FALSE ⇔ IFNULL(x,1) = 0;
                    # IS UNKNOWN ⇔ IS NULL (ref: builtin_op.go isTrue/isFalse)
                    if kind == "UNKNOWN":
                        e: ast.Node = ast.IsNull(left)
                    elif kind == "TRUE":
                        e = ast.BinaryOp("ne", ast.FuncCall("ifnull", [left, ast.Literal(0)]), ast.Literal(0))
                    else:
                        e = ast.BinaryOp("eq", ast.FuncCall("ifnull", [left, ast.Literal(1)]), ast.Literal(0))
                    left = ast.UnaryOp("not", e) if neg else e
                    continue
                self.expect_kw("NULL")
                left = ast.IsNull(left, negated=neg)
                continue
            neg = False
            save = self.i
            if self.at_kw("NOT"):
                self.next()
                neg = True
            if self.at_kw("IN"):
                self.next()
                self.expect_op("(")
                if self.at_kw("SELECT", "WITH"):
                    sel = self.parse_select_stmt()
                    self.expect_op(")")
                    left = ast.InList(left, [ast.SubqueryExpr(sel, "in")], negated=neg)
                else:
                    items = [self.parse_expr()]
                    while self.eat_op(","):
                        items.append(self.parse_expr())
                    self.expect_op(")")
                    left = ast.InList(left, items, negated=neg)
                continue
            if self.at_kw("BETWEEN"):
                self.next()
                lo = self._bitor()
                self.expect_kw("AND")
                hi = self._bitor()
                left = ast.Between(left, lo, hi, negated=neg)
                continue
            if self.at_kw("LIKE"):
                self.next()
                left = ast.Like(left, self._bitor(), negated=neg)
                continue
            if self.at_kw("REGEXP", "RLIKE"):
                self.next()
                left = ast.Like(left, self._bitor(), negated=neg, regexp=True)
                continue
            if neg:
                self.i = save
            return left

    def _quantified_cmp(self, op: str, left: ast.Node) -> ast.Node:
        """`expr OP ANY|SOME|ALL (subquery)` → QuantifiedCmp, lowered by the
        planner per context (ref: expression_rewriter.go quantified
        comparison handling)."""
        is_all = self.at_kw("ALL")
        self.next()
        self.expect_op("(")
        sel = self.parse_select_stmt()
        self.expect_op(")")
        if len(sel.items) != 1 or isinstance(sel.items[0].expr, ast.Wildcard):
            raise ParseError("quantified subquery must select exactly one column", self.peek())
        return ast.QuantifiedCmp(op, left, sel, is_all)

    def _bitor(self) -> ast.Node:
        left = self._bitand()
        while self.at_op("|"):
            self.next()
            left = ast.BinaryOp("bitor", left, self._bitand())
        return left

    def _bitand(self) -> ast.Node:
        left = self._shift()
        while self.at_op("&"):
            self.next()
            left = ast.BinaryOp("bitand", left, self._shift())
        return left

    def _shift(self) -> ast.Node:
        left = self._additive()
        while self.at_op("<<", ">>"):
            op = "shl" if self.next().value == "<<" else "shr"
            left = ast.BinaryOp(op, left, self._additive())
        return left

    def _additive(self) -> ast.Node:
        left = self._multiplicative()
        while self.at_op("+", "-"):
            op = "plus" if self.next().value == "+" else "minus"
            left = ast.BinaryOp(op, left, self._multiplicative())
        return left

    def _multiplicative(self) -> ast.Node:
        left = self._bitxor()
        while True:
            if self.at_op("*"):
                self.next()
                left = ast.BinaryOp("mul", left, self._bitxor())
            elif self.at_op("/"):
                self.next()
                left = ast.BinaryOp("div", left, self._bitxor())
            elif self.at_op("%") or self.at_kw("MOD"):
                self.next()
                left = ast.BinaryOp("mod", left, self._bitxor())
            elif self.at_kw("DIV"):
                self.next()
                left = ast.BinaryOp("intdiv", left, self._bitxor())
            else:
                return left

    def _bitxor(self) -> ast.Node:
        # MySQL: ^ binds tighter than * (and looser than unary)
        left = self._unary()
        while self.at_op("^"):
            self.next()
            left = ast.BinaryOp("bitxor", left, self._unary())
        return left

    def _postfix_json(self, e: ast.Node) -> ast.Node:
        """col -> '$.path' and col ->> '$.path' (ref: JSON column paths)."""
        while self.at_op("->") or self.at_op("->>"):
            unquote = self.peek().value == "->>"
            self.next()
            t = self.next()
            if t.kind != "str":
                raise ParseError("expected JSON path string", t)
            path = ast.Literal(t.value)
            e = ast.FuncCall("json_extract", [e, path])
            if unquote:
                e = ast.FuncCall("json_unquote", [e])
        return e

    def _unary(self) -> ast.Node:
        if self.at_op("-"):
            self.next()
            return ast.UnaryOp("unaryminus", self._unary())
        if self.at_op("+"):
            self.next()
            return self._unary()
        if self.at_op("~"):
            self.next()
            return ast.UnaryOp("bitneg", self._unary())
        if self.at_kw("BINARY") and not (
            # CAST-style "BINARY(n)" never appears in expression position;
            # bare BINARY here is MySQL's unary collate-to-binary operator
            # (ref: parser.y SimpleExpr "BINARY SimpleExpr")
            self.peek(1).kind == "op" and self.peek(1).value in (")", ",")
        ):
            self.next()
            return ast.Collate(self._unary(), "binary")
        e = self._postfix_json(self._primary())
        # postfix COLLATE binds tightest of all operators
        # (ref: parser.y "Expression COLLATE CollationName")
        while self.eat_kw("COLLATE"):
            e = ast.Collate(e, self.ident().lower())
        return e

    def _primary(self) -> ast.Node:
        t = self.peek()
        if t.kind == "op" and t.value == "?":
            self.next()
            m = ast.ParamMarker(self.param_count)
            self.param_count += 1
            return m
        if t.kind == "op" and t.value == "@":
            self.next()
            if self.at_op("@"):
                self.next()
                scope = "session"
                name = self.ident()
                if name.lower() in ("global", "session") and self.eat_op("."):
                    scope = name.lower()
                    name = self.ident()
                return ast.UserVar(name.lower(), sys=True, scope=scope)
            return ast.UserVar(self.ident().lower())
        if t.kind == "int":
            self.next()
            return ast.Literal(int(t.value))
        if t.kind == "float":
            self.next()
            return ast.Literal(t.value, hint="decimal")
        if t.kind == "str":
            self.next()
            return ast.Literal(t.value)
        if self.at_op("("):
            self.next()
            if self.at_kw("SELECT", "WITH"):
                sel = self.parse_select_stmt()
                self.expect_op(")")
                return ast.SubqueryExpr(sel)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind == "qident":
            return self._column_or_call()
        if t.kind != "ident":
            raise ParseError("expected expression", t)
        kw = t.value.upper()
        if kw == "NULL":
            self.next()
            return ast.Literal(None)
        if kw == "TRUE":
            self.next()
            return ast.Literal(True)
        if kw == "FALSE":
            self.next()
            return ast.Literal(False)
        if kw in ("DATE", "TIMESTAMP", "TIME") and self.peek(1).kind == "str":
            self.next()
            lit = self.next()
            return ast.Literal(lit.value, hint=kw.lower())
        if kw == "VALUES" and self.peek(1).value == "(":
            # VALUES(col) inside ON DUPLICATE KEY UPDATE
            self.next()
            self.next()
            col = ast.ColumnName(self.ident())
            self.expect_op(")")
            return ast.FuncCall("values", [col])
        if kw == "CASE":
            return self._case()
        if kw == "CAST":
            self.next()
            self.expect_op("(")
            e = self.parse_expr()
            self.expect_kw("AS")
            td = self.parse_typedef()
            self.expect_op(")")
            return ast.Cast(e, td)
        if kw == "EXISTS" and self.peek(1).value == "(":
            self.next()
            self.next()
            sel = self.parse_select_stmt()
            self.expect_op(")")
            return ast.SubqueryExpr(sel, "exists")
        if kw == "INTERVAL":
            # INTERVAL n DAY — folded into date arithmetic by the planner
            self.next()
            n = self.parse_expr()
            unit = self.ident().lower()
            return ast.FuncCall("interval", [n, ast.Literal(unit)])
        return self._column_or_call()

    def _trim_call(self) -> ast.Node:
        """TRIM([{BOTH|LEADING|TRAILING}] [remstr] FROM str) | TRIM(str) —
        lowered to trim(str[, remstr, mode]) with mode 0=both 1=lead 2=trail."""
        mode = 0
        explicit = False
        if self.eat_kw("BOTH"):
            explicit = True
        elif self.eat_kw("LEADING"):
            mode, explicit = 1, True
        elif self.eat_kw("TRAILING"):
            mode, explicit = 2, True
        rem = None
        if explicit:
            if not self.at_kw("FROM"):
                rem = self.parse_expr()
            self.expect_kw("FROM")
            s = self.parse_expr()
        else:
            first = self.parse_expr()
            if self.eat_kw("FROM"):
                rem, s = first, self.parse_expr()
            else:
                s = first
        self.expect_op(")")
        args = [s]
        if rem is not None or mode != 0:
            args.append(rem if rem is not None else ast.Literal(" "))
            args.append(ast.Literal(mode))
        return ast.FuncCall("trim", args)

    def _column_or_call(self) -> ast.Node:
        t = self.peek()
        if t.kind == "ident" and t.value.upper() in RESERVED:
            # reserved words used as functions (REPLACE(x,..), LEFT(s,n), …)
            if self.peek(1).kind == "op" and self.peek(1).value == "(":
                pass
            else:
                raise ParseError("expected expression", t)
        name = self.ident()
        if self.at_op("("):
            self.next()
            lname = name.lower()
            if lname == "trim":
                return self._trim_call()
            fc = ast.FuncCall(lname)
            if self.at_op("*"):
                self.next()
                fc.star = True
            elif not self.at_op(")"):
                fc.distinct = self.eat_kw("DISTINCT")
                fc.args.append(self.parse_expr())
                while self.eat_op(","):
                    fc.args.append(self.parse_expr())
                if lname == "group_concat" and self.eat_kw("ORDER"):
                    self.expect_kw("BY")
                    fc.order_by = []
                    while True:
                        e = self.parse_expr()
                        desc = bool(self.eat_kw("DESC"))
                        if not desc:
                            self.eat_kw("ASC")
                        fc.order_by.append((e, desc))
                        if not self.eat_op(","):
                            break
                if lname == "group_concat" and self.eat_kw("SEPARATOR"):
                    sep = self.peek()
                    if sep.kind != "str":
                        raise ParseError("SEPARATOR expects a string literal", sep)
                    self.next()
                    fc.separator = sep.value
            self.expect_op(")")
            if self.at_kw("OVER"):
                self.next()
                fc.over = self._window_spec()
            return fc
        table = db = ""
        if self.eat_op("."):
            table, name = name, self.ident()
            if self.eat_op("."):
                db, table, name = table, name, self.ident()
        return ast.ColumnName(name, table=table, db=db)

    def _window_spec(self) -> ast.WindowSpec:
        self.expect_op("(")
        spec = ast.WindowSpec()
        if self.at_kw("PARTITION"):
            self.next()
            self.expect_kw("BY")
            spec.partition_by.append(self.parse_expr())
            while self.eat_op(","):
                spec.partition_by.append(self.parse_expr())
        if self.at_kw("ORDER"):
            self.next()
            self.expect_kw("BY")
            spec.order_by = self.parse_order_items()
        if self.at_kw("ROWS", "RANGE", "GROUPS"):
            unit = self.next().value.upper()

            def bound(is_start: bool):
                if self.eat_kw("UNBOUNDED"):
                    self.expect_kw("PRECEDING" if is_start else "FOLLOWING")
                    return ("unbounded", 0)
                if self.eat_kw("CURRENT"):
                    self.expect_kw("ROW")
                    return ("current", 0)
                t = self.next()
                if t.kind != "int":
                    raise ParseError("expected frame offset", t)
                if self.eat_kw("PRECEDING"):
                    return ("preceding", int(t.value))
                self.expect_kw("FOLLOWING")
                return ("following", int(t.value))

            if self.eat_kw("BETWEEN"):
                start = bound(True)
                self.expect_kw("AND")
                end = bound(False)
            else:
                start = bound(True)
                end = ("current", 0)
            # canonical spellings of the implicit frames
            if start == ("unbounded", 0) and end == ("current", 0):
                spec.rows_frame = unit == "ROWS"
            elif start == ("unbounded", 0) and end[0] == "unbounded":
                spec.whole_partition = True
            elif unit == "ROWS":
                spec.frame = (start[0], start[1], end[0], end[1])
            else:
                raise ParseError("bounded RANGE/GROUPS frames are not supported", self.peek())
        self.expect_op(")")
        return spec

    def _case(self) -> ast.CaseWhen:
        self.expect_kw("CASE")
        operand = None
        if not self.at_kw("WHEN"):
            operand = self.parse_expr()
        branches = []
        while self.eat_kw("WHEN"):
            cond = self.parse_expr()
            self.expect_kw("THEN")
            branches.append((cond, self.parse_expr()))
        else_v = self.parse_expr() if self.eat_kw("ELSE") else None
        self.expect_kw("END")
        return ast.CaseWhen(operand, branches, else_v)

    # -- DML ------------------------------------------------------------------
    def parse_insert(self) -> ast.Insert:
        replace = self.eat_kw("REPLACE")
        if not replace:
            self.expect_kw("INSERT")
        ignore = self.eat_kw("IGNORE")
        self.eat_kw("INTO")
        tbl = self._table_ref_simple()
        ins = ast.Insert(tbl, replace=replace, ignore=ignore)
        if self.at_op("("):
            self.next()
            ins.columns.append(self.ident())
            while self.eat_op(","):
                ins.columns.append(self.ident())
            self.expect_op(")")
        if self.at_kw("VALUES", "VALUE"):
            self.next()
            while True:
                self.expect_op("(")
                row = [] if self.at_op(")") else [self.parse_expr()]
                while self.eat_op(","):
                    row.append(self.parse_expr())
                self.expect_op(")")
                ins.values.append(row)
                if not self.eat_op(","):
                    break
        elif self.at_kw("SELECT", "WITH"):
            ins.select = self.parse_select_stmt()
        if self.at_kw("ON"):
            self.next()
            self.expect_kw("DUPLICATE")
            self.expect_kw("KEY")
            self.expect_kw("UPDATE")
            while True:
                cname = self.ident()
                self.expect_op("=")
                ins.on_dup_update.append((cname, self.parse_expr()))
                if not self.eat_op(","):
                    break
        return ins

    def parse_update(self) -> ast.Update:
        self.expect_kw("UPDATE")
        tbl = self._table_ref_simple(allow_alias=True)
        self.expect_kw("SET")
        upd = ast.Update(tbl)
        while True:
            colname = self._column_or_call()
            if not isinstance(colname, ast.ColumnName):
                raise ParseError("expected column in SET", self.peek())
            self.expect_op("=")
            upd.assignments.append((colname, self.parse_expr()))
            if not self.eat_op(","):
                break
        if self.eat_kw("WHERE"):
            upd.where = self.parse_expr()
        if self.at_kw("ORDER"):
            self.next()
            self.expect_kw("BY")
            upd.order_by = self.parse_order_items()
        if self.eat_kw("LIMIT"):
            upd.limit = self._limit_value()
        return upd

    def parse_delete(self) -> ast.Delete:
        self.expect_kw("DELETE")
        self.expect_kw("FROM")
        tbl = self._table_ref_simple(allow_alias=True)
        d = ast.Delete(tbl)
        if self.eat_kw("WHERE"):
            d.where = self.parse_expr()
        if self.at_kw("ORDER"):
            self.next()
            self.expect_kw("BY")
            d.order_by = self.parse_order_items()
        if self.eat_kw("LIMIT"):
            d.limit = self._limit_value()
        return d

    def _table_ref_simple(self, allow_alias: bool = False) -> ast.TableRef:
        name = self.ident()
        db = ""
        if self.eat_op("."):
            db, name = name, self.ident()
        alias = ""
        if allow_alias:
            if self.eat_kw("AS"):
                alias = self.ident()
            elif self.peek().kind in ("ident", "qident") and not self.at_kw("SET", "WHERE", "ORDER", "LIMIT"):
                alias = self.ident()
        return ast.TableRef(name, db=db, alias=alias)

    # -- DDL ------------------------------------------------------------------
    def parse_typedef(self) -> ast.TypeDef:
        name = self.ident().lower()
        if name == "double" and self.at_kw("PRECISION"):
            self.next()
        td = ast.TypeDef(name)
        if self.at_op("("):
            self.next()
            td.length = int(self.next().value)
            if self.eat_op(","):
                td.scale = int(self.next().value)
            self.expect_op(")")
        if self.eat_kw("UNSIGNED"):
            td.unsigned = True
        self.eat_kw("SIGNED")
        # charset is noise; collation is semantic (ci vs bin compares)
        if self.eat_kw("CHARACTER"):
            self.expect_kw("SET")
            self.ident()
        if self.eat_kw("COLLATE"):
            td.collate = self.ident().lower()
        return td

    def parse_create(self) -> ast.Node:
        self.expect_kw("CREATE")
        if self.eat_kw("USER"):
            return self.parse_create_user()
        if self.at_kw("RESOURCE"):
            return self._resource_group("create")
        if self.at_kw("GLOBAL", "SESSION", "BINDING"):
            is_global = self.eat_kw("GLOBAL")
            if not is_global:
                self.eat_kw("SESSION")
            self.expect_kw("BINDING")
            self.expect_kw("FOR")
            fstart = self.peek().pos
            self.parse_select_stmt()
            if not self.at_kw("USING"):
                raise ParseError("expected USING", self.peek())
            fend = self.peek().pos
            self.next()
            ustart = self.peek().pos
            self.parse_select_stmt()
            return ast.CreateBinding(
                self.sql[fstart:fend].strip(),
                self.sql[ustart:].rstrip().rstrip(";"),
                is_global,
            )
        or_replace = False
        if self.at_kw("OR"):
            self.next()
            self.expect_kw("REPLACE")
            or_replace = True
        if self.eat_kw("VIEW"):
            tbl = self._table_ref_simple()
            cols: list[str] = []
            if self.eat_op("("):
                cols.append(self.ident())
                while self.eat_op(","):
                    cols.append(self.ident())
                self.expect_op(")")
            self.expect_kw("AS")
            start = self.peek().pos
            self.parse_select_stmt()  # validate the definition now
            text = self.sql[start:].rstrip().rstrip(";")
            return ast.CreateView(tbl, [c.lower() for c in cols], text, or_replace)
        if or_replace:
            raise ParseError("OR REPLACE only applies to CREATE VIEW", self.peek())
        if self.eat_kw("SEQUENCE"):
            ine = self._if_not_exists()
            tbl = self._table_ref_simple()
            cs = ast.CreateSequence(tbl.name, db=tbl.db, if_not_exists=ine)
            while self.peek().kind == "ident" and not self.at_op(";"):
                kw = self.ident().upper()
                if kw == "START":
                    self.eat_kw("WITH")
                    self.eat_op("=")
                    cs.start = int(self.next().value)
                elif kw == "INCREMENT":
                    self.eat_kw("BY")
                    self.eat_op("=")
                    cs.increment = int(self.next().value)
                elif kw in ("CACHE", "MINVALUE", "MAXVALUE"):
                    self.next()  # value (ignored: single-process)
                elif kw in ("NOCACHE", "NOCYCLE", "CYCLE"):
                    pass
                else:
                    raise ParseError(f"unknown sequence option {kw!r}", self.peek())
            return cs
        if self.at_kw("DATABASE", "SCHEMA"):
            self.next()
            ine = self._if_not_exists()
            return ast.CreateDatabase(self.ident(), if_not_exists=ine)
        if self.at_kw("UNIQUE", "INDEX"):
            unique = self.eat_kw("UNIQUE")
            self.expect_kw("INDEX")
            iname = self.ident()
            self.expect_kw("ON")
            tbl = self._table_ref_simple()
            self.expect_op("(")
            cols = [self.ident()]
            while self.eat_op(","):
                cols.append(self.ident())
            self.expect_op(")")
            return ast.CreateIndex(ast.IndexDef(iname, cols, unique=unique), tbl)
        self.expect_kw("TABLE")
        ine = self._if_not_exists()
        tbl = self._table_ref_simple()
        ct = ast.CreateTable(tbl, if_not_exists=ine)
        self.expect_op("(")
        while True:
            cons_name = ""
            if self.at_kw("CONSTRAINT"):
                self.next()
                if not self.at_kw("FOREIGN", "PRIMARY", "UNIQUE"):
                    cons_name = self.ident()
            if self.at_kw("FOREIGN"):
                self.next()
                self.expect_kw("KEY")
                if self.peek().kind in ("ident", "qident") and not self.at_op("("):
                    iname = self.ident()  # always consume the index name
                    cons_name = cons_name or iname
                ct.foreign_keys.append(self._fk_tail(cons_name or f"fk_{len(ct.foreign_keys) + 1}"))
            elif self.at_kw("PRIMARY"):
                self.next()
                self.expect_kw("KEY")
                self.expect_op("(")
                cols = [self.ident()]
                while self.eat_op(","):
                    cols.append(self.ident())
                self.expect_op(")")
                ct.indexes.append(ast.IndexDef("primary", cols, unique=True, primary=True))
            elif self.at_kw("UNIQUE", "INDEX", "KEY"):
                unique = self.eat_kw("UNIQUE")
                if not self.eat_kw("INDEX"):
                    self.eat_kw("KEY")
                iname = self.ident() if self.peek().kind in ("ident", "qident") and not self.at_op("(") else ""
                self.expect_op("(")
                cols = [self.ident()]
                while self.eat_op(","):
                    cols.append(self.ident())
                self.expect_op(")")
                ct.indexes.append(ast.IndexDef(iname or f"idx_{len(ct.indexes)}", cols, unique=unique))
            else:
                cname = self.ident()
                td = self.parse_typedef()
                cd = ast.ColumnDef(cname, td)
                while True:
                    if self.eat_kw("NOT"):
                        self.expect_kw("NULL")
                        cd.not_null = True
                    elif self.eat_kw("NULL"):
                        pass
                    elif self.eat_kw("DEFAULT"):
                        cd.default = self._primary() if not self.at_op("-") else self.parse_expr()
                    elif self.at_kw("PRIMARY"):
                        self.next()
                        self.expect_kw("KEY")
                        cd.primary_key = True
                    elif self.eat_kw("UNIQUE"):
                        self.eat_kw("KEY")
                        cd.unique = True
                    elif self.eat_kw("AUTO_INCREMENT"):
                        cd.auto_increment = True
                    elif self.eat_kw("COMMENT"):
                        self.next()
                    else:
                        break
                ct.columns.append(cd)
            if not self.eat_op(","):
                break
        self.expect_op(")")
        if self.at_kw("PARTITION"):
            self.next()
            self.expect_kw("BY")
            if self.eat_kw("HASH"):
                self.expect_op("(")
                col = self.ident().lower()
                self.expect_op(")")
                self.expect_kw("PARTITIONS")
                ntok = self.next()
                if ntok.kind != "int" or int(ntok.value) < 1:
                    raise ParseError("expected partition count", ntok)
                ct.partition_by = ast.PartitionByDef("hash", col, num=int(ntok.value))
            else:
                self.expect_kw("RANGE")
                self.expect_op("(")
                col = self.ident().lower()
                self.expect_op(")")
                self.expect_op("(")
                defs = [self._partition_def()]
                while self.eat_op(","):
                    defs.append(self._partition_def())
                self.expect_op(")")
                ct.partition_by = ast.PartitionByDef("range", col, defs=defs)
        # table options: TTL parsed, everything else swallowed
        while self.peek().kind == "ident" and not self.at_op(";"):
            if self.at_kw("TTL"):
                self.next()
                self.expect_op("=")
                ct.ttl = self._ttl_spec()
                continue
            if self.peek().value.upper() == "TTL_ENABLE":
                self.next()
                self.expect_op("=")
                ct.ttl_enable = self._string_lit().upper() == "ON"
                continue
            if self.at_kw("AUTO_INCREMENT"):
                self.next()
                self.expect_op("=")
                t = self.next()
                ct.auto_increment_base = int(t.value)
                continue
            self.next()
            if self.eat_op("="):
                self.next()
        return ct

    def _ttl_spec(self) -> tuple[str, int]:
        """`col` + INTERVAL n DAY"""
        col = self.ident().lower()
        self.expect_op("+")
        self.expect_kw("INTERVAL")
        t = self.next()
        if t.kind != "int":
            raise ParseError("expected TTL interval count", t)
        unit = self.ident().lower()
        days = int(t.value)
        if unit in ("day", "days"):
            pass
        elif unit in ("week", "weeks"):
            days *= 7
        elif unit in ("month", "months"):
            days *= 30
        else:
            raise ParseError(f"unsupported TTL unit {unit!r}", t)
        return col, days

    def _if_not_exists(self) -> bool:
        if self.at_kw("IF"):
            self.next()
            self.expect_kw("NOT")
            self.expect_kw("EXISTS")
            return True
        return False

    def parse_drop(self) -> ast.Node:
        self.expect_kw("DROP")
        if self.at_kw("RESOURCE"):
            return self._resource_group("drop")
        if self.at_kw("GLOBAL", "SESSION", "BINDING"):
            is_global = self.eat_kw("GLOBAL")
            if not is_global:
                self.eat_kw("SESSION")
            self.expect_kw("BINDING")
            self.expect_kw("FOR")
            fstart = self.peek().pos
            self.parse_select_stmt()
            return ast.DropBinding(self.sql[fstart:].rstrip().rstrip(";"), is_global)
        if self.eat_kw("USER"):
            ie = self._if_exists()
            users = [self._user_spec()]
            while self.eat_op(","):
                users.append(self._user_spec())
            return ast.DropUser(users, ie)
        if self.at_kw("DATABASE", "SCHEMA"):
            self.next()
            ie = self._if_exists()
            return ast.DropDatabase(self.ident(), if_exists=ie)
        if self.at_kw("INDEX"):
            self.next()
            name = self.ident()
            self.expect_kw("ON")
            return ast.DropIndex(name, self._table_ref_simple())
        if self.eat_kw("VIEW"):
            ie = self._if_exists()
            tables = [self._table_ref_simple()]
            while self.eat_op(","):
                tables.append(self._table_ref_simple())
            return ast.DropView(tables, ie)
        if self.eat_kw("SEQUENCE"):
            ie = self._if_exists()
            names = [self.ident().lower()]
            while self.eat_op(","):
                names.append(self.ident().lower())
            return ast.DropSequence(names, ie)
        self.expect_kw("TABLE")
        ie = self._if_exists()
        tables = [self._table_ref_simple()]
        while self.eat_op(","):
            tables.append(self._table_ref_simple())
        return ast.DropTable(tables, if_exists=ie)

    def _if_exists(self) -> bool:
        if self.at_kw("IF"):
            self.next()
            self.expect_kw("EXISTS")
            return True
        return False

    def parse_plan_replayer(self) -> ast.Node:
        """PLAN REPLAYER DUMP EXPLAIN <stmt> | PLAN REPLAYER LOAD '<path>'
        (ref: parser.y PlanReplayerStmt)."""
        self.expect_kw("PLAN")
        self.expect_kw("REPLAYER")
        if self.eat_kw("LOAD"):
            return ast.PlanReplayer("load", path=self._string_lit())
        self.expect_kw("DUMP")
        self.expect_kw("EXPLAIN")
        start = self.peek().pos
        self.parse_statement()  # validate; the dump captures the raw text
        return ast.PlanReplayer("dump", sql=self.sql[start:].strip().rstrip(";"))

    def parse_alter(self):
        self.expect_kw("ALTER")
        if self.at_kw("RESOURCE"):
            return self._resource_group("alter")
        if self.eat_kw("USER"):
            ie = self._if_exists()
            users = [self._user_spec()]
            while self.eat_op(","):
                users.append(self._user_spec())
            return ast.AlterUser(users, ie)
        self.expect_kw("TABLE")
        tbl = self._table_ref_simple()
        at = ast.AlterTable(tbl)
        if self.eat_kw("ADD"):
            if self.at_kw("CONSTRAINT", "FOREIGN"):
                cons_name = ""
                if self.eat_kw("CONSTRAINT") and not self.at_kw("FOREIGN"):
                    cons_name = self.ident()
                self.expect_kw("FOREIGN")
                self.expect_kw("KEY")
                if self.peek().kind in ("ident", "qident") and not self.at_op("("):
                    iname = self.ident()  # always consume the index name
                    cons_name = cons_name or iname
                at.action, at.fk = "add_fk", self._fk_tail(cons_name)
            elif self.at_kw("PARTITION"):
                self.next()
                self.expect_op("(")
                name, lt = self._partition_def()
                self.expect_op(")")
                at.action, at.name, at.less_than = "add_partition", name, lt
            elif self.at_kw("INDEX", "KEY", "UNIQUE"):
                unique = self.eat_kw("UNIQUE")
                if not self.eat_kw("INDEX"):
                    self.eat_kw("KEY")
                iname = self.ident()
                self.expect_op("(")
                cols = [self.ident()]
                while self.eat_op(","):
                    cols.append(self.ident())
                self.expect_op(")")
                at.action, at.index = "add_index", ast.IndexDef(iname, cols, unique=unique)
            else:
                self.eat_kw("COLUMN")
                cname = self.ident()
                td = self.parse_typedef()
                cd = ast.ColumnDef(cname, td)
                if self.eat_kw("NOT"):
                    self.expect_kw("NULL")
                    cd.not_null = True
                if self.eat_kw("DEFAULT"):
                    cd.default = self.parse_expr()
                at.action, at.column = "add_column", cd
        elif self.eat_kw("DROP"):
            if self.at_kw("FOREIGN"):
                self.next()
                self.expect_kw("KEY")
                at.action, at.name = "drop_fk", self.ident().lower()
            elif self.at_kw("PARTITION"):
                self.next()
                at.action, at.name = "drop_partition", self.ident()
            elif self.at_kw("INDEX", "KEY"):
                self.next()
                at.action, at.name = "drop_index", self.ident()
            else:
                self.eat_kw("COLUMN")
                at.action, at.name = "drop_column", self.ident()
        elif self.eat_kw("TRUNCATE"):
            self.expect_kw("PARTITION")
            at.action, at.name = "truncate_partition", self.ident()
        elif self.at_kw("TTL"):
            self.next()
            self.expect_op("=")
            at.action, at.ttl = "set_ttl", self._ttl_spec()
        elif self.peek().value.upper() == "TTL_ENABLE":
            self.next()
            self.expect_op("=")
            at.action, at.ttl_enable = "ttl_enable", self._string_lit().upper() == "ON"
        elif self.eat_kw("REMOVE"):
            self.expect_kw("TTL")
            at.action = "remove_ttl"
        elif self.eat_kw("RENAME"):
            self.eat_kw("TO")
            at.action, at.name = "rename", self.ident()
        else:
            raise ParseError("unsupported ALTER action", self.peek())
        return at

    def _fk_tail(self, name: str) -> "ast.FKDef":
        """(cols) REFERENCES tbl (cols) [ON DELETE act] [ON UPDATE act]
        (ref: parser.y ReferenceDef)."""
        self.expect_op("(")
        cols = [self.ident()]
        while self.eat_op(","):
            cols.append(self.ident())
        self.expect_op(")")
        self.expect_kw("REFERENCES")
        ref = self._table_ref_simple()
        self.expect_op("(")
        rcols = [self.ident()]
        while self.eat_op(","):
            rcols.append(self.ident())
        self.expect_op(")")
        fk = ast.FKDef(name.lower(), [c.lower() for c in cols], ref, [c.lower() for c in rcols])

        def action() -> str:
            if self.eat_kw("RESTRICT"):
                return "restrict"
            if self.eat_kw("CASCADE"):
                return "cascade"
            if self.eat_kw("SET"):
                self.expect_kw("NULL")
                return "set_null"
            self.expect_kw("NO")
            self.expect_kw("ACTION")
            return "no_action"

        while self.at_kw("ON"):
            self.next()
            if self.eat_kw("DELETE"):
                fk.on_delete = action()
            else:
                self.expect_kw("UPDATE")
                fk.on_update = action()
        return fk

    def _partition_def(self) -> tuple[str, "int | None"]:
        """PARTITION name VALUES LESS THAN (n) | MAXVALUE"""
        self.expect_kw("PARTITION")
        name = self.ident().lower()
        self.expect_kw("VALUES")
        self.expect_kw("LESS")
        self.expect_kw("THAN")
        if self.eat_kw("MAXVALUE"):
            return name, None
        self.expect_op("(")
        if self.eat_kw("MAXVALUE"):
            self.expect_op(")")
            return name, None
        neg = self.eat_op("-")
        tok = self.next()
        if tok.kind != "int":
            raise ParseError("expected integer partition bound", tok)
        self.expect_op(")")
        return name, int(tok.value) * (-1 if neg else 1)

    def parse_truncate(self) -> ast.TruncateTable:
        self.expect_kw("TRUNCATE")
        self.eat_kw("TABLE")
        return ast.TruncateTable(self._table_ref_simple())

    # -- misc -----------------------------------------------------------------
    def parse_explain(self):
        self.next()  # EXPLAIN/DESC/DESCRIBE
        analyze = self.eat_kw("ANALYZE")
        # DESCRIBE t / EXPLAIN t: table describe == SHOW COLUMNS FROM t
        t = self.peek()
        if not analyze and t.kind in ("ident", "qident") and t.value.upper() not in (
            "SELECT", "INSERT", "UPDATE", "DELETE", "REPLACE", "WITH", "TABLE", "FORMAT"
        ):
            ref = self._table_ref_simple()
            target = f"{ref.db}.{ref.name}" if ref.db else ref.name
            return ast.Show("columns", target=target)
        return ast.Explain(self.parse_statement(), analyze=analyze)

    def parse_rename(self) -> ast.Node:
        # RENAME TABLE a TO b [, c TO d ...] → validated + applied as a unit
        self.expect_kw("RENAME")
        self.expect_kw("TABLE")
        pairs = []
        while True:
            old = self._table_ref_simple()
            self.expect_kw("TO")
            pairs.append((old, self._table_ref_simple()))
            if not self.eat_op(","):
                break
        return ast.RenameTables(pairs)

    def parse_do(self) -> ast.Node:
        self.expect_kw("DO")
        exprs = [self.parse_expr()]
        while self.eat_op(","):
            exprs.append(self.parse_expr())
        return ast.DoStmt(exprs)

    def parse_checksum(self) -> ast.Node:
        self.expect_kw("CHECKSUM")
        self.expect_kw("TABLE")
        names = [self._table_ref_simple()]
        while self.eat_op(","):
            names.append(self._table_ref_simple())
        return ast.ChecksumTable(names)

    def parse_table_stmt(self) -> ast.Node:
        # MySQL 8.0 TABLE t [ORDER BY ...] [LIMIT ...] == SELECT * FROM t ...
        self.expect_kw("TABLE")
        sel = ast.Select(items=[ast.SelectItem(ast.Wildcard())], from_=self._table_ref_simple())
        if self.eat_kw("ORDER"):
            self.expect_kw("BY")
            sel.order_by = self.parse_order_items()
        self._parse_limit(sel)
        return sel

    def parse_set(self):
        self.expect_kw("SET")
        if self.at_kw("RESOURCE"):
            self.next()
            self.expect_kw("GROUP")
            return ast.SetResourceGroup(self.ident().lower())
        scope = "session"
        if self.eat_kw("GLOBAL"):
            scope = "global"
        elif self.eat_kw("SESSION"):
            pass
        if self.at_op("@"):
            self.next()
            if self.at_op("@"):
                self.next()
                # @@global.x / @@session.x
                name = self.ident()
                if name.lower() in ("global", "session") and self.eat_op("."):
                    scope = name.lower()
                    name = self.ident()
            else:
                name = "@" + self.ident()
        else:
            name = self.ident()
        if not self.eat_op("="):
            self.expect_op(":=")
        val = self.parse_expr()
        return ast.SetVariable(name.lower(), val, scope=scope)

    def _string_lit(self) -> str:
        t = self.next()
        if t.kind != "str":
            raise ParseError("expected string literal", t)
        return t.value.decode() if isinstance(t.value, bytes) else t.value

    def parse_import(self) -> ast.ImportInto:
        self.expect_kw("IMPORT")
        self.expect_kw("INTO")
        tbl = self._table_ref_simple()
        self.expect_kw("FROM")
        path = self._string_lit()
        opts: dict = {}
        if self.eat_kw("WITH"):
            while True:
                name = self.ident().lower()
                if self.eat_op("="):
                    v = self.next()
                    val = v.value.decode() if isinstance(v.value, bytes) else v.value
                else:
                    val = 1
                opts[name] = val
                if not self.eat_op(","):
                    break
        return ast.ImportInto(tbl, path, opts)

    def parse_backup(self) -> ast.Backup:
        self.expect_kw("BACKUP")
        db = ""
        tables: list = []
        if self.eat_kw("DATABASE"):
            db = self.ident().lower()
        else:
            self.expect_kw("TABLE")
            tables = [self._table_ref_simple()]
            while self.eat_op(","):
                tables.append(self._table_ref_simple())
        self.expect_kw("TO")
        return ast.Backup(self._string_lit(), db=db, tables=tables)

    def parse_restore(self) -> ast.Restore:
        self.expect_kw("RESTORE")
        self.expect_kw("DATABASE")
        db = ""
        if not self.at_kw("FROM"):
            db = self.ident().lower()
        self.expect_kw("FROM")
        return ast.Restore(self._string_lit(), db=db)

    def _user_spec(self) -> ast.UserSpec:
        t = self.peek()
        if t.kind == "str":
            self.next()
            name = t.value.decode() if isinstance(t.value, bytes) else t.value
        else:
            name = self.ident()
        host = "%"
        if self.at_op("@"):
            self.next()
            h = self.peek()
            if h.kind == "str":
                self.next()
                host = h.value.decode() if isinstance(h.value, bytes) else h.value
            else:
                host = self.ident()
        spec = ast.UserSpec(name, host)
        if self.eat_kw("IDENTIFIED"):
            spec.has_auth = True
            if self.eat_kw("WITH"):
                t = self.peek()
                if t.kind == "str":
                    self.next()
                    spec.plugin = t.value.decode() if isinstance(t.value, bytes) else t.value
                else:
                    spec.plugin = self.ident()
                if self.eat_kw("BY"):
                    spec.password = self._string_lit()
            else:
                self.expect_kw("BY")
                spec.password = self._string_lit()
        return spec

    def parse_create_user(self) -> ast.CreateUser:
        # caller consumed CREATE USER
        ine = self._if_not_exists()
        users = [self._user_spec()]
        while self.eat_op(","):
            users.append(self._user_spec())
        return ast.CreateUser(users, ine)

    _PRIV_KWS = ("SELECT", "INSERT", "UPDATE", "DELETE", "CREATE", "DROP", "INDEX", "ALTER", "SUPER")

    def parse_grant(self) -> ast.Grant:
        revoke = bool(self.eat_kw("REVOKE"))
        if not revoke:
            self.expect_kw("GRANT")
        privs: list[str] = []
        if self.eat_kw("ALL"):
            self.eat_kw("PRIVILEGES")
            privs = ["all"]
        else:
            while True:
                kw = self.next()
                if kw.value.upper() not in self._PRIV_KWS:
                    raise ParseError(f"unknown privilege {kw.value!r}", kw)
                privs.append(kw.value.lower())
                if not self.eat_op(","):
                    break
        self.expect_kw("ON")
        db = table = ""
        if self.eat_op("*"):
            self.expect_op(".")
            self.expect_op("*")
        else:
            first = self.ident()
            if self.eat_op("."):
                if self.eat_op("*"):
                    db = first.lower()
                else:
                    db, table = first.lower(), self.ident().lower()
            else:
                table = first.lower()  # bare table → current db at exec
        self.expect_kw("FROM" if revoke else "TO")
        spec = self._user_spec()
        return ast.Grant(privs, db, table, spec.name, spec.host, revoke)

    def _resource_group(self, op: str) -> ast.ResourceGroupStmt:
        self.expect_kw("RESOURCE")
        self.expect_kw("GROUP")
        st = ast.ResourceGroupStmt(op, "")
        if op == "create":
            st.if_not_exists = self._if_not_exists()
        if op == "drop":
            st.if_exists = self._if_exists()
        st.name = self.ident().lower()
        if op == "drop":
            return st
        while self.peek().kind == "ident" and not self.at_op(";"):
            kw = self.ident().upper()
            if kw == "RU_PER_SEC":
                self.expect_op("=")
                st.ru_per_sec = int(self.next().value)
            elif kw == "BURSTABLE":
                if self.eat_op("="):
                    self.next()
                st.burstable = True
            elif kw == "QUERY_LIMIT":
                self.expect_op("=")
                self.expect_op("(")
                while not self.eat_op(")"):
                    opt = self.ident().upper()
                    self.expect_op("=")
                    if opt == "EXEC_ELAPSED":
                        st.exec_elapsed_s = _parse_duration(self._string_lit())
                    elif opt == "ACTION":
                        st.action = self.ident().upper()
                    else:
                        raise ParseError(f"unknown QUERY_LIMIT option {opt!r}", self.peek())
                    self.eat_op(",")
            else:
                raise ParseError(f"unknown resource group option {kw!r}", self.peek())
            self.eat_op(",")
        return st

    def parse_recover(self) -> ast.RecoverTable:
        self.next()  # RECOVER | FLASHBACK
        self.expect_kw("TABLE")
        tbl = self._table_ref_simple()
        new_name = ""
        if self.eat_kw("TO"):
            new_name = self.ident().lower()
        return ast.RecoverTable(tbl, new_name)

    def parse_admin(self) -> ast.Admin:
        self.expect_kw("ADMIN")
        if self.eat_kw("CHECK"):
            if self.eat_kw("TABLE"):
                return ast.Admin("check_table", self._table_ref_simple())
            self.expect_kw("INDEX")
            tbl = self._table_ref_simple()
            return ast.Admin("check_index", tbl, self.ident().lower())
        self.expect_kw("SHOW")
        self.expect_kw("DDL")
        self.expect_kw("JOBS")
        return ast.Admin("show_ddl_jobs")

    def parse_kill(self) -> ast.Kill:
        self.expect_kw("KILL")
        query_only = True
        if self.eat_kw("CONNECTION"):
            query_only = False
        else:
            self.eat_kw("QUERY")
        t = self.next()
        if t.kind != "int":
            raise ParseError("expected connection id", t)
        return ast.Kill(int(t.value), query_only)

    def parse_prepare(self) -> ast.Prepare:
        self.expect_kw("PREPARE")
        name = self.ident().lower()
        self.expect_kw("FROM")
        t = self.peek()
        if t.kind == "str":
            self.next()
            text = t.value.decode() if isinstance(t.value, bytes) else t.value
            return ast.Prepare(name, text=text)
        if self.at_op("@"):
            self.next()
            return ast.Prepare(name, from_var=self.ident().lower())
        raise ParseError("expected string literal or @var after FROM", t)

    def parse_execute_stmt(self) -> ast.ExecutePrepared:
        self.expect_kw("EXECUTE")
        name = self.ident().lower()
        using: list[str] = []
        if self.eat_kw("USING"):
            while True:
                self.expect_op("@")
                using.append(self.ident().lower())
                if not self.eat_op(","):
                    break
        return ast.ExecutePrepared(name, using)

    def parse_deallocate(self) -> ast.Deallocate:
        self.expect_kw("DEALLOCATE")
        self.expect_kw("PREPARE")
        return ast.Deallocate(self.ident().lower())

    def parse_show(self) -> ast.Show:
        self.expect_kw("SHOW")
        if self.eat_kw("TABLES"):
            like = None
            if self.eat_kw("LIKE"):
                like = self.next().value
            return ast.Show("tables", like=like)
        if self.eat_kw("DATABASES"):
            return ast.Show("databases")
        if self.eat_kw("PROCESSLIST"):
            return ast.Show("processlist")
        if self.at_kw("GLOBAL", "SESSION", "BINDINGS"):
            self.eat_kw("GLOBAL") or self.eat_kw("SESSION")
            if self.eat_kw("BINDINGS"):
                return ast.Show("bindings")
            if self.eat_kw("VARIABLES"):
                like = None
                if self.eat_kw("LIKE"):
                    like = self.next().value
                return ast.Show("variables", like=like)
            if self.eat_kw("STATUS"):
                like = None
                if self.eat_kw("LIKE"):
                    like = self.next().value
                return ast.Show("status", like=like)
            raise ParseError("expected BINDINGS, VARIABLES, or STATUS", self.peek())
        if self.eat_kw("GRANTS"):
            target = ""
            if self.eat_kw("FOR"):
                spec = self._user_spec()
                target = f"{spec.name}@{spec.host}"
            return ast.Show("grants", target=target)
        if self.eat_kw("FULL") and self.eat_kw("PROCESSLIST"):
            return ast.Show("processlist")
        if self.eat_kw("VARIABLES"):
            like = None
            if self.eat_kw("LIKE"):
                like = self.next().value
            return ast.Show("variables", like=like)
        if self.eat_kw("CREATE"):
            if self.eat_kw("DATABASE") or self.eat_kw("SCHEMA"):
                return ast.Show("create_database", target=self.ident())
            self.expect_kw("TABLE")
            name = self.ident()
            if self.eat_op("."):  # qualified `db`.`table`
                name = f"{name}.{self.ident()}"
            return ast.Show("create_table", target=name)
        if self.at_kw("TABLE") and self.peek(1).value.upper() == "STATUS":
            self.next()
            self.next()
            like = None
            if self.eat_kw("LIKE"):
                like = self.next().value
            return ast.Show("table_status", like=like)
        if self.eat_kw("COLLATION"):
            like = None
            if self.eat_kw("LIKE"):
                like = self.next().value
            return ast.Show("collation", like=like)
        if self.eat_kw("CHARSET") or (self.at_kw("CHARACTER") and self.peek(1).value.upper() == "SET"):
            if self.at_kw("SET"):
                self.next()
            elif self.at_kw("CHARACTER"):
                self.next()
                self.next()
            like = None
            if self.eat_kw("LIKE"):
                like = self.next().value
            return ast.Show("charset", like=like)
        if self.eat_kw("ENGINES"):
            return ast.Show("engines")
        if self.eat_kw("TRIGGERS"):
            return ast.Show("triggers")
        if self.eat_kw("STATUS"):
            like = None
            if self.eat_kw("LIKE"):
                like = self.next().value
            return ast.Show("status", like=like)
        if self.eat_kw("WARNINGS"):
            return ast.Show("warnings")
        if self.eat_kw("ERRORS"):
            return ast.Show("errors")
        if self.at_kw("COUNT"):  # SHOW COUNT(*) WARNINGS | ERRORS
            self.next()
            self.expect_op("(")
            self.expect_op("*")
            self.expect_op(")")
            if self.eat_kw("WARNINGS"):
                return ast.Show("warning_count")
            self.expect_kw("ERRORS")
            return ast.Show("error_count")
        if self.eat_kw("COLUMNS") or self.eat_kw("FIELDS"):
            self.expect_kw("FROM")
            return ast.Show("columns", target=self.ident())
        if self.eat_kw("INDEX") or self.eat_kw("INDEXES") or self.eat_kw("KEYS"):
            self.expect_kw("FROM")
            return ast.Show("index", target=self.ident())
        if self.eat_kw("STATS_HISTOGRAMS"):
            return ast.Show("stats_histograms")
        if self.eat_kw("STATS_TOPN"):
            return ast.Show("stats_topn")
        if self.eat_kw("STATS_BUCKETS"):
            return ast.Show("stats_buckets")
        raise ParseError("unsupported SHOW", self.peek())

    def parse_use(self) -> ast.UseDatabase:
        self.expect_kw("USE")
        return ast.UseDatabase(self.ident())

    def parse_begin(self) -> ast.Begin:
        if self.eat_kw("START"):
            self.expect_kw("TRANSACTION")
        else:
            self.expect_kw("BEGIN")
        mode = ""
        if self.eat_kw("PESSIMISTIC"):
            mode = "pessimistic"
        elif self.eat_kw("OPTIMISTIC"):
            mode = "optimistic"
        return ast.Begin(mode=mode)

    def parse_load_data(self) -> "ast.LoadData":
        """LOAD DATA [LOCAL] INFILE 'path' INTO TABLE t [FIELDS TERMINATED
        BY 'x' [ENCLOSED BY 'y']] [LINES TERMINATED BY 'z'] [IGNORE n
        LINES|ROWS] [(cols)] (ref: parser.y LoadDataStmt)."""
        self.expect_kw("LOAD")
        self.expect_kw("DATA")
        local = self.eat_kw("LOCAL")
        self.expect_kw("INFILE")
        t = self.next()
        if t.kind != "str":
            raise ParseError("expected file path string", t)
        path = t.value
        dup_mode = ""
        if self.eat_kw("IGNORE"):
            dup_mode = "ignore"
        elif self.eat_kw("REPLACE"):
            dup_mode = "replace"
        self.expect_kw("INTO")
        self.expect_kw("TABLE")
        tbl = self._table_ref_simple()
        stmt = ast.LoadData(path=path, table=tbl, local=local, dup_mode=dup_mode)
        if self.eat_kw("FIELDS") or self.eat_kw("COLUMNS"):
            while self.at_kw("TERMINATED", "ENCLOSED", "ESCAPED", "OPTIONALLY"):
                self.eat_kw("OPTIONALLY")
                if self.eat_kw("TERMINATED"):
                    self.expect_kw("BY")
                    stmt.fields_terminated = self.next().value
                elif self.eat_kw("ENCLOSED"):
                    self.expect_kw("BY")
                    stmt.fields_enclosed = self.next().value
                elif self.eat_kw("ESCAPED"):
                    self.expect_kw("BY")
                    self.next()  # accepted; csv module's default escape rules
        if self.eat_kw("LINES"):
            self.expect_kw("TERMINATED")
            self.expect_kw("BY")
            self.next()  # newline terminators only (csv reader)
        if self.eat_kw("IGNORE"):
            stmt.ignore_lines = int(self.next().value)
            if not (self.eat_kw("LINES") or self.eat_kw("ROWS")):
                raise ParseError("expected LINES/ROWS after IGNORE n", self.peek())
        if self.eat_op("("):
            stmt.columns.append(self.ident().lower())
            while self.eat_op(","):
                stmt.columns.append(self.ident().lower())
            self.expect_op(")")
        return stmt

    def parse_analyze(self) -> ast.AnalyzeTable:
        self.expect_kw("ANALYZE")
        self.expect_kw("TABLE")
        tables = [self._table_ref_simple()]
        # ANALYZE TABLE t PARTITION p0[, p1...] — partition-level analyze
        # whose results merge into table-level global stats (ref:
        # statistics/handle/globalstats)
        if self.at_kw("PARTITION"):
            self.next()
            parts = [self.ident().lower()]
            while self.eat_op(","):
                parts.append(self.ident().lower())
            tables[0].partitions = parts
            return ast.AnalyzeTable(tables)
        while self.eat_op(","):
            tables.append(self._table_ref_simple())
        return ast.AnalyzeTable(tables)


def _parse_hints(text: str) -> list:
    """'READ_FROM_STORAGE(TPU[t]), USE_INDEX(t, i)' → [(name, [args])].
    Unknown hints parse fine and are ignored downstream (MySQL semantics)."""
    out = []
    p = Parser(text)
    while p.peek().kind != "eof":
        if p.peek().kind not in ("ident", "qident"):
            p.next()
            continue
        name = p.ident().lower()
        args: list[str] = []
        if p.eat_op("("):
            depth = 1
            buf = ""
            while depth > 0 and p.peek().kind != "eof":
                t = p.next()
                if t.kind == "op" and t.value == "(":
                    depth += 1
                    buf += "("
                elif t.kind == "op" and t.value == ")":
                    depth -= 1
                    if depth > 0:
                        buf += ")"
                elif t.kind == "op" and t.value == "," and depth == 1:
                    args.append(buf.strip())
                    buf = ""
                else:
                    v = t.value
                    buf += (v.decode() if isinstance(v, bytes) else str(v)) + " "
            if buf.strip():
                args.append(buf.strip())
        out.append((name, args))
        p.eat_op(",")
    return out


def _parse_duration(s: str) -> float:
    """'1s' / '500ms' / '2m' → seconds."""
    s = s.strip().lower()
    for suffix, mult in (("ms", 1e-3), ("s", 1.0), ("m", 60.0), ("h", 3600.0)):
        if s.endswith(suffix):
            return float(s[: -len(suffix)]) * mult
    return float(s)


# full lexer+parser invocations since process start — the statement fast
# lane (session._stmt_cache) is asserted against this: a warm repeated
# statement must not move it (see tests/test_fastlane.py)
_N_PARSES = 0


def parse_count() -> int:
    return _N_PARSES


def parse(sql: str) -> ast.Node:
    return parse_with_params(sql)[0]


def parse_with_params(sql: str) -> tuple[ast.Node, int]:
    """Parse one statement; also report how many ``?`` markers it contains
    (prepared-statement surface, ref: ast.ParamMarkerExpr counting)."""
    global _N_PARSES
    _N_PARSES += 1
    p = Parser(sql)
    stmt = p.parse_statement()
    p.eat_op(";")
    if p.peek().kind != "eof":
        raise ParseError("trailing input", p.peek())
    return stmt, p.param_count


def parse_many(sql: str) -> list[ast.Node]:
    p = Parser(sql)
    out = []
    while p.peek().kind != "eof":
        out.append(p.parse_statement())
        while p.eat_op(";"):
            pass
    return out
