"""SQL frontend.

Reference parity: pkg/parser — a 16,850-line yacc grammar there; here a
hand-written lexer + recursive-descent parser over the MySQL subset the rest
of the stack supports (SURVEY §7.5 explicitly scopes this down: "use a small
SQL grammar, not 16k-line yacc compatibility"). Single entry point:
``parse(sql) -> ast.Statement`` (multi-statement: ``parse_many``).
"""

from tidb_tpu.parser.parser import parse, parse_count, parse_many, parse_with_params, ParseError

__all__ = ["parse", "parse_count", "parse_many", "parse_with_params", "ParseError"]
