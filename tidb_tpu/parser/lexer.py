"""SQL lexer (ref: pkg/parser/lexer.go). Produces (kind, value, pos) tokens.

Kinds: ident, qident (backquoted), int, float, str, op, eof. Keywords are NOT
a separate kind — the parser matches identifiers case-insensitively, which is
how MySQL treats non-reserved words anyway.
"""

from __future__ import annotations

from dataclasses import dataclass


class LexError(Exception):
    def __init__(self, msg: str, pos: int):
        super().__init__(f"{msg} at offset {pos}")
        self.pos = pos


@dataclass(frozen=True)
class Token:
    kind: str  # ident | qident | int | float | str | hexstr | op | eof
    value: str
    pos: int


_OPS = [
    "->>", "->",
    "<=>", "<<", ">>", "<=", ">=", "<>", "!=", ":=", "||", "&&",
    "(", ")", ",", ".", ";", "+", "-", "*", "/", "%", "=", "<", ">",
    "!", "~", "^", "&", "|", "@", "?", "[", "]",
]


def tokenize(sql: str) -> list[Token]:
    toks: list[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c in " \t\r\n":
            i += 1
            continue
        # comments
        if c == "-" and sql.startswith("--", i):
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if c == "#":
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if c == "/" and sql.startswith("/*", i):
            j = sql.find("*/", i + 2)
            if j < 0:
                raise LexError("unterminated comment", i)
            if sql.startswith("/*+", i):
                # optimizer hint comment → token (ref: parser hint scanning)
                toks.append(Token("hint", sql[i + 3 : j].strip(), i))
            i = j + 2
            continue
        # strings
        if c in ("'", '"'):
            q = c
            j = i + 1
            buf = []
            while j < n:
                ch = sql[j]
                if ch == "\\" and j + 1 < n:
                    esc = sql[j + 1]
                    if esc in ("%", "_"):
                        # \% and \_ keep the backslash: they are LIKE-pattern
                        # escapes resolved at match time, not string escapes
                        # (ref: MySQL string-literal rules for \% \_)
                        buf.append("\\" + esc)
                    else:
                        buf.append({"n": "\n", "t": "\t", "r": "\r", "0": "\0", "\\": "\\", q: q}.get(esc, esc))
                    j += 2
                    continue
                if ch == q:
                    if j + 1 < n and sql[j + 1] == q:  # doubled quote
                        buf.append(q)
                        j += 2
                        continue
                    break
                buf.append(ch)
                j += 1
            if j >= n:
                raise LexError("unterminated string", i)
            toks.append(Token("str", "".join(buf), i))
            i = j + 1
            continue
        # backquoted identifier
        if c == "`":
            j = sql.find("`", i + 1)
            if j < 0:
                raise LexError("unterminated identifier", i)
            toks.append(Token("qident", sql[i + 1 : j], i))
            i = j + 1
            continue
        # numbers
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            isfloat = False
            if sql.startswith("0x", i) or sql.startswith("0X", i):
                j = i + 2
                while j < n and sql[j] in "0123456789abcdefABCDEF":
                    j += 1
                toks.append(Token("int", str(int(sql[i:j], 16)), i))
                i = j
                continue
            while j < n and sql[j].isdigit():
                j += 1
            if j < n and sql[j] == ".":
                isfloat = True
                j += 1
                while j < n and sql[j].isdigit():
                    j += 1
            if j < n and sql[j] in "eE":
                k = j + 1
                if k < n and sql[k] in "+-":
                    k += 1
                if k < n and sql[k].isdigit():
                    isfloat = True
                    j = k
                    while j < n and sql[j].isdigit():
                        j += 1
            toks.append(Token("float" if isfloat else "int", sql[i:j], i))
            i = j
            continue
        # identifiers / keywords
        if c.isalpha() or c == "_" or c == "$":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] in "_$"):
                j += 1
            toks.append(Token("ident", sql[i:j], i))
            i = j
            continue
        # operators
        for op in _OPS:
            if sql.startswith(op, i):
                toks.append(Token("op", op, i))
                i += len(op)
                break
        else:
            raise LexError(f"unexpected character {c!r}", i)
    toks.append(Token("eof", "", n))
    return toks
