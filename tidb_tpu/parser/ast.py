"""AST nodes (ref: pkg/parser/ast — trimmed to the supported surface)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


class Node:
    pass


# -- expressions ------------------------------------------------------------


@dataclass
class Literal(Node):
    value: Any  # int | float | str | bytes | None | bool
    # hints: "date"/"time"/"decimal" for typed literals (DATE '1994-01-01')
    hint: str = ""
    # which EXECUTE parameter produced this literal (-1 = a plain literal);
    # the value-agnostic prepared-plan cache traces parameters through the
    # builder by this index (ref: plan-cache parameter markers)
    param_idx: int = -1


@dataclass
class ParamMarker(Node):
    """``?`` placeholder in a prepared statement (ref: ast.ParamMarkerExpr)."""

    idx: int


@dataclass
class UserVar(Node):
    """``@name`` user variable or ``@@name`` system variable reference."""

    name: str
    sys: bool = False
    scope: str = "session"


@dataclass
class ColumnName(Node):
    name: str
    table: str = ""
    db: str = ""

    def __str__(self):
        parts = [p for p in (self.db, self.table, self.name) if p]
        return ".".join(parts)


@dataclass
class BinaryOp(Node):
    op: str  # or/xor/and/eq/ne/lt/le/gt/ge/plus/minus/mul/div/intdiv/mod
    left: Node
    right: Node


@dataclass
class UnaryOp(Node):
    op: str  # not/unaryminus/unaryplus
    operand: Node


@dataclass
class IsNull(Node):
    operand: Node
    negated: bool = False


@dataclass
class InList(Node):
    operand: Node
    items: list[Node]
    negated: bool = False


@dataclass
class Between(Node):
    operand: Node
    low: Node
    high: Node
    negated: bool = False


@dataclass
class Like(Node):
    operand: Node
    pattern: Node
    negated: bool = False
    regexp: bool = False  # a REGEXP/RLIKE b (search semantics, not LIKE)


@dataclass
class Collate(Node):
    """expr COLLATE name / BINARY expr — explicit collation override; the
    strongest coercibility level, it wins over both operands' implicit
    collations (ref: parser.y "Expression COLLATE", expression/collation.go
    deriveCollation explicit-priority rule)."""

    operand: Node
    collation: str  # lowercased MySQL collation name, or "binary"


@dataclass
class FuncCall(Node):
    name: str  # lowercased
    args: list[Node] = field(default_factory=list)
    distinct: bool = False
    star: bool = False  # COUNT(*)
    over: Optional["WindowSpec"] = None  # window call when set
    separator: Optional[str] = None  # GROUP_CONCAT(... SEPARATOR 'x')
    order_by: Optional[list] = None  # GROUP_CONCAT(... ORDER BY e [DESC])


@dataclass
class WindowSpec(Node):
    """OVER (PARTITION BY ... ORDER BY ... [frame]) (ref: ast.WindowSpec)."""

    partition_by: list[Node] = field(default_factory=list)
    order_by: list["OrderItem"] = field(default_factory=list)
    # frames: whole-partition (no ORDER BY, or UNBOUNDED..UNBOUNDED),
    # RANGE UNBOUNDED..CURRENT (default with ORDER BY; peers share the
    # frame), or ROWS UNBOUNDED..CURRENT (exact cut at the current row)
    whole_partition: bool = False
    rows_frame: bool = False
    # bounded ROWS frame: (start_kind, start_n, end_kind, end_n) with kinds
    # "preceding"/"current"/"following"/"unbounded" (ref: ast.FrameBound)
    frame: Optional[tuple] = None

    def key(self) -> str:
        return repr((self.partition_by, self.order_by, self.whole_partition, self.rows_frame, self.frame))


@dataclass
class CaseWhen(Node):
    operand: Optional[Node]  # CASE x WHEN ... vs CASE WHEN ...
    branches: list[tuple[Node, Node]] = field(default_factory=list)
    else_value: Optional[Node] = None


@dataclass
class Cast(Node):
    operand: Node
    target: "TypeDef"


@dataclass
class Wildcard(Node):  # t.* or *
    table: str = ""


@dataclass
class SubqueryExpr(Node):
    select: "Select"
    # modifier: "" (scalar) | "exists" | "in" | "any" | "all"
    modifier: str = ""


@dataclass
class QuantifiedCmp(Node):
    """`left OP ANY|ALL (subquery)` — lowered by the planner per context
    (WHERE: EXISTS rewrite; value: NULL-correct extreme comparison)."""

    op: str  # eq/ne/lt/le/gt/ge
    left: Node
    select: "Select"
    is_all: bool = False


# -- type definitions (DDL) -------------------------------------------------


@dataclass
class TypeDef(Node):
    name: str  # bigint/int/double/varchar/decimal/date/datetime/...
    length: int = -1
    scale: int = 0
    unsigned: bool = False
    collate: str = ""  # e.g. utf8mb4_general_ci


# -- statements -------------------------------------------------------------


@dataclass
class SelectItem(Node):
    expr: Node
    alias: str = ""


@dataclass
class TableRef(Node):
    name: str
    db: str = ""
    alias: str = ""
    as_of: Optional[Node] = None  # stale read: AS OF TIMESTAMP expr
    # USE/IGNORE/FORCE INDEX (...) table hints: [(kind, [index names])]
    index_hints: Optional[list] = None
    # t PARTITION (p0, p1) explicit partition selection (ref: parser.y
    # TableFactor PartitionNameListOpt; logical_plan_builder partition check)
    partitions: Optional[list] = None


@dataclass
class Join(Node):
    left: Node  # TableRef | Join | SubquerySource
    right: Node
    kind: str = "inner"  # inner/left/right/cross
    on: Optional[Node] = None


@dataclass
class SubquerySource(Node):
    select: "Select"
    alias: str = ""
    # CTE column renames: WITH c(a, b) AS (...) — applied over the built
    # subquery's schema by the planner
    col_aliases: list[str] = field(default_factory=list)


@dataclass
class ValuesSource(Node):
    """A materialized in-memory rowset used as a table source (the planner's
    landing pad for recursive-CTE fixpoints and memtable feeds)."""

    rows: list  # list[tuple] of logical Python values
    names: list[str]
    ftypes: list  # list[FieldType]
    alias: str = ""


@dataclass
class CTEDef(Node):
    """One WITH-list entry (ref: ast.CommonTableExpression)."""

    name: str
    columns: list[str]
    query: Node  # Select | SetOp
    recursive: bool = False


@dataclass
class OrderItem(Node):
    expr: Node
    desc: bool = False


@dataclass
class Select(Node):
    items: list[SelectItem]
    from_: Optional[Node] = None  # TableRef | Join | SubquerySource
    where: Optional[Node] = None
    group_by: list[Node] = field(default_factory=list)
    # GROUP BY ... WITH ROLLUP (ref: parser.y WITH ROLLUP production)
    rollup: bool = False
    having: Optional[Node] = None
    order_by: list[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: int = 0
    distinct: bool = False
    for_update: bool = False
    # WITH clause attached to this query block (ref: SelectStmt.With)
    ctes: list["CTEDef"] = field(default_factory=list)
    # optimizer hints: [(name_lower, [args...])] (ref: TableOptimizerHint)
    hints: list = field(default_factory=list)


@dataclass
class SetOp(Node):
    """UNION / INTERSECT / EXCEPT chain (ref: ast.SetOprStmt).

    ``order_by``/``limit`` apply to the whole compound result (MySQL: a
    trailing ORDER BY binds to the union, not the last operand)."""

    left: Node  # Select | SetOp
    right: Node  # Select | SetOp
    op: str  # "union" | "intersect" | "except"
    all: bool = False
    order_by: list[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: int = 0
    ctes: list["CTEDef"] = field(default_factory=list)


@dataclass
class Insert(Node):
    table: TableRef
    columns: list[str] = field(default_factory=list)
    values: list[list[Node]] = field(default_factory=list)
    select: Optional[Select] = None
    replace: bool = False
    ignore: bool = False
    on_dup_update: list[tuple[str, Node]] = field(default_factory=list)


@dataclass
class Update(Node):
    table: TableRef
    assignments: list[tuple[ColumnName, Node]] = field(default_factory=list)
    where: Optional[Node] = None
    order_by: list[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None


@dataclass
class Delete(Node):
    table: TableRef
    where: Optional[Node] = None
    order_by: list[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None


@dataclass
class ColumnDef(Node):
    name: str
    type: TypeDef
    not_null: bool = False
    default: Optional[Node] = None
    primary_key: bool = False
    unique: bool = False
    auto_increment: bool = False


@dataclass
class IndexDef(Node):
    name: str
    columns: list[str]
    unique: bool = False
    primary: bool = False


@dataclass
class FKDef(Node):
    """FOREIGN KEY (cols) REFERENCES tbl (cols) with referential actions
    (ref: ast.Constraint ConstraintForeignKey + model.FKInfo)."""

    name: str
    columns: list[str]
    ref_table: "TableRef"
    ref_columns: list[str]
    on_delete: str = "restrict"  # restrict | cascade | set_null | no_action
    on_update: str = "restrict"


@dataclass
class PartitionByDef(Node):
    """PARTITION BY RANGE (col) (...) | HASH (col) PARTITIONS n."""

    type: str  # "range" | "hash"
    column: str
    defs: list[tuple[str, Optional[int]]] = field(default_factory=list)  # (name, less_than)
    num: int = 0  # hash partition count


@dataclass
class CreateTable(Node):
    table: TableRef
    columns: list[ColumnDef] = field(default_factory=list)
    indexes: list[IndexDef] = field(default_factory=list)
    foreign_keys: list[FKDef] = field(default_factory=list)
    if_not_exists: bool = False
    partition_by: Optional[PartitionByDef] = None
    ttl: Optional[tuple[str, int]] = None  # (column, days)
    ttl_enable: bool = True
    auto_increment_base: Optional[int] = None  # AUTO_INCREMENT = n option


@dataclass
class CreateSequence(Node):
    name: str
    db: str = ""
    start: int = 1
    increment: int = 1
    if_not_exists: bool = False


@dataclass
class DropSequence(Node):
    names: list[str]
    if_exists: bool = False


@dataclass
class CreateView(Node):
    """CREATE [OR REPLACE] VIEW v [(cols)] AS <select> — definition kept as
    SQL text (ref: model.ViewInfo.SelectStmt)."""

    table: TableRef
    columns: list[str]
    text: str
    or_replace: bool = False


@dataclass
class DropView(Node):
    tables: list[TableRef]
    if_exists: bool = False


@dataclass
class DropTable(Node):
    tables: list[TableRef]
    if_exists: bool = False


@dataclass
class TruncateTable(Node):
    table: TableRef


@dataclass
class AlterTable(Node):
    table: TableRef
    # one action per statement (reference supports lists; keep one)
    # actions: add_column/drop_column/add_index/drop_index/rename/
    #          add_partition/drop_partition/truncate_partition
    action: str = ""
    column: Optional[ColumnDef] = None
    index: Optional[IndexDef] = None
    fk: Optional[FKDef] = None  # add_fk payload
    name: str = ""  # drop target, rename target, or partition name
    less_than: Optional[int] = None  # add_partition bound (None = MAXVALUE)
    ttl: Optional[tuple[str, int]] = None  # set_ttl payload
    ttl_enable: bool = True


@dataclass
class CreateIndex(Node):
    index: IndexDef
    table: TableRef


@dataclass
class DropIndex(Node):
    name: str
    table: TableRef


@dataclass
class CreateDatabase(Node):
    name: str
    if_not_exists: bool = False


@dataclass
class DropDatabase(Node):
    name: str
    if_exists: bool = False


@dataclass
class UseDatabase(Node):
    name: str


@dataclass
class Explain(Node):
    stmt: Node
    analyze: bool = False


@dataclass
class SetVariable(Node):
    name: str
    value: Node
    scope: str = "session"  # session | global


@dataclass
class ImportInto(Node):
    """IMPORT INTO t FROM 'file.csv' [WITH opt=val, ...] (ref:
    disttask/importinto SQL surface)."""

    table: TableRef
    path: str
    options: dict = field(default_factory=dict)


@dataclass
class Backup(Node):
    """BACKUP DATABASE db | TABLE t[, t2] TO 'dest' (ref: executor/brie.go)."""

    dest: str
    db: str = ""
    tables: list[TableRef] = field(default_factory=list)


@dataclass
class Restore(Node):
    """RESTORE DATABASE [db] FROM 'src' (ref: executor/brie.go)."""

    src: str
    db: str = ""


@dataclass
class Prepare(Node):
    """PREPARE name FROM 'text' | @var (ref: ast.PrepareStmt)."""

    name: str
    text: Optional[str] = None
    from_var: Optional[str] = None


@dataclass
class ExecutePrepared(Node):
    """EXECUTE name [USING @a, @b] (ref: ast.ExecuteStmt)."""

    name: str
    using: list[str] = field(default_factory=list)


@dataclass
class Deallocate(Node):
    """DEALLOCATE PREPARE name (ref: ast.DeallocateStmt)."""

    name: str


@dataclass
class Show(Node):
    kind: str  # tables/databases/create_table/variables/columns
    target: str = ""
    like: Optional[str] = None


@dataclass
class RenameTables(Node):
    pairs: list = field(default_factory=list)  # [(old, new)]


@dataclass
class DoStmt(Node):
    exprs: list = field(default_factory=list)


@dataclass
class ChecksumTable(Node):
    tables: list = field(default_factory=list)


@dataclass
class Begin(Node):
    mode: str = ""  # "" (session default) | pessimistic | optimistic


@dataclass
class Commit(Node):
    pass


@dataclass
class Rollback(Node):
    pass


@dataclass
class UserSpec(Node):
    name: str
    host: str = "%"
    password: str = ""
    plugin: str = "mysql_native_password"
    # IDENTIFIED clause present? (ALTER USER without one must not touch
    # the stored credential)
    has_auth: bool = False


@dataclass
class CreateUser(Node):
    users: list[UserSpec] = field(default_factory=list)
    if_not_exists: bool = False


@dataclass
class DropUser(Node):
    users: list[UserSpec] = field(default_factory=list)
    if_exists: bool = False


@dataclass
class PlanReplayer(Node):
    """PLAN REPLAYER DUMP EXPLAIN <sql> | LOAD '<path>' (ref:
    ast.PlanReplayerStmt)."""

    kind: str  # dump | load
    sql: str = ""
    path: str = ""


@dataclass
class AlterUser(Node):
    """ALTER USER ... IDENTIFIED BY (ref: ast.AlterUserStmt)."""

    users: list[UserSpec] = field(default_factory=list)
    if_exists: bool = False


@dataclass
class Grant(Node):
    """GRANT privs ON level TO user (ref: ast.GrantStmt). REVOKE shares the
    shape via ``revoke=True``."""

    privs: list[str] = field(default_factory=list)  # lowercase; ["all"] = all
    db: str = ""  # "" = *.* (global)
    table: str = ""  # "" = db.* (db level)
    user: str = ""
    host: str = "%"
    revoke: bool = False


@dataclass
class ResourceGroupStmt(Node):
    """CREATE/ALTER/DROP RESOURCE GROUP (ref: ast.CreateResourceGroupStmt)."""

    op: str  # create | alter | drop
    name: str
    ru_per_sec: int = 0
    burstable: bool = False
    exec_elapsed_s: float = 0.0
    action: str = "KILL"
    if_not_exists: bool = False
    if_exists: bool = False


@dataclass
class SetResourceGroup(Node):
    name: str


@dataclass
class Trace(Node):
    """TRACE <stmt> (ref: ast.TraceStmt)."""

    stmt: Node


@dataclass
class CreateBinding(Node):
    """CREATE [GLOBAL|SESSION] BINDING FOR <stmt> USING <stmt>
    (ref: ast.CreateBindingStmt / pkg/bindinfo)."""

    for_text: str
    using_text: str
    is_global: bool = False


@dataclass
class DropBinding(Node):
    for_text: str
    is_global: bool = False


@dataclass
class RecoverTable(Node):
    """RECOVER TABLE t / FLASHBACK TABLE t [TO t2] (ref: ast.RecoverTableStmt,
    FlashBackTableStmt)."""

    table: TableRef
    new_name: str = ""


@dataclass
class Admin(Node):
    """ADMIN CHECK TABLE / CHECK INDEX / SHOW DDL JOBS (ref: ast.AdminStmt)."""

    kind: str  # check_table | check_index | show_ddl_jobs
    table: Optional[TableRef] = None
    index: str = ""


@dataclass
class Kill(Node):
    """KILL [QUERY|CONNECTION] conn_id (ref: ast.KillStmt)."""

    conn_id: int
    query_only: bool = True


@dataclass
class AnalyzeTable(Node):
    tables: list[TableRef] = field(default_factory=list)


@dataclass
class LoadData(Node):
    """LOAD DATA [LOCAL] INFILE 'path' INTO TABLE t ... (ref:
    pkg/executor/load_data.go; the INSERT-like bulk path over a CSV file —
    IMPORT INTO's statement-level sibling)."""

    path: str
    table: TableRef
    local: bool = False
    fields_terminated: str = "\t"  # MySQL default: TAB
    fields_enclosed: str = ""
    ignore_lines: int = 0
    columns: list = field(default_factory=list)  # subset/reorder; [] = all
    dup_mode: str = ""  # "" | "ignore" | "replace"


def bind_params(node, values, mark: bool = False):
    """Return a copy of the AST with each ParamMarker replaced by a Literal
    of the corresponding value (EXECUTE ... USING binding). With ``mark``,
    each produced Literal remembers its parameter index so the builder's
    Constants stay traceable to EXECUTE parameters (the value-agnostic
    prepared-plan cache mutates them in place on later executions)."""
    import dataclasses

    def conv(v):
        if isinstance(v, ParamMarker):
            return Literal(values[v.idx], param_idx=v.idx if mark else -1)
        if isinstance(v, Node) and dataclasses.is_dataclass(v):
            return type(v)(**{f.name: conv(getattr(v, f.name)) for f in dataclasses.fields(v)})
        if isinstance(v, list):
            return [conv(x) for x in v]
        if isinstance(v, tuple):
            return tuple(conv(x) for x in v)
        return v

    return conv(node)
