"""AST → logical plan with name resolution, type coercion and constant
folding (ref: pkg/planner/core/logical_plan_builder.go + expression
rewriter)."""

from __future__ import annotations

from dataclasses import dataclass, replace
from decimal import Decimal
from typing import Callable, Optional

import numpy as np

from tidb_tpu.catalog import Catalog
from tidb_tpu.expression.expr import (
    AggDesc,
    AGG_FUNCS,
    ColumnRef,
    Constant,
    EvalBatch,
    Expression,
    ScalarFunc,
    eval_to_column,
    func,
)
from tidb_tpu.parser import ast
from tidb_tpu.planner.plans import (
    LogicalAggregation,
    LogicalDistinct,
    LogicalDual,
    LogicalJoin,
    LogicalLimit,
    LogicalPlan,
    LogicalProjection,
    LogicalScan,
    LogicalSelection,
    LogicalSetOp,
    LogicalSort,
    OutCol,
    PlanError,
)
from tidb_tpu.types import FieldType, TypeKind
from tidb_tpu.types.field_type import bigint_type, bool_type, decimal_type, double_type, string_type
from tidb_tpu.types.datum import date_to_days, datetime_to_micros

# parser func name → registry sig aliases
_FN_ALIAS = {
    "power": "pow",
    "log": "ln",
    "char_length": "length",
    "character_length": "length",
    "substr": "substring",
    "mid": "substring",
    "day": "dayofmonth",
    "lcase": "lower",
    "ucase": "upper",
    "ceiling": "ceil",
    "std": "stddev_pop",
    "stddev": "stddev_pop",
    "variance": "var_pop",
    "adddate": "date_add_days",
    "position": "locate",
}


# builtins whose first argument is a date/datetime (string literals coerce —
# else dictionary codes would be read as day counts) or a time
_DATE_ARG0_FNS = {
    "year", "month", "quarter", "dayofmonth", "dayofweek", "weekday", "week",
    "dayofyear", "to_days", "last_day", "date", "monthname", "dayname",
    "date_format", "unix_timestamp", "yearweek", "weekofyear",
}
_TIME_ARG0_FNS = {"hour", "minute", "second", "time_to_sec"}


def _common_type(l: FieldType, r: FieldType) -> FieldType:
    """Result type of a set-operation column pair (ref: unionJoinFieldType,
    expression/util.go aggFieldType): numeric promotion, else exact kind."""
    nullable = l.nullable or r.nullable
    if l.kind == TypeKind.NULLTYPE:
        return replace(r, nullable=True)
    if r.kind == TypeKind.NULLTYPE:
        return replace(l, nullable=True)
    if l.kind == r.kind:
        if l.kind == TypeKind.DECIMAL and l.scale != r.scale:
            return replace(decimal_type(18, max(l.scale, r.scale)), nullable=nullable)
        return replace(l, nullable=nullable)
    numeric = {TypeKind.INT, TypeKind.UINT, TypeKind.FLOAT, TypeKind.DECIMAL}
    if l.kind in numeric and r.kind in numeric:
        if TypeKind.FLOAT in (l.kind, r.kind):
            return replace(double_type(), nullable=nullable)
        if TypeKind.DECIMAL in (l.kind, r.kind):
            d = l if l.kind == TypeKind.DECIMAL else r
            return replace(decimal_type(18, d.scale), nullable=nullable)
        return replace(bigint_type(), nullable=nullable)
    raise PlanError(f"incompatible set-operand column types {l.kind.name} vs {r.kind.name}")


# known collation names → the engine's two-way collation model
# (catalog/infoschema.py COLLATIONS is the introspection mirror of this)
_COLLATION_MAP = {"utf8mb4_bin": "bin", "utf8mb4_general_ci": "ci", "binary": "bin"}


def _collate_expr(e: Expression, name: str) -> Expression:
    """expr COLLATE name / BINARY expr: override the expression's collation.

    Explicit collation is the strongest coercibility level — comparisons
    propagate it to the other operand (ref: expression/collation.go
    deriveCollation; CoercibilityExplicit wins)."""
    import copy as _copy
    from dataclasses import replace as _dc_replace

    if name not in _COLLATION_MAP:
        raise PlanError(f"Unknown collation: '{name}'")
    coll = _COLLATION_MAP[name]
    out = _copy.copy(e)
    if out.ftype.kind == TypeKind.STRING:
        out.ftype = _dc_replace(out.ftype, collation=coll)
    out._explicit_collation = coll  # type: ignore[attr-defined]
    return out


def _apply_explicit_collation(a: Expression, b: Expression):
    """If either comparison operand carries an explicit COLLATE, it governs
    the whole comparison: rewrite BOTH operands' string collation to it."""
    import copy as _copy
    from dataclasses import replace as _dc_replace

    coll = getattr(a, "_explicit_collation", None) or getattr(b, "_explicit_collation", None)
    if coll is None:
        return a, b
    out = []
    for e in (a, b):
        if e.ftype.kind == TypeKind.STRING and e.ftype.collation != coll:
            e = _copy.copy(e)
            e.ftype = _dc_replace(e.ftype, collation=coll)
        out.append(e)
    return out[0], out[1]


def _cast_expr(e: Expression, target: ast.TypeDef) -> Expression:
    """CAST target mapping (shared by the plain and mixed resolvers)."""
    tname = target.name
    if tname in ("signed", "int", "integer", "bigint", "unsigned"):
        return func("cast_int", e)
    if tname in ("double", "float", "real"):
        return func("cast_float", e)
    if tname in ("decimal", "numeric"):
        ft = decimal_type(target.length if target.length > 0 else 10, target.scale)
        return func("cast_decimal", e, ret=ft)
    if tname in ("char", "varchar", "binary", "nchar"):
        # ret_type.length carries CHAR(n)'s truncation length to the eval
        return func("cast_string", e, ret=string_type(length=target.length))
    if tname == "date":
        return func("cast_date", e)
    if tname == "datetime":
        return func("cast_datetime", e)
    raise PlanError(f"unsupported CAST target {tname}")


@dataclass
class BuildCtx:
    """Name-resolution scope."""

    schema: list  # list[OutCol]
    # aggregation context: when set, agg funcalls resolve into it
    agg_list: Optional[list[AggDesc]] = None
    agg_base: Optional[list] = None  # schema under the agg (for agg args)
    # alias → expression over current schema (SELECT aliases in HAVING/ORDER)
    aliases: Optional[dict[str, Expression]] = None


class Builder:
    def __init__(
        self,
        catalog: Catalog,
        current_db: str,
        subquery_runner: Optional[Callable] = None,
        user_vars: Optional[dict] = None,
        sys_vars: Optional[dict] = None,
        global_vars: Optional[dict] = None,
        memtable_provider: Optional[Callable] = None,
        scan_checker: Optional[Callable] = None,
        dyn_sys_vars: Optional[dict] = None,
        warn: Optional[Callable] = None,
    ):
        self.dyn_sys_vars = dyn_sys_vars
        self.warn = warn
        self.catalog = catalog
        self.db = current_db
        self.subquery_runner = subquery_runner
        self.user_vars = user_vars
        self.sys_vars = sys_vars
        self.global_vars = global_vars if global_vars is not None else sys_vars
        self.memtable_provider = memtable_provider
        self.scan_checker = scan_checker  # privilege hook per scanned table
        self._view_depth = 0
        self.hints: list = []  # current query block's optimizer hints
        # set when the built plan bakes in plan-time state (subquery results,
        # variable reads) and must not enter the plan cache
        self.uncacheable = False
        # ast window-call node id → ColumnRef into a LogicalWindow's output
        self._win_map: dict[int, Expression] = {}

    # -- statements ---------------------------------------------------------
    def build_query(self, node) -> LogicalPlan:
        """SELECT or a UNION/INTERSECT/EXCEPT compound (ref: buildSetOpr in
        logical_plan_builder.go)."""
        if isinstance(node, ast.Select):
            return self.build_select(node)
        if isinstance(node, ast.SetOp):
            return self._build_setop(node)
        raise PlanError(f"unsupported query {type(node).__name__}")

    def _build_setop(self, node: ast.SetOp) -> LogicalPlan:
        left = self.build_query(node.left)
        right = self.build_query(node.right)
        if len(left.schema) != len(right.schema):
            raise PlanError("set operands have a different number of columns")
        # unify column types: numeric promotion, else exact-kind match
        target: list[FieldType] = []
        for lc, rc in zip(left.schema, right.schema):
            target.append(_common_type(lc.ftype, rc.ftype))
        left = self._cast_to(left, target)
        right = self._cast_to(right, target)
        schema = [
            OutCol(left.schema[i].name, target[i]) for i in range(len(target))
        ]
        plan: LogicalPlan = LogicalSetOp(
            op=node.op, all=node.all, schema=schema, children=[left, right]
        )
        if node.order_by:
            by = []
            for oi in node.order_by:
                by.append((self._resolve_order(oi.expr, plan.schema, {}), oi.desc))
            plan = LogicalSort(by=by, children=[plan])
        if node.limit is not None:
            plan = LogicalLimit(limit=node.limit, offset=node.offset, children=[plan])
        return plan

    def _cast_to(self, plan: LogicalPlan, target: list[FieldType]) -> LogicalPlan:
        """Wrap ``plan`` in a projection casting each column to the target
        kind where it differs."""
        exprs: list[Expression] = []
        changed = False
        for i, (oc, ft) in enumerate(zip(plan.schema, target)):
            e: Expression = ColumnRef(i, oc.ftype, oc.name)
            scale_diff = ft.kind == TypeKind.DECIMAL and oc.ftype.scale != ft.scale
            if oc.ftype.kind != ft.kind or scale_diff:
                changed = True
                if ft.kind == TypeKind.FLOAT:
                    e = func("cast_float", e)
                elif ft.kind == TypeKind.DECIMAL:
                    e = func("cast_decimal", e, ret=ft)
                elif ft.kind in (TypeKind.INT, TypeKind.UINT):
                    e = func("cast_int", e)
                else:
                    raise PlanError(
                        f"cannot unify set-operand column types {oc.ftype.kind} vs {ft.kind}"
                    )
            exprs.append(e)
        if not changed:
            return plan
        proj = LogicalProjection(exprs=exprs, children=[plan])
        proj.schema = [
            OutCol(plan.schema[i].name, exprs[i].ftype, plan.schema[i].table, plan.schema[i].slot)
            for i in range(len(exprs))
        ]
        return proj

    def build_select(self, sel: ast.Select) -> LogicalPlan:
        prev_hints = self.hints
        prev_sub_map = getattr(self, "_scalar_sub_map", None)
        self.hints = getattr(sel, "hints", []) or prev_hints
        try:
            return self._build_select(sel)
        finally:
            self.hints = prev_hints
            self._scalar_sub_map = prev_sub_map

    def _build_select(self, sel: ast.Select) -> LogicalPlan:
        if sel.from_ is None:
            plan: LogicalPlan = LogicalDual()
        else:
            # the WHERE travels down to memtable sources as pushdown HINTS
            # (simple col-vs-literal conjuncts only): the log memtables use
            # them to filter their wire sweep server-side. Saved/restored —
            # derived tables re-enter here with their own WHERE.
            prev_w = getattr(self, "_mt_where", None)
            self._mt_where = sel.where
            try:
                plan = self._build_from(sel.from_)
            finally:
                self._mt_where = prev_w

        if sel.where is not None:
            residual: list[ast.Node] = []
            scalar_conds: list[Expression] = []
            pre_width = len(plan.schema)  # semi/anti joins keep the schema
            for cj in _split_ast_conj(sel.where):
                if isinstance(cj, ast.QuantifiedCmp):
                    cj = _quantified_to_exists(cj)
                elif isinstance(cj, ast.UnaryOp) and cj.op == "not" and isinstance(cj.operand, ast.QuantifiedCmp):
                    cj = ast.UnaryOp("not", _quantified_to_exists(cj.operand))
                joined = self._try_subquery_join(plan, cj)
                if joined is not None:
                    plan = joined
                    continue
                scalar = self._try_scalar_corr_join(plan, cj)
                if scalar is not None:
                    plan, cond = scalar
                    scalar_conds.append(cond)
                    continue
                residual.append(cj)
            conds: list[Expression] = list(scalar_conds)
            for cj in residual:
                conds.extend(self._split_conj(self.resolve(cj, BuildCtx(plan.schema))))
            if conds:
                plan = LogicalSelection(conditions=conds, children=[plan])
            if len(plan.schema) > pre_width:
                # trim correlated-scalar agg columns appended by the joins
                tp = LogicalProjection(
                    exprs=[
                        ColumnRef(i, plan.schema[i].ftype, plan.schema[i].name)
                        for i in range(pre_width)
                    ],
                    children=[plan],
                )
                tp.schema = plan.schema[:pre_width]
                plan = tp

        # correlated scalar subqueries in the SELECT list (ref: scalar Apply
        # decorrelation in projections, rule_decorrelate.go): each expands to
        # a LEFT JOIN against the per-key inner aggregate; the item resolves
        # to the joined agg column via _scalar_sub_map
        pre_sub_width = len(plan.schema)
        sub_map_saved = getattr(self, "_scalar_sub_map", None)
        self._scalar_sub_map = dict(sub_map_saved or {})
        for it in sel.items:
            if isinstance(it.expr, ast.Wildcard):
                continue
            for sub in _scalar_subquery_nodes(it.expr):
                if isinstance(sub.select, ast.Select) and self._is_correlated(sub.select, plan.schema):
                    got = self._scalar_corr_expand(plan, sub)
                    if got is not None:
                        plan, e = got
                        self._scalar_sub_map[id(sub)] = e

        # aggregation detection
        has_agg = bool(sel.group_by) or any(
            _contains_agg(it.expr) for it in sel.items
        ) or (sel.having is not None and _contains_agg(sel.having))

        # window functions (ref: buildWindowFunctions): one LogicalWindow per
        # distinct OVER spec, each appending result columns to the schema
        win_calls: list = []
        for it in sel.items:
            if not isinstance(it.expr, ast.Wildcard):
                _collect_windows(it.expr, win_calls)
        for oi in sel.order_by:
            _collect_windows(oi.expr, win_calls)
        # SELECT * must expand to the pre-window, pre-scalar-join schema only
        wild_n = pre_sub_width
        if win_calls:
            if has_agg:
                raise PlanError(
                    "window functions combined with GROUP BY/aggregates are not supported yet"
                )
            plan = self._build_windows(plan, win_calls)

        aliases: dict[str, Expression] = {}
        hidden = 0
        order_agg_map: dict[int, int] = {}  # order-item idx → hidden agg col
        order_hidden_map: dict[int, int] = {}  # order-item idx → hidden proj col
        order_agg_base = 0
        if has_agg:
            base_schema = plan.schema
            aggs: list[AggDesc] = []
            # GROUP BY accepts select-item aliases (MySQL extension):
            # an unresolvable bare name retries as the aliased expression
            alias_map: dict = {}
            dup_aliases: set = set()
            for it in sel.items:
                if it.alias:
                    a = it.alias.lower()
                    if a in alias_map:
                        dup_aliases.add(a)
                    alias_map[a] = it.expr

            def resolve_group(g):
                try:
                    return self.resolve(g, BuildCtx(base_schema))
                except PlanError:
                    if isinstance(g, ast.ColumnName) and not g.table and g.name.lower() in alias_map:
                        if g.name.lower() in dup_aliases:
                            raise PlanError(
                                f"Column '{g.name}' in group statement is ambiguous"
                            )
                        return self.resolve(alias_map[g.name.lower()], BuildCtx(base_schema))
                    raise

            group_exprs = [resolve_group(g) for g in sel.group_by]
            agg_ctx = BuildCtx(schema=[], agg_list=aggs, agg_base=base_schema)

            # first pass: group-key expressions resolve positionally
            def agg_schema():
                cols = []
                for i, a in enumerate(aggs):
                    cols.append(OutCol(f"agg#{i}", a.ftype))
                for i, g in enumerate(group_exprs):
                    name = sel.group_by[i].name if isinstance(sel.group_by[i], ast.ColumnName) else f"gb#{i}"
                    src = _source_outcol(g, base_schema)
                    cols.append(OutCol(name, g.ftype, table=src.table if src else "", slot=src.slot if src else -1))
                return cols

            proj_exprs: list[Expression] = []
            names: list[str] = []
            for it in sel.items:
                if isinstance(it.expr, ast.Wildcard):
                    raise PlanError("SELECT * with GROUP BY is not supported")
                e = self._resolve_in_agg(it.expr, base_schema, aggs, group_exprs, sel.group_by, rollup=sel.rollup)
                proj_exprs.append(e)
                nm = it.alias or _display_name(it.expr)
                names.append(nm)
                if it.alias:
                    aliases[it.alias.lower()] = e
            agg = LogicalAggregation(group_by=group_exprs, aggs=aggs, children=[plan])
            plan = agg
            having_conds: list[Expression] = []
            if sel.having is not None:
                h = self._resolve_in_agg(sel.having, base_schema, aggs, group_exprs, sel.group_by, aliases, rollup=sel.rollup)
                having_conds = self._split_conj(h)
            # ORDER BY items containing aggregates resolve against the agg
            # (may append new aggs, so this must precede finalization); they
            # ride as hidden projection columns trimmed after the sort
            order_agg_exprs: list[Expression] = []
            if sel.order_by:
                for i_o, oi in enumerate(sel.order_by):
                    # aggregates AND group-by expressions (ORDER BY YEAR(dt)
                    # after GROUP BY YEAR(dt)) resolve against the agg — the
                    # projection schema no longer carries the base columns
                    if _contains_agg(oi.expr) or _contains_group_expr(oi.expr, sel.group_by or []):
                        e_o = self._resolve_in_agg(oi.expr, base_schema, aggs, group_exprs, sel.group_by, aliases, rollup=sel.rollup)
                        order_agg_map[i_o] = len(order_agg_exprs)
                        order_agg_exprs.append(e_o)
            # agg list is final now: patch deferred group-key refs everywhere
            agg.schema = agg_schema()
            ng = len(group_exprs)
            proj_exprs = [_patch_group_refs(e, len(aggs), ng) for e in proj_exprs]
            having_conds = [_patch_group_refs(e, len(aggs), ng) for e in having_conds]
            order_agg_exprs = [_patch_group_refs(e, len(aggs), ng) for e in order_agg_exprs]
            for a in aliases:
                aliases[a] = _patch_group_refs(aliases[a], len(aggs), ng)
            if sel.rollup:
                # GROUP BY ... WITH ROLLUP: mark the agg and extend its
                # schema with the GROUPING() flag columns — the OPTIMIZER
                # picks between the fused one-pass device rollup and the
                # per-set union fallback (_expand_rollup); the deferred
                # schema layout matches the union's exactly, so every
                # downstream reference (incl. patched GROUPING() sentinels)
                # is route-independent
                import dataclasses as _dc

                agg.rollup = True
                flag_ft = bigint_type(nullable=False)
                rolled_schema = list(agg.schema)
                for j in range(ng):
                    oc = rolled_schema[len(aggs) + j]
                    if not oc.ftype.nullable:
                        rolled_schema[len(aggs) + j] = _dc.replace(
                            oc, ftype=_dc.replace(oc.ftype, nullable=True)
                        )
                agg.schema = rolled_schema + [
                    OutCol(f"grouping#{j}", flag_ft) for j in range(ng)
                ]
                plan = agg
            if having_conds:
                plan = LogicalSelection(conditions=having_conds, children=[plan])
            proj = LogicalProjection(exprs=proj_exprs, children=[plan])
            proj.schema = []
            for i in range(len(proj_exprs)):
                src = _source_outcol(proj_exprs[i], plan.schema)
                proj.schema.append(
                    OutCol(
                        names[i],
                        proj_exprs[i].ftype,
                        table=src.table if src else "",
                        slot=src.slot if src else -1,
                    )
                )
            if order_agg_exprs:
                order_agg_base = len(proj.schema)
                for k, e_o in enumerate(order_agg_exprs):
                    proj.exprs.append(e_o)
                    proj.schema.append(OutCol(f"__agg_order#{k}", e_o.ftype))
                hidden += len(order_agg_exprs)
            plan = proj
        else:
            # plain projection
            proj_exprs, names, srcs = [], [], []
            for it in sel.items:
                if isinstance(it.expr, ast.Wildcard):
                    for i, oc in enumerate(plan.schema[:wild_n]):
                        if it.expr.table and oc.table.lower() != it.expr.table.lower():
                            continue
                        proj_exprs.append(ColumnRef(i, oc.ftype, oc.name))
                        names.append(oc.name)
                        srcs.append(oc)
                    continue
                e = self.resolve(it.expr, BuildCtx(plan.schema))
                proj_exprs.append(e)
                names.append(it.alias or _display_name(it.expr))
                srcs.append(_source_outcol(e, plan.schema))
                if it.alias:
                    aliases[it.alias.lower()] = e
            if not proj_exprs:
                raise PlanError("empty select list")
            proj = LogicalProjection(exprs=proj_exprs, children=[plan])
            proj.schema = [
                OutCol(
                    names[i],
                    proj_exprs[i].ftype,
                    table=srcs[i].table if srcs[i] else "",
                    slot=srcs[i].slot if srcs[i] else -1,
                )
                for i in range(len(proj_exprs))
            ]
            # ORDER BY may reference non-projected columns → hidden extras
            if sel.order_by and sel.from_ is not None:
                base = plan.schema
                for i_o, oi in enumerate(sel.order_by):
                    if self._order_needs_hidden(oi.expr, proj.schema, aliases):
                        e = self.resolve(oi.expr, BuildCtx(base))
                        src = _source_outcol(e, base)
                        # the sort must target this slot directly — the order
                        # expression references BASE columns the projection no
                        # longer carries (ORDER BY COALESCE(v,-1) where only
                        # the alias survives), so re-resolving it against the
                        # projection schema would fail
                        order_hidden_map[i_o] = len(proj.schema)
                        # name the hidden column after its source so ORDER BY
                        # resolution finds it (duplicates with visible items
                        # are impossible — those wouldn't need a hidden col)
                        hname = src.name if src else (oi.expr.name if isinstance(oi.expr, ast.ColumnName) else f"__hidden#{hidden}")
                        proj.exprs.append(e)
                        proj.schema.append(
                            OutCol(
                                hname,
                                e.ftype,
                                table=src.table if src else "",
                                slot=src.slot if src else -1,
                            )
                        )
                        hidden += 1
            plan = proj
            if self._win_map:
                # ORDER BY resolves over the projection's schema — retarget
                # window refs (pre-projection space) onto the projected column
                for key, ref in list(self._win_map.items()):
                    for j, pe in enumerate(proj.exprs):
                        if isinstance(pe, ColumnRef) and pe.index == ref.index:
                            self._win_map[key] = ColumnRef(j, ref.ftype, ref.name)
                            break

        if sel.distinct:
            plan = LogicalDistinct(children=[plan])

        if sel.order_by:
            by = []
            for i_o, oi in enumerate(sel.order_by):
                if i_o in order_agg_map:
                    idx = order_agg_base + order_agg_map[i_o]
                    e: Expression = ColumnRef(idx, plan.schema[idx].ftype, plan.schema[idx].name)
                elif i_o in order_hidden_map:
                    idx = order_hidden_map[i_o]
                    e = ColumnRef(idx, plan.schema[idx].ftype, plan.schema[idx].name)
                else:
                    e = self._resolve_order(oi.expr, plan.schema, aliases)
                by.append((e, oi.desc))
            plan = LogicalSort(by=by, children=[plan])

        if sel.limit is not None:
            plan = LogicalLimit(limit=sel.limit, offset=sel.offset, children=[plan])

        if hidden:
            # trim hidden sort columns with a final projection
            vis = len(plan.schema) - hidden
            tp = LogicalProjection(
                exprs=[ColumnRef(i, plan.schema[i].ftype, plan.schema[i].name) for i in range(vis)],
                children=[plan],
            )
            tp.schema = plan.schema[:vis]
            plan = tp
        return plan

    # -- correlated subqueries → semi/anti join (ref: decorrelation rules,
    # core/rule/rule_decorrelate.go; only equality correlation is supported,
    # the common EXISTS/IN shape) --------------------------------------------
    def _try_subquery_join(self, plan: LogicalPlan, cj: ast.Node) -> Optional[LogicalPlan]:
        """If ``cj`` is a correlated [NOT] EXISTS / [NOT] IN-subquery
        predicate, rewrite it into a semi/anti join against ``plan`` and
        return the join; otherwise return None (the eager uncorrelated path
        in _resolve handles it)."""
        negated = False
        node = cj
        if isinstance(node, ast.UnaryOp) and node.op == "not":
            negated, node = True, node.operand
        operand_ast = None
        null_aware = False
        if isinstance(node, ast.SubqueryExpr) and node.modifier == "exists":
            inner = node.select
        elif (
            isinstance(node, ast.InList)
            and len(node.items) == 1
            and isinstance(node.items[0], ast.SubqueryExpr)
        ):
            inner = node.items[0].select
            operand_ast = node.operand
            negated = negated != node.negated
            null_aware = negated
        else:
            return None
        if not isinstance(inner, ast.Select):
            return None  # set-op subqueries stay on the eager path
        if not self._is_correlated(inner, plan.schema):
            return None
        if inner.limit is not None or inner.order_by:
            raise PlanError("correlated subquery with ORDER BY/LIMIT is not supported")
        # rewrite a private copy — probe builds must never see a mutated AST
        import copy as _copy

        inner = _copy.deepcopy(inner)
        # split the inner WHERE into correlation equalities vs local filters;
        # a probe builder resolves without executing nested subqueries
        probe = Builder(self.catalog, self.db, subquery_runner=lambda _sel: [])
        inner_from = probe._build_from(inner.from_) if inner.from_ is not None else LogicalDual()
        inner_schema = inner_from.schema
        corr: list[tuple[ast.Node, ast.Node]] = []  # (outer side, inner side)
        keep: list[ast.Node] = []
        corr_other: list[ast.Node] = []  # correlated NON-equality conjuncts
        for c in _split_ast_conj(inner.where) if inner.where is not None else []:
            pair = self._corr_eq_pair(c, inner_schema, plan.schema, probe)
            if pair is not None:
                corr.append(pair)
            elif self._conj_is_mixed(c, inner_schema, plan.schema, probe):
                # e.g. `x.v > outer.v`: becomes a join other-condition over
                # the joined row (ref: Apply/semi-join otherConds in the
                # reference's decorrelation; rule_decorrelate.go keeps
                # non-eq correlated filters on the join)
                corr_other.append(c)
            else:
                keep.append(c)
        inner_has_agg = bool(inner.group_by) or any(
            not isinstance(it.expr, ast.Wildcard) and _contains_agg(it.expr) for it in inner.items
        )
        if inner_has_agg:
            if operand_ast is None and not inner.group_by:
                # EXISTS over an ungrouped aggregate: exactly one row always
                # exists — but the stripped body must still be valid SQL
                inner.where = _and_join_ast(keep)
                try:
                    probe.build_select(inner)
                except PlanError as err:
                    if "Unknown column" in str(err) and _unknown_col_in_schema(str(err), plan.schema):
                        raise PlanError(
                            "unsupported correlated subquery: correlation must be a plain equality"
                        )
                    raise
                if not negated:
                    return plan
                return LogicalSelection(conditions=[Constant(0, bool_type())], children=[plan])
            # grouped inner / IN-with-agg: decorrelate by pulling the
            # correlation keys into GROUP BY (agg-over-join; ref:
            # rule_decorrelate.go aggregate pull-up). For a fixed outer key k
            # the (g, k)-groups of the key-stripped inner ARE the original
            # per-k groups — the extra keys split nothing — so HAVING stays a
            # local group filter and the join tests existence per (operand,
            # corr keys). NULL-key inner rows form their own groups and match
            # no outer row, exactly like the stripped equality dropped them.
            if corr_other:
                # a correlated NON-equality conjunct filters rows BEFORE the
                # aggregate — it cannot move above the agg with the keys
                raise PlanError("unsupported correlated subquery with aggregation")
            if not corr and operand_ast is None:
                raise PlanError("unsupported correlated subquery (no equality correlation)")
            if not inner.group_by:
                # An UNGROUPED aggregate yields one row even for outer keys
                # with no inner match (COUNT()=0, AVG()=NULL); the grouped
                # rewrite forms NO group there, so refuse exactly the cases
                # where that phantom row is observable: negated operands
                # (the missing {NULL}/{0} row flips NOT IN from UNKNOWN to
                # TRUE) and aggregates whose empty-set value is non-NULL
                # (COUNT and the BIT_* family — `x = 0` must see the 0).
                names: set = set()
                for it in inner.items:
                    if not isinstance(it.expr, ast.Wildcard):
                        _agg_names(it.expr, names)
                if inner.having is not None:
                    _agg_names(inner.having, names)
                if negated or names & {"count", "bit_and", "bit_or", "bit_xor"}:
                    raise PlanError("unsupported correlated subquery with aggregation")
            inner.group_by = list(inner.group_by or []) + [s for _, s in corr]
        if not corr and operand_ast is None and not corr_other:
            raise PlanError("unsupported correlated subquery (no equality correlation)")
        if corr_other and negated and null_aware:
            raise PlanError("NOT IN with non-equality correlation is not supported")
        inner.where = _and_join_ast(keep)
        base_items = len(inner.items)
        # inner-side columns the non-eq conjuncts reference must be projected
        # (before the corr items, which stay the LAST n_extra of the schema).
        # Each gets a synthetic __corr#k alias and the conjunct's references
        # rewrite to it: MySQL scoping says an unqualified name that exists
        # in BOTH scopes binds to the INNER one, and the alias sidesteps the
        # joined-layout resolver calling it ambiguous.
        corr_other = [_copy.deepcopy(c) for c in corr_other]
        inner_refs: list[ast.Node] = []
        for c in corr_other:
            for col_node in _column_nodes(c):
                if _resolves(probe, col_node, inner_schema):
                    for j, prev in enumerate(inner_refs):
                        if _ast_eq(col_node, prev):
                            k = j
                            break
                    else:
                        k = len(inner_refs)
                        inner_refs.append(_copy.deepcopy(col_node))
                        inner.items.append(ast.SelectItem(inner_refs[k], alias=f"__corr#{k}"))
                    # rewrite IN PLACE to the aliased projection
                    col_node.name, col_node.table, col_node.db = f"__corr#{k}", "", ""
        for _, inner_side in corr:
            inner.items.append(ast.SelectItem(inner_side))
        try:
            inner_plan = self.build_select(inner)
        except PlanError as err:
            if "Unknown column" in str(err) and _unknown_col_in_schema(str(err), plan.schema):
                raise PlanError(
                    "unsupported correlated subquery: correlation must be a plain equality"
                )
            raise  # a genuine unknown column — keep the original message
        n_extra = len(corr)
        eq_conds: list[tuple[int, int]] = []
        if operand_ast is not None:
            op_e = self.resolve(operand_ast, BuildCtx(plan.schema))
            if not isinstance(op_e, ColumnRef):
                raise PlanError("IN-subquery operand must be a column for correlated rewrite")
            if base_items != 1:
                raise PlanError("IN subquery must select exactly one column")
            eq_conds.append((op_e.index, 0))
        first_extra = len(inner_plan.schema) - n_extra
        for i, (outer_side, _) in enumerate(corr):
            oe = self.resolve(outer_side, BuildCtx(plan.schema))
            if not isinstance(oe, ColumnRef):
                raise PlanError("correlated comparison must reference a plain outer column")
            eq_conds.append((oe.index, first_extra + i))
        other_exprs = []
        if corr_other:
            # resolve over the JOINED layout [outer cols ++ inner cols] —
            # table aliases disambiguate same-named columns across sides
            joined_schema = list(plan.schema) + list(inner_plan.schema)
            for c in corr_other:
                other_exprs.append(self.resolve(c, BuildCtx(joined_schema)))
        return LogicalJoin(
            kind="anti" if negated else "semi",
            eq_conds=eq_conds,
            other_conds=other_exprs,
            null_aware=null_aware,
            schema=[OutCol(c.name, c.ftype, c.table, c.slot) for c in plan.schema],
            children=[plan, inner_plan],
        )

    def _try_scalar_corr_join(self, plan: LogicalPlan, cj: ast.Node):
        """Correlated *scalar* subquery in a comparison —
        ``outer.x CMP (SELECT agg(..) FROM t2 WHERE t2.k = outer.k)`` —
        rewritten by aggregate pull-up (ref: rule_decorrelate.go pulling the
        agg above a left outer join): the inner aggregates per correlation
        key, LEFT JOINs onto the outer, and the comparison becomes a filter
        over the joined agg column (NULL when no inner row, which the
        comparison correctly rejects; COUNT wraps in IFNULL(.., 0))."""
        if not (isinstance(cj, ast.BinaryOp) and cj.op in ("eq", "ne", "lt", "le", "gt", "ge")):
            return None
        for side, flip in (("right", False), ("left", True)):
            sub = getattr(cj, side)
            if isinstance(sub, ast.SubqueryExpr) and sub.modifier == "":
                other_ast = cj.left if side == "right" else cj.right
                break
        else:
            return None
        if not (isinstance(sub.select, ast.Select) and self._is_correlated(sub.select, plan.schema)):
            return None
        got = self._scalar_corr_expand(plan, sub)
        if got is None:
            return None
        join, sub_ref = got
        other_e = self.resolve(other_ast, BuildCtx(join.schema))
        a, b = (sub_ref, other_e) if flip else (other_e, sub_ref)
        return join, func(cj.op, a, b)

    def _scalar_corr_expand(self, plan: LogicalPlan, sub: ast.SubqueryExpr):
        """Expand one correlated scalar-aggregate subquery into a LEFT JOIN
        of ``plan`` against the per-correlation-key inner aggregate.
        → (join_plan, Expression for the scalar value) or None when the node
        isn't an expandable scalar subquery. Shared by the WHERE-comparison
        and SELECT-item paths."""
        inner = sub.select
        if not isinstance(inner, ast.Select) or len(inner.items) != 1:
            return None
        if inner.group_by or inner.limit is not None or inner.order_by or inner.having is not None:
            raise PlanError("correlated scalar subquery with GROUP BY/ORDER BY/LIMIT is not supported")
        item = inner.items[0]
        if isinstance(item.expr, ast.Wildcard) or not _contains_agg(item.expr):
            # non-aggregated correlated scalar: can yield >1 row — unsupported
            raise PlanError("correlated scalar subquery must be an aggregate")
        import copy as _copy

        inner = _copy.deepcopy(inner)
        probe = Builder(self.catalog, self.db, subquery_runner=lambda _sel: [])
        inner_from = probe._build_from(inner.from_) if inner.from_ is not None else LogicalDual()
        inner_schema = inner_from.schema
        corr: list[tuple[ast.Node, ast.Node]] = []
        keep: list[ast.Node] = []
        for c in _split_ast_conj(inner.where) if inner.where is not None else []:
            pair = self._corr_eq_pair(c, inner_schema, plan.schema, probe)
            if pair is not None:
                corr.append(pair)
            else:
                keep.append(c)
        if not corr:
            raise PlanError("unsupported correlated subquery (no equality correlation)")
        inner.where = _and_join_ast(keep)
        inner.group_by = [inner_side for _, inner_side in corr]
        for inner_side in inner.group_by:
            inner.items.append(ast.SelectItem(inner_side))
        try:
            inner_plan = self.build_select(inner)
        except PlanError as err:
            if "Unknown column" in str(err) and _unknown_col_in_schema(str(err), plan.schema):
                raise PlanError(
                    "unsupported correlated subquery: correlation must be a plain equality"
                )
            raise
        base_width = len(plan.schema)
        eq_conds: list[tuple[int, int]] = []
        for i, (outer_side, _) in enumerate(corr):
            oe = self.resolve(outer_side, BuildCtx(plan.schema))
            if not isinstance(oe, ColumnRef):
                raise PlanError("correlated comparison must reference a plain outer column")
            eq_conds.append((oe.index, 1 + i))
        join_schema = [OutCol(c.name, c.ftype, c.table, c.slot) for c in plan.schema] + [
            OutCol(f"__ssub#{base_width + i}", c.ftype) for i, c in enumerate(inner_plan.schema)
        ]
        join = LogicalJoin(
            kind="left",
            eq_conds=eq_conds,
            schema=join_schema,
            children=[plan, inner_plan],
        )
        agg_ft = inner_plan.schema[0].ftype
        sub_ref: Expression = ColumnRef(base_width, agg_ft, join_schema[base_width].name)
        if isinstance(item.expr, ast.FuncCall) and _FN_ALIAS.get(item.expr.name, item.expr.name) == "count":
            # COUNT over no rows is 0, not NULL
            sub_ref = func("ifnull", sub_ref, Constant(0, agg_ft))
        return join, sub_ref

    def _is_correlated(self, inner: ast.Select, outer_schema) -> bool:
        """True when the subquery fails to resolve alone but its unknown
        columns exist in the outer scope. The probe's nested subqueries
        resolve against empty results so nothing executes twice."""
        probe = Builder(self.catalog, self.db, subquery_runner=lambda _sel: [])
        try:
            probe.build_select(inner)
            return False
        except PlanError as err:
            if "Unknown column" not in str(err):
                raise
            if _unknown_col_in_schema(str(err), outer_schema):
                return True
            raise

    def _conj_is_mixed(self, c: ast.Node, inner_schema, outer_schema, probe: "Builder") -> bool:
        """True when ``c`` references BOTH scopes (a correlated non-eq
        conjunct) — every column resolves somewhere, at least one per side."""
        saw_inner = saw_outer = False
        for node in _column_nodes(c):
            if _resolves(probe, node, inner_schema):
                saw_inner = True
            elif _resolves(probe, node, outer_schema):
                saw_outer = True
            else:
                return False  # a genuinely unknown column: not ours to claim
        return saw_inner and saw_outer

    def _corr_eq_pair(self, c: ast.Node, inner_schema, outer_schema, probe: "Builder"):
        """(outer_ast, inner_ast) when ``c`` is `inner_col = outer_col` (either
        orientation), else None. ``probe`` resolves without executing."""
        if not (isinstance(c, ast.BinaryOp) and c.op == "eq"):
            return None

        def scope(x: ast.Node) -> str:
            try:
                probe.resolve(x, BuildCtx(inner_schema))
                return "inner"
            except PlanError:
                pass
            try:
                probe.resolve(x, BuildCtx(outer_schema))
                return "outer"
            except PlanError:
                return "none"

        ls, rs = scope(c.left), scope(c.right)
        if ls == "inner" and rs == "outer":
            return (c.right, c.left)
        if ls == "outer" and rs == "inner":
            return (c.left, c.right)
        return None

    def _build_windows(self, plan: LogicalPlan, win_calls: list) -> LogicalPlan:
        from tidb_tpu.planner.plans import LogicalWindow, WindowFuncDesc

        groups: dict[str, list] = {}
        seen: set[int] = set()
        for fc in win_calls:
            if id(fc) in seen:
                continue
            seen.add(id(fc))
            groups.setdefault(fc.over.key(), []).append(fc)
        for calls in groups.values():
            spec = calls[0].over
            ctx = BuildCtx(plan.schema)
            part = [self.resolve(e, ctx) for e in spec.partition_by]
            order = [(self.resolve(oi.expr, ctx), oi.desc) for oi in spec.order_by]
            base_n = len(plan.schema)
            funcs: list[WindowFuncDesc] = []
            for fc in calls:
                if fc.distinct:
                    raise PlanError("DISTINCT in a window function is not supported")
                name = _FN_ALIAS.get(fc.name, fc.name)
                args = [] if (name == "count" and fc.star) else [self.resolve(a, ctx) for a in fc.args]
                if name in ("lead", "lag"):
                    for extra in args[1:]:  # offset and default
                        if not isinstance(extra, Constant):
                            raise PlanError(f"{name}() offset/default must be constant")
                if name == "ntile":
                    if not (args and isinstance(args[0], Constant)):
                        raise PlanError("ntile() bucket count must be constant")
                    if int(args[0].value or 0) < 1:
                        raise PlanError("ntile() bucket count must be positive")
                funcs.append(WindowFuncDesc(name, args, _window_ftype(name, args, order)))
            win = LogicalWindow(
                funcs=funcs,
                partition_by=part,
                order_by=order,
                whole_partition=spec.whole_partition or (not spec.order_by and spec.frame is None),
                rows_frame=spec.rows_frame,
                frame=spec.frame,
                children=[plan],
            )
            win.schema = list(plan.schema) + [
                OutCol(f"win#{base_n + i}", f.ftype) for i, f in enumerate(funcs)
            ]
            for i, fc in enumerate(calls):
                self._win_map[id(fc)] = ColumnRef(base_n + i, funcs[i].ftype, _display_name(fc))
            plan = win
        return plan

    # -- FROM ---------------------------------------------------------------
    def _build_from(self, node: ast.Node) -> LogicalPlan:
        if isinstance(node, ast.TableRef):
            db = node.db or self.db
            if db.lower() == "information_schema" and self.memtable_provider is not None:
                mem = self.memtable_provider(
                    node.name.lower(),
                    _memtable_hints(getattr(self, "_mt_where", None)),
                )
                if mem is None:
                    raise PlanError(f"Unknown table 'information_schema.{node.name}'")
                names, ftypes, rows = mem
                self.uncacheable = True  # memtables snapshot runtime state
                from tidb_tpu.planner.plans import LogicalMemSource

                alias = node.alias or node.name
                ms = LogicalMemSource(
                    rows=rows,
                    schema=[OutCol(nm, ft, table=alias) for nm, ft in zip(names, ftypes)],
                )
                return ms
            view = self.catalog.view(db, node.name) if hasattr(self.catalog, "view") else None
            if view is not None:
                # expand the view definition as a derived table (ref:
                # planbuilder BuildDataSourceFromView)
                if self._view_depth >= 8:
                    raise PlanError(f"view nesting too deep at '{node.name}'")
                from tidb_tpu.parser import parse

                self._view_depth += 1
                try:
                    sub = self.build_query(parse(view.text))
                finally:
                    self._view_depth -= 1
                alias = node.alias or node.name
                if view.columns:
                    if len(view.columns) != len(sub.schema):
                        raise PlanError(f"view '{node.name}' column count mismatch")
                    for oc, nm in zip(sub.schema, view.columns):
                        oc.name = nm
                for oc in sub.schema:
                    oc.table = alias
                self.uncacheable = True  # definition text can change
                return sub
            t = self.catalog.table(db, node.name)
            if self.scan_checker is not None:
                self.scan_checker(db, node.name)
            alias = node.alias or node.name
            scan = LogicalScan(db=db, table=t, alias=alias)
            if node.partitions is not None:
                if t.partition is None:
                    raise PlanError(f"PARTITION () clause on nonpartitioned table '{t.name}'")
                known_parts = {d.name.lower() for d in t.partition.defs}
                for pn in node.partitions:
                    if pn not in known_parts:
                        raise PlanError(f"Unknown partition '{pn}' in table '{t.name}'")
                scan.partition_select = list(node.partitions)
            for hname, hargs in self.hints:
                if hname in ("use_index", "ignore_index") and len(hargs) >= 2:
                    if hargs[0].strip().lower() in (alias.lower(), node.name.lower()):
                        hnames = [a.strip().lower() for a in hargs[1:]]
                        if hname == "use_index":
                            scan.use_index = hnames[0]
                            scan.allowed_indexes = frozenset(hnames) | (scan.allowed_indexes or frozenset())
                        else:
                            scan.ignored_indexes = scan.ignored_indexes | frozenset(hnames)
                elif hname == "use_index_merge" and hargs:
                    if hargs[0].strip().lower() in (alias.lower(), node.name.lower()):
                        scan.use_index_merge = True
            known = {i.name for i in t.indexes} | ({"primary"} if t.pk_is_handle else set())
            for kind, names in node.index_hints or []:
                # table-level USE/IGNORE/FORCE INDEX (...) — MySQL merges
                # every clause on the reference: USE/FORCE union into the
                # candidate restriction (empty = USE INDEX () = table scan)
                # with cost choosing among the candidates, IGNORE unions
                # into the exclusion set, FORCE additionally demotes the
                # table scan to a last resort (ref: the tableHintInfo →
                # path pruning in planbuilder.go)
                for nm in names:
                    if nm not in known:
                        # ER_KEY_DOES_NOT_EXIST — a typo must not silently
                        # disable every index on the table
                        raise PlanError(f"Key '{nm}' doesn't exist in table '{t.name}'")
                if kind in ("use", "force"):
                    scan.allowed_indexes = frozenset(names) | (scan.allowed_indexes or frozenset())
                    if kind == "force":
                        scan.force_index = True
                else:
                    scan.ignored_indexes = scan.ignored_indexes | frozenset(names)
            scan.schema = [
                OutCol(c.name, c.ftype, table=alias, slot=c.offset) for c in t.columns
            ]
            return scan
        if isinstance(node, ast.SubquerySource):
            sub = self.build_query(node.select)
            alias = node.alias or "subquery"
            if node.col_aliases:
                if len(node.col_aliases) != len(sub.schema):
                    raise PlanError(
                        f"derived table '{alias}' has {len(node.col_aliases)} column "
                        f"aliases for {len(sub.schema)} columns"
                    )
                for oc, nm in zip(sub.schema, node.col_aliases):
                    oc.name = nm
            for oc in sub.schema:
                oc.table = alias
            return sub
        if isinstance(node, ast.ValuesSource):
            from tidb_tpu.planner.plans import LogicalMemSource

            alias = node.alias or "values"
            schema = [
                OutCol(nm, ft, table=alias) for nm, ft in zip(node.names, node.ftypes)
            ]
            return LogicalMemSource(rows=node.rows, schema=schema)
        if isinstance(node, ast.Join):
            left = self._build_from(node.left)
            right = self._build_from(node.right)
            schema = [OutCol(c.name, c.ftype, c.table, c.slot) for c in left.schema] + [
                OutCol(c.name, c.ftype, c.table, c.slot) for c in right.schema
            ]
            join = LogicalJoin(kind=node.kind, schema=schema, children=[left, right])
            if node.on is not None:
                conds = self._split_conj(self.resolve(node.on, BuildCtx(schema)))
                nleft = len(left.schema)
                for c in conds:
                    pair = _as_equi_pair(c, nleft)
                    if pair is not None:
                        join.eq_conds.append(pair)
                    else:
                        join.other_conds.append(c)
            # join-algorithm hints (ref: HASH_JOIN/MERGE_JOIN/INL_JOIN hints,
            # planner hint handling). Scope: the build/inner (right) side's
            # tables, plus the left side only when it is a single base table —
            # a chain's upper joins must not match a lower join's table just
            # because its columns flow through the accumulated schema
            tables = {c.table.lower() for c in right.schema if c.table}
            left_tables = {c.table.lower() for c in left.schema if c.table}
            if len(left_tables) == 1:
                tables |= left_tables
            for hname, hargs in self.hints:
                h = hname.lower()
                alg = {"hash_join": "hash", "merge_join": "merge", "inl_join": "index", "index_join": "index"}.get(h)
                if alg and any(a.strip().lower() in tables for a in hargs):
                    join.preferred = alg
            return join
        raise PlanError(f"unsupported FROM clause {type(node).__name__}")

    # -- expression resolution ----------------------------------------------
    def _fold_warn(self, level, code, msg):
        # a fold-time warning is data-independent but STATEMENT-scoped: the
        # plan must not be cached, or repeats would silently stop warning
        self.uncacheable = True
        if self.warn is not None:
            self.warn(level, code, msg)

    def resolve(self, node: ast.Node, ctx: BuildCtx) -> Expression:
        e = self._resolve(node, ctx)
        return _fold(e, self._fold_warn)

    def _resolve(self, node: ast.Node, ctx: BuildCtx) -> Expression:
        if isinstance(node, ast.Literal):
            return _literal(node)
        if isinstance(node, ast.ParamMarker):
            raise PlanError("parameter marker outside PREPARE/EXECUTE")
        if isinstance(node, ast.UserVar):
            # user/system variable reads fold to constants at plan time →
            # such plans must not be cached (ref: plan-cache skips them)
            self.uncacheable = True
            if node.sys:
                if self.dyn_sys_vars is not None and node.name in self.dyn_sys_vars:
                    # statement-scope dynamics (@@warning_count/@@error_count
                    # — ref: session.go variable read hooks)
                    return _literal(ast.Literal(self.dyn_sys_vars[node.name]))
                src = self.sys_vars if node.scope != "global" else self.global_vars
                if src is None or node.name not in src:
                    raise PlanError(f"unknown system variable '{node.name}'")
                return _literal(ast.Literal(src[node.name]))
            val = (self.user_vars or {}).get(node.name)
            if isinstance(val, str):
                val = val.encode()
            return _literal(ast.Literal(val))
        if isinstance(node, ast.ColumnName):
            return self._resolve_column(node, ctx)
        if isinstance(node, ast.BinaryOp):
            # date ± INTERVAL n unit (ref: MySQL date arithmetic)
            if node.op in ("plus", "minus"):
                for side, other in ((node.right, node.left), (node.left, node.right)):
                    if isinstance(side, ast.FuncCall) and side.name == "interval":
                        if side is node.left and node.op == "minus":
                            raise PlanError("INTERVAL - date is invalid")
                        n = self._resolve(side.args[0], ctx)
                        unit = side.args[1].value
                        base = self._resolve(other, ctx)
                        neg = node.op == "minus"
                        return self._date_interval(base, n, unit, neg)
            left = self._resolve(node.left, ctx)
            right = self._resolve(node.right, ctx)
            return self._binary(node.op, left, right)
        if isinstance(node, ast.UnaryOp):
            if node.op == "not":
                return func("not", self._resolve(node.operand, ctx))
            if node.op == "unaryminus":
                return func("unaryminus", self._resolve(node.operand, ctx))
            if node.op == "bitneg":
                return func("bitneg", self._resolve(node.operand, ctx))
            raise PlanError(f"unsupported unary op {node.op}")
        if isinstance(node, ast.IsNull):
            e = func("isnull", self._resolve(node.operand, ctx))
            return func("not", e) if node.negated else e
        if isinstance(node, ast.InList):
            if len(node.items) == 1 and isinstance(node.items[0], ast.SubqueryExpr):
                vals = self._run_subquery(node.items[0].select, expect_cols=1)
                items = [_const_like(v[0]) for v in vals]
                if not items:
                    return Constant(0 if not node.negated else 1, bool_type())
            else:
                items = [self._resolve(x, ctx) for x in node.items]
            operand = self._resolve(node.operand, ctx)
            items = [self._coerce_to(operand.ftype, it) for it in items]
            e = func("in", operand, *items)
            return func("not", e) if node.negated else e
        if isinstance(node, ast.Between):
            operand = self._resolve(node.operand, ctx)
            lo = self._coerce_to(operand.ftype, self._resolve(node.low, ctx))
            hi = self._coerce_to(operand.ftype, self._resolve(node.high, ctx))
            e = func("and", self._binary("ge", operand, lo), self._binary("le", operand, hi))
            return func("not", e) if node.negated else e
        if isinstance(node, ast.Like):
            sig = "regexp" if node.regexp else "like"
            operand = self._resolve(node.operand, ctx)
            pattern = self._resolve(node.pattern, ctx)
            operand, pattern = _apply_explicit_collation(operand, pattern)
            e = func(sig, operand, pattern)
            return func("not", e) if node.negated else e
        if isinstance(node, ast.Collate):
            return _collate_expr(self._resolve(node.operand, ctx), node.collation)
        if isinstance(node, ast.FuncCall) and node.name in ("date_add", "date_sub", "adddate", "subdate") and len(node.args) == 2 and isinstance(node.args[1], ast.FuncCall) and node.args[1].name == "interval":
            base = self._resolve(node.args[0], ctx)
            iv = node.args[1]
            n = self._resolve(iv.args[0], ctx)
            return self._date_interval(base, n, iv.args[1].value, node.name in ("date_sub", "subdate"))
        if isinstance(node, ast.FuncCall):
            if self._win_map and id(node) in self._win_map:
                return self._win_map[id(node)]
            return self._func_call(node, ctx)
        if isinstance(node, ast.CaseWhen):
            args: list[Expression] = []
            for cond, val in node.branches:
                c = self._resolve(cond, ctx)
                if node.operand is not None:
                    c = self._binary("eq", self._resolve(node.operand, ctx), c)
                args.append(c)
                args.append(self._resolve(val, ctx))
            if node.else_value is not None:
                args.append(self._resolve(node.else_value, ctx))
            return func("case_when", *args)
        if isinstance(node, ast.Cast):
            return _cast_expr(self._resolve(node.operand, ctx), node.target)
        if isinstance(node, ast.QuantifiedCmp):
            return self._resolve_quantified(node, ctx)
        if isinstance(node, ast.SubqueryExpr):
            m = getattr(self, "_scalar_sub_map", None)
            if m and id(node) in m:
                return m[id(node)]  # pre-expanded correlated scalar join col
            if node.modifier == "exists":
                vals = self._run_subquery(node.select, limit=1)
                return Constant(1 if vals else 0, bool_type())
            vals = self._run_subquery(node.select, expect_cols=1, limit=2)
            if len(vals) > 1:
                raise PlanError("scalar subquery returned more than one row")
            return _const_like(vals[0][0]) if vals else Constant(None, FieldType(TypeKind.NULLTYPE))
        raise PlanError(f"unsupported expression {type(node).__name__}")

    def _resolve_quantified(self, node: "ast.QuantifiedCmp", ctx: BuildCtx) -> Expression:
        """Value-context `left OP ANY|ALL (S)` with full three-valued-logic
        semantics: S runs eagerly (uncorrelated) and the result folds to a
        comparison against the relevant extreme, OR/AND-ed with NULL when S
        contains NULLs — so SELECT-list uses return NULL exactly where MySQL
        does (ref: expression_rewriter.go buildQuantifierPlan min/max form)."""
        # eq ANY ≡ IN, ne ALL ≡ NOT IN — exact, reuse those paths
        if node.op == "eq" and not node.is_all:
            return self._resolve(ast.InList(node.left, [ast.SubqueryExpr(node.select, "in")]), ctx)
        if node.op == "ne" and node.is_all:
            return self._resolve(
                ast.InList(node.left, [ast.SubqueryExpr(node.select, "in")], negated=True), ctx
            )
        vals = self._run_subquery(node.select, expect_cols=1)
        left = self._resolve(node.left, ctx)
        xs = [v[0] for v in vals]
        has_null = any(x is None for x in xs)
        nn = sorted({x for x in xs if x is not None})
        null_c = Constant(None, FieldType(TypeKind.NULLTYPE))
        if not nn:
            if not vals:  # empty set: ALL vacuously TRUE, ANY FALSE
                return Constant(1 if node.is_all else 0, bool_type())
            return null_c  # only NULLs: every comparison is NULL
        if node.op in ("lt", "le", "gt", "ge"):
            if node.is_all:
                ext = nn[0] if node.op in ("lt", "le") else nn[-1]
            else:
                ext = nn[-1] if node.op in ("lt", "le") else nn[0]
            base = self._binary(node.op, left, _const_like(ext))
            if has_null:
                return func("and" if node.is_all else "or", base, null_c)
            return base
        if node.op == "eq":  # eq ALL: all values must equal left
            base = self._binary("eq", left, _const_like(nn[0]))
            if len(nn) > 1:  # two distinct values: FALSE for any non-NULL left
                base = func("and", base, self._binary("eq", left, _const_like(nn[1])))
            return func("and", base, null_c) if has_null else base
        # ne ANY: some value differs from left
        base = self._binary("ne", left, _const_like(nn[0]))
        if len(nn) > 1:
            base = func("or", base, self._binary("ne", left, _const_like(nn[1])))
        return func("or", base, null_c) if has_null else base

    def _date_interval(self, base, n, unit: str, negate: bool):
        """date ± INTERVAL n unit → the date_add_* builtins (ref: MySQL
        date arithmetic units; day-ish units in days, sub-day in micros,
        month-ish via calendar month math with day clamping)."""
        from tidb_tpu.expression.expr import Constant
        from tidb_tpu.types.field_type import bigint_type

        def times(e, k: int):
            if k == 1:
                return e
            return func("mul", e, Constant(k, bigint_type(nullable=False)))

        if base.ftype.kind == TypeKind.STRING:
            if not isinstance(base, Constant):
                # no runtime string→temporal cast yet: dictionary-code
                # arithmetic would be garbage — fail loudly instead
                raise PlanError("INTERVAL arithmetic needs a DATE/DATETIME operand (CAST the string column)")
            v = base.value.decode() if isinstance(base.value, bytes) else str(base.value)
            kind = TypeKind.DATETIME if ":" in v else TypeKind.DATE
            base = self._coerce_to(FieldType(kind), base)
        if negate:
            n = func("unaryminus", n)
        u = unit.lower()
        if u in ("day", "week"):
            return func("date_add_days", base, times(n, 7 if u == "week" else 1))
        if u in ("month", "quarter", "year"):
            k = {"month": 1, "quarter": 3, "year": 12}[u]
            return func("date_add_months", base, times(n, k))
        if u in ("hour", "minute", "second", "microsecond"):
            k = {"hour": 3_600_000_000, "minute": 60_000_000, "second": 1_000_000, "microsecond": 1}[u]
            return func("date_add_micros", base, times(n, k))
        raise PlanError(f"unsupported INTERVAL unit {unit}")

    def _resolve_column(self, node: ast.ColumnName, ctx: BuildCtx) -> Expression:
        name = node.name.lower()
        tbl = node.table.lower()
        matches = [
            i
            for i, oc in enumerate(ctx.schema)
            if oc.name.lower() == name and (not tbl or oc.table.lower() == tbl)
        ]
        if not matches and ctx.aliases and not tbl and name in ctx.aliases:
            return ctx.aliases[name]
        if not matches:
            raise PlanError(f"Unknown column '{node}'")
        if len(matches) > 1:
            raise PlanError(f"Column '{node}' is ambiguous")
        oc = ctx.schema[matches[0]]
        return ColumnRef(matches[0], oc.ftype, oc.name)

    def _func_call(self, node: ast.FuncCall, ctx: BuildCtx) -> Expression:
        name = _FN_ALIAS.get(node.name, node.name)
        if node.over is not None:
            raise PlanError(f"window function {name}() is not allowed in this clause")
        if name in PURE_WINDOW_FUNCS:
            raise PlanError(f"{name}() requires an OVER clause")
        if name in AGG_FUNCS or (name == "count" and node.star):
            # agg calls are intercepted by _resolve_in_agg's rewrite pass;
            # reaching here means an agg in a pure scalar context
            raise PlanError(f"aggregate {name}() used outside aggregation context")
        if name == "interval":
            raise PlanError("INTERVAL outside date arithmetic")
        if name in ("nextval", "setval"):
            # sequence functions allocate at resolve time (each INSERT row
            # resolves separately, so every row draws a fresh value)
            self.uncacheable = True
            if not node.args or not isinstance(node.args[0], ast.ColumnName):
                raise PlanError(f"{name}() takes a sequence name")
            ref = node.args[0]
            seq_db = ref.table or self.db
            if name == "nextval":
                v = self.catalog.sequence_nextval(seq_db, ref.name)
            else:
                if len(node.args) != 2:
                    raise PlanError("setval(seq, value)")
                arg = self.resolve(node.args[1], ctx)
                if not isinstance(arg, Constant):
                    raise PlanError("setval value must be constant")
                v = self.catalog.sequence_setval(seq_db, ref.name, int(arg.value))
            return Constant(v, bigint_type(nullable=False))
        if name in ("now", "current_timestamp"):
            import datetime

            return Constant(datetime.datetime.now(), FieldType(TypeKind.DATETIME, nullable=False))
        if name in ("curdate", "current_date"):
            import datetime

            return Constant(datetime.date.today(), FieldType(TypeKind.DATE, nullable=False))
        if name in ("curtime", "current_time"):
            import datetime

            t = datetime.datetime.now().time()
            us = ((t.hour * 3600 + t.minute * 60 + t.second) * 1_000_000) + t.microsecond
            return Constant(us, FieldType(TypeKind.DURATION, nullable=False))
        if name in ("utc_date", "utc_timestamp", "utc_time"):
            import datetime

            u = datetime.datetime.now(datetime.timezone.utc).replace(tzinfo=None, microsecond=0)
            if name == "utc_date":
                return Constant(u.date(), FieldType(TypeKind.DATE, nullable=False))
            if name == "utc_timestamp":
                return Constant(u, FieldType(TypeKind.DATETIME, nullable=False))
            us = ((u.hour * 3600 + u.minute * 60 + u.second) * 1_000_000) + u.microsecond
            return Constant(us, FieldType(TypeKind.DURATION, nullable=False))
        if name == "pi" and not node.args:
            return Constant(3.141592653589793, FieldType(TypeKind.FLOAT, nullable=False))
        if name == "last_insert_id" and not node.args:
            self.uncacheable = True  # session-scope dynamic, like @@warning_count
            v = (self.dyn_sys_vars or {}).get("last_insert_id", 0)
            return Constant(int(v), bigint_type(nullable=False))
        if name == "any_value" and len(node.args) == 1:
            # MySQL: suppresses ONLY_FULL_GROUP_BY checking; value passthrough
            return self._resolve(node.args[0], ctx)
        if name in ("timestampdiff", "timestampadd") and len(node.args) == 3:
            return self._timestamp_func(name, node, ctx, self._resolve)
        if name == "str_to_date" and len(node.args) == 2:
            # result kind depends on the format string: time specifiers →
            # DATETIME, else DATE (ref: builtin_time.go strToDate)
            args = [self._resolve(a, ctx) for a in node.args]
            fmt = args[1]
            if isinstance(fmt, Constant) and isinstance(fmt.value, (str, bytes)):
                from tidb_tpu.expression.eval import str_to_date_has_time

                f = fmt.value.decode() if isinstance(fmt.value, bytes) else fmt.value
                kind = TypeKind.DATETIME if str_to_date_has_time(f) else TypeKind.DATE
                return func("str_to_date", *args, ret=FieldType(kind, nullable=True))
            return func("str_to_date", *args)
        if name in ("datediff", "timediff", "addtime", "subtime"):
            # string-literal operands coerce to the temporal kind MySQL
            # implies: dates for DATEDIFF; for the time functions a literal
            # with a date part reads as DATETIME, else as a DURATION
            def time_like(e):
                if not (isinstance(e, Constant) and e.ftype.kind == TypeKind.STRING):
                    return e
                v = e.value.decode() if isinstance(e.value, bytes) else str(e.value)
                kind = TypeKind.DATETIME if ("-" in v.lstrip("-") or " " in v.strip()) else TypeKind.DURATION
                return self._coerce_to(FieldType(kind), e)

            args = [self._resolve(a, ctx) for a in node.args]
            if len(args) == 2:
                a, b = args
                if name == "datediff":
                    tgt = FieldType(TypeKind.DATE)
                    a = self._coerce_to(tgt, a) if a.ftype.kind == TypeKind.STRING else a
                    b = self._coerce_to(tgt, b) if b.ftype.kind == TypeKind.STRING else b
                else:  # addtime/subtime/timediff: both sides time-like
                    a = time_like(a)
                    b = time_like(b)
                return func(name, a, b)
            return func(name, *args)
        if name == "nullif":
            a = self._resolve(node.args[0], ctx)
            b = self._resolve(node.args[1], ctx)
            return func("case_when", self._binary("eq", a, b), Constant(None, FieldType(TypeKind.NULLTYPE)), a)
        args = [self._resolve(a, ctx) for a in node.args]
        if name in _DATE_ARG0_FNS and args and isinstance(args[0], Constant) and args[0].ftype.kind == TypeKind.STRING:
            v = args[0].value.decode() if isinstance(args[0].value, bytes) else str(args[0].value)
            kind = TypeKind.DATETIME if ":" in v else TypeKind.DATE
            args[0] = self._coerce_to(FieldType(kind), args[0])
        elif name in _TIME_ARG0_FNS and args and isinstance(args[0], Constant) and args[0].ftype.kind == TypeKind.STRING:
            args[0] = self._coerce_to(FieldType(TypeKind.DURATION), args[0])
        try:
            return func(name, *args)
        except KeyError:
            raise PlanError(f"unknown function {node.name}()")

    def _binary(self, op: str, left: Expression, right: Expression) -> Expression:
        if op in ("eq", "ne", "lt", "le", "gt", "ge"):
            left, right = self._coerce_cmp(left, right)
            left, right = _apply_explicit_collation(left, right)
        return func(op, left, right)

    def _coerce_cmp(self, a: Expression, b: Expression):
        """Implicit comparison casts (MySQL type-conversion rules):
        temporal vs string constant parses the literal; numeric vs string
        compares as floating point (both sides to DOUBLE)."""
        for x, y in ((a, b), (b, a)):
            if x.ftype.is_temporal and isinstance(y, Constant) and y.ftype.kind == TypeKind.STRING:
                conv = self._coerce_to(x.ftype, y)
                if x is a:
                    return a, conv
                return conv, b
        numeric = {TypeKind.INT, TypeKind.UINT, TypeKind.FLOAT, TypeKind.DECIMAL}
        for x, y in ((a, b), (b, a)):
            if x.ftype.kind in numeric and y.ftype.kind == TypeKind.STRING and not x.ftype.is_temporal:
                conv = func("cast_float", y)
                if x is a:
                    return a, conv
                return conv, b
        return a, b

    def _coerce_to(self, ft: FieldType, e: Expression) -> Expression:
        if not isinstance(e, Constant) or e.value is None:
            return e
        v = e.value
        if ft.kind == TypeKind.DATE and isinstance(v, (str, bytes)):
            s = v.decode() if isinstance(v, bytes) else v
            return Constant(date_to_days(s), ft.not_null())
        if ft.kind == TypeKind.DATETIME and isinstance(v, (str, bytes)):
            s = v.decode() if isinstance(v, bytes) else v
            try:
                return Constant(datetime_to_micros(s), ft.not_null())
            except ValueError:
                return Constant(datetime_to_micros(s + " 00:00:00"), ft.not_null())
        if ft.kind == TypeKind.DURATION and isinstance(v, (str, bytes)):
            from tidb_tpu.types.datum import duration_to_micros

            s = v.decode() if isinstance(v, bytes) else v
            return Constant(duration_to_micros(s), ft.not_null())
        return e

    # -- agg resolution -------------------------------------------------------
    def _resolve_in_agg(self, node, base_schema, aggs, group_exprs, group_asts, aliases=None, rollup=False):
        """Resolve an expression in SELECT/HAVING of an aggregated query:
        agg calls → refs into the agg output; group-by exprs → group key refs;
        bare columns → implicit first_row (MySQL non-strict)."""
        agg_schema_len = lambda: len(aggs)  # noqa: E731

        def walk(n):
            # whole-expression matches a group-by item? (deferred index: agg
            # count isn't final yet — ColumnRef(-1-gi) is patched afterwards)
            for gi, gast in enumerate(group_asts):
                if _ast_eq(n, gast):
                    e = group_exprs[gi]
                    return ColumnRef(-1 - gi, e.ftype, f"gb#{gi}")
            if isinstance(n, ast.FuncCall):
                name = _FN_ALIAS.get(n.name, n.name)
                if name == "grouping" and len(n.args) == 1:
                    # GROUPING(g): 1 on super-aggregate (rolled-up) rows,
                    # 0 otherwise (ref: expression.grouping + Expand). Only
                    # meaningful under WITH ROLLUP; resolves to a deferred
                    # flag-column ref the rollup rewrite materializes.
                    if not rollup:
                        raise PlanError("GROUPING() is only valid with GROUP BY ... WITH ROLLUP")
                    for gi, gast in enumerate(group_asts):
                        if _ast_eq(n.args[0], gast):
                            return ColumnRef(-20001 - gi, bigint_type(nullable=False), f"grouping#{gi}")
                    raise PlanError("GROUPING() argument must be a GROUP BY expression")
                if name in AGG_FUNCS or n.star:
                    if n.star:
                        desc = AggDesc("count", None)
                    else:
                        if name == "group_concat" and len(n.args) > 1:
                            # GROUP_CONCAT(a, b, ...) concatenates the values
                            # per row first (MySQL semantics)
                            parts = [self.resolve(a, BuildCtx(base_schema)) for a in n.args]
                            parts = [
                                p if p.ftype.kind == TypeKind.STRING else func("cast_string", p, ret=string_type())
                                for p in parts
                            ]
                            arg = func("concat", *parts)
                        else:
                            arg = self.resolve(n.args[0], BuildCtx(base_schema))
                        gc_order = []
                        if name == "group_concat" and n.order_by:
                            gc_order = [
                                (self.resolve(e, BuildCtx(base_schema)), d) for e, d in n.order_by
                            ]
                        desc = AggDesc(
                            name,
                            arg,
                            distinct=n.distinct,
                            sep=n.separator if n.separator is not None else ",",
                            order_by=gc_order,
                        )
                    for i, existing in enumerate(aggs):
                        if repr(existing) == repr(desc):
                            return ColumnRef(i, existing.ftype, f"agg#{i}")
                    aggs.append(desc)
                    return ColumnRef(len(aggs) - 1, desc.ftype, f"agg#{len(aggs) - 1}")
                if name in ("timestampdiff", "timestampadd") and len(n.args) == 3:
                    # args[0] is the unit keyword, not a column
                    return ast.FuncCall(n.name, [n.args[0], walk(n.args[1]), walk(n.args[2])])
                if name == "any_value" and len(n.args) == 1:
                    return walk(n.args[0])
                return ast.FuncCall(n.name, [walk(a) for a in n.args], n.distinct, n.star)
            if isinstance(n, ast.BinaryOp):
                return ast.BinaryOp(n.op, walk(n.left), walk(n.right))
            if isinstance(n, ast.UnaryOp):
                return ast.UnaryOp(n.op, walk(n.operand))
            if isinstance(n, ast.ColumnName):
                # group key column? (matched above); SELECT alias (HAVING/
                # ORDER BY)? else implicit first_row (MySQL non-strict)
                if not n.table and aliases and n.name.lower() in aliases:
                    return aliases[n.name.lower()]
                arg = self.resolve(n, BuildCtx(base_schema))
                desc = AggDesc("first_row", arg)
                for i, existing in enumerate(aggs):
                    if repr(existing) == repr(desc):
                        return ColumnRef(i, existing.ftype, f"agg#{i}")
                aggs.append(desc)
                return ColumnRef(len(aggs) - 1, desc.ftype, f"agg#{len(aggs) - 1}")
            if isinstance(n, ast.SubqueryExpr):
                m = getattr(self, "_scalar_sub_map", None)
                if m and id(n) in m:
                    # pre-expanded correlated scalar: functionally dependent
                    # on its correlation keys — implicit first_row per group
                    desc = AggDesc("first_row", m[id(n)])
                    for i, existing in enumerate(aggs):
                        if repr(existing) == repr(desc):
                            return ColumnRef(i, existing.ftype, f"agg#{i}")
                    aggs.append(desc)
                    return ColumnRef(len(aggs) - 1, desc.ftype, f"agg#{len(aggs) - 1}")
                return n
            if isinstance(n, (ast.Literal, Expression)):
                return n
            if isinstance(n, ast.CaseWhen):
                return ast.CaseWhen(
                    walk(n.operand) if n.operand else None,
                    [(walk(c), walk(v)) for c, v in n.branches],
                    walk(n.else_value) if n.else_value else None,
                )
            if isinstance(n, ast.IsNull):
                return ast.IsNull(walk(n.operand), n.negated)
            if isinstance(n, ast.InList):
                return ast.InList(walk(n.operand), [walk(x) for x in n.items], n.negated)
            if isinstance(n, ast.Between):
                return ast.Between(walk(n.operand), walk(n.low), walk(n.high), n.negated)
            if isinstance(n, ast.Cast):
                return ast.Cast(walk(n.operand), n.target)
            return n

        rewritten = walk(node)
        # now resolve the rewritten tree against the agg output schema;
        # embedded Expression nodes pass through untouched
        agg_out = []
        for i, a in enumerate(aggs):
            agg_out.append(OutCol(f"agg#{i}", a.ftype))
        for gi, g in enumerate(group_exprs):
            agg_out.append(OutCol(f"gb#{gi}", g.ftype))
        # NOTE: group-key refs stay negative (deferred) — the caller patches
        # them once the agg list stops growing (after all items + HAVING)
        return self._resolve_mixed(rewritten, BuildCtx(agg_out, aliases=aliases))


    _TS_UNIT_US = {
        "microsecond": 1,
        "second": 1_000_000,
        "minute": 60_000_000,
        "hour": 3_600_000_000,
        "day": 86_400_000_000,
        "week": 7 * 86_400_000_000,
    }

    def _timestamp_func(self, name, node, ctx, rfn):
        """TIMESTAMPDIFF/TIMESTAMPADD(unit, ...) — shared by the plain and
        the aggregate resolution paths (``rfn`` resolves the non-unit args;
        the unit arrives as a bare identifier, never a column)."""
        u = node.args[0]
        unit = u.name.lower() if isinstance(u, ast.ColumnName) and not u.table else None
        if unit and unit.startswith("sql_tsi_"):
            unit = unit[8:]
        if unit is None or (unit not in self._TS_UNIT_US and unit not in ("month", "quarter", "year")):
            raise PlanError(f"unknown interval unit for {name.upper()}")

        def dt_coerce(e):
            if isinstance(e, Constant) and e.ftype.kind == TypeKind.STRING:
                v = e.value.decode() if isinstance(e.value, bytes) else str(e.value)
                kind = TypeKind.DATETIME if ":" in v else TypeKind.DATE
                return self._coerce_to(FieldType(kind), e)
            return e

        if name == "timestampadd":
            nexp = rfn(node.args[1], ctx)
            base = dt_coerce(rfn(node.args[2], ctx))
            return self._date_interval(base, nexp, unit, False)
        a = dt_coerce(rfn(node.args[1], ctx))
        b = dt_coerce(rfn(node.args[2], ctx))
        if unit in ("month", "quarter", "year"):
            months = func("tsdiff_months", a, b)
            if unit == "month":
                return months
            per = 3 if unit == "quarter" else 12
            return func("intdiv", months, Constant(per, bigint_type(nullable=False)))
        diff = func("tsdiff_micros", a, b)
        if self._TS_UNIT_US[unit] == 1:
            return diff
        return func("intdiv", diff, Constant(self._TS_UNIT_US[unit], bigint_type(nullable=False)))

    def _resolve_mixed(self, node, ctx: BuildCtx) -> Expression:
        if isinstance(node, Expression):
            return node
        if isinstance(node, ast.BinaryOp):
            return self._binary(node.op, self._resolve_mixed(node.left, ctx), self._resolve_mixed(node.right, ctx))
        if isinstance(node, ast.UnaryOp):
            op = "not" if node.op == "not" else node.op
            return func(op if op != "unaryplus" else "plus", self._resolve_mixed(node.operand, ctx))
        if isinstance(node, ast.FuncCall):
            name = _FN_ALIAS.get(node.name, node.name)
            if name in ("timestampdiff", "timestampadd") and len(node.args) == 3:
                return self._timestamp_func(name, node, ctx, self._resolve_mixed)
            if name == "any_value" and len(node.args) == 1:
                return self._resolve_mixed(node.args[0], ctx)
            args = [self._resolve_mixed(a, ctx) for a in node.args]
            return func(name, *args)
        if isinstance(node, ast.CaseWhen):
            args = []
            for c, v in node.branches:
                cc = self._resolve_mixed(c, ctx)
                if node.operand is not None:
                    cc = self._binary("eq", self._resolve_mixed(node.operand, ctx), cc)
                args.append(cc)
                args.append(self._resolve_mixed(v, ctx))
            if node.else_value is not None:
                args.append(self._resolve_mixed(node.else_value, ctx))
            return func("case_when", *args)
        if isinstance(node, ast.IsNull):
            e = func("isnull", self._resolve_mixed(node.operand, ctx))
            return func("not", e) if node.negated else e
        if isinstance(node, ast.InList):
            e = func("in", self._resolve_mixed(node.operand, ctx), *[self._resolve_mixed(x, ctx) for x in node.items])
            return func("not", e) if node.negated else e
        if isinstance(node, ast.Between):
            operand = self._resolve_mixed(node.operand, ctx)
            e = func(
                "and",
                self._binary("ge", operand, self._resolve_mixed(node.low, ctx)),
                self._binary("le", operand, self._resolve_mixed(node.high, ctx)),
            )
            return func("not", e) if node.negated else e
        if isinstance(node, ast.Cast):
            return _cast_expr(self._resolve_mixed(node.operand, ctx), node.target)
        return _fold(self._resolve(node, ctx))

    def _order_needs_hidden(self, node, proj_schema, aliases) -> bool:
        if isinstance(node, ast.Literal):
            return False
        if isinstance(node, ast.ColumnName):
            name = node.name.lower()
            if not node.table and aliases and name in aliases:
                return False
            for oc in proj_schema:
                if oc.name.lower() == name and (not node.table or oc.table.lower() == node.table.lower()):
                    return False
            return True
        return True  # complex order expr → compute as hidden column

    def _resolve_order(self, node, schema, aliases) -> Expression:
        if isinstance(node, ast.Literal) and isinstance(node.value, int):
            idx = node.value - 1  # ORDER BY ordinal
            if not (0 <= idx < len(schema)):
                raise PlanError(f"ORDER BY position {node.value} out of range")
            return ColumnRef(idx, schema[idx].ftype, schema[idx].name)
        return self.resolve(node, BuildCtx(schema, aliases=aliases))

    def _split_conj(self, e: Expression) -> list[Expression]:
        if isinstance(e, ScalarFunc) and e.sig == "and":
            return self._split_conj(e.args[0]) + self._split_conj(e.args[1])
        return [e]

    def _run_subquery(self, sel: ast.Select, expect_cols: Optional[int] = None, limit: Optional[int] = None):
        if self.subquery_runner is None:
            raise PlanError("subqueries not supported in this context")
        self.uncacheable = True  # plan bakes in subquery results as of now
        rows = self.subquery_runner(sel)
        if expect_cols is not None and rows and len(rows[0]) != expect_cols:
            raise PlanError("Operand should contain 1 column(s)")
        if limit is not None:
            rows = rows[:limit]
        return rows


def _expand_rollup(agg: "LogicalAggregation") -> "LogicalSetOp":
    """GROUP BY a, b WITH ROLLUP → UNION ALL of the grouping-set branches
    (a, b), (a), () — each a plain aggregation whose projection NULL-extends
    the rolled-up keys and emits the GROUPING() flags.

    Ref: the reference's MPP Expand executor (cophandler/mpp_exec.go:422-466)
    replicates every input row once per grouping set before a single shared
    aggregation. Redesigned for the device path: row replication multiplies
    the HBM working set by the set count, while branch aggregations re-read
    the SAME cached device lanes (the fragment/device caches key on table
    state, not plan), so each extra set costs one more tiny reduction over
    resident data instead of a full copy."""
    import copy

    from tidb_tpu.planner.plans import LogicalProjection, LogicalSetOp
    from tidb_tpu.types.field_type import bigint_type

    A = len(agg.aggs)
    G = len(agg.group_by)
    flag_ft = bigint_type(nullable=False)
    out_schema = list(agg.schema) + [OutCol(f"grouping#{j}", flag_ft) for j in range(G)]
    # rolled-up key columns turn nullable in the union output
    for j in range(G):
        oc = out_schema[A + j]
        if not oc.ftype.nullable:
            import dataclasses

            out_schema[A + j] = dataclasses.replace(
                oc, ftype=dataclasses.replace(oc.ftype, nullable=True)
            )
    branches = []
    for k in range(G, -1, -1):
        aggs_b = copy.deepcopy(agg.aggs)
        if k == 0:
            # the () grand-total branch is a scalar aggregation, which always
            # yields one row — MySQL semantics want one row IFF the input is
            # non-empty, and want it even with no aggregate functions at all:
            # a hidden COUNT(*) provides both (filtered below, not projected)
            aggs_b.append(AggDesc("count", None))
        b: "LogicalPlan" = LogicalAggregation(
            group_by=[copy.deepcopy(g) for g in agg.group_by[:k]],
            aggs=aggs_b,
            children=[copy.deepcopy(agg.children[0])],
        )
        b.schema = [OutCol(f"agg#{i}", a.ftype) for i, a in enumerate(aggs_b)] + [
            agg.schema[A + j] for j in range(k)
        ]
        if k == 0:
            from tidb_tpu.expression.expr import func as _func
            from tidb_tpu.planner.plans import LogicalSelection

            b = LogicalSelection(
                conditions=[
                    _func("gt", ColumnRef(A, bigint_type(nullable=False)), Constant(0, bigint_type(nullable=False)))
                ],
                children=[b],
            )
        exprs: list[Expression] = [
            ColumnRef(i, agg.schema[i].ftype, agg.schema[i].name) for i in range(A)
        ]
        for j in range(G):
            oc = out_schema[A + j]
            if j < k:
                exprs.append(ColumnRef(A + j, oc.ftype, oc.name))
            else:
                exprs.append(Constant(None, oc.ftype))
        for j in range(G):
            exprs.append(Constant(0 if j < k else 1, flag_ft))
        branches.append(LogicalProjection(exprs=exprs, schema=list(out_schema), children=[b]))
    # the set-op executor is binary: fold into a left-deep UNION ALL chain
    plan = branches[0]
    for nxt in branches[1:]:
        plan = LogicalSetOp(op="union", all=True, schema=out_schema, children=[plan, nxt])
    return plan


def _patch_group_refs(e: Expression, n_aggs: int, n_groups: int = 0) -> Expression:
    """Rewrite deferred group-key refs (negative indices) now that the agg
    lane count is final: ColumnRef(-1-gi) → ColumnRef(n_aggs+gi); deferred
    GROUPING flags ColumnRef(-20001-gi) → ColumnRef(n_aggs+n_groups+gi)
    (the rollup rewrite appends one flag column per group key)."""
    if isinstance(e, ColumnRef) and e.index <= -20001:
        gi = -20001 - e.index
        return ColumnRef(n_aggs + n_groups + gi, e.ftype, e.name)
    if isinstance(e, ColumnRef) and e.index < 0:
        gi = -1 - e.index
        return ColumnRef(n_aggs + gi, e.ftype, e.name)
    if isinstance(e, ScalarFunc):
        return ScalarFunc(e.sig, [_patch_group_refs(a, n_aggs, n_groups) for a in e.args], e.ftype)
    return e


# -- helpers ----------------------------------------------------------------


def _literal(node: ast.Literal) -> Constant:
    c = _literal_const(node)
    if node.param_idx >= 0:
        # keep EXECUTE-parameter provenance: the value-agnostic prepared-plan
        # cache mutates these Constants in place on later executions
        c.param_idx = node.param_idx
    return c


def _literal_const(node: ast.Literal) -> Constant:
    v = node.value
    if node.hint == "date":
        return Constant(date_to_days(v), FieldType(TypeKind.DATE, nullable=False))
    if node.hint in ("timestamp", "time"):
        return Constant(datetime_to_micros(v), FieldType(TypeKind.DATETIME, nullable=False))
    if node.hint == "decimal":
        d = Decimal(v)
        exp = d.as_tuple().exponent
        scale = -exp if exp < 0 else 0
        return Constant(d, decimal_type(max(len(d.as_tuple().digits), scale + 1), scale, nullable=False))
    if v is None:
        return Constant(None, FieldType(TypeKind.NULLTYPE))
    if isinstance(v, bool):
        return Constant(int(v), bool_type().not_null())
    if isinstance(v, int):
        return Constant(v, bigint_type(nullable=False))
    if isinstance(v, float):
        return Constant(v, double_type(nullable=False))
    import datetime

    if isinstance(v, datetime.timedelta):
        from tidb_tpu.types.datum import duration_to_micros

        return Constant(duration_to_micros(v), FieldType(TypeKind.DURATION, nullable=False))
    if isinstance(v, datetime.datetime):
        return Constant(datetime_to_micros(v), FieldType(TypeKind.DATETIME, nullable=False))
    if isinstance(v, datetime.date):
        return Constant(date_to_days(v), FieldType(TypeKind.DATE, nullable=False))
    return Constant(v, string_type(nullable=False))


def _const_like(v) -> Constant:
    if v is None:
        return Constant(None, FieldType(TypeKind.NULLTYPE))
    if isinstance(v, bool):
        return Constant(int(v), bool_type().not_null())
    if isinstance(v, int):
        return Constant(v, bigint_type(nullable=False))
    if isinstance(v, float):
        return Constant(v, double_type(nullable=False))
    if isinstance(v, Decimal):
        exp = -v.as_tuple().exponent
        return Constant(v, decimal_type(38, max(exp, 0), nullable=False))
    import datetime

    if isinstance(v, datetime.datetime):
        return Constant(datetime_to_micros(v), FieldType(TypeKind.DATETIME, nullable=False))
    if isinstance(v, datetime.date):
        return Constant(date_to_days(v), FieldType(TypeKind.DATE, nullable=False))
    if isinstance(v, datetime.timedelta):
        from tidb_tpu.types.datum import duration_to_micros

        return Constant(duration_to_micros(v), FieldType(TypeKind.DURATION, nullable=False))
    return Constant(v, string_type(nullable=False))


def _contains_group_expr(node, group_asts) -> bool:
    """Does the expression contain a subtree matching a GROUP BY item?
    (bare column names excluded — the projection path already handles them)"""
    if not group_asts:
        return False
    if not isinstance(node, ast.ColumnName) and any(_ast_eq(node, g) for g in group_asts):
        return True
    if isinstance(node, ast.FuncCall):
        return any(_contains_group_expr(a, group_asts) for a in node.args)
    for attr in ("left", "right", "operand", "low", "high", "else_value"):
        v = getattr(node, attr, None)
        if v is not None and isinstance(v, ast.Node) and _contains_group_expr(v, group_asts):
            return True
    if isinstance(node, ast.CaseWhen):
        return any(
            _contains_group_expr(c, group_asts) or _contains_group_expr(v, group_asts)
            for c, v in node.branches
        )
    return False


def _contains_agg(node) -> bool:
    if isinstance(node, ast.FuncCall):
        name = _FN_ALIAS.get(node.name, node.name)
        # GROUPING() resolves against the agg output like an aggregate
        if node.over is None and (name in AGG_FUNCS or node.star or name == "grouping"):
            return True
        return any(_contains_agg(a) for a in node.args)
    for attr in ("left", "right", "operand", "low", "high", "pattern", "else_value"):
        v = getattr(node, attr, None)
        if v is not None and isinstance(v, ast.Node) and _contains_agg(v):
            return True
    if isinstance(node, ast.CaseWhen):
        return any(_contains_agg(c) or _contains_agg(v) for c, v in node.branches)
    if isinstance(node, ast.InList):
        return any(_contains_agg(x) for x in node.items)
    return False


def _agg_names(node, out: set) -> None:
    """Collect the (alias-normalized) aggregate function names under
    ``node`` — the decorrelation guard needs to know WHICH aggregates an
    ungrouped subquery computes, not just that one exists."""
    if isinstance(node, ast.FuncCall):
        name = _FN_ALIAS.get(node.name, node.name)
        if node.over is None and (name in AGG_FUNCS or node.star):
            out.add("count" if node.star else name)
        for a in node.args:
            _agg_names(a, out)
        return
    for attr in ("left", "right", "operand", "low", "high", "pattern", "else_value"):
        v = getattr(node, attr, None)
        if v is not None and isinstance(v, ast.Node):
            _agg_names(v, out)
    if isinstance(node, ast.CaseWhen):
        for c, v in node.branches:
            _agg_names(c, out)
            _agg_names(v, out)
    if isinstance(node, ast.InList):
        for x in node.items:
            _agg_names(x, out)


def _unknown_col_in_schema(err_msg: str, schema) -> bool:
    """Does the column named in an 'Unknown column' PlanError exist in
    ``schema``? (used to distinguish correlation from typos)"""
    name = err_msg.split("'")[1] if "'" in err_msg else ""
    col = name.split(".")[-1].lower()
    tbl = name.split(".")[0].lower() if "." in name else ""
    return any(
        oc.name.lower() == col and (not tbl or oc.table.lower() == tbl) for oc in schema
    )


def _quantified_to_exists(q: "ast.QuantifiedCmp") -> ast.Node:
    """WHERE-context lowering of `left OP ANY|ALL (S)` (ref:
    expression_rewriter.go):

    - OP ANY (S)  ⇔  EXISTS (SELECT 1 FROM (S) q WHERE left OP q.v)
    - OP ALL (S)  ⇔  NOT EXISTS (SELECT 1 FROM (S) q WHERE
                       NOT(left OP q.v) OR (left OP q.v) IS NULL)

    Exact in WHERE context: ANY is TRUE iff some comparison is TRUE; ALL is
    not-TRUE iff some comparison is FALSE or NULL (vacuously TRUE on empty).
    Value contexts need the NULL-distinguishing form instead (_resolve)."""
    import copy as _copy

    sel = _copy.deepcopy(q.select)
    sel.items[0].alias = "__qv"
    src = ast.SubquerySource(sel, alias="__qsub")
    cmp = ast.BinaryOp(q.op, q.left, ast.ColumnName("__qv", table="__qsub"))
    if q.is_all:
        cond: ast.Node = ast.BinaryOp("or", ast.UnaryOp("not", cmp), ast.IsNull(cmp))
        inner = ast.Select([ast.SelectItem(ast.Literal(1))], from_=src, where=cond)
        return ast.UnaryOp("not", ast.SubqueryExpr(inner, "exists"))
    inner = ast.Select([ast.SelectItem(ast.Literal(1))], from_=src, where=cmp)
    return ast.SubqueryExpr(inner, "exists")


def _scalar_subquery_nodes(node) -> list:
    """All bare scalar SubqueryExpr nodes (modifier '') in an expression,
    excluding those nested inside deeper selects (their own build handles
    them)."""
    out = []
    if isinstance(node, ast.SubqueryExpr):
        if node.modifier == "":
            out.append(node)
        return out  # don't descend into the subquery body
    if isinstance(node, ast.Select):
        return out
    if isinstance(node, (list, tuple)):
        for x in node:
            out.extend(_scalar_subquery_nodes(x))
        return out
    if hasattr(node, "__dataclass_fields__"):
        for f in node.__dataclass_fields__:
            out.extend(_scalar_subquery_nodes(getattr(node, f)))
    return out


def _column_nodes(node) -> list:
    """All ast.ColumnName nodes inside an expression tree (dataclass walk)."""
    out = []
    if isinstance(node, ast.ColumnName):
        out.append(node)
        return out
    if isinstance(node, (list, tuple)):
        for x in node:
            out.extend(_column_nodes(x))
        return out
    if hasattr(node, "__dataclass_fields__"):
        for f in node.__dataclass_fields__:
            out.extend(_column_nodes(getattr(node, f)))
    return out


def _resolves(probe: "Builder", node, schema) -> bool:
    try:
        probe.resolve(node, BuildCtx(schema))
        return True
    except PlanError:
        return False


def _split_ast_conj(node: ast.Node) -> list:
    if isinstance(node, ast.BinaryOp) and node.op == "and":
        return _split_ast_conj(node.left) + _split_ast_conj(node.right)
    return [node]


def _memtable_hints(where) -> list:
    """Extract ``(column_lower, op, literal)`` triples from the simple
    col-vs-literal conjuncts of a WHERE — the memtable pushdown hints.
    Strictly advisory: the full WHERE still evaluates as a LogicalSelection
    above the source, so dropping a conjunct here never changes results —
    only how many rows a cluster sweep ships."""
    if where is None:
        return []
    flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq", "ne": "ne"}
    out = []
    for cj in _split_ast_conj(where):
        if not isinstance(cj, ast.BinaryOp) or cj.op not in flip:
            continue
        le, ri = cj.left, cj.right
        if isinstance(le, ast.ColumnName) and isinstance(ri, ast.Literal):
            out.append((le.name.lower(), cj.op, ri.value))
        elif isinstance(ri, ast.ColumnName) and isinstance(le, ast.Literal):
            out.append((ri.name.lower(), flip[cj.op], le.value))
    return out


def _and_join_ast(conds: list):
    if not conds:
        return None
    e = conds[0]
    for c in conds[1:]:
        e = ast.BinaryOp("and", e, c)
    return e


def _collect_windows(node, out: list) -> None:
    """Collect FuncCall nodes with an OVER clause, outermost first."""
    if not isinstance(node, ast.Node):
        return
    if isinstance(node, ast.FuncCall):
        if node.over is not None:
            out.append(node)
        for a in node.args:
            _collect_windows(a, out)
        return
    for attr in ("left", "right", "operand", "low", "high", "pattern", "else_value", "expr"):
        v = getattr(node, attr, None)
        if isinstance(v, ast.Node):
            _collect_windows(v, out)
    if isinstance(node, ast.CaseWhen):
        for c, v in node.branches:
            _collect_windows(c, out)
            _collect_windows(v, out)
    if isinstance(node, ast.InList):
        for x in node.items:
            _collect_windows(x, out)


# window functions beyond the aggregate set (ref: ast.WindowFuncs)
PURE_WINDOW_FUNCS = {
    "row_number",
    "rank",
    "dense_rank",
    "percent_rank",
    "cume_dist",
    "ntile",
    "lead",
    "lag",
    "first_value",
    "last_value",
}


def _window_ftype(name: str, args: list, win_order: list) -> FieldType:
    if name in ("row_number", "rank", "dense_rank", "ntile"):
        return bigint_type(nullable=False)
    if name in ("percent_rank", "cume_dist"):
        return replace(double_type(), nullable=False)
    if name in ("lead", "lag", "first_value", "last_value"):
        if not args:
            raise PlanError(f"{name}() needs an argument")
        return replace(args[0].ftype, nullable=True)
    if name == "count":
        return bigint_type(nullable=False)
    if name in ("sum", "avg", "min", "max"):
        return AggDesc(name, args[0]).ftype
    raise PlanError(f"unsupported window function {name}()")


def _ast_eq(a, b) -> bool:
    return type(a) is type(b) and a == b


def _display_name(node) -> str:
    if isinstance(node, ast.ColumnName):
        return node.name
    if isinstance(node, ast.FuncCall):
        inner = "*" if node.star else ", ".join(_display_name(a) for a in node.args)
        return f"{node.name}({inner})"
    if isinstance(node, ast.Literal):
        return str(node.value)
    if isinstance(node, ast.BinaryOp):
        return f"{_display_name(node.left)} {node.op} {_display_name(node.right)}"
    return type(node).__name__.lower()


def _source_outcol(e: Expression, schema) -> Optional[OutCol]:
    if isinstance(e, ColumnRef) and e.index < len(schema):
        return schema[e.index]
    return None


def _as_equi_pair(cond: Expression, nleft: int):
    if isinstance(cond, ScalarFunc) and cond.sig == "eq":
        a, b = cond.args
        if isinstance(a, ColumnRef) and isinstance(b, ColumnRef):
            if a.index < nleft <= b.index:
                return (a.index, b.index - nleft)
            if b.index < nleft <= a.index:
                return (b.index, a.index - nleft)
    return None


def _fold(e: Expression, warn=None) -> Expression:
    """Constant folding: all-constant scalar funcs evaluate at build time.
    ``warn`` receives fold-time diagnostics (SELECT 1/0 → 1365) so constant
    expressions warn like row expressions do."""
    if isinstance(e, ScalarFunc):
        e = ScalarFunc(e.sig, [_fold(a, warn) for a in e.args], e.ftype)
        if e.sig != "like" and all(isinstance(a, Constant) for a in e.args):
            batch = EvalBatch([], [], 1, warn)
            try:
                col = eval_to_column(e, batch, np)
            except Exception:
                return e
            return Constant(col.logical_value(0), e.ftype)
    return e
