"""Common table expressions: WITH inlining + WITH RECURSIVE fixpoint.

Reference parity: pkg/planner/core/logical_plan_builder.go (buildWith /
buildCte / buildRecursiveCTE) and the CTEExec iterate-until-empty executor
(pkg/executor/cte.go). Redesigned for this planner:

- Non-recursive CTEs are *inlined* at each reference site as a derived table
  (the reference does this too under tidb_opt_force_inline_cte; our engine
  caches pushed fragments per table so repeated inline scans stay cheap).
- Recursive CTEs are materialized bottom-up before planning: the seed part
  runs once, then each recursive part re-runs with the CTE reference bound to
  the previous iteration's delta rows until no new rows appear (semi-naive
  evaluation, exactly CTEExec's computeRecursivePart loop). The final rowset
  lands in the plan as an in-memory values source.

Expansion is a pure AST→AST rewrite, so CTE references work anywhere a table
can appear (joins, subqueries, set operations, nested WITH with shadowing).
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Callable

from tidb_tpu.parser import ast
from tidb_tpu.planner.plans import PlanError

# hard stop for runaway recursion (MySQL cte_max_recursion_depth default)
MAX_RECURSION_DEPTH = 1000

# runner(select_ast) -> (rows, schema: list[OutCol])
Runner = Callable[[ast.Node], tuple]


def expand_ctes(stmt: ast.Node, runner: Runner) -> ast.Node:
    """Rewrite every WITH clause in ``stmt`` away. Idempotent."""
    _expand(stmt, runner)
    return stmt


def _expand(node: ast.Node, runner: Runner) -> None:
    if isinstance(node, (ast.Select, ast.SetOp)) and node.ctes:
        ctes, node.ctes = node.ctes, []
        names_seen = set()
        for cte in ctes:
            if cte.name.lower() in names_seen:
                raise PlanError(f"Duplicate query name '{cte.name}' in WITH clause")
            names_seen.add(cte.name.lower())
        bindings: list[tuple[str, tuple]] = []
        for cte in ctes:
            # earlier CTEs in the same WITH list are visible to later bodies
            for bname, b in bindings:
                _substitute(cte.query, bname, b)
            if cte.recursive and _references(cte.query, cte.name):
                binding = _materialize_recursive(cte, runner)
            else:
                if _references(cte.query, cte.name):
                    raise PlanError(
                        f"Table '{cte.name}' doesn't exist (self-reference requires WITH RECURSIVE)"
                    )
                binding = ("inline", cte.query, cte.columns)
            bindings.append((cte.name, binding))
        for bname, b in bindings:
            _substitute(node, bname, b)
    for child in _ast_children(node):
        _expand(child, runner)


# ---------------------------------------------------------------------------
# generic AST walking (all nodes are dataclasses)
# ---------------------------------------------------------------------------


def _ast_children(node: ast.Node):
    if not dataclasses.is_dataclass(node):
        return
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        if isinstance(v, ast.Node):
            yield v
        elif isinstance(v, list):
            for item in v:
                if isinstance(item, ast.Node):
                    yield item
                elif isinstance(item, tuple):
                    for x in item:
                        if isinstance(x, ast.Node):
                            yield x


def _map_node(node: ast.Node, fn) -> ast.Node:
    """Replace each child c with fn(c), in place; returns fn(node)'s result
    for the node itself is handled by callers."""
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        if isinstance(v, ast.Node):
            setattr(node, f.name, fn(v))
        elif isinstance(v, list):
            for i, item in enumerate(v):
                if isinstance(item, ast.Node):
                    v[i] = fn(item)
                elif isinstance(item, tuple):
                    v[i] = tuple(fn(x) if isinstance(x, ast.Node) else x for x in item)
    return node


def _shadows(node: ast.Node, name: str) -> bool:
    return isinstance(node, (ast.Select, ast.SetOp)) and any(
        c.name == name for c in node.ctes
    )


def _substitute(root: ast.Node, name: str, binding: tuple) -> None:
    """Replace every unqualified TableRef ``name`` in table-source position
    with the binding (inline derived table or materialized values). Nested
    query blocks that define their own CTE of the same name shadow it."""

    def visit(n: ast.Node) -> ast.Node:
        if isinstance(n, ast.TableRef) and not n.db and n.name.lower() == name:
            return _make_source(binding, n)
        if _shadows(n, name):
            return n
        return _map_node(n, visit)

    _map_node(root, visit)


def _make_source(binding: tuple, ref: ast.TableRef) -> ast.Node:
    alias = ref.alias or ref.name
    if binding[0] == "inline":
        _, body, cols = binding
        return ast.SubquerySource(copy.deepcopy(body), alias=alias, col_aliases=list(cols))
    _, rows, names, ftypes = binding
    return ast.ValuesSource(rows=rows, names=names, ftypes=ftypes, alias=alias)


def _reference_count(root: ast.Node, name: str) -> int:
    count = 0

    def visit(n: ast.Node) -> ast.Node:
        nonlocal count
        if isinstance(n, ast.TableRef) and not n.db and n.name.lower() == name:
            count += 1
            return n
        if _shadows(n, name):
            return n
        return _map_node(n, visit)

    _map_node(root, visit)
    return count


def _references(root: ast.Node, name: str) -> bool:
    return _reference_count(root, name) > 0


# ---------------------------------------------------------------------------
# recursive CTE: semi-naive fixpoint (ref: executor/cte.go computeSeedPart /
# computeRecursivePart)
# ---------------------------------------------------------------------------


def _flatten_union(node: ast.Node) -> tuple[list[ast.Node], bool]:
    """Flatten a top-level UNION chain into operands. Returns (operands,
    distinct) where distinct is True if any link is UNION DISTINCT."""
    if isinstance(node, ast.SetOp):
        if node.op != "union":
            raise PlanError(
                "recursive CTE body must be a UNION of a seed part and a recursive part"
            )
        if node.order_by or node.limit is not None:
            raise PlanError("ORDER BY/LIMIT over a recursive CTE body is not supported")
        lops, ldist = _flatten_union(node.left)
        rops, rdist = _flatten_union(node.right)
        return lops + rops, ldist or rdist or not node.all
    return [node], False


def _union_all(operands: list[ast.Node]) -> ast.Node:
    node = operands[0]
    for op in operands[1:]:
        node = ast.SetOp(node, op, "union", all=True)
    return node


def _materialize_recursive(cte: ast.CTEDef, runner: Runner) -> tuple:
    operands, distinct = _flatten_union(cte.query)
    seed_ops = [op for op in operands if not _references(op, cte.name)]
    rec_ops = [op for op in operands if _references(op, cte.name)]
    if not seed_ops:
        raise PlanError(f"recursive CTE '{cte.name}' needs a non-recursive seed part")
    for op in rec_ops:
        if not isinstance(op, ast.Select):
            raise PlanError("recursive part of a recursive CTE must be a plain SELECT")
        if op.group_by or op.distinct or op.order_by or op.limit is not None:
            raise PlanError(
                f"Recursive Common Table Expression '{cte.name}' can contain neither "
                "aggregation nor ORDER BY/LIMIT/DISTINCT in its recursive part"
            )
        if _reference_count(op, cte.name) > 1:
            # semi-naive delta substitution is wrong for self-joins; MySQL
            # rejects multiple references in the recursive member too
            raise PlanError(
                f"In recursive query block of Recursive Common Table Expression "
                f"'{cte.name}', the recursive table must be referenced only once"
            )

    rows, schema = runner(_union_all([copy.deepcopy(op) for op in seed_ops]))
    names = cte.columns or [oc.name for oc in schema]
    if len(names) != len(schema):
        raise PlanError(
            f"WITH column list of '{cte.name}' has {len(names)} names for {len(schema)} columns"
        )
    ftypes = [oc.ftype for oc in schema]

    seen: set = set()
    if distinct:
        deduped = []
        for r in rows:
            if r not in seen:
                seen.add(r)
                deduped.append(r)
        rows = deduped
    all_rows = list(rows)
    delta = rows
    iters = 0
    while delta and rec_ops:
        iters += 1
        if iters > MAX_RECURSION_DEPTH:
            raise PlanError(
                f"Recursive query aborted after {MAX_RECURSION_DEPTH} iterations "
                "(cte_max_recursion_depth)"
            )
        produced: list[tuple] = []
        for op in rec_ops:
            op2 = copy.deepcopy(op)
            _substitute(op2, cte.name, ("values", delta, names, ftypes))
            # the recursive operand may still be correlated/nested — one plain
            # query per iteration with the previous delta as a memsource
            r, rschema = runner(op2)
            if len(rschema) != len(names):
                raise PlanError(
                    f"The recursive part of CTE '{cte.name}' returns "
                    f"{len(rschema)} columns, expected {len(names)}"
                )
            produced.extend(r)
        if distinct:
            fresh = []
            for r in produced:
                if r not in seen:
                    seen.add(r)
                    fresh.append(r)
        else:
            fresh = produced
        all_rows.extend(fresh)
        delta = fresh
    return ("values", all_rows, names, ftypes)
