"""Logical and physical plan nodes (ref: pkg/planner/core logical/physical
operators, trimmed)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from tidb_tpu.catalog.schema import TableInfo
from tidb_tpu.expression.expr import AggDesc, Expression
from tidb_tpu.kv.kv import KeyRange, StoreType
from tidb_tpu.types import FieldType


class PlanError(Exception):
    pass


@dataclass
class OutCol:
    """One output column of a plan node."""

    name: str
    ftype: FieldType
    table: str = ""  # qualifier (alias) for resolution
    # storage slot when this is a direct table column (dictionary lookup)
    slot: int = -1


Schema = list  # list[OutCol]


class LogicalPlan:
    children: list["LogicalPlan"]
    schema: Schema

    def child(self) -> "LogicalPlan":
        return self.children[0]


@dataclass
class LogicalScan(LogicalPlan):
    db: str
    table: TableInfo
    alias: str
    schema: Schema = field(default_factory=list)
    children: list = field(default_factory=list)
    # filled by predicate pushdown / range derivation
    ranges: Optional[list[KeyRange]] = None
    # optimizer hints targeting this table (ref: USE_INDEX/IGNORE_INDEX/
    # USE_INDEX_MERGE)
    use_index: Optional[str] = None  # preferred index (tried first)
    # candidate restriction from USE/FORCE INDEX (None = every index);
    # an EMPTY set (USE INDEX ()) allows none — forced table scan
    allowed_indexes: Optional[frozenset] = None
    ignored_indexes: frozenset = frozenset()
    # FORCE INDEX: a table scan becomes the last resort, not a baseline
    force_index: bool = False
    use_index_merge: bool = False
    # explicit `t PARTITION (p0, ...)` selection: lowercased partition names
    # (ref: logical_plan_builder.go partition-name check + PartitionPruning)
    partition_select: Optional[list] = None


@dataclass
class LogicalDual(LogicalPlan):
    """SELECT with no FROM — one row, zero columns."""

    schema: Schema = field(default_factory=list)
    children: list = field(default_factory=list)


@dataclass
class LogicalMemSource(LogicalPlan):
    """In-memory rowset source: recursive-CTE fixpoints, information_schema
    memtables (ref: infoschema memtable retrievers + CTE storage)."""

    rows: list  # list[tuple] of logical Python values
    schema: Schema = field(default_factory=list)
    children: list = field(default_factory=list)


@dataclass
class LogicalSelection(LogicalPlan):
    conditions: list[Expression]
    children: list = field(default_factory=list)

    @property
    def schema(self):
        return self.children[0].schema


@dataclass
class LogicalProjection(LogicalPlan):
    exprs: list[Expression]
    schema: Schema = field(default_factory=list)
    children: list = field(default_factory=list)


@dataclass
class LogicalAggregation(LogicalPlan):
    group_by: list[Expression]
    aggs: list[AggDesc]
    schema: Schema = field(default_factory=list)  # [aggs..., group keys...]
    children: list = field(default_factory=list)
    # GROUP BY ... WITH ROLLUP: schema additionally carries one GROUPING()
    # flag column per key; the optimizer fuses the grouping-set expansion
    # into ONE device pass or falls back to a per-set union (ref: the
    # reference's Expand operator, cophandler/mpp_exec.go:422-466)
    rollup: bool = False


@dataclass
class LogicalSort(LogicalPlan):
    by: list[tuple[Expression, bool]]  # (expr, desc)
    children: list = field(default_factory=list)

    @property
    def schema(self):
        return self.children[0].schema


@dataclass
class LogicalLimit(LogicalPlan):
    limit: int
    offset: int = 0
    children: list = field(default_factory=list)

    @property
    def schema(self):
        return self.children[0].schema


@dataclass
class LogicalJoin(LogicalPlan):
    kind: str  # inner/left/right/cross/semi/anti
    # equi-join keys resolved to (left_idx, right_idx) pairs + other conds
    eq_conds: list[tuple[int, int]] = field(default_factory=list)
    other_conds: list[Expression] = field(default_factory=list)
    # NOT IN: a NULL on either side of the key poisons the anti-match
    null_aware: bool = False
    # join-algorithm hint: "" (cost-based) | hash | merge | index
    preferred: str = ""
    schema: Schema = field(default_factory=list)
    children: list = field(default_factory=list)


@dataclass
class WindowFuncDesc:
    """One window call (ref: aggregation.WindowFuncDesc)."""

    name: str
    args: list  # resolved Expressions
    ftype: FieldType

    def __repr__(self):
        return f"{self.name}({', '.join(map(repr, self.args))})"


@dataclass
class LogicalWindow(LogicalPlan):
    """Window functions over one OVER spec; appends one output column per
    func to the child schema (ref: LogicalWindow, rule_window builders)."""

    funcs: list[WindowFuncDesc]
    partition_by: list  # Expressions
    order_by: list  # (Expression, desc) pairs
    whole_partition: bool = False
    rows_frame: bool = False
    frame: object = None  # bounded ROWS frame tuple (see ast.WindowSpec)
    schema: Schema = field(default_factory=list)
    children: list = field(default_factory=list)


@dataclass
class LogicalSetOp(LogicalPlan):
    """UNION / INTERSECT / EXCEPT (ref: LogicalUnionAll + set-op builders in
    logical_plan_builder.go). Children already project to a unified schema."""

    op: str  # union | intersect | except
    all: bool = False
    schema: Schema = field(default_factory=list)
    children: list = field(default_factory=list)


@dataclass
class LogicalDistinct(LogicalPlan):
    children: list = field(default_factory=list)

    @property
    def schema(self):
        return self.children[0].schema


# ---------------------------------------------------------------------------
# physical plans
# ---------------------------------------------------------------------------


class PhysicalPlan:
    children: list["PhysicalPlan"]
    schema: Schema


@dataclass
class PhysTableReader(PhysicalPlan):
    """The pushed-down fragment: executed by an engine via the cop client
    (ref: PhysicalTableReader + ConstructDAGReq)."""

    db: str
    table: TableInfo
    store_type: StoreType
    # pushed operators, in DAG order after the implicit scan
    pushed_conditions: list[Expression] = field(default_factory=list)
    pushed_agg: Optional[LogicalAggregation] = None
    pushed_agg_mode: str = "partial"
    pushed_topn: Optional[tuple[list, int]] = None  # (order_by, limit+offset)
    pushed_limit: Optional[int] = None
    # window executed inside the coprocessor fragment (ref: tipb window
    # pushdown to TiFlash); appends one output column per func to the scan
    # schema, evaluated between Selection and any pushed Agg
    pushed_window: Optional[LogicalWindow] = None
    scan_slots: list[int] = field(default_factory=list)  # storage slots scanned
    ranges: Optional[list[KeyRange]] = None
    keep_order: bool = False
    schema: Schema = field(default_factory=list)
    children: list = field(default_factory=list)
    # partitioned tables: pruned partition views to scan (None = all;
    # ref: rule_partition_processor pruning + PartitionIDAndRanges)
    partitions: Optional[list] = None
    # re-derives ``ranges`` from the (possibly parameter-mutated) pushed
    # conditions — the value-agnostic prepared-plan cache calls
    # ``range_maker(range_conds)`` per EXECUTE (ref: RebuildPlan4CachedPlan
    # re-running ranger); None on plans whose ranges never came from
    # conditions. The maker is a PURE function of the condition tuple so a
    # cloned plan instance (copy-on-execute) rebuilds from its OWN cloned
    # conditions, never the template's.
    range_maker: Optional[object] = field(default=None, repr=False, compare=False)
    range_conds: Optional[tuple] = field(default=None, repr=False, compare=False)
    # partitioned tables: ``partition_pruner(partition_conds)`` re-prunes the
    # partition set per execution — a cached plan whose parameter moved to a
    # different partition must re-route, not serve the plan-time pruning
    partition_pruner: Optional[object] = field(default=None, repr=False, compare=False)
    partition_conds: Optional[tuple] = field(default=None, repr=False, compare=False)


@dataclass
class PhysIndexReader(PhysicalPlan):
    """Covering-index scan: every needed column lives in the index key (or is
    the handle), so no table lookup happens (ref: PhysicalIndexReader).
    Index scans are served by the host engine only — the TPU engine, like
    TiFlash, serves columnar table fragments (planbuilder engine isolation)."""

    db: str
    table: TableInfo
    index: object  # IndexInfo
    ranges: list[KeyRange] = field(default_factory=list)
    # outputs, in scan-schema order: storage slot per column (-1 == handle)
    output_slots: list[int] = field(default_factory=list)
    # residual filters; ColumnRefs index into the output schema
    pushed_conditions: list[Expression] = field(default_factory=list)
    # union-scan fallback (dirty txn): the original conditions over the same
    # schema, replayed host-side over a membuffer-merged table scan
    all_conditions: list[Expression] = field(default_factory=list)
    schema: Schema = field(default_factory=list)
    children: list = field(default_factory=list)
    # value-agnostic prepared-plan support: ``range_maker(range_conds)``
    # re-runs index-range detachment over the parameter-mutated conditions;
    # ``range_used_pos`` snapshots WHICH positions of ``range_conds`` the
    # ranges consumed at plan time — a rebuild that consumes a different set
    # means the cached residual split is no longer valid and the whole
    # statement must re-plan. Positional (not object-identity) so the check
    # survives copy-on-execute cloning.
    range_maker: Optional[object] = field(default=None, repr=False, compare=False)
    range_conds: Optional[tuple] = field(default=None, repr=False, compare=False)
    range_used_pos: Optional[frozenset] = field(default=None, repr=False, compare=False)


@dataclass
class PhysIndexLookUp(PhysicalPlan):
    """Two-phase read: index scan yields handles, table side fetches rows and
    applies residual filters (ref: PhysicalIndexLookUpReader / IndexLookUp
    double worker pipeline, executor/distsql.go:439)."""

    db: str
    table: TableInfo
    index: object  # IndexInfo
    ranges: list[KeyRange] = field(default_factory=list)
    scan_slots: list[int] = field(default_factory=list)  # table-side outputs
    # residual filters over the table-side scan schema
    residual_conditions: list[Expression] = field(default_factory=list)
    all_conditions: list[Expression] = field(default_factory=list)
    schema: Schema = field(default_factory=list)
    children: list = field(default_factory=list)
    # same contract as PhysIndexReader.range_maker / range_used_pos
    range_maker: Optional[object] = field(default=None, repr=False, compare=False)
    range_conds: Optional[tuple] = field(default=None, repr=False, compare=False)
    range_used_pos: Optional[frozenset] = field(default=None, repr=False, compare=False)


@dataclass
class PhysIndexMerge(PhysicalPlan):
    """Union (OR) or intersection (AND) of several index/PK access paths
    feeding ONE table lookup (ref: PhysicalIndexMergeReader /
    executor/index_merge_reader.go:88; path derivation
    planner/core/indexmerge_path.go). Each path contributes a handle set;
    handles are set-combined, the table side fetches the rows, and the FULL
    original condition list re-filters them (paths may over-approximate
    their disjunct)."""

    db: str
    table: TableInfo
    # per path: ("idx", IndexInfo, [KeyRange]) or ("table", [KeyRange])
    paths: list = field(default_factory=list)
    intersection: bool = False
    scan_slots: list[int] = field(default_factory=list)
    residual_conditions: list[Expression] = field(default_factory=list)
    all_conditions: list[Expression] = field(default_factory=list)
    schema: Schema = field(default_factory=list)
    children: list = field(default_factory=list)
    # value-agnostic prepared-plan support: ``path_makers[i](path_conds[i])``
    # re-derives path i's access ranges from its (parameter-mutated) disjunct
    # conjunction. Tightness is not load-bearing — the executor re-applies
    # the full condition list after the fetch — but a path whose SHAPE shifts
    # (table↔index, or a different winning index) forces a re-plan.
    path_makers: Optional[list] = field(default=None, repr=False, compare=False)
    path_conds: Optional[list] = field(default=None, repr=False, compare=False)


@dataclass
class PhysSelection(PhysicalPlan):
    conditions: list[Expression]
    children: list = field(default_factory=list)

    @property
    def schema(self):
        return self.children[0].schema


@dataclass
class PhysProjection(PhysicalPlan):
    exprs: list[Expression]
    schema: Schema = field(default_factory=list)
    children: list = field(default_factory=list)


@dataclass
class PhysFinalAgg(PhysicalPlan):
    """Merges partial-agg chunks from the reader (or performs the whole agg
    when nothing was pushed)."""

    group_by: list[Expression]
    aggs: list[AggDesc]
    partial_input: bool  # True: child emits partial state lanes
    schema: Schema = field(default_factory=list)
    children: list = field(default_factory=list)
    # rollup partials interleave grouping flags after the keys: the merge
    # groups by (keys, flags) and passes the flags through
    rollup: bool = False


@dataclass
class PhysSort(PhysicalPlan):
    by: list[tuple[Expression, bool]]
    children: list = field(default_factory=list)

    @property
    def schema(self):
        return self.children[0].schema


@dataclass
class PhysLimit(PhysicalPlan):
    limit: int
    offset: int = 0
    children: list = field(default_factory=list)

    @property
    def schema(self):
        return self.children[0].schema


@dataclass
class PhysHashJoin(PhysicalPlan):
    kind: str
    eq_conds: list[tuple[int, int]]
    other_conds: list[Expression]
    null_aware: bool = False
    schema: Schema = field(default_factory=list)
    children: list = field(default_factory=list)


@dataclass
class PhysMergeJoin(PhysicalPlan):
    """Sort-merge join over key-ordered inputs (ref: executor/join/
    merge_join.go; chosen when both sides stream in join-key order, e.g.
    handle-ordered PK scans — no build table, no hash memory)."""

    kind: str  # inner/left
    eq_conds: list[tuple[int, int]] = field(default_factory=list)
    other_conds: list = field(default_factory=list)
    schema: Schema = field(default_factory=list)
    children: list = field(default_factory=list)


@dataclass
class PhysIndexJoin(PhysicalPlan):
    """Index nested-loop join (ref: executor/join index-join variants,
    builder.go:216-320): probe-side rows drive point lookups into the inner
    table's index/PK, reading only matching inner rows."""

    kind: str  # inner/left
    eq_conds: list[tuple[int, int]] = field(default_factory=list)
    other_conds: list = field(default_factory=list)
    inner_index: object = None  # IndexInfo | None (None = PK/handle)
    schema: Schema = field(default_factory=list)
    children: list = field(default_factory=list)  # [outer, inner PhysTableReader template]


@dataclass
class PhysDistinct(PhysicalPlan):
    children: list = field(default_factory=list)

    @property
    def schema(self):
        return self.children[0].schema


@dataclass
class PhysWindow(PhysicalPlan):
    funcs: list[WindowFuncDesc]
    partition_by: list
    order_by: list
    whole_partition: bool = False
    rows_frame: bool = False
    frame: object = None  # bounded ROWS frame tuple (see ast.WindowSpec)
    schema: Schema = field(default_factory=list)
    children: list = field(default_factory=list)


@dataclass
class PhysSetOp(PhysicalPlan):
    op: str
    all: bool = False
    schema: Schema = field(default_factory=list)
    children: list = field(default_factory=list)


@dataclass
class PhysDual(PhysicalPlan):
    schema: Schema = field(default_factory=list)
    children: list = field(default_factory=list)


@dataclass
class PhysMemSource(PhysicalPlan):
    rows: list
    schema: Schema = field(default_factory=list)
    children: list = field(default_factory=list)


@dataclass
class PhysPointGet(PhysicalPlan):
    """Fast path: PK point lookup bypassing the coprocessor entirely
    (ref: core/point_get_plan.go:957 TryFastPlan)."""

    db: str
    table: TableInfo
    handle: int
    schema: Schema = field(default_factory=list)
    children: list = field(default_factory=list)


def explain_plan(p, indent: int = 0, stats=None) -> str:
    """EXPLAIN output (ref: the reference's indented explain format). With
    ``stats`` (a RuntimeStatsColl), appends per-node execution info the way
    EXPLAIN ANALYZE's `execution info` column does."""
    pad = "  " * indent

    def _info(node) -> str:
        if stats is None:
            return ""
        r = stats.render(node)
        return f"  | {r}" if r else ""

    name = type(p).__name__
    extra = ""
    if isinstance(p, PhysTableReader):
        ops = ["Scan"]
        if p.pushed_conditions:
            ops.append(f"Selection({', '.join(map(repr, p.pushed_conditions))})")
        if p.pushed_window is not None:
            w = p.pushed_window
            over = f"partition by {w.partition_by}" if w.partition_by else "()"
            ops.append(f"Window({', '.join(map(repr, w.funcs))} over {over})")
        if p.pushed_agg is not None:
            roll = " ROLLUP" if getattr(p.pushed_agg, "rollup", False) else ""
            ops.append(f"{'Partial' if p.pushed_agg_mode == 'partial' else ''}Agg({', '.join(map(repr, p.pushed_agg.aggs))}){roll}")
        if p.pushed_topn is not None:
            ops.append(f"TopN({p.pushed_topn[1]})")
        if p.pushed_limit is not None:
            ops.append(f"Limit({p.pushed_limit})")
        extra = f"[{p.store_type.value}] {p.table.name}: " + " -> ".join(ops)
    elif isinstance(p, PhysFinalAgg):
        extra = ", ".join(map(repr, p.aggs)) + (" (merge partial)" if p.partial_input else "")
    elif isinstance(p, PhysSelection):
        extra = ", ".join(map(repr, p.conditions))
    elif isinstance(p, PhysProjection):
        extra = ", ".join(map(repr, p.exprs))
    elif isinstance(p, PhysSort):
        extra = ", ".join(f"{e!r}{' desc' if d else ''}" for e, d in p.by)
    elif isinstance(p, PhysLimit):
        extra = f"limit={p.limit} offset={p.offset}"
    elif isinstance(p, PhysHashJoin):
        extra = f"{p.kind} on {p.eq_conds}"
    elif isinstance(p, PhysMergeJoin):
        extra = f"{p.kind} on {p.eq_conds} (sorted inputs)"
    elif isinstance(p, PhysIndexJoin):
        idx = p.inner_index.name if p.inner_index is not None else "PRIMARY"
        extra = f"{p.kind} on {p.eq_conds} (inner index {idx})"
    elif isinstance(p, PhysSetOp):
        extra = f"{p.op}{' all' if p.all else ''}"
    elif isinstance(p, PhysWindow):
        over = f"partition by {p.partition_by}" if p.partition_by else "()"
        extra = f"{', '.join(map(repr, p.funcs))} over {over}"
    elif isinstance(p, PhysPointGet):
        extra = f"{p.table.name} handle={p.handle}"
    elif isinstance(p, PhysMemSource):
        extra = f"{len(p.rows)} rows"
    elif isinstance(p, PhysIndexReader):
        conds = f" -> Selection({', '.join(map(repr, p.pushed_conditions))})" if p.pushed_conditions else ""
        extra = f"[host] {p.table.name}: IndexScan({p.index.name}, {len(p.ranges)} ranges){conds}"
    elif isinstance(p, PhysIndexLookUp):
        conds = f" -> Selection({', '.join(map(repr, p.residual_conditions))})" if p.residual_conditions else ""
        extra = f"[host] {p.table.name}: IndexScan({p.index.name}, {len(p.ranges)} ranges) -> TableRowIDScan{conds}"
    elif isinstance(p, PhysIndexMerge):
        parts = []
        for path in p.paths:
            if path[0] == "idx":
                parts.append(f"{path[1].name}({len(path[2])} ranges)")
            else:
                parts.append(f"PRIMARY({len(path[1])} ranges)")
        kind = "intersection" if p.intersection else "union"
        conds = f" -> Selection({', '.join(map(repr, p.residual_conditions))})" if p.residual_conditions else ""
        extra = f"[host] {p.table.name}: IndexMerge({kind}: {', '.join(parts)}) -> TableRowIDScan{conds}"
    from tidb_tpu.parallel.gather import PhysMPPGather

    if isinstance(p, PhysMPPGather):
        if p.joins:
            ex = ",".join(j.exchange for j in p.joins)
            extra = f"{len(p.fragments)} fragments, {ex} join exchange"
        else:
            extra = f"{len(p.fragments)} fragments"
        lines = [f"{pad}{name} {extra}{_info(p)}"]
        for fr in p.fragments:
            lines.append(f"{pad}  {fr}")
        for r in p.readers:
            lines.append(explain_plan(r, indent + 1, stats))
        return "\n".join(lines)
    lines = [f"{pad}{name} {extra}".rstrip() + _info(p)]
    for c in getattr(p, "children", []):
        lines.append(explain_plan(c, indent + 1, stats))
    return "\n".join(lines)
