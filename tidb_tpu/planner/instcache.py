"""Instance-level (cross-session) plan cache.

Reference parity: the ``tidb_enable_instance_plan_cache`` plan cache
(pkg/planner/core/plan_cache_instance.go) — one LRU shared by every session
of the SQL instance, so short-lived connections reuse the warm parse/plan
state a long-lived session would have accumulated. Here "instance" is the
:class:`~tidb_tpu.session.DB` handle (one embedded SQL node); the DB owns
two of these — statement-text → AST entries and prepared-plan templates.

Concurrency: the LRU is lock-striped — each key hashes to one of N
independent (lock, OrderedDict) stripes, so concurrent sessions contend
only when their statements land on the same stripe, not on one global
mutex. Entries carry their own validity epochs in the KEY (schema/stats/
binding versions, session-shaped knobs), so an invalidated entry is simply
never looked up again and ages out of its stripe's LRU tail.

Values must be safe to SHARE across sessions: ASTs are reused read-only
(planning never mutates its input), and plan templates are immutable — each
execution clones the mutable leaves (``prepcache.instantiate``) before
rebinding parameters.
"""

from __future__ import annotations

import threading
from collections import OrderedDict


class InstancePlanCache:
    """Lock-striped LRU: capacity splits evenly across the stripes (each
    stripe evicts independently, so the total stays bounded by ``capacity``
    without a global lock on every touch)."""

    def __init__(self, capacity: int = 512, stripes: int = 8):
        stripes = max(int(stripes), 1)
        self._per_cap = max(int(capacity) // stripes, 1)
        self._stripes = [
            (threading.Lock(), OrderedDict()) for _ in range(stripes)
        ]

    def _stripe(self, key):
        return self._stripes[hash(key) % len(self._stripes)]

    def get(self, key):
        lock, od = self._stripe(key)
        with lock:
            v = od.get(key)
            if v is not None:
                od.move_to_end(key)
            return v

    def put(self, key, value) -> None:
        lock, od = self._stripe(key)
        with lock:
            od[key] = value
            od.move_to_end(key)
            while len(od) > self._per_cap:
                od.popitem(last=False)

    def pop(self, key):
        lock, od = self._stripe(key)
        with lock:
            return od.pop(key, None)

    def clear(self) -> None:
        for lock, od in self._stripes:
            with lock:
                od.clear()

    def __len__(self) -> int:
        n = 0
        for lock, od in self._stripes:
            with lock:
                n += len(od)
        return n

    def values(self) -> list:
        """Snapshot of every cached value (tests / diagnostics)."""
        out = []
        for lock, od in self._stripes:
            with lock:
                out.extend(od.values())
        return out
