"""Logical optimization + physical planning.

Reference parity: pkg/planner/core/optimizer.go — the rule list at :84 runs
column pruning, predicate pushdown, agg/topN/limit pushdown in that spirit;
physicalOptimize (:1125) is replaced by deterministic pushdown-greedy
construction (cost-based search is a later round once statistics exist).
The engine-isolation hook (planbuilder.go:1357 filterPathByIsolationRead)
lives in ``_pick_engine``: a fragment goes to the TPU engine iff the session
allows it and every pushed expression is device-legal.
"""

from __future__ import annotations

import copy
from typing import Optional

from tidb_tpu.expression.expr import AggDesc, ColumnRef, Constant, Expression, ScalarFunc, can_push_down
from tidb_tpu.kv import tablecodec
from tidb_tpu.kv.kv import KeyRange, StoreType
from tidb_tpu.planner import ranger
from tidb_tpu.planner.plans import (
    LogicalAggregation,
    LogicalDistinct,
    LogicalDual,
    LogicalJoin,
    LogicalLimit,
    LogicalMemSource,
    LogicalPlan,
    LogicalProjection,
    LogicalScan,
    LogicalSelection,
    LogicalSetOp,
    LogicalSort,
    LogicalWindow,
    OutCol,
    PhysDual,
    PhysDistinct,
    PhysFinalAgg,
    PhysHashJoin,
    PhysIndexJoin,
    PhysIndexLookUp,
    PhysIndexMerge,
    PhysIndexReader,
    PhysMergeJoin,
    PhysLimit,
    PhysMemSource,
    PhysPointGet,
    PhysProjection,
    PhysSelection,
    PhysSetOp,
    PhysSort,
    PhysWindow,
    PhysTableReader,
    PhysicalPlan,
    PlanError,
)
from tidb_tpu.types import TypeKind
from tidb_tpu.utils import sysvar_int


def optimize(plan: LogicalPlan, engines: list[str], stats=None, vars=None) -> PhysicalPlan:
    """engines: allowed read engines in preference order (session var
    tidb_isolation_read_engines analog). ``stats``: StatsHandle feeding the
    cost-based access-path choice (pseudo-stats heuristics when absent);
    ``vars``: session variables for planner toggles."""
    plan, _ = _prune(plan, None)
    plan = _push_selections(plan)
    fast = _try_point_get(plan)
    if fast is not None:
        return fast
    return _physical(plan, engines, stats, vars or {})


# ---------------------------------------------------------------------------
# column pruning (ref: rule_column_pruning.go)
# ---------------------------------------------------------------------------


def _remap_expr(e: Expression, mapping: dict[int, int]) -> Expression:
    if isinstance(e, ColumnRef):
        return ColumnRef(mapping[e.index], e.ftype, e.name)
    if isinstance(e, ScalarFunc):
        return ScalarFunc(e.sig, [_remap_expr(a, mapping) for a in e.args], e.ftype)
    return e


def _subst_refs(e: Expression, exprs: list[Expression]):
    """Rewrite ColumnRefs through a projection's exprs (None = not mappable)."""
    if isinstance(e, ColumnRef):
        return exprs[e.index] if e.index < len(exprs) else None
    if isinstance(e, ScalarFunc):
        args = [_subst_refs(a, exprs) for a in e.args]
        if any(a is None for a in args):
            return None
        return ScalarFunc(e.sig, args, e.ftype)
    return e


def _expr_cols(e: Expression, out: set[int]) -> None:
    if isinstance(e, ColumnRef):
        out.add(e.index)
    for c in e.children():
        _expr_cols(c, out)


def _prune(plan: LogicalPlan, needed: Optional[set[int]]):
    """Bottom-up pruning. Returns (plan, mapping old_idx→new_idx for the
    node's output schema)."""
    if isinstance(plan, LogicalScan):
        if needed is None:
            return plan, {i: i for i in range(len(plan.schema))}
        keep = sorted(needed)
        if not keep and plan.schema:
            # COUNT(*) / constant projections need no columns, but a
            # zero-column source loses the row count — keep one column
            # (ref: rule_column_pruning.go PruneColumns keeps one)
            keep = [0]
        mapping = {old: new for new, old in enumerate(keep)}
        plan.schema = [plan.schema[i] for i in keep]
        return plan, mapping
    if isinstance(plan, LogicalDual):
        return plan, {}
    if isinstance(plan, LogicalMemSource):
        if needed is None:
            return plan, {i: i for i in range(len(plan.schema))}
        keep = sorted(needed)
        if not keep and plan.schema:
            keep = [0]  # see LogicalScan: never prune to zero columns
        mapping = {old: new for new, old in enumerate(keep)}
        plan.schema = [plan.schema[i] for i in keep]
        plan.rows = [tuple(r[i] for i in keep) for r in plan.rows]
        return plan, mapping
    if isinstance(plan, LogicalProjection):
        if needed is None:
            keep = list(range(len(plan.exprs)))
        else:
            keep = sorted(needed)
            if not keep and plan.exprs:
                keep = [0]  # see LogicalScan: never prune to zero columns
        child_needed: set[int] = set()
        for i in keep:
            _expr_cols(plan.exprs[i], child_needed)
        child, cmap = _prune(plan.children[0], child_needed)
        plan.children = [child]
        plan.exprs = [_remap_expr(plan.exprs[i], cmap) for i in keep]
        plan.schema = [plan.schema[i] for i in keep]
        return plan, {old: new for new, old in enumerate(keep)}
    if isinstance(plan, LogicalSelection):
        child_needed = None if needed is None else set(needed)
        if child_needed is not None:
            for c in plan.conditions:
                _expr_cols(c, child_needed)
        child, cmap = _prune(plan.children[0], child_needed)
        plan.children = [child]
        plan.conditions = [_remap_expr(c, cmap) for c in plan.conditions]
        return plan, cmap
    if isinstance(plan, LogicalAggregation):
        child_needed: set[int] = set()
        for g in plan.group_by:
            _expr_cols(g, child_needed)
        for a in plan.aggs:
            if a.arg is not None:
                _expr_cols(a.arg, child_needed)
            for e, _ in a.order_by:
                _expr_cols(e, child_needed)
        child, cmap = _prune(plan.children[0], child_needed)
        plan.children = [child]
        plan.group_by = [_remap_expr(g, cmap) for g in plan.group_by]
        plan.aggs = [
            AggDesc(
                a.name,
                _remap_expr(a.arg, cmap) if a.arg is not None else None,
                a.distinct,
                a.sep,
                order_by=[(_remap_expr(e, cmap), d) for e, d in a.order_by],
            )
            for a in plan.aggs
        ]
        return plan, {i: i for i in range(len(plan.schema))}
    if isinstance(plan, (LogicalSort, LogicalLimit, LogicalDistinct)):
        child_needed = None if needed is None else set(needed)
        if isinstance(plan, LogicalSort) and child_needed is not None:
            for e, _ in plan.by:
                _expr_cols(e, child_needed)
        child, cmap = _prune(plan.children[0], child_needed)
        plan.children = [child]
        if isinstance(plan, LogicalSort):
            plan.by = [(_remap_expr(e, cmap), d) for e, d in plan.by]
        return plan, cmap
    if isinstance(plan, LogicalSetOp):
        # row identity spans every column — children keep their full schemas
        for i, c in enumerate(plan.children):
            plan.children[i], _ = _prune(c, set(range(len(c.schema))))
        return plan, {i: i for i in range(len(plan.schema))}
    if isinstance(plan, LogicalWindow):
        # appended columns index past the child schema — keep the child whole
        plan.children[0], _ = _prune(
            plan.children[0], set(range(len(plan.children[0].schema)))
        )
        return plan, {i: i for i in range(len(plan.schema))}
    if isinstance(plan, LogicalJoin) and plan.kind in ("semi", "anti"):
        # output schema is the LEFT side only; right contributes join keys
        # (and any columns the non-eq other_conds evaluate over)
        nleft = len(plan.children[0].schema)
        ln = set(needed) if needed is not None else set(range(nleft))
        rn: set[int] = set()
        for l, r in plan.eq_conds:
            ln.add(l)
            rn.add(r)
        for c in plan.other_conds:
            s: set[int] = set()
            _expr_cols(c, s)
            for i in s:
                (ln if i < nleft else rn).add(i if i < nleft else i - nleft)
        lchild, lmap = _prune(plan.children[0], ln)
        rchild, rmap = _prune(plan.children[1], rn)
        plan.children = [lchild, rchild]
        plan.eq_conds = [(lmap[l], rmap[r]) for l, r in plan.eq_conds]
        full_map = dict(lmap)
        for old, new in rmap.items():
            full_map[old + nleft] = new + len(lchild.schema)
        plan.other_conds = [_remap_expr(c, full_map) for c in plan.other_conds]
        plan.schema = [plan.schema[i] for i in sorted(lmap)]
        return plan, {old: new for new, old in enumerate(sorted(lmap))}
    if isinstance(plan, LogicalJoin):
        nleft = len(plan.children[0].schema)
        ln: set[int] = set()
        rn: set[int] = set()
        if needed is None:
            ln = set(range(nleft))
            rn = set(range(len(plan.children[1].schema)))
        else:
            for i in needed:
                (ln if i < nleft else rn).add(i if i < nleft else i - nleft)
        for l, r in plan.eq_conds:
            ln.add(l)
            rn.add(r)
        for c in plan.other_conds:
            s: set[int] = set()
            _expr_cols(c, s)
            for i in s:
                (ln if i < nleft else rn).add(i if i < nleft else i - nleft)
        lchild, lmap = _prune(plan.children[0], ln)
        rchild, rmap = _prune(plan.children[1], rn)
        plan.children = [lchild, rchild]
        new_nleft = len(lchild.schema)
        full_map = {}
        for old, new in lmap.items():
            full_map[old] = new
        for old, new in rmap.items():
            full_map[old + nleft] = new + new_nleft
        plan.eq_conds = [(lmap[l], rmap[r]) for l, r in plan.eq_conds]
        plan.other_conds = [_remap_expr(c, full_map) for c in plan.other_conds]
        plan.schema = [plan.schema[i] for i in sorted(full_map)]
        return plan, {old: new for new, old in enumerate(sorted(full_map))}
    raise PlanError(f"prune: unhandled node {type(plan).__name__}")


# ---------------------------------------------------------------------------
# predicate pushdown (ref: rule_predicate_push_down.go)
# ---------------------------------------------------------------------------


def _push_selections(plan: LogicalPlan) -> LogicalPlan:
    for i, c in enumerate(getattr(plan, "children", [])):
        plan.children[i] = _push_selections(c)
    if isinstance(plan, LogicalSelection) and isinstance(plan.children[0], LogicalJoin):
        join = plan.children[0]
        nleft = len(join.children[0].schema)
        if join.kind in ("semi", "anti", "left"):
            # left-side-only conditions commute with the join: semi/anti
            # joins only FILTER left rows, and a left join preserves every
            # left row while such conditions never read the NULL-extended
            # side. Pushing them below (and recursing) lets residual WHERE
            # equalities reach a cross join a subquery rewrite left
            # underneath — where they become equi-join keys — instead of
            # stranding above the semi/anti/left join as a host Selection.
            down: list[Expression] = []
            stay: list[Expression] = []
            for cond in plan.conditions:
                s: set[int] = set()
                _expr_cols(cond, s)
                (down if s and max(s) < nleft else stay).append(cond)
            if down:
                join.children[0] = _push_selections(
                    LogicalSelection(conditions=down, children=[join.children[0]])
                )
                if not stay:
                    return join
                plan.conditions = stay
            return plan
        keep: list[Expression] = []
        for cond in plan.conditions:
            s: set[int] = set()
            _expr_cols(cond, s)
            if join.kind in ("inner", "cross") and s and max(s) < nleft:
                join.children[0] = LogicalSelection(conditions=[cond], children=[join.children[0]])
            elif join.kind in ("inner", "cross") and s and min(s) >= nleft:
                remapped = _remap_expr(cond, {i: i - nleft for i in s})
                join.children[1] = LogicalSelection(conditions=[remapped], children=[join.children[1]])
            elif (
                join.kind in ("inner", "cross")
                and isinstance(cond, ScalarFunc)
                and cond.sig == "eq"
                and all(isinstance(a, ColumnRef) for a in cond.args)
                and len({a.index < nleft for a in cond.args}) == 2  # type: ignore[union-attr]
            ):
                # WHERE equality across a comma/cross join → join key
                # (ref: ppdSolver turning cartesian + filter into equi-join)
                l, r = cond.args
                if l.index >= nleft:  # type: ignore[union-attr]
                    l, r = r, l
                join.eq_conds.append((l.index, r.index - nleft))  # type: ignore[union-attr]
                join.kind = "inner"
            elif join.kind in ("inner", "cross") and s and len({i < nleft for i in s}) == 2:
                join.other_conds.append(cond)
                join.kind = "inner"
            else:
                keep.append(cond)
        # merge adjacent selections on the same side
        for side in (0, 1):
            ch = join.children[side]
            if isinstance(ch, LogicalSelection) and isinstance(ch.children[0], LogicalSelection):
                inner = ch.children[0]
                inner.conditions = ch.conditions + inner.conditions
                join.children[side] = inner
        if not keep:
            return join
        plan.conditions = keep
    return plan


# ---------------------------------------------------------------------------
# point-get fast path (ref: point_get_plan.go:957 TryFastPlan)
# ---------------------------------------------------------------------------


def _try_point_get(plan: LogicalPlan):
    proj = plan
    if not isinstance(proj, LogicalProjection):
        return None
    sel = proj.children[0]
    if not (isinstance(sel, LogicalSelection) and isinstance(sel.children[0], LogicalScan)):
        return None
    scan = sel.children[0]
    if not scan.table.pk_is_handle or len(sel.conditions) != 1 or scan.partition_select is not None:
        return None
    cond = sel.conditions[0]
    if not (isinstance(cond, ScalarFunc) and cond.sig == "eq"):
        return None
    a, b = cond.args
    colref, const = (a, b) if isinstance(a, ColumnRef) else (b, a)
    if not (isinstance(colref, ColumnRef) and isinstance(const, Constant)) or const.value is None:
        return None
    if scan.schema[colref.index].slot != scan.table.pk_offset:
        return None
    if not all(isinstance(e, ColumnRef) for e in proj.exprs):
        return None
    table = scan.table
    handle = int(const.value)
    if table.partition is not None:
        # route the handle to its partition's physical table (ref: point-get
        # partition pruning, planner/core/point_get_plan.go)
        p = table.partition
        if p.col_offset != table.pk_offset:
            return None
        if p.type == "hash":
            d = p.defs[handle % len(p.defs)]
        else:
            d = next(
                (d for d in p.defs if d.less_than is None or handle < d.less_than), None
            )
            if d is None:
                return None  # no partition holds this value → empty result
        table = table.partition_view(d.id)
    pg = PhysPointGet(db=scan.db, table=table, handle=handle, schema=proj.schema)
    pg.scan_slots = [scan.schema[e.index].slot for e in proj.exprs]  # type: ignore[attr-defined]
    return pg


# ---------------------------------------------------------------------------
# access-path selection (ref: planbuilder getPossibleAccessPaths +
# find_best_task; cost-based when ANALYZE stats exist, skyline heuristics
# otherwise)
# ---------------------------------------------------------------------------

# relative per-row cost factors (ref: plan_cost_ver2 coefficients, rescaled
# for a columnar device engine: sequential scans are cheap, random handle
# lookups are not)
_COST_TABLE_ROW = 1.0
_COST_IDX_ROW = 1.5
_COST_LOOKUP_ROW = 6.0
_COST_SETUP = 40.0


def _has_collation_override(e, schema) -> bool:
    """True when any column reference in the expression compares under a
    collation other than the column's declared one — the footprint of an
    explicit COLLATE override (builder._collate_expr rewrites the ref's
    ftype; optimization rules copy refs, so the ftype diff is the durable
    signal). Index ranges are ordered by the DECLARED collation, so such
    conditions must not drive index access."""
    if isinstance(e, ColumnRef) and e.ftype.kind == TypeKind.STRING:
        if 0 <= e.index < len(schema) and schema[e.index].ftype.kind == TypeKind.STRING:
            if e.ftype.collation != schema[e.index].ftype.collation:
                return True
    return any(_has_collation_override(c, schema) for c in e.children())


def _idx_eligible(scan, idx) -> bool:
    """Hint-aware candidate filter: public state, not IGNOREd, and inside
    the USE/FORCE restriction when one is present (an empty restriction —
    USE INDEX () — allows nothing, forcing the table scan)."""
    if idx.state != "public" or idx.name in scan.ignored_indexes:
        return False
    return scan.allowed_indexes is None or idx.name in scan.allowed_indexes


def _choose_index_path(scan: LogicalScan, conds: list[Expression], stats=None):
    """Access-path choice. With statistics: estimate rows per candidate index
    from histograms and compare costs against the columnar full scan (ref:
    find_best_task + cardinality.Selectivity). Without: an index wins only on
    point (eq/IN) leading-column conditions — the one reliably-cheaper case.
    PK handle ranges are handled by _derive_ranges on the table-reader path."""
    t = scan.table
    if scan.use_index is not None:
        # the forced pick still honors IGNORE/USE sets (IGNORE beats USE)
        idx = next((i for i in t.indexes if i.name == scan.use_index and _idx_eligible(scan, i)), None)
        if idx is not None:
            forced = _index_path_for(scan, idx, conds)
            if forced is not None:
                return forced
    if t.partition is not None:
        # partitioned tables read via pruned per-partition table scans;
        # local-index access paths are a later round (ref: TiDB dynamic
        # prune mode restricting plans similarly)
        return None
    tstats = stats.get(t.id) if stats is not None else None
    best = None
    if tstats is not None and tstats.row_count > 0:
        from tidb_tpu.statistics.selectivity import estimate_selectivity

        total = tstats.row_count
        # full columnar scan baseline: sequential, device-friendly —
        # unless FORCE INDEX demotes it to a last resort
        best_cost = float("inf") if scan.force_index else float(total) * _COST_TABLE_ROW
        for idx in t.indexes:
            if not _idx_eligible(scan, idx):
                continue  # in-flight online-DDL / hint-ignored indexes
            acc = ranger.detach_index_conditions(conds, scan.schema, t, idx)
            if acc is None or not acc.used:
                continue
            rows = total * estimate_selectivity(acc.used, scan.schema, tstats)
            covering = all(
                oc.slot in idx.column_offsets or (t.pk_is_handle and oc.slot == t.pk_offset)
                for oc in scan.schema
            )
            cost = _COST_SETUP + rows * (_COST_IDX_ROW if covering else _COST_LOOKUP_ROW)
            if cost < best_cost:
                best_cost = cost
                best = ((), acc)
    else:
        for idx in t.indexes:
            if not _idx_eligible(scan, idx):
                continue  # in-flight online-DDL / hint-ignored indexes
            acc = ranger.detach_index_conditions(conds, scan.schema, t, idx)
            if acc is None or not acc.used:
                continue
            if acc.eq_prefix_len == 0 and not scan.force_index:
                # range-only access wins no heuristic without stats — except
                # under FORCE INDEX, where the table scan is the last resort
                continue
            key = (acc.eq_prefix_len, idx.unique, acc.has_range)
            if best is None or key > best[0]:
                best = (key, acc)
    if best is None:
        return None
    # PK point conditions beat any secondary index (handled downstream)
    if t.pk_is_handle:
        hr = ranger.derive_handle_ranges(conds, scan.schema, t)
        if hr is not None and hr[1] == 1:
            return None
    return _build_index_access(scan, best[1], conds)


def _flatten_bool(e: Expression, sig: str, out: list) -> None:
    if isinstance(e, ScalarFunc) and e.sig == sig:
        for a in e.args:
            _flatten_bool(a, sig, out)
    else:
        out.append(e)


def _try_index_merge(scan: LogicalScan, conds: list[Expression], stats=None):
    """Union-type IndexMerge (ref: planner/core/indexmerge_path.go
    generateIndexMergeOrPaths): an OR condition whose every disjunct is
    independently index- (or PK-) accessible becomes a union of handle sets
    feeding one table lookup. Chosen when no single-index path exists (the
    classic a=? OR b=? shape defeats single-index pruning) or when forced by
    USE_INDEX_MERGE. Correctness does not depend on path tightness: the
    executor re-applies the full condition list after the fetch."""
    t = scan.table
    if t.partition is not None:
        return None
    or_cond = None
    for c in conds:
        if isinstance(c, ScalarFunc) and c.sig == "or":
            or_cond = c
            break
    if or_cond is None:
        return None
    disjuncts: list[Expression] = []
    _flatten_bool(or_cond, "or", disjuncts)
    if len(disjuncts) < 2:
        return None
    paths = []
    makers = []
    path_conds = []
    est_rows = 0.0
    tstats = stats.get(t.id) if stats is not None else None
    for d in disjuncts:
        conjs: list[Expression] = []
        _flatten_bool(d, "and", conjs)
        path, est = _merge_path_for(scan, conjs, tstats)
        if path is None:
            return None  # one unindexable disjunct sinks the whole merge
        est_rows += est
        paths.append(path)
        path_conds.append(tuple(conjs))
        # value-agnostic rebuild hook: pure function of the disjunct's
        # conjunction, so cloned plan instances re-derive from their OWN
        # cloned conditions (stats omitted — the shape is already chosen,
        # the rebuild only refreshes ranges)
        makers.append(lambda cs, scan=scan: _merge_path_for(scan, list(cs), None)[0])
    # cost gate (ref: the index-merge path pruning by row estimates): random
    # handle lookups must beat the columnar full scan
    if not scan.use_index_merge and tstats is not None and tstats.row_count > 0:
        if _COST_SETUP + est_rows * _COST_LOOKUP_ROW >= tstats.row_count * _COST_TABLE_ROW:
            return None
    return PhysIndexMerge(
        db=scan.db,
        table=t,
        paths=paths,
        scan_slots=[oc.slot for oc in scan.schema],
        residual_conditions=list(conds),
        all_conditions=list(conds),
        schema=scan.schema,
        path_makers=makers,
        path_conds=path_conds,
    )


def _merge_path_for(scan: LogicalScan, conjs: list[Expression], tstats):
    """One disjunct's index-merge access path: a bounded PK handle range
    (point/two-sided only — a one-sided bound is a near-full scan and would
    sink the union without stats) or the best single-index detachment.
    Returns ``(path, est_rows)``; ``(None, 0.0)`` when the disjunct is
    unindexable. Shared by plan-time derivation and the value-agnostic
    rebuild (which passes ``tstats=None`` — the estimate is only consulted
    by the plan-time cost gate)."""
    t = scan.table
    hr = _derive_ranges(scan, conjs)
    if hr is not None:
        spans = [tablecodec.range_to_handles(kr, t.id) for kr in hr]
        if all(-(2**62) < lo and hi < 2**62 for lo, hi in spans):
            est = 0.0
            if tstats is not None and tstats.row_count > 0:
                # PK paths cost lookups too: a wide handle range must
                # count against the merge, not ride for free
                est = min(float(sum(hi - lo for lo, hi in spans)), float(tstats.row_count))
            return ("table", hr), est
    best = None
    for idx in t.indexes:
        if not _idx_eligible(scan, idx):
            continue
        acc = ranger.detach_index_conditions(conjs, scan.schema, t, idx)
        if acc is None or not acc.used:
            continue
        key = (acc.eq_prefix_len, idx.unique, acc.has_range)
        if best is None or key > best[0]:
            best = (key, acc)
    if best is None:
        return None, 0.0
    est = 0.0
    if tstats is not None and tstats.row_count > 0:
        from tidb_tpu.statistics.selectivity import estimate_selectivity

        est = tstats.row_count * estimate_selectivity(best[1].used, scan.schema, tstats)
    return ("idx", best[1].index, best[1].ranges), est


def _index_path_for(scan: LogicalScan, idx, conds: list[Expression]):
    """USE_INDEX hint: force an access path over ``idx`` when any range can
    be derived from the conditions."""
    acc = ranger.detach_index_conditions(conds, scan.schema, scan.table, idx)
    if acc is None:
        return None
    return _build_index_access(scan, acc, conds)


def _build_index_access(scan: LogicalScan, acc, conds: list[Expression]):
    t = scan.table
    covering = all(
        oc.slot in acc.index.column_offsets or (t.pk_is_handle and oc.slot == t.pk_offset)
        for oc in scan.schema
    )
    # value-agnostic prepared plans re-run the detachment over the plan
    # instance's OWN condition objects (``range_conds``, cloned per
    # execution) after parameter mutation; range_used_pos lets the rebuild
    # verify the used/residual split did not shift under the new values
    # (shifted split → the cached plan must not be reused). Positional,
    # so the check survives copy-on-execute cloning.
    maker = lambda cs, scan=scan, t=t, idx=acc.index: (  # noqa: E731
        ranger.detach_index_conditions(list(cs), scan.schema, t, idx)
    )
    acc_used = {id(c) for c in acc.used}
    used_pos = frozenset(i for i, c in enumerate(conds) if id(c) in acc_used)
    if covering:
        output_slots = [
            -1 if (t.pk_is_handle and oc.slot == t.pk_offset) else oc.slot for oc in scan.schema
        ]
        return PhysIndexReader(
            db=scan.db,
            table=t,
            index=acc.index,
            ranges=acc.ranges,
            output_slots=output_slots,
            pushed_conditions=list(acc.residual),
            all_conditions=list(conds),
            schema=scan.schema,
            range_maker=maker,
            range_conds=tuple(conds),
            range_used_pos=used_pos,
        )
    return PhysIndexLookUp(
        db=scan.db,
        table=t,
        index=acc.index,
        ranges=acc.ranges,
        scan_slots=[oc.slot for oc in scan.schema],
        residual_conditions=list(acc.residual),
        all_conditions=list(conds),
        schema=scan.schema,
        range_maker=maker,
        range_conds=tuple(conds),
        range_used_pos=used_pos,
    )


# ---------------------------------------------------------------------------
# physical planning
# ---------------------------------------------------------------------------


def _ci_order_keys(exprs) -> bool:
    """Any general_ci string among ``exprs`` used as an ORDER key (TopN)?
    Device order semantics come from sorted-dictionary byte ranks, but ci
    orders by weight class ('a' ≡ 'A' < 'B'), so a device TopN could select
    the wrong candidate SET, not just a different tie order — found by
    graftfuzz; such keys stay host-side (the host sort paths rank by
    weight). MIN/MAX arguments no longer demote: the binder compacts ci
    dictionaries under the weight order itself (Dictionary.compact(ci=True)),
    making code reduction collation-correct."""
    return any(
        e is not None and e.ftype.kind == TypeKind.STRING and e.ftype.collation == "ci"
        for e in exprs
    )


def _demote_ci_order(st: StoreType, engines: list[str], exprs) -> Optional[StoreType]:
    """TPU → HOST when ``exprs`` are ci-order-sensitive; None when no engine
    can serve them (push must be skipped, the root executor handles it)."""
    if st != StoreType.TPU or not _ci_order_keys(exprs):
        return st
    return StoreType.HOST if "host" in engines else None


def _pick_engine(engines: list[str], exprs: list[Expression]) -> StoreType:
    for name in engines:
        if name == "tpu" and all(can_push_down(e, "tpu") for e in exprs):
            return StoreType.TPU
        if name == "host" and all(can_push_down(e, "host") for e in exprs):
            return StoreType.HOST
    # nothing fits wholly; host engine accepts the most
    return StoreType.HOST


def _derive_ranges(scan: LogicalScan, conds: list[Expression]) -> Optional[list[KeyRange]]:
    """Handle-range derivation for pk_is_handle predicates (util/ranger lite).
    Conservative: intersects simple top-level comparisons on the pk column."""
    t = scan.table
    if not t.pk_is_handle:
        return None
    pk_positions = [i for i, oc in enumerate(scan.schema) if oc.slot == t.pk_offset]
    if not pk_positions:
        return None
    pk_idx = pk_positions[0]
    lo, hi = -(2**63), 2**63 - 2  # hi inclusive
    found = False
    for c in conds:
        if not (isinstance(c, ScalarFunc) and c.sig in ("eq", "lt", "le", "gt", "ge")):
            continue
        a, b = c.args
        sig = c.sig
        if isinstance(b, ColumnRef) and isinstance(a, Constant):
            a, b = b, a
            sig = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}[sig]
        if not (isinstance(a, ColumnRef) and a.index == pk_idx and isinstance(b, Constant)):
            continue
        if b.value is None or a.ftype.kind not in (TypeKind.INT, TypeKind.UINT):
            continue
        v = int(b.value)
        found = True
        if sig == "eq":
            lo, hi = max(lo, v), min(hi, v)
        elif sig == "lt":
            hi = min(hi, v - 1)
        elif sig == "le":
            hi = min(hi, v)
        elif sig == "gt":
            lo = max(lo, v + 1)
        elif sig == "ge":
            lo = max(lo, v)
    if not found:
        return None
    if lo > hi:
        return []
    return [tablecodec.handle_range(t.id, lo, hi)]


def _physical(plan: LogicalPlan, engines: list[str], stats=None, vars=None) -> PhysicalPlan:
    vars = vars or {}
    if isinstance(plan, LogicalDual):
        return PhysDual(schema=plan.schema)
    if isinstance(plan, LogicalMemSource):
        return PhysMemSource(rows=plan.rows, schema=plan.schema)
    if isinstance(plan, LogicalScan):
        reader = PhysTableReader(
            db=plan.db,
            table=plan.table,
            store_type=_pick_engine(engines, []),
            scan_slots=[oc.slot for oc in plan.schema],
            ranges=plan.ranges,
            schema=plan.schema,
        )
        if plan.partition_select is not None:
            sel = set(plan.partition_select)
            reader.partitions = [
                plan.table.partition_view(d.id)
                for d in plan.table.partition.defs
                if d.name.lower() in sel
            ]
        return reader
    if isinstance(plan, LogicalSelection):
        if (
            isinstance(plan.children[0], LogicalScan)
            and plan.children[0].partition_select is None
            and not any(_has_collation_override(c, plan.children[0].schema) for c in plan.conditions)
        ):
            # an explicit COLLATE override changes comparison semantics away
            # from the index's stored order — index ranges derived from such
            # conditions would return wrong rows, so keep the full scan
            ipath = _choose_index_path(plan.children[0], plan.conditions, stats)
            if ipath is None and sysvar_int(vars, "tidb_enable_index_merge", 1):
                # OR shapes defeat single-index pruning; a union of index
                # paths can still serve them (ref: indexmerge_path.go)
                ipath = _try_index_merge(plan.children[0], plan.conditions, stats)
            if ipath is not None:
                return ipath
        child = _physical(plan.children[0], engines, stats, vars)
        if (
            isinstance(child, PhysTableReader)
            and child.pushed_agg is None
            and child.pushed_topn is None
            and child.pushed_limit is None
            and child.pushed_window is None
        ):
            st = _pick_engine(engines, plan.conditions)
            pushable = [c for c in plan.conditions if can_push_down(c, st.value)]
            host_side = [c for c in plan.conditions if not can_push_down(c, st.value)]
            child.store_type = st
            child.pushed_conditions.extend(pushable)
            if isinstance(plan.children[0], LogicalScan):
                scan0 = plan.children[0]
                r = _derive_ranges(scan0, pushable)
                if r is not None:
                    child.ranges = r
                # value-agnostic prepared plans re-derive handle ranges from
                # the plan instance's OWN conditions (cloned per execution)
                # after parameter mutation; table ranges only narrow the scan
                # (conditions still filter), so any rebuild outcome —
                # including None (full scan) — is safe
                child.range_maker = (
                    lambda cs, scan0=scan0: _derive_ranges(scan0, list(cs))
                )
                child.range_conds = tuple(pushable)
                if plan.children[0].table.partition is not None:
                    from tidb_tpu.planner.partition import prune_partitions

                    if scan0.partition_select is None:
                        # value-agnostic rebuild hook: re-prune per execution
                        # so a parameter moving to another partition re-routes
                        # (explicit PARTITION (p, ...) selections stay baked —
                        # such plans refuse the template)
                        child.partition_pruner = (
                            lambda cs, t=child.table, sch=plan.children[0].schema: (
                                prune_partitions(t, sch, list(cs))
                            )
                        )
                        child.partition_conds = tuple(plan.conditions)
                    pruned = prune_partitions(
                        child.table, plan.children[0].schema, plan.conditions
                    )
                    if pruned is not None:
                        if child.partitions is not None:
                            # intersect condition pruning with explicit
                            # PARTITION (p, ...) selection
                            keep_ids = {v.id for v in child.partitions}
                            child.partitions = [v for v in pruned if v.id in keep_ids]
                        else:
                            child.partitions = pruned
            if host_side:
                # host-only residue forces the host engine for correctness of
                # the whole fragment ordering? No — residue evaluates above
                # the reader, engine-independent.
                return PhysSelection(conditions=host_side, children=[child])
            return child
        return PhysSelection(conditions=plan.conditions, children=[child])
    if isinstance(plan, LogicalAggregation) and plan.rollup:
        return _physical_rollup(plan, engines, stats, vars)
    if isinstance(plan, LogicalAggregation):
        child = _physical(plan.children[0], engines, stats, vars)
        # look through row-preserving projections (ref: projection elimination
        # before agg pushdown): remap group/arg exprs through each projection
        # so the agg can land in the reader fragment — the path that fuses
        # Agg over a cop-pushed Window into one device program
        reader = child
        proj_stack: list[PhysProjection] = []
        while isinstance(reader, PhysProjection):
            proj_stack.append(reader)
            reader = reader.children[0]

        def _remap_through(e: Expression) -> Optional[Expression]:
            for pr in proj_stack:
                e = _subst_refs(e, pr.exprs)
                if e is None:
                    return None
            return e

        group_r = plan.group_by
        aggs_r = plan.aggs
        remap_ok = True
        if proj_stack:
            group_r = [_remap_through(g) for g in plan.group_by]
            aggs_r = []
            for a in plan.aggs:
                na = _remap_through(a.arg) if a.arg is not None else None
                if a.arg is not None and na is None:
                    remap_ok = False
                ob = [(_remap_through(e), d) for e, d in a.order_by]
                if any(e is None for e, _ in ob):
                    remap_ok = False
                aggs_r.append(AggDesc(a.name, na, a.distinct, a.sep, order_by=ob))
            remap_ok = remap_ok and all(g is not None for g in group_r)
        can_push = (
            remap_ok
            and isinstance(reader, PhysTableReader)
            and reader.pushed_agg is None
            and reader.pushed_topn is None
            and reader.pushed_limit is None
            and not any(a.distinct for a in plan.aggs)
            # group_concat has no distributable partial state (value order
            # would be lost across task merges) — keep it at the root
            and all(a.name != "group_concat" for a in plan.aggs)
        )
        if can_push:
            exprs: list[Expression] = list(group_r) + [a.arg for a in aggs_r if a.arg is not None]
            st = _pick_engine(engines, list(reader.pushed_conditions) + exprs)
            # ci MIN/MAX args no longer demote: the binder rank-compacts the
            # dictionary under the general_ci weight order (byte tiebreak),
            # so device code reduction picks the same member the host's
            # _string_minmax ranking would — found by graftfuzz, closed here
            if st is not None and all(can_push_down(e, st.value) for e in exprs) and all(
                can_push_down(c, st.value) for c in reader.pushed_conditions
            ):
                reader.store_type = st
                pushed = LogicalAggregation(
                    group_by=group_r, aggs=aggs_r, schema=plan.schema, children=[reader]
                )
                reader.pushed_agg = pushed
                reader.pushed_agg_mode = "partial"
                # reader output schema = partial lanes + keys
                reader.schema = _partial_schema(pushed)
                final = PhysFinalAgg(
                    group_by=plan.group_by, aggs=plan.aggs, partial_input=True, schema=plan.schema, children=[reader]
                )
                return final
        return PhysFinalAgg(group_by=plan.group_by, aggs=plan.aggs, partial_input=False, schema=plan.schema, children=[child])
    if isinstance(plan, LogicalSort):
        child = _physical(plan.children[0], engines, stats, vars)
        return PhysSort(by=plan.by, children=[child])
    if isinstance(plan, LogicalLimit):
        child = _physical(plan.children[0], engines, stats, vars)
        # limit+offset saturates at int64 max — MySQL's u64 "no limit" idiom
        # must stay a valid device scalar (never reach a jit boundary wider)
        total = min(plan.limit + plan.offset, 2**63 - 1)
        # topN pushdown: Limit(Sort([Projection](reader))) → reader TopN +
        # root merge sort; sort keys remap through the projection
        if isinstance(child, PhysSort):
            below = child.children[0]
            by = child.by
            reader = None
            if isinstance(below, PhysTableReader):
                reader = below
            elif isinstance(below, PhysProjection) and isinstance(
                below.children[0], PhysTableReader
            ):
                remapped = [(_subst_refs(e, below.exprs), d) for e, d in by]
                if all(r is not None for r, _ in remapped):
                    reader = below.children[0]
                    by = remapped
            if (
                reader is not None
                and reader.pushed_agg is None
                and reader.pushed_topn is None
                and reader.pushed_limit is None
            ):
                st = _pick_engine(engines, list(reader.pushed_conditions) + [e for e, _ in by])
                st = _demote_ci_order(st, engines, [e for e, _ in by])
                if st is not None and all(can_push_down(e, st.value) for e, _ in by) and all(
                    can_push_down(c, st.value) for c in reader.pushed_conditions
                ):
                    reader.store_type = st
                    reader.pushed_topn = (by, total)
        else:
            # plain LIMIT pushes through row-preserving projections into the
            # reader (ref: limit pushdown, planner/core/rule/rule_topn_push_down)
            below = child
            while isinstance(below, PhysProjection):
                below = below.children[0]
            if (
                isinstance(below, PhysTableReader)
                and below.pushed_agg is None
                and below.pushed_topn is None
                and below.pushed_limit is None
            ):
                below.pushed_limit = total
        return PhysLimit(limit=plan.limit, offset=plan.offset, children=[child])
    if isinstance(plan, LogicalProjection):
        child = _physical(plan.children[0], engines, stats, vars)
        return PhysProjection(exprs=plan.exprs, schema=plan.schema, children=[child])
    if isinstance(plan, LogicalDistinct):
        child = _physical(plan.children[0], engines, stats, vars)
        return PhysDistinct(children=[child])
    if isinstance(plan, LogicalWindow):
        child = _physical(plan.children[0], engines, stats, vars)
        if _try_push_window(plan, child, engines):
            return child  # the reader absorbed the window
        return PhysWindow(
            funcs=plan.funcs,
            partition_by=plan.partition_by,
            order_by=plan.order_by,
            whole_partition=plan.whole_partition,
            rows_frame=plan.rows_frame,
            frame=plan.frame,
            schema=plan.schema,
            children=[child],
        )
    if isinstance(plan, LogicalSetOp):
        return PhysSetOp(
            op=plan.op,
            all=plan.all,
            schema=plan.schema,
            children=[_physical(c, engines, stats, vars) for c in plan.children],
        )
    if isinstance(plan, LogicalJoin):
        left = _physical(plan.children[0], engines, stats, vars)
        right = _physical(plan.children[1], engines, stats, vars)
        return _choose_join(plan, left, right, stats)
    raise PlanError(f"physical: unhandled node {type(plan).__name__}")


def _try_push_window(plan: LogicalWindow, child, engines: list[str]) -> bool:
    """Window pushdown into the coprocessor fragment (ref: the role tipb
    window pushdown plays for TiFlash in pkg/planner/core — window executed
    inside the columnar engine, feeding a fused device program). Gated on the
    TPU engine: a host cop window would just move the same host sweep behind
    an extra indirection. The cop client falls back to a host-side window
    when the table spans multiple regions (partition rows must share one
    computation)."""
    if not (
        isinstance(child, PhysTableReader)
        and child.pushed_agg is None
        and child.pushed_topn is None
        and child.pushed_limit is None
        and child.pushed_window is None
        and child.table.partition is None
    ):
        return False
    from tidb_tpu.ops.window_core import derive_specs

    spec = derive_specs(
        plan.funcs,
        whole_partition=plan.whole_partition,
        rows_frame=plan.rows_frame,
        frame=plan.frame,
        # string order keys are legal in the fragment: the device binder
        # rank-sorts the dictionary, the host fallback compares bytes
        order_is_string=False,
    )
    if spec is None:
        return False
    keys = list(plan.partition_by) + [e for e, _ in plan.order_by]
    # ci collation folds at compare time — device dictionary codes are raw-
    # byte identities, so case-insensitive grouping/ordering stays host-side
    if any(e.ftype.kind == TypeKind.STRING and e.ftype.collation == "ci" for e in keys):
        return False
    exprs = keys + [a for f in plan.funcs for a in f.args]
    st = _pick_engine(engines, list(child.pushed_conditions) + exprs)
    if st != StoreType.TPU:
        return False
    if not all(can_push_down(e, st.value) for e in exprs):
        return False
    child.store_type = st
    child.pushed_window = plan
    child.schema = plan.schema
    return True


_INT_JOIN_KINDS = (TypeKind.INT, TypeKind.UINT, TypeKind.DECIMAL, TypeKind.DATE, TypeKind.DATETIME, TypeKind.DURATION)


def _plain_reader(rd) -> bool:
    return (
        isinstance(rd, PhysTableReader)
        and rd.pushed_agg is None
        and rd.pushed_topn is None
        and rd.pushed_limit is None
        and rd.pushed_window is None
        and rd.table.partition is None
    )


def _merge_join_ok(plan: LogicalJoin, left, right) -> bool:
    """Both inputs stream in join-key order: single-key equi-join where each
    side's key IS its table's integer handle (readers return handle order)."""
    if plan.kind not in ("inner", "left") or len(plan.eq_conds) != 1 or plan.null_aware:
        return False
    l, r = plan.eq_conds[0]

    def sorted_on_key(rd, pos):
        return (
            _plain_reader(rd)
            and rd.table.pk_is_handle
            and pos < len(rd.schema)
            and rd.schema[pos].slot == rd.table.pk_offset
        )

    return sorted_on_key(left, l) and sorted_on_key(right, r)


def _index_join_inner(plan: LogicalJoin, right):
    """('pk', None) / ('idx', IndexInfo) when the inner (right) side is point-
    readable on the join keys; None otherwise."""
    if plan.kind not in ("inner", "left") or not plan.eq_conds or plan.null_aware:
        return None
    if not _plain_reader(right):
        return None
    if any(right.schema[r].ftype.kind not in _INT_JOIN_KINDS for _, r in plan.eq_conds):
        return None
    key_slots = [right.schema[r].slot for _, r in plan.eq_conds]
    t = right.table
    if len(key_slots) == 1 and t.pk_is_handle and key_slots[0] == t.pk_offset:
        return ("pk", None)
    for idx in t.indexes:
        if idx.state == "public" and list(idx.column_offsets[: len(key_slots)]) == key_slots:
            return ("idx", idx)
    return None


def _choose_join(plan: LogicalJoin, left, right, stats):
    """Join algorithm by cost (ref: physical join enumeration in
    find_best_task / builder.go:216-320), overridable by HASH_JOIN /
    MERGE_JOIN / INL_JOIN hints. Index join wins when the outer side is
    far smaller than the indexed inner (reads only matching inner rows);
    merge join wins for handle-ordered inputs (no build memory); hash
    otherwise."""
    hash_join = PhysHashJoin(
        kind=plan.kind,
        eq_conds=plan.eq_conds,
        other_conds=plan.other_conds,
        null_aware=plan.null_aware,
        schema=plan.schema,
        children=[left, right],
    )
    if plan.kind in ("semi", "anti", "cross", "right"):
        return hash_join
    inner = _index_join_inner(plan, right)
    merge_ok = _merge_join_ok(plan, left, right)

    def mk(alg):
        if alg == "merge" and merge_ok:
            return PhysMergeJoin(
                kind=plan.kind,
                eq_conds=plan.eq_conds,
                other_conds=plan.other_conds,
                schema=plan.schema,
                children=[left, right],
            )
        if alg == "index" and inner is not None:
            return PhysIndexJoin(
                kind=plan.kind,
                eq_conds=plan.eq_conds,
                other_conds=plan.other_conds,
                inner_index=inner[1],
                schema=plan.schema,
                children=[left, right],
            )
        return hash_join

    if plan.preferred:
        return mk(plan.preferred)
    l_rows = r_rows = None
    if stats is not None:
        if isinstance(left, PhysTableReader):
            st = stats.get(left.table.id)
            l_rows = st.row_count if st is not None else None
        if isinstance(right, PhysTableReader):
            st = stats.get(right.table.id)
            r_rows = st.row_count if st is not None else None
    if (
        inner is not None
        and l_rows is not None
        and r_rows is not None
        and l_rows <= 100_000
        and l_rows * 16 < r_rows
    ):
        return mk("index")
    if merge_ok:
        return mk("merge")
    return hash_join


def _physical_rollup(plan: LogicalAggregation, engines, stats, vars) -> PhysicalPlan:
    """GROUP BY ... WITH ROLLUP. Preferred route: push ONE rollup partial
    aggregation into the reader — the device kernel computes every grouping
    set in a single pass over the scan (a (G+1)-hot MXU dot; the Expand
    fusion, ref: cophandler/mpp_exec.go:422-466) and the final merge groups
    by (keys, flags). Fallback: the per-set UNION rewrite (one aggregation
    per grouping set), which every engine already runs."""
    G = len(plan.group_by)
    # cheap shape gates FIRST: a non-fusable rollup must not pay a wasted
    # full child-planning pass before the union fallback re-plans per set
    fusable = (
        sysvar_int(vars, "tidb_opt_fused_rollup", 1) != 0
        and not any(a.distinct for a in plan.aggs)
        and all(a.name != "group_concat" for a in plan.aggs)
    )
    child = _physical(plan.children[0], engines, stats, vars) if fusable else None
    can_push = (
        fusable
        and isinstance(child, PhysTableReader)
        and child.pushed_agg is None
        and child.pushed_topn is None
        and child.pushed_limit is None
        and child.pushed_window is None
    )
    if can_push:
        exprs: list[Expression] = list(plan.group_by) + [
            a.arg for a in plan.aggs if a.arg is not None
        ]
        st = _pick_engine(engines, list(child.pushed_conditions) + exprs)
        # ci MIN/MAX: device-legal via ci-weight dictionary compaction (see
        # the plain agg-pushdown site above) — only ORDER keys still demote
        if st is not None and all(can_push_down(e, st.value) for e in exprs) and all(
            can_push_down(c, st.value) for c in child.pushed_conditions
        ):
            child.store_type = st
            pushed = LogicalAggregation(
                group_by=plan.group_by,
                aggs=plan.aggs,
                schema=plan.schema,
                children=[child],
                rollup=True,
            )
            child.pushed_agg = pushed
            child.pushed_agg_mode = "partial"
            child.schema = _partial_schema(pushed)
            return PhysFinalAgg(
                group_by=plan.group_by,
                aggs=plan.aggs,
                partial_input=True,
                schema=plan.schema,
                children=[child],
                rollup=True,
            )
    # union fallback over the LOGICAL child (the per-branch deep copies
    # re-derive their own physical plans)
    from tidb_tpu.planner.builder import _expand_rollup

    plain = LogicalAggregation(
        group_by=plan.group_by,
        aggs=plan.aggs,
        schema=plan.schema[: len(plan.schema) - G],
        children=plan.children,
    )
    return _physical(_expand_rollup(plain), engines, stats, vars)


def _partial_schema(agg: LogicalAggregation) -> list:
    from tidb_tpu.types.field_type import bigint_type

    out = []
    for i, a in enumerate(agg.aggs):
        for pk in a.partial_kinds:
            if pk == "count":
                out.append(OutCol(f"p{i}_count", bigint_type(nullable=False)))
            elif pk == "sum":
                out.append(OutCol(f"p{i}_sum", AggDesc("sum", a.arg).ftype))
            else:
                ft = a.arg.ftype if a.arg is not None else bigint_type()
                out.append(OutCol(f"p{i}_{pk}", ft))
    for gi, g in enumerate(agg.group_by):
        src = agg.children[0].schema[g.index] if isinstance(g, ColumnRef) else None
        out.append(OutCol(f"gb#{gi}", g.ftype, slot=src.slot if src else -1, table=src.table if src else ""))
    if agg.rollup:
        # grouping flags ride after the keys: part of the merge identity
        for gi in range(len(agg.group_by)):
            out.append(OutCol(f"grouping#{gi}", bigint_type(nullable=False)))
    return out
