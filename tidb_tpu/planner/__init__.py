"""Planner.

Reference parity: pkg/planner (~101k LoC) collapsed to the load-bearing
spine: AST → logical plan (builder.py, ref core/logical_plan_builder.go),
rule-based optimization in the reference's rule order — column pruning,
predicate pushdown, aggregation/topN/limit pushdown (optimizer.py, ref
core/optimizer.go:84 rule list) — then physical planning where the
engine-isolation hook decides which store executes the pushed fragment
(ref core/planbuilder.go:1357 filterPathByIsolationRead).
"""

from tidb_tpu.planner.plans import PlanError
from tidb_tpu.planner.optimizer import optimize

__all__ = ["optimize", "PlanError"]
