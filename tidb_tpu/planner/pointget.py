"""Point-get fast path: `SELECT ... FROM t WHERE pk = const`.

Reference parity: planner TryFastPlan (core/point_get_plan.go:957) — the
planner is bypassed entirely for single-row primary-key lookups; the row is
fetched with one KV get (PointGetExecutor analog) instead of a coprocessor
scan. Only clustered integer primary keys (pk_is_handle) qualify, matching
the reference's handle fast path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from tidb_tpu.catalog.schema import TableInfo
from tidb_tpu.parser import ast


@dataclass
class PointGetPlan:
    db: str
    table: TableInfo
    # one handle = Point_Get; several = Batch_Point_Get (ref:
    # BatchPointGetPlan for pk IN (...) lists)
    handles: list[int]
    # projected column offsets, in output order
    out_offsets: list[int]
    out_names: list[str]

    @property
    def handle(self) -> int:
        return self.handles[0]


def _const_int(node: ast.Node) -> Optional[int]:
    if isinstance(node, ast.Literal) and node.hint == "" and isinstance(node.value, int) and not isinstance(node.value, bool):
        return node.value
    if (
        isinstance(node, ast.UnaryOp)
        and node.op == "unaryminus"
        and isinstance(node.operand, ast.Literal)
        and isinstance(node.operand.value, int)
    ):
        return -node.operand.value
    return None


def detect_point_get(catalog, current_db: str, stmt: ast.Node) -> Optional[PointGetPlan]:
    """Return a PointGetPlan when the statement is exactly a clustered-PK
    single-row lookup; None means take the regular planner path."""
    if not isinstance(stmt, ast.Select):
        return None
    if (
        stmt.ctes
        or stmt.group_by
        or stmt.having is not None
        or stmt.order_by
        or stmt.distinct
        or stmt.for_update
        or stmt.offset
        or stmt.limit == 0
    ):
        return None
    if not isinstance(stmt.from_, ast.TableRef):
        return None
    if stmt.from_.as_of is not None:
        return None  # stale reads take the planner path
    if stmt.where is None:
        return None
    # WHERE must be exactly `pk = const` / `const = pk` / `pk IN (consts)`
    w = stmt.where
    try:
        t = catalog.table(stmt.from_.db or current_db, stmt.from_.name)
    except Exception:
        return None
    if not t.pk_is_handle or t.pk_offset < 0:
        return None
    if t.partition is not None:
        return None  # partitioned point lookups take the planner path
    pk_name = t.columns[t.pk_offset].name.lower()
    alias = (stmt.from_.alias or stmt.from_.name).lower()

    def is_pk_col(n):
        return (
            isinstance(n, ast.ColumnName)
            and n.name.lower() == pk_name
            and (not n.table or n.table.lower() == alias)
        )

    handles: Optional[list[int]] = None
    if isinstance(w, ast.BinaryOp) and w.op == "eq":
        h = None
        if is_pk_col(w.left):
            h = _const_int(w.right)
        elif is_pk_col(w.right):
            h = _const_int(w.left)
        if h is not None:
            handles = [h]
    elif isinstance(w, ast.InList) and not w.negated and is_pk_col(w.operand):
        vals = [_const_int(x) for x in w.items]
        if all(v is not None for v in vals):
            # MySQL batch point get preserves the IN-list order, deduped
            handles = list(dict.fromkeys(vals))  # type: ignore[arg-type]
    if handles is None:
        return None

    # select list: plain columns or *
    out_offsets: list[int] = []
    out_names: list[str] = []
    for it in stmt.items:
        if isinstance(it.expr, ast.Wildcard):
            if it.expr.table and it.expr.table.lower() != alias:
                return None
            for c in t.columns:
                out_offsets.append(c.offset)
                out_names.append(c.name)
            continue
        if isinstance(it.expr, ast.ColumnName):
            if it.expr.table and it.expr.table.lower() != alias:
                return None
            c = t.column(it.expr.name)
            if c is None:
                return None
            out_offsets.append(c.offset)
            out_names.append(it.alias or c.name)
            continue
        return None
    if not out_offsets:
        return None
    return PointGetPlan(stmt.from_.db or current_db, t, handles, out_offsets, out_names)


def _to_logical(v, ft):
    """Storage repr → logical Python value (mirrors Column.logical_value)."""
    from tidb_tpu.types import TypeKind
    from tidb_tpu.types.datum import days_to_date, micros_to_datetime

    if v is None:
        return None
    k = ft.kind
    if k == TypeKind.STRING:
        return v.decode("utf-8", "replace")
    if k == TypeKind.DECIMAL:
        if ft.scale == 0:
            return int(v)
        from decimal import Decimal

        return Decimal(int(v)).scaleb(-ft.scale)
    if k == TypeKind.DATE:
        return days_to_date(int(v))
    if k == TypeKind.DATETIME:
        return micros_to_datetime(int(v))
    if k == TypeKind.FLOAT:
        return float(v)
    if k == TypeKind.UINT and v < 0:
        return int(v) + (1 << 64)
    return int(v)


def run_point_get(session, plan: PointGetPlan) -> list[tuple]:
    """KV gets for the plan's handles through the txn-aware read path
    (membuffer overlay first, then MVCC snapshot at the session read ts).
    Autocommit snapshot reads ride the cross-session point-get batcher:
    concurrent sessions' lookups coalesce into one multi-key store dispatch
    (TiKV batch-commands idiom) instead of one RPC each."""
    from tidb_tpu.kv import tablecodec
    from tidb_tpu.kv.rowcodec import RowSchema, decode_row

    txn = session._txn
    schema = RowSchema(plan.table.storage_schema)
    keys = [tablecodec.record_key(plan.table.id, h) for h in plan.handles]
    if txn is None:
        from tidb_tpu.copr.client import batched_point_get

        raws = batched_point_get(session.store, session.read_ts(), keys)
    else:
        # dirty-txn gets ride the batcher too: membuffer overlay first, then
        # one coalesced dispatch for the snapshot misses (Txn.batch_get)
        raws = txn.batch_get(keys)
    out: list[tuple] = []
    for raw in raws:
        if raw is None:
            continue
        vals = decode_row(schema, raw)
        out.append(
            tuple(_to_logical(vals[o], plan.table.columns[o].ftype) for o in plan.out_offsets)
        )
    return out
