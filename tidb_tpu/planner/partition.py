"""Partition pruning (ref: core/rule/rule_partition_processor.go).

Intersects simple top-level comparisons on the partitioning column with each
partition's value range (RANGE) or routes equality to one bucket (HASH).
Conservative: anything unrecognized keeps all partitions — pruning only ever
removes provably-empty reads.
"""

from __future__ import annotations

from typing import Optional

from tidb_tpu.catalog.schema import TableInfo
from tidb_tpu.expression.expr import ColumnRef, Constant, ScalarFunc

_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}


def prune_partitions(t: TableInfo, scan_schema, conds) -> Optional[list[TableInfo]]:
    """→ pruned partition views, or None for "scan all" (also when the table
    is not partitioned). ``conds`` are resolved pushdown conditions over
    ``scan_schema`` positions."""
    p = t.partition
    if p is None:
        return None
    positions = [i for i, oc in enumerate(scan_schema) if getattr(oc, "slot", -1) == p.col_offset]
    if not positions:
        return None
    pos = positions[0]

    lo, hi = None, None  # inclusive bounds on the partition column
    for c in conds:
        if not (isinstance(c, ScalarFunc) and c.sig in _FLIP):
            continue
        a, b = c.args
        sig = c.sig
        if isinstance(b, ColumnRef) and isinstance(a, Constant):
            a, b = b, a
            sig = _FLIP[sig]
        if not (isinstance(a, ColumnRef) and a.index == pos and isinstance(b, Constant)):
            continue
        if b.value is None:
            continue
        try:
            v = int(b.value)
        except (TypeError, ValueError):
            continue
        if sig == "eq":
            lo = v if lo is None else max(lo, v)
            hi = v if hi is None else min(hi, v)
        elif sig == "lt":
            hi = v - 1 if hi is None else min(hi, v - 1)
        elif sig == "le":
            hi = v if hi is None else min(hi, v)
        elif sig == "gt":
            lo = v + 1 if lo is None else max(lo, v + 1)
        elif sig == "ge":
            lo = v if lo is None else max(lo, v)

    if lo is None and hi is None:
        return None
    if lo is not None and hi is not None and lo > hi:
        return []

    if p.type == "hash":
        if lo is not None and lo == hi:
            return [t.partition_view(p.defs[lo % len(p.defs)].id)]
        return None

    # RANGE: partition d covers [prev_bound, d.less_than)
    out = []
    prev: Optional[int] = None
    for d in p.defs:
        p_lo = prev  # None = -inf
        p_hi = None if d.less_than is None else d.less_than - 1  # inclusive
        prev = d.less_than if d.less_than is not None else prev
        if lo is not None and p_hi is not None and lo > p_hi:
            continue
        if hi is not None and p_lo is not None and hi < p_lo:
            continue
        out.append(t.partition_view(d.id))
    # NULLs live in the first partition; a NULL-matching predicate can't be
    # a comparison (those never match NULL), so no extra handling needed
    return out
