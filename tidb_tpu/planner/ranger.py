"""Index/handle range derivation from pushed-down conditions.

Reference parity: pkg/util/ranger (DetachCondAndBuildRangeForIndex /
BuildTableRange). Given the AND-ed conditions on a scan, split them into
(a) an access condition prefix over an index's columns — longest run of
equality/IN conditions, optionally followed by one range condition on the
next column — encoded into memcomparable index key ranges, and (b) the
remaining filter conditions. The same datum encoding as
executor/write.index_entry keeps scan ranges and stored entries aligned.
"""

from __future__ import annotations

from dataclasses import dataclass
from decimal import Decimal
from typing import Optional

from tidb_tpu.catalog.schema import IndexInfo, TableInfo
from tidb_tpu.expression.expr import ColumnRef, Constant, Expression, ScalarFunc
from tidb_tpu.kv import tablecodec
from tidb_tpu.kv.kv import KeyRange
from tidb_tpu.types import TypeKind
from tidb_tpu.utils import codec

_INT_KINDS = (TypeKind.INT, TypeKind.UINT, TypeKind.DATE, TypeKind.DATETIME, TypeKind.DECIMAL, TypeKind.DURATION)

_SWAP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}


def prefix_next(key: bytes) -> bytes:
    """Smallest byte string greater than every string prefixed by ``key``
    (ref: kv.Key.PrefixNext)."""
    b = bytearray(key)
    for i in range(len(b) - 1, -1, -1):
        if b[i] != 0xFF:
            b[i] += 1
            return bytes(b[: i + 1])
    return key + b"\xff" * 9  # all-0xFF: unreachable for flagged datums


@dataclass
class ColBound:
    """Integer/raw bound set for one column: None = unbounded."""

    eq: Optional[list] = None  # list of point values (IN / eq)
    lo: Optional[object] = None  # inclusive low
    hi: Optional[object] = None  # inclusive high
    empty: bool = False


def _as_rational(v) -> Decimal:
    if isinstance(v, Decimal):
        return v
    if isinstance(v, float):
        return Decimal(str(v))
    return Decimal(int(v))


def _int_bound(v, scale: int, side: str) -> Optional[int]:
    """Convert a constant to an integer bound on a 10**scale-scaled column.
    side: 'lo' → ceil, 'hi' → floor, 'eq' → exact or None."""
    r = _as_rational(v) * (10**scale)
    i = int(r)
    if r == i:
        return i
    if side == "eq":
        return None
    if side == "lo":
        return i + 1 if r > 0 else i  # ceil for non-integral
    return i if r > 0 else i - 1  # floor


def _wrap_uint(iv: int, ftype) -> Optional[int]:
    """UNSIGNED columns store values wrapped to signed int64 (see
    executor/write.to_physical); apply the same wrap to point constants.
    Returns None when the value is outside the uint64 domain."""
    if ftype.kind != TypeKind.UINT:
        return iv
    if iv < 0 or iv >= 1 << 64:
        return None
    return iv - (1 << 64) if iv >= 1 << 63 else iv


def _phys_const(v, ftype):
    """Logical constant → physical storage value for key encoding."""
    k = ftype.kind
    if k == TypeKind.STRING:
        if isinstance(v, str):
            return v.encode("utf-8")
        if isinstance(v, bytes):
            return v
        return str(v).encode("utf-8")
    if k == TypeKind.FLOAT:
        return float(v)
    return v  # int-backed kinds handled by _int_bound


def _encode_datum(v, ftype) -> bytes:
    k = ftype.kind
    if v is None:
        return codec.encode_key_nil()
    if k == TypeKind.STRING:
        return codec.encode_key_bytes(_phys_const(v, ftype))
    if k == TypeKind.FLOAT:
        return codec.encode_key_float(float(v))
    return codec.encode_key_int(int(v))


def _extract_col_conds(conds: list[Expression], col_idx: int, ftype) -> tuple[ColBound, list[Expression]]:
    """Collect eq/in/cmp conditions on schema position col_idx.
    Returns (bound, used_conditions)."""
    b = ColBound()
    used: list[Expression] = []
    scale = ftype.scale if ftype.kind == TypeKind.DECIMAL else 0
    int_backed = ftype.kind in _INT_KINDS

    def tighten_lo(v, inclusive: bool):
        if int_backed:
            iv = _int_bound(v, scale, "lo")
            if not inclusive:
                ivx = _int_bound(v, scale, "eq")
                iv = ivx + 1 if ivx is not None else iv
            b.lo = iv if b.lo is None else max(b.lo, iv)
        else:
            pv = _phys_const(v, ftype)
            cur = (pv, inclusive)
            if b.lo is None or cur[0] > b.lo[0] or (cur[0] == b.lo[0] and not inclusive):
                b.lo = cur

    def tighten_hi(v, inclusive: bool):
        if int_backed:
            iv = _int_bound(v, scale, "hi")
            if not inclusive:
                ivx = _int_bound(v, scale, "eq")
                iv = ivx - 1 if ivx is not None else iv
            b.hi = iv if b.hi is None else min(b.hi, iv)
        else:
            pv = _phys_const(v, ftype)
            cur = (pv, inclusive)
            if b.hi is None or cur[0] < b.hi[0] or (cur[0] == b.hi[0] and not inclusive):
                b.hi = cur

    for c in conds:
        if not isinstance(c, ScalarFunc):
            continue
        if c.sig == "in":
            op = c.args[0]
            if isinstance(op, ColumnRef) and op.index == col_idx and all(
                isinstance(a, Constant) and a.value is not None for a in c.args[1:]
            ):
                pts = []
                for a in c.args[1:]:
                    if int_backed:
                        iv = _int_bound(a.value, scale, "eq")
                        if iv is None:
                            continue  # non-representable point matches nothing
                        iv = _wrap_uint(iv, ftype)
                        if iv is None:
                            continue  # out of the uint64 domain
                        pts.append(iv)
                    else:
                        pts.append(_phys_const(a.value, ftype))
                pts = sorted(set(pts))
                b.eq = pts if b.eq is None else sorted(set(b.eq) & set(pts))
                used.append(c)
            continue
        if c.sig not in ("eq", "lt", "le", "gt", "ge"):
            continue
        a0, a1 = c.args
        sig = c.sig
        if isinstance(a1, ColumnRef) and isinstance(a0, Constant):
            a0, a1 = a1, a0
            sig = _SWAP[sig]
        if not (isinstance(a0, ColumnRef) and a0.index == col_idx and isinstance(a1, Constant)):
            continue
        v = a1.value
        if v is None:
            b.empty = True  # cmp with NULL selects nothing
            used.append(c)
            continue
        if ftype.kind == TypeKind.STRING and not isinstance(v, (str, bytes)):
            continue
        if ftype.kind in _INT_KINDS and isinstance(v, (str, bytes)):
            continue
        if ftype.kind == TypeKind.UINT and sig != "eq":
            # sign-wrapped uint storage breaks key order for ranges: leave
            # the condition as a residual filter (correct, just unindexed)
            continue
        used.append(c)
        if sig == "eq":
            if int_backed:
                iv = _int_bound(v, scale, "eq")
                if iv is not None:
                    iv = _wrap_uint(iv, ftype)
                if iv is None:
                    b.empty = True
                    continue
                v = iv
            else:
                v = _phys_const(v, ftype)
            b.eq = [v] if b.eq is None else sorted(set(b.eq) & {v})
        elif sig in ("ge", "gt"):
            tighten_lo(v, sig == "ge")
        else:
            tighten_hi(v, sig == "le")
    # normalize: clamp to the int64 key domain (out-of-domain bounds must
    # not wrap in encode_int_raw), then filter eq points by lo/hi
    i64_min, i64_max = -(2**63), 2**63 - 1
    if int_backed:
        if b.lo is not None:
            if b.lo > i64_max:
                b.empty = True
            b.lo = max(b.lo, i64_min)
        if b.hi is not None:
            if b.hi < i64_min:
                b.empty = True
            b.hi = min(b.hi, i64_max)
    if b.eq is not None:
        if int_backed:
            lo = b.lo if b.lo is not None else i64_min
            hi = b.hi if b.hi is not None else i64_max
            b.eq = [p for p in b.eq if lo <= p <= hi]
        if not b.eq:
            b.empty = True
    elif int_backed and b.lo is not None and b.hi is not None and b.lo > b.hi:
        b.empty = True
    return b, used


@dataclass
class IndexAccess:
    """Result of detaching access conditions for one index."""

    index: IndexInfo
    ranges: list[KeyRange]
    used: list[Expression]  # conditions consumed into ranges
    residual: list[Expression]  # must still be filtered after the scan
    eq_prefix_len: int  # number of leading columns with point conditions
    has_range: bool  # a range condition on the next column
    point_count: int  # total number of point ranges (IN fan-out product)


def detach_index_conditions(
    conds: list[Expression], scan_schema, table: TableInfo, index: IndexInfo
) -> Optional[IndexAccess]:
    """ref: ranger.DetachCondAndBuildRangeForIndex — longest eq/IN prefix,
    then one range column. scan_schema maps schema positions → storage slots
    via OutCol.slot."""
    slot_to_pos = {oc.slot: i for i, oc in enumerate(scan_schema)}
    prefixes: list[list[bytes]] = [b""]  # encoded value prefixes (fan-out via IN)
    used_all: list[Expression] = []
    eq_len = 0
    point_count = 1
    has_range = False
    lo_key_suffix = b""
    hi_key_suffix: Optional[bytes] = None

    for depth, off in enumerate(index.column_offsets):
        pos = slot_to_pos.get(off)
        if pos is None:
            break
        ftype = table.columns[off].ftype
        if ftype.kind == TypeKind.STRING and ftype.collation == "ci":
            # index keys are byte-encoded raw values, but general_ci equality
            # holds across byte-distinct members of a weight class ('a' ≡
            # 'A'): a byte range can only under-select. Stop the usable
            # prefix here — comparisons on this column stay residual filters
            # (which evaluate collation-aware). Found by graftfuzz's TLP
            # oracle on BOTH engines (repro tests/fuzz_corpus/repro_s42_c20.py)
            break
        bound, used = _extract_col_conds(conds, pos, ftype)
        if bound.empty:
            return IndexAccess(index, [], used_all + used, [c for c in conds], eq_len, False, 0)
        if bound.eq is not None:
            new_prefixes = []
            for p in prefixes:
                for v in bound.eq:
                    new_prefixes.append(p + _encode_datum(v, ftype))
            prefixes = new_prefixes
            point_count *= len(bound.eq)
            if point_count > 256:
                # IN fan-out cap: an unbounded range list is worse than a
                # columnar full scan → no index access at all
                return None
            used_all.extend(used)
            eq_len += 1
            continue
        if bound.lo is not None or bound.hi is not None:
            has_range = True
            used_all.extend(used)
            int_backed = ftype.kind in _INT_KINDS
            # comparisons never match NULL: skip NIL-flagged entries (flag
            # 0x00 sorts before every typed datum) when there is no low bound
            lo_key_suffix = bytes([codec.NIL_FLAG + 1])
            if bound.lo is not None:
                if int_backed:
                    lo_key_suffix = _encode_datum(bound.lo, ftype)
                else:
                    v, inc = bound.lo
                    enc = _encode_datum(v, ftype)
                    lo_key_suffix = enc if inc else prefix_next(enc)
            if bound.hi is not None:
                if int_backed:
                    hi_key_suffix = prefix_next(_encode_datum(bound.hi, ftype))
                else:
                    v, inc = bound.hi
                    enc = _encode_datum(v, ftype)
                    hi_key_suffix = prefix_next(enc) if inc else enc
        break  # range column (or nothing) ends the prefix

    if eq_len == 0 and not has_range:
        return None
    ranges: list[KeyRange] = []
    p0 = tablecodec.index_prefix(table.id, index.id)
    for pref in prefixes:
        if has_range:
            start = p0 + pref + lo_key_suffix
            end = p0 + pref + hi_key_suffix if hi_key_suffix is not None else prefix_next(p0 + pref)
        elif pref:
            start = p0 + pref
            end = prefix_next(p0 + pref)
        else:
            continue
        if start < end:
            ranges.append(KeyRange(start, end))
    used_ids = {id(c) for c in used_all}
    # eq/IN conditions are fully enforced by the range; the range-column
    # bounds too (integer bounds are exact). Everything else is residual.
    residual = [c for c in conds if id(c) not in used_ids]
    return IndexAccess(index, ranges, used_all, residual, eq_len, has_range, point_count if prefixes else 0)


def derive_handle_ranges(conds: list[Expression], scan_schema, table: TableInfo) -> Optional[tuple[list[KeyRange], int]]:
    """PK-as-handle table ranges (ref: ranger.BuildTableRange). Returns
    (ranges, eq_prefix_len 0/1) or None when no pk condition exists."""
    if not table.pk_is_handle:
        return None
    pk_pos = None
    for i, oc in enumerate(scan_schema):
        if oc.slot == table.pk_offset:
            pk_pos = i
            break
    if pk_pos is None:
        return None
    ftype = table.columns[table.pk_offset].ftype
    bound, used = _extract_col_conds(conds, pk_pos, ftype)
    if not used:
        return None
    if bound.empty:
        return [], 1
    if bound.eq is not None:
        return [tablecodec.handle_range(table.id, v, v) for v in bound.eq], 1
    lo = bound.lo if bound.lo is not None else None
    hi = bound.hi if bound.hi is not None else None
    return [tablecodec.handle_range(table.id, lo, hi)], 0
