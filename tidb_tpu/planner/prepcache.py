"""Value-agnostic prepared-plan reuse.

Reference parity: pkg/planner/core plan_cache.go — a prepared statement
caches ONE physical plan regardless of the bound parameter values
(``RebuildPlan4CachedPlan``): parameters live in the plan as shared
``Constant`` objects carrying their parameter index, and each EXECUTE
(a) rewrites those constants' values in place and (b) re-runs the ranger
derivation (``planner/ranger.py``) so scan ranges follow the new values.

The template is built once per (statement text, parameter-type signature)
by walking the finished physical plan:

- every ``Constant`` with ``param_idx >= 0`` is collected per parameter;
- every range-bearing node contributes a rebuild hook (``range_maker``,
  attached by the optimizer at derivation time, closing over the SAME
  condition objects the plan carries — mutation is visible to the rebuild);
- shapes whose ranges cannot be re-derived safely (index merge, partition
  pruning, a parameter folded away by constant folding, an unknown plan
  node) refuse the template — the session falls back to value-keyed
  caching, exactly the pre-refinement behavior.

Rebuild safety for index paths: the detachment may consume a DIFFERENT
subset of conditions under new values (e.g. a parameter turning NULL drops
an IN-list from the access path). The residual split baked into the plan
would then be stale, so ``rebind`` compares the consumed-condition identity
set against the plan-time snapshot and reports failure — the caller
re-plans from scratch for that execution.
"""

from __future__ import annotations

import dataclasses
import datetime
import enum
from decimal import Decimal
from typing import Optional

import numpy as np

from tidb_tpu.catalog.schema import ColumnInfo, IndexInfo, TableInfo
from tidb_tpu.expression.expr import Constant, Expression
from tidb_tpu.kv.kv import KeyRange
from tidb_tpu.planner.plans import (
    OutCol,
    PhysIndexLookUp,
    PhysIndexMerge,
    PhysIndexReader,
    PhysTableReader,
)
from tidb_tpu.types import FieldType

# traversal leaves: never hold parameter constants, never need rebuilding
_ATOMS = (
    str,
    bytes,
    int,
    float,
    bool,
    complex,
    type(None),
    Decimal,
    datetime.date,
    datetime.time,
    datetime.timedelta,
    enum.Enum,
    np.ndarray,
    np.generic,
    KeyRange,
    FieldType,
    TableInfo,
    IndexInfo,
    ColumnInfo,
    OutCol,
    frozenset,
)


def param_sig(p) -> object:
    """Parameter-type signature component: plans are typed from the bound
    value's Python type at first EXECUTE (builder._literal), so a cached
    plan is only reusable for parameters that would type identically."""
    if isinstance(p, Decimal):
        return ("Decimal", p.as_tuple().exponent)
    return type(p).__name__


@dataclasses.dataclass
class PlanTemplate:
    """One cached value-agnostic plan + its parameter rewrite points."""

    plan: object
    # param idx → every Constant in the plan carrying that parameter
    param_consts: dict[int, list[Constant]]
    # () -> bool per range-bearing node; False = split shifted, re-plan
    rebuilders: list


class _Walk:
    __slots__ = ("seen", "consts", "rebuilders", "ok")

    def __init__(self):
        self.seen: set[int] = set()
        self.consts: dict[int, list[Constant]] = {}
        self.rebuilders: list = []
        self.ok = True


def _table_rebuilder(node: PhysTableReader):
    def rebuild() -> bool:
        # table ranges only narrow the scan — the pushed conditions still
        # filter exactly — so every derivation outcome (incl. None = full
        # scan) is safe to install
        node.ranges = node.range_maker()
        return True

    return rebuild


def _index_rebuilder(node):
    def rebuild() -> bool:
        acc = node.range_maker()
        if acc is None:
            return False
        if frozenset(id(c) for c in acc.used) != node.range_used_ids:
            return False  # used/residual split shifted under the new values
        node.ranges = acc.ranges
        return True

    return rebuild


def _walk(obj, st: _Walk) -> None:
    if not st.ok or obj is None or isinstance(obj, _ATOMS):
        return
    oid = id(obj)
    if oid in st.seen:
        return
    st.seen.add(oid)
    if isinstance(obj, Constant):
        if obj.param_idx >= 0:
            st.consts.setdefault(obj.param_idx, []).append(obj)
        return
    if isinstance(obj, (list, tuple)):
        for x in obj:
            _walk(x, st)
        return
    if isinstance(obj, dict):
        for v in obj.values():
            _walk(v, st)
        return
    if isinstance(obj, PhysIndexMerge):
        # per-path ranges have no rebuild hook (paths mix PK and index
        # derivations) — not value-agnostic
        st.ok = False
        return
    if isinstance(obj, PhysTableReader):
        if obj.partitions is not None:
            st.ok = False  # partition pruning picked partitions by value
            return
        if obj.range_maker is not None:
            st.rebuilders.append(_table_rebuilder(obj))
        elif obj.ranges is not None:
            st.ok = False  # ranges of unknown provenance can't be rebuilt
            return
    elif isinstance(obj, (PhysIndexReader, PhysIndexLookUp)):
        if obj.range_maker is None or obj.range_used_ids is None:
            st.ok = False
            return
        st.rebuilders.append(_index_rebuilder(obj))
    if dataclasses.is_dataclass(obj):
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name, None)
            if callable(v) and not isinstance(v, Expression):
                continue  # rebuild hooks / warn sinks
            _walk(v, st)
        return
    if callable(obj):
        return
    # an unrecognized plan shape: refuse rather than risk a stale bake
    st.ok = False


def make_template(plan, n_params: int) -> Optional[PlanTemplate]:
    """Build a reuse template for ``plan``, or None when the plan is not
    provably value-agnostic (some parameter folded into an untraceable
    position, or a range/partition shape we cannot re-derive)."""
    if n_params <= 0:
        return None
    st = _Walk()
    _walk(plan, st)
    if not st.ok:
        return None
    if set(st.consts) != set(range(n_params)):
        # a parameter vanished (constant-folded / baked into a limit):
        # its value is burned into the plan — not reusable
        return None
    return PlanTemplate(plan, st.consts, st.rebuilders)


def _plan_value(p):
    """A parameter's PLAN-TIME value: route through the same literal
    conversion the builder applied at template build (date → day number,
    datetime/timedelta → microseconds, bool → int). Assigning the raw
    Python value would desynchronize the cached plan from what a fresh
    bind would have produced."""
    from tidb_tpu.parser import ast
    from tidb_tpu.planner.builder import _literal_const

    return _literal_const(ast.Literal(p)).value


def rebind(tmpl: PlanTemplate, params: list) -> bool:
    """Point the template's parameter constants at ``params`` and re-derive
    every dependent range set. False = this plan cannot serve these values
    (the caller must re-plan); the template itself stays structurally valid
    for values that keep the original derivation shape."""
    for idx, consts in tmpl.param_consts.items():
        v = _plan_value(params[idx])
        for c in consts:
            c.value = v
    for rb in tmpl.rebuilders:
        if not rb():
            return False
    return True
