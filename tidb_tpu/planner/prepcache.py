"""Value-agnostic prepared-plan reuse.

Reference parity: pkg/planner/core plan_cache.go — a prepared statement
caches ONE physical plan regardless of the bound parameter values
(``RebuildPlan4CachedPlan``): parameters live in the plan as ``Constant``
objects carrying their parameter index, and each EXECUTE (a) rewrites those
constants' values and (b) re-runs the ranger derivation
(``planner/ranger.py``) so scan ranges follow the new values.

The template is built once per (statement text, parameter-type signature)
by walking the finished physical plan:

- every ``Constant`` with ``param_idx >= 0`` is collected per parameter;
- every range-bearing node contributes a rebuild hook: ``range_maker``
  (handle/index ranges), ``partition_pruner`` (pruned-partition plans) and
  ``path_makers`` (index-merge paths), attached by the optimizer at
  derivation time as PURE functions of a condition tuple the node carries;
- shapes whose ranges cannot be re-derived safely (a parameter folded away
  by constant folding, an explicit PARTITION (...) selection, an unknown
  plan node) refuse the template — the session falls back to value-keyed
  caching, exactly the pre-refinement behavior.

**Copy-on-execute** (the instance-plan-cache concurrency discipline): the
cached template is IMMUTABLE. Each EXECUTE first clones the plan graph
(:func:`instantiate`) — sharing every frozen leaf (schemas, table/index
infos, key ranges, ndarrays) and every pure hook, deep-copying only the
mutable spine (plan nodes, expressions, containers) — then rebinds
parameters into the CLONE. Two sessions executing one cached template
concurrently therefore never observe each other's parameters, and the
template bytes never change (``plan_fingerprint`` is the audit primitive).

Rebuild safety for index paths: the detachment may consume a DIFFERENT
subset of conditions under new values (e.g. a parameter turning NULL drops
an IN-list from the access path). The residual split baked into the plan
would then be stale, so ``rebind`` compares the consumed-condition POSITION
set against the plan-time snapshot and reports failure — the caller
re-plans from scratch for that execution.
"""

from __future__ import annotations

import copy
import dataclasses
import datetime
import enum
from decimal import Decimal
from typing import Optional

import numpy as np

from tidb_tpu.catalog.schema import ColumnInfo, IndexInfo, TableInfo
from tidb_tpu.expression.expr import Constant, Expression
from tidb_tpu.kv.kv import KeyRange
from tidb_tpu.planner.plans import (
    OutCol,
    PhysIndexLookUp,
    PhysIndexMerge,
    PhysIndexReader,
    PhysTableReader,
)
from tidb_tpu.types import FieldType

# traversal leaves: never hold parameter constants, never need rebuilding
_ATOMS = (
    str,
    bytes,
    int,
    float,
    bool,
    complex,
    type(None),
    Decimal,
    datetime.date,
    datetime.time,
    datetime.timedelta,
    enum.Enum,
    np.ndarray,
    np.generic,
    KeyRange,
    FieldType,
    TableInfo,
    IndexInfo,
    ColumnInfo,
    OutCol,
    frozenset,
)


def param_sig(p) -> object:
    """Parameter-type signature component: plans are typed from the bound
    value's Python type at first EXECUTE (builder._literal), so a cached
    plan is only reusable for parameters that would type identically."""
    if isinstance(p, Decimal):
        return ("Decimal", p.as_tuple().exponent)
    return type(p).__name__


@dataclasses.dataclass
class PlanTemplate:
    """One cached value-agnostic plan + its parameter rewrite points.

    The cached (shared) template is never rebound directly — callers go
    through :func:`instantiate` and rebind the per-execution clone."""

    plan: object
    # param idx → every Constant in the plan carrying that parameter
    param_consts: dict[int, list[Constant]]
    # (rebuild_fn, node) per range-bearing node — ``rebuild_fn(node) ->
    # bool``, False = split shifted, re-plan. Node references (not bound
    # closures) so :func:`instantiate` can remap them through the clone memo
    rebuilders: list


class _Walk:
    __slots__ = ("seen", "consts", "rebuilders", "ok")

    def __init__(self):
        self.seen: set[int] = set()
        self.consts: dict[int, list[Constant]] = {}
        self.rebuilders: list = []
        self.ok = True


def _rebuild_table(node: PhysTableReader) -> bool:
    # table ranges only narrow the scan — the pushed conditions still
    # filter exactly — so every derivation outcome (incl. None = full
    # scan) is safe to install
    node.ranges = node.range_maker(node.range_conds)
    return True


def _rebuild_partitions(node: PhysTableReader) -> bool:
    # re-prune per execution: None = scan every partition (a safe
    # superset — the conditions still filter), a list re-routes the
    # scan to exactly the partitions the new values can touch
    node.partitions = node.partition_pruner(node.partition_conds)
    return True


def _rebuild_index(node) -> bool:
    acc = node.range_maker(node.range_conds)
    if acc is None:
        return False
    used = {id(c) for c in acc.used}
    pos = frozenset(i for i, c in enumerate(node.range_conds) if id(c) in used)
    if pos != node.range_used_pos:
        return False  # used/residual split shifted under the new values
    node.ranges = acc.ranges
    return True


def _rebuild_merge(node: PhysIndexMerge) -> bool:
    new_paths = []
    for maker, cs, old in zip(node.path_makers, node.path_conds, node.paths):
        p = maker(cs)
        if p is None or p[0] != old[0]:
            return False  # a disjunct lost its access-path shape
        if p[0] == "idx" and p[1] is not old[1]:
            return False  # the winning index flipped under new values
        new_paths.append(p)
    node.paths = new_paths
    return True


def _walk(obj, st: _Walk) -> None:
    if not st.ok or obj is None or isinstance(obj, _ATOMS):
        return
    oid = id(obj)
    if oid in st.seen:
        return
    st.seen.add(oid)
    if isinstance(obj, Constant):
        if obj.param_idx >= 0:
            st.consts.setdefault(obj.param_idx, []).append(obj)
        return
    if isinstance(obj, (list, tuple)):
        for x in obj:
            _walk(x, st)
        return
    if isinstance(obj, dict):
        for v in obj.values():
            _walk(v, st)
        return
    if isinstance(obj, PhysIndexMerge):
        if obj.path_makers is None or obj.path_conds is None:
            st.ok = False  # pre-hook plan shape: not value-agnostic
            return
        st.rebuilders.append((_rebuild_merge, obj))
        # fall through to the field walk — the conditions carry the params
    elif isinstance(obj, PhysTableReader):
        if obj.partition_pruner is not None and obj.partition_conds is not None:
            st.rebuilders.append((_rebuild_partitions, obj))
        elif obj.partitions is not None:
            # an explicit PARTITION (p, ...) selection baked the set by hand
            st.ok = False
            return
        if obj.range_maker is not None:
            if obj.range_conds is None:
                st.ok = False
                return
            st.rebuilders.append((_rebuild_table, obj))
        elif obj.ranges is not None:
            st.ok = False  # ranges of unknown provenance can't be rebuilt
            return
    elif isinstance(obj, (PhysIndexReader, PhysIndexLookUp)):
        if obj.range_maker is None or obj.range_used_pos is None or obj.range_conds is None:
            st.ok = False
            return
        st.rebuilders.append((_rebuild_index, obj))
    if dataclasses.is_dataclass(obj):
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name, None)
            if callable(v) and not isinstance(v, Expression):
                continue  # rebuild hooks / warn sinks
            _walk(v, st)
        return
    if callable(obj):
        return
    # an unrecognized plan shape: refuse rather than risk a stale bake
    st.ok = False


def make_template(plan, n_params: int) -> Optional[PlanTemplate]:
    """Build a reuse template for ``plan``, or None when the plan is not
    provably value-agnostic (some parameter folded into an untraceable
    position, or a range/partition shape we cannot re-derive)."""
    if n_params <= 0:
        return None
    st = _Walk()
    _walk(plan, st)
    if not st.ok:
        return None
    if set(st.consts) != set(range(n_params)):
        # a parameter vanished (constant-folded / baked into a limit):
        # its value is burned into the plan — not reusable
        return None
    return PlanTemplate(plan, st.consts, st.rebuilders)


# -- copy-on-execute --------------------------------------------------------


def _clone(obj, memo: dict):
    """Structural clone of the plan graph: plan nodes, expressions and
    containers copy; atoms (``_ATOMS``) and pure callables (rebuild hooks,
    engine functions) share. The memo preserves ALIASING — the same
    Constant reachable from both ``pushed_conditions`` and ``range_conds``
    stays one object in the clone, which is what makes the rebuild hooks
    see the rebound parameter values."""
    if obj is None or isinstance(obj, _ATOMS):
        return obj
    oid = id(obj)
    got = memo.get(oid)
    if got is not None:
        return got
    if isinstance(obj, list):
        new: list = []
        memo[oid] = new
        new.extend(_clone(x, memo) for x in obj)
        return new
    if isinstance(obj, tuple):
        new = tuple(_clone(x, memo) for x in obj)
        memo[oid] = new
        return new
    if isinstance(obj, dict):
        nd: dict = {}
        memo[oid] = nd
        for k, v in obj.items():
            nd[k] = _clone(v, memo)
        return nd
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        cp = copy.copy(obj)  # shallow: non-field attrs (digest memos) ride along
        memo[oid] = cp
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name, None)
            if v is None or (callable(v) and not isinstance(v, Expression)):
                continue  # pure hooks shared; they read the clone's conds
            setattr(cp, f.name, _clone(v, memo))
        return cp
    # callables and anything make_template's walk vetted as shareable
    return obj


def instantiate(tmpl: PlanTemplate) -> PlanTemplate:
    """One execution's private plan instance: clone the template's plan
    graph and remap its parameter constants and rebuild nodes through the
    clone memo — one traversal, and a mapping that cannot silently diverge
    (an unreachable constant/node would raise, not drop a rebuilder). The
    shared template is never touched — rebinding the instance cannot race
    another session's execution of the same template."""
    memo: dict = {}
    plan2 = _clone(tmpl.plan, memo)
    consts = {
        idx: [memo[id(c)] for c in cs] for idx, cs in tmpl.param_consts.items()
    }
    rebuilders = [(fn, memo[id(node)]) for fn, node in tmpl.rebuilders]
    return PlanTemplate(plan2, consts, rebuilders)


def plan_fingerprint(plan) -> tuple:
    """Deterministic snapshot of every mutable leaf a rebind may touch —
    parameter constants, scan ranges, pruned partitions, index-merge paths —
    in traversal order. The plan-immutability audit compares a template's
    fingerprint before/after concurrent executions: equal fingerprints mean
    the shared bytes never changed."""
    out: list = []
    seen: set[int] = set()

    def go(obj):
        if obj is None or isinstance(obj, _ATOMS):
            return
        if id(obj) in seen:
            return
        seen.add(id(obj))
        if isinstance(obj, Constant):
            out.append(("const", obj.param_idx, repr(obj.value)))
            return
        if isinstance(obj, (list, tuple)):
            for x in obj:
                go(x)
            return
        if isinstance(obj, dict):
            for v in obj.values():
                go(v)
            return
        if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
            if isinstance(obj, (PhysTableReader, PhysIndexReader, PhysIndexLookUp)):
                out.append(("ranges", repr(getattr(obj, "ranges", None))))
            if isinstance(obj, PhysTableReader):
                parts = getattr(obj, "partitions", None)
                out.append(
                    ("partitions", repr([getattr(v, "id", v) for v in parts]) if parts is not None else "None")
                )
            if isinstance(obj, PhysIndexMerge):
                out.append(
                    ("paths", repr([(p[0], repr(p[1:])) for p in obj.paths]))
                )
            for f in dataclasses.fields(obj):
                v = getattr(obj, f.name, None)
                if callable(v) and not isinstance(v, Expression):
                    continue
                go(v)

    go(plan)
    return tuple(out)


def _plan_value(p):
    """A parameter's PLAN-TIME value: route through the same literal
    conversion the builder applied at template build (date → day number,
    datetime/timedelta → microseconds, bool → int). Assigning the raw
    Python value would desynchronize the cached plan from what a fresh
    bind would have produced."""
    from tidb_tpu.parser import ast
    from tidb_tpu.planner.builder import _literal_const

    return _literal_const(ast.Literal(p)).value


def rebind(tmpl: PlanTemplate, params: list) -> bool:
    """Point a plan INSTANCE's parameter constants at ``params`` and
    re-derive every dependent range/partition/path set. Callers hand this an
    :func:`instantiate` clone, never the shared cached template. False =
    this plan cannot serve these values (the caller must re-plan); the
    cached template stays structurally valid for values that keep the
    original derivation shape."""
    for idx, consts in tmpl.param_consts.items():
        v = _plan_value(params[idx])
        for c in consts:
            c.value = v
    for fn, node in tmpl.rebuilders:
        if not fn(node):
            return False
    return True
