"""Extension points (ref: pkg/extension + pkg/plugin — audit/auth plugin
hooks): extensions register callbacks observing connection and statement
events; the bundled AuditLogger is both the sample extension and the audit
log implementation."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class StmtEvent:
    time: float
    user: str
    db: str
    sql: str
    event: str  # "ok" | "error"
    error: str = ""
    duration_s: float = 0.0


@dataclass
class ConnEvent:
    time: float
    event: str  # "connected" | "rejected" | "disconnected"
    user: str
    host: str
    conn_id: int


class Extension:
    """Subclass and override the hooks you need (ref: extension.Manifest)."""

    name = "extension"

    def on_stmt_event(self, ev: StmtEvent) -> None:  # pragma: no cover
        pass

    def on_connection_event(self, ev: ConnEvent) -> None:  # pragma: no cover
        pass


class ExtensionRegistry:
    def __init__(self):
        self._exts: list[Extension] = []

    def register(self, ext: Extension) -> None:
        self._exts.append(ext)

    @property
    def have(self) -> bool:
        return bool(self._exts)

    def list(self) -> list[Extension]:
        return list(self._exts)

    def notify_stmt(self, ev: StmtEvent) -> None:
        for e in self._exts:
            try:
                e.on_stmt_event(ev)
            except Exception:
                _hook_error(e, "stmt")  # extensions never break queries

    def notify_conn(self, ev: ConnEvent) -> None:
        for e in self._exts:
            try:
                e.on_connection_event(ev)
            except Exception:
                _hook_error(e, "conn")


def _hook_error(ext: "Extension", hook: str) -> None:
    """A broken extension must not break queries, but its failures must be
    visible AND attributable: count per (extension, hook) so /metrics names
    the misbehaving plugin instead of it failing silently forever. (Label
    cardinality is the registered-extension set — bounded per process.)"""
    from tidb_tpu.utils import metrics as _m

    _m.EXT_HOOK_ERRORS.inc(ext=getattr(ext, "name", type(ext).__name__), hook=hook)


class AuditLogger(Extension):
    """Audit extension (ref: the enterprise audit plugin surface): ring of
    statement + connection events."""

    name = "audit_log"

    def __init__(self, capacity: int = 1024):
        from collections import deque

        self.stmt_log: "deque[StmtEvent]" = deque(maxlen=capacity)
        self.conn_log: "deque[ConnEvent]" = deque(maxlen=capacity)

    def on_stmt_event(self, ev: StmtEvent) -> None:
        self.stmt_log.append(ev)

    def on_connection_event(self, ev: ConnEvent) -> None:
        self.conn_log.append(ev)
