"""Table/index key layout (ref: pkg/tablecodec/tablecodec.go:50-51,111).

Layout (memcomparable, same shape as the reference so range semantics match):

- record key:  ``t`` + enc_int(table_id) + ``_r`` + enc_int(handle)
- index key:   ``t`` + enc_int(table_id) + ``_i`` + enc_int(index_id) + flagged datums
- meta keys live under the ``m`` prefix (tidb_tpu.catalog.meta)
"""

from __future__ import annotations

from tidb_tpu.kv.kv import KeyRange
from tidb_tpu.utils import codec

TABLE_PREFIX = b"t"
RECORD_SEP = b"_r"
INDEX_SEP = b"_i"

_RECORD_KEY_LEN = 1 + 8 + 2 + 8


def record_key(table_id: int, handle: int) -> bytes:
    return TABLE_PREFIX + codec.encode_int_raw(table_id) + RECORD_SEP + codec.encode_int_raw(handle)


def record_prefix(table_id: int) -> bytes:
    return TABLE_PREFIX + codec.encode_int_raw(table_id) + RECORD_SEP


def table_prefix(table_id: int) -> bytes:
    return TABLE_PREFIX + codec.encode_int_raw(table_id)


def decode_record_key(key: bytes) -> tuple[int, int]:
    """→ (table_id, handle). Raises on non-record keys."""
    if len(key) != _RECORD_KEY_LEN or key[:1] != TABLE_PREFIX or key[9:11] != RECORD_SEP:
        raise ValueError(f"not a record key: {key!r}")
    return codec.decode_int_raw(key, 1), codec.decode_int_raw(key, 11)


def is_record_key(key: bytes) -> bool:
    return len(key) == _RECORD_KEY_LEN and key[:1] == TABLE_PREFIX and key[9:11] == RECORD_SEP


def table_id_of(key: bytes) -> int:
    """table_id of ANY table-space key (record, index, or bare prefix);
    -1 for keys outside the ``t`` keyspace (meta, election, placement)."""
    if key[:1] != TABLE_PREFIX or len(key) < 9:
        return -1
    return codec.decode_int_raw(key, 1)


def record_range(table_id: int) -> KeyRange:
    """Full-table scan range: [t{id}_r, t{id}_s)."""
    p = record_prefix(table_id)
    return KeyRange(p, p[:-1] + bytes([p[-1] + 1]))


def handle_range(table_id: int, lo: int | None, hi: int | None) -> KeyRange:
    """Range over handles [lo, hi] inclusive (None = unbounded)."""
    full = record_range(table_id)
    start = record_key(table_id, lo) if lo is not None else full.start
    end = record_key(table_id, hi + 1) if hi is not None else full.end
    return KeyRange(start, end)


def range_to_handles(kr: KeyRange, table_id: int) -> tuple[int, int]:
    """Project a key range onto handle space → [lo, hi) over int64 handles,
    saturating at the int64 bounds (a row at handle INT64_MAX is not
    addressable by a half-open int64 range — the autoid allocator never
    hands it out, matching the reference's IntHandle edge)."""
    p = record_prefix(table_id)
    i64_max = 2**63 - 1

    def project(k: bytes) -> int:
        # smallest handle whose record key is >= k, saturated
        if k <= p:
            return -(2**63)
        if not k.startswith(p):
            return i64_max  # k is past this table's record space
        body = k[len(p) :]
        if len(body) >= 8:
            h = codec.decode_int_raw(body[:8])
            if len(body) > 8:  # key extends past the handle → next handle up
                h = min(h + 1, i64_max)
            return h
        return codec.decode_int_raw(body + b"\x00" * (8 - len(body)))

    return project(kr.start), project(kr.end)


def index_key(table_id: int, index_id: int, encoded_values: bytes, handle: int | None = None) -> bytes:
    """Non-unique indexes append the handle to make keys unique; unique
    indexes omit it (handle lives in the value)."""
    k = TABLE_PREFIX + codec.encode_int_raw(table_id) + INDEX_SEP + codec.encode_int_raw(index_id) + encoded_values
    if handle is not None:
        k += codec.encode_int_raw(handle)
    return k


def index_prefix(table_id: int, index_id: int) -> bytes:
    return TABLE_PREFIX + codec.encode_int_raw(table_id) + INDEX_SEP + codec.encode_int_raw(index_id)


def index_range(table_id: int, index_id: int, low: bytes = b"", high: bytes | None = None) -> KeyRange:
    """Range over encoded index values [low, high); None high = whole index."""
    p = index_prefix(table_id, index_id)
    if high is None:
        return KeyRange(p + low, p + b"\xff" * 9 + b"\x00")  # past any flagged datum
    return KeyRange(p + low, p + high)
