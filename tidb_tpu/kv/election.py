"""Quorum-replicated owner election with fenced leases — the PD/etcd analog.

Reference parity: the reference keeps ``owner.Manager`` pluggable over an
etcd campaign (pkg/owner/manager.go:49) precisely so a real deployment swaps
in a quorum backend. This module IS that backend for the sharded fleet:
lease/term state replicates to a **majority of store shards** instead of
pinning to shard 0, so losing any single shard — including shard 0 — no
longer halts the control plane, and split-brain stays impossible by
construction.

Protocol (a fenced-lease election, the etcd-lease/raft-term hybrid every
PD-shaped control plane runs):

- Each store shard hosts an :class:`ElectionReplica`: per key it records
  ``(term, owner_id, deadline)``. The **term is the fencing token** — it
  increases monotonically on every ownership grant and never regresses.
- Replica accept rule: a proposal is accepted iff its term is HIGHER than
  the local term, or it matches the local term AND comes from the recorded
  owner (a renewal/vacate). First writer wins within a term; two candidates
  proposing the same new term can therefore never both assemble a majority
  (any two majorities intersect, and the shared replica accepted only one).
- ``campaign`` reads a majority, takes the highest-term record as truth,
  and only proposes ``term+1`` when that record is vacant or its lease has
  expired; while a lease is live, the client rule alone keeps competitors
  out, and past expiry the per-replica first-wins rule decides the race.
- ``renew`` (a campaign carrying the fencing token) re-proposes the SAME
  term: accepted only where the proposer is still the recorded owner, so a
  deposed owner's renewals die at every replica that has seen the new term
  — majority acceptance is impossible once a successor was elected.
- A minority partition can neither grant nor refresh a lease: every verb
  needs a majority of replicas to answer, and fewer surfaces
  ``ConnectionError`` (the etcd-quorum-loss behavior — owners keep their
  last verdict until the lease runs out, then self-fence).
- Dead shards are skipped under the existing retry layer (each store's own
  boRPC Backoffer bounds the probe); replicas that return behind the fleet
  are **read-repaired** to the highest-term record during the next sweep.

Deadlines are wall-clock (``time.time()``) because they cross process
boundaries; the same-host clock assumption is the one the fleet TSO already
documents (kv/sharded.py module docstring). An owner whose lease expired
must re-campaign at a fresh term — same-term renewal past expiry is exactly
the window where a competitor may already be assembling a majority.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional

from tidb_tpu.utils import eventlog as _ev
from tidb_tpu.utils.backoff import Backoffer, BackoffExhausted, boStoreDown

# campaign outcomes worth waking a reader: a grant and a fencing are state
# transitions; renewals/losses are steady-state churn and stay at debug
_OUTCOME_LEVEL = {"won": _ev.INFO, "fenced": _ev.WARN}


def _campaign_event(outcome: str, key: str, node_id: str, term: int) -> None:
    lvl = _OUTCOME_LEVEL.get(outcome, _ev.DEBUG)
    lg = _ev.on(lvl)
    if lg is not None:
        lg.emit(lvl, "election", outcome, key=key, node=node_id, term=term)


@dataclass
class _Record:
    term: int = 0
    owner_id: Optional[str] = None
    deadline: float = 0.0  # wall-clock epoch seconds; 0 = vacated


class ElectionReplica:
    """One shard's share of the election keyspace (the etcd-member role).

    Deliberately dumb: it enforces only the term/ownership accept rule and
    stores what it accepted. All lease reasoning (expiry, who may bump the
    term) lives client-side in :class:`QuorumElection` — replicas must stay
    symmetric so a majority of ANY of them reconstructs the truth."""

    def __init__(self):
        self._mu = threading.Lock()
        self._records: dict[str, _Record] = {}

    def propose(self, key: str, node_id: str, term: int, deadline: float) -> tuple[bool, int]:
        """→ (accepted, replica's current term). Accept iff ``term`` beats
        the local term, or equals it and ``node_id`` is the recorded owner
        (renew/vacate). Idempotent: re-proposing an accepted record
        re-accepts, so the wire verb is replay-safe."""
        with self._mu:
            rec = self._records.setdefault(key, _Record())
            if term > rec.term or (term == rec.term and node_id == rec.owner_id):
                rec.term = term
                rec.owner_id = node_id
                rec.deadline = deadline
                return True, rec.term
            return False, rec.term

    def read(self, key: str) -> tuple[int, Optional[str], float]:
        with self._mu:
            rec = self._records.get(key)
            return (rec.term, rec.owner_id, rec.deadline) if rec else (0, None, 0.0)


class QuorumElection:
    """Client half: campaign/renew/resign as quorum writes, owner reads
    resolved from a majority (highest term wins). Holds a REFERENCE to the
    fleet's store list, so authority changes (tests swapping a dead store
    back in) are visible immediately."""

    def __init__(self, stores: list, lease_s: float = 10.0, budget_ms: float = 2000.0):
        self.stores = stores
        self.lease_s = lease_s
        self._budget_ms = budget_ms
        self._mu = threading.Lock()
        # highest term this CLIENT has observed per key — the local
        # monotonicity witness (a regression here would mean split-brain)
        self._seen_terms: dict[str, int] = {}
        # dead-shard cooldowns: shard index → (skip_until, cooldown_s).
        # Probing a dead REMOTE shard burns its whole boRPC reconnect budget
        # (seconds at production defaults), so without a cooldown every
        # keepalive tick would pay it and a renewal could outlast its own
        # lease. Cooldowns back off exponentially (1 s → 15 s), clear on the
        # first successful verb, and are IGNORED the moment a sweep falls
        # below quorum — a possibly-alive shard is always re-probed before
        # this client reports the keyspace unreachable.
        self._down_mu = threading.Lock()
        self._down: dict[int, tuple[float, float]] = {}
        # (key, node_id) → the term of the node's last successful grant or
        # renewal: lets the lease holder learn its fencing token without
        # paying a second majority sweep right after campaigning
        self._granted: dict[tuple[str, str], int] = {}
        # returning-replica anti-entropy hook (``catchup_fn(shard_idx)``):
        # when a shard that was marked down answers again, the hook replays
        # the majority's records onto it BEFORE its reads count toward
        # quorum — a killed-and-restarted-EMPTY replica otherwise rejoins
        # blank and is only read-repaired lazily, key by key (the carried
        # PR-2 gap). ShardedStore installs a replayer covering the meta
        # keyspace, election records, and placement bindings. Best-effort:
        # a failed catch-up leaves the shard to lazy read-repair.
        self.catchup_fn = None
        self._catchup_busy: set[int] = set()

    @property
    def quorum(self) -> int:
        return len(self.stores) // 2 + 1

    # -- dead-shard cooldown -------------------------------------------------
    def _skip(self, i: int) -> bool:
        with self._down_mu:
            ent = self._down.get(i)
            return ent is not None and ent[0] > time.monotonic()

    def _mark_down(self, i: int) -> None:
        with self._down_mu:
            prev = self._down.get(i)
            cd = min(prev[1] * 2.0, 15.0) if prev else 1.0
            self._down[i] = (time.monotonic() + cd, cd)

    def _mark_up(self, i: int) -> None:
        with self._down_mu:
            self._down.pop(i, None)

    def _any_cooldown(self) -> bool:
        with self._down_mu:
            return bool(self._down)

    def _clear_cooldowns(self) -> None:
        # zero the skip deadlines but KEEP the entries: membership in _down
        # is also the "this shard is RETURNING" witness the anti-entropy
        # catch-up keys off — dropping it here would let a restarted-empty
        # shard rejoin without the replay (only _mark_up, after a
        # successful contact ran the catch-up gate, removes an entry)
        with self._down_mu:
            self._down = {i: (0.0, cd) for i, (_, cd) in self._down.items()}

    # -- quorum plumbing ----------------------------------------------------
    def _run_catchup(self, i: int) -> None:
        """Fire the returning-replica hook once per return (guarded against
        re-entry: the hook itself runs majority reads through this client)."""
        with self._down_mu:
            if i in self._catchup_busy:
                return
            self._catchup_busy.add(i)
        try:
            self.catchup_fn(i)
        # the shard flapped again mid-catch-up; lazy read-repair still
        # covers every key the replay missed
        except Exception:  # graftcheck: off=except-swallow
            pass
        finally:
            with self._down_mu:
                self._catchup_busy.discard(i)

    def _sweep_reads(self, key: str):
        """One pass over every replica not in cooldown → ([(idx, (term,
        owner, deadline))], last ConnectionError). Dead shards are skipped;
        each store's own Backoffer already bounded the probe. A shard seen
        DOWN on an earlier sweep that answers now gets the catch-up hook
        replayed onto it (then re-read) before its vote counts — a
        restarted-empty replica must not vote its blank keyspace."""
        out, last = [], None
        for i, st in enumerate(self.stores):
            if self._skip(i):
                continue
            returning = False
            with self._down_mu:
                returning = i in self._down and i not in self._catchup_busy
            try:
                rec = st.election_read(key)
                if returning and self.catchup_fn is not None:
                    self._run_catchup(i)
                    rec = st.election_read(key)  # post-replay state votes
            except ConnectionError as e:
                self._mark_down(i)
                last = e
                continue
            self._mark_up(i)
            out.append((i, rec))
        return out, last

    @staticmethod
    def _resolve(reads, quorum: int):
        """Pick the authoritative record from a read set: highest term, and
        WITHIN that term the owner holding a majority of replicas, if any.
        A same-term split vote (a losing candidate's straggler record on a
        minority) must not outrank the majority-granted record — resolving
        by deadline alone would misreport the owner and fence the legitimate
        winner. With no majority owner visible (partial sweep of a split
        term) the longest deadline wins: the conservative direction, since
        overestimating a lease only delays the next takeover."""
        maxterm = max(r[0] for _, r in reads)
        top = [r for _, r in reads if r[0] == maxterm]
        by_owner: dict = {}
        for r in top:
            by_owner.setdefault(r[1], []).append(r)
        for owner, recs in by_owner.items():
            if owner is not None and len(recs) >= quorum:
                return max(recs, key=lambda r: r[2])
        return max(top, key=lambda r: r[2])

    def _read_majority(self, key: str):
        """Read the key from a majority (backing off on below-quorum sweeps
        until the budget runs out — sweep wall time is charged against the
        budget, since each dead remote shard burns its own reconnect budget
        before surfacing), read-repair stragglers, and return the resolved
        record as ``(term, owner, deadline)``."""
        from tidb_tpu.utils import metrics as _m

        bo = Backoffer(budget_ms=self._budget_ms)
        swept_ms = 0.0
        cleared = False
        while True:
            t0 = time.monotonic()
            reads, last = self._sweep_reads(key)
            swept_ms += (time.monotonic() - t0) * 1000.0
            if len(reads) >= self.quorum:
                break
            if swept_ms >= bo.remaining_ms():
                raise ConnectionError(
                    f"election keyspace below quorum for {key!r}: "
                    f"{len(reads)}/{len(self.stores)} replicas reachable "
                    f"(need {self.quorum}); cannot grant or refresh a lease"
                ) from last
            if not cleared and self._any_cooldown():
                # shards in cooldown may be alive — re-probe everything once
                # before sleeping or giving up
                cleared = True
                self._clear_cooldowns()
                continue
            try:
                bo.backoff(boStoreDown, last)
            except BackoffExhausted:
                raise ConnectionError(
                    f"election keyspace below quorum for {key!r}: "
                    f"{len(reads)}/{len(self.stores)} replicas reachable "
                    f"(need {self.quorum}); cannot grant or refresh a lease"
                ) from last
        wterm, wowner, wdeadline = self._resolve(reads, self.quorum)
        # read repair: a replica that was down during earlier grants answers
        # with a stale term — push the resolved record back (best-effort; its
        # accept rule takes the higher term)
        if wterm > 0 and wowner is not None:
            for i, (term, _, _) in reads:
                if term < wterm:
                    try:
                        self.stores[i].election_propose(key, wowner, wterm, wdeadline)
                        _m.ELECTION_CAMPAIGN.inc(key=key, outcome="repair")
                    except ConnectionError:
                        self._mark_down(i)
        self._note_term(key, wterm)
        return wterm, wowner, wdeadline

    def _propose_majority(self, key: str, node_id: str, term: int, deadline: float) -> bool:
        """Propose to every replica; True iff a majority accepted. Fewer
        than a majority REACHABLE raises (a minority partition must not
        believe it refreshed a lease it can no longer defend). Shards in
        cooldown are skipped — but re-probed once before giving up."""
        for attempt in range(2):
            acks, reached, last = 0, 0, None
            for i, st in enumerate(self.stores):
                if self._skip(i):
                    continue
                with self._down_mu:
                    returning = i in self._down and i not in self._catchup_busy
                try:
                    ok, _ = st.election_propose(key, node_id, term, deadline)
                except ConnectionError as e:
                    self._mark_down(i)
                    last = e
                    continue
                if returning and self.catchup_fn is not None:
                    # a returning replica whose first contact is a PROPOSE
                    # still gets the anti-entropy replay before _mark_up
                    # erases the returning witness — its ack for THIS record
                    # already stands, but its blank keyspace must not vote
                    # in later read sweeps un-caught-up
                    self._run_catchup(i)
                self._mark_up(i)
                reached += 1
                if ok:
                    acks += 1
            if reached >= self.quorum:
                break
            if attempt == 0 and self._any_cooldown():
                self._clear_cooldowns()
                continue
            raise ConnectionError(
                f"election keyspace below quorum for {key!r}: "
                f"{reached}/{len(self.stores)} replicas reachable (need {self.quorum})"
            ) from last
        if acks >= self.quorum:
            self._note_term(key, term)
            with self._mu:
                self._granted[(key, node_id)] = term
            return True
        return False

    def granted_term(self, key: str, node_id: str) -> Optional[int]:
        """The fencing token of ``node_id``'s last successful grant/renewal
        of ``key`` — locally cached, no quorum sweep. None before any grant."""
        with self._mu:
            return self._granted.get((key, node_id))

    def _note_term(self, key: str, term: int) -> None:
        from tidb_tpu.utils import metrics as _m

        with self._mu:
            prev = self._seen_terms.get(key, 0)
            if term > prev:
                self._seen_terms[key] = term
        if term > prev:
            _m.ELECTION_TERM.set(term, key=key)

    # -- election surface ---------------------------------------------------
    def campaign(
        self,
        key: str,
        node_id: str,
        lease_s: Optional[float] = None,
        term: Optional[int] = None,
    ) -> bool:
        """Try to become (or stay) the owner of ``key``.

        With ``term`` given this is a FENCED RENEWAL: it refreshes the lease
        only while the fleet's highest term still equals ``term`` and
        ``node_id`` is its owner — a deposed owner observably fails here
        instead of silently double-running. Without ``term`` it campaigns:
        renewing a live lease we already hold at the current term, or
        proposing ``term+1`` when the key is vacant/expired."""
        from tidb_tpu.utils import metrics as _m

        lease = lease_s if lease_s is not None else self.lease_s
        wterm, wowner, wdeadline = self._read_majority(key)
        now = time.time()
        if term is not None:
            # renewal under the fencing token: any term movement = deposed
            if wterm != term or wowner != node_id or wdeadline <= now:
                _m.ELECTION_CAMPAIGN.inc(key=key, outcome="fenced")
                _campaign_event("fenced", key, node_id, wterm)
                return False
            ok = self._propose_majority(key, node_id, term, now + lease)
            _m.ELECTION_CAMPAIGN.inc(key=key, outcome="renewed" if ok else "fenced")
            _campaign_event("renewed" if ok else "fenced", key, node_id, term)
            return ok
        if wowner == node_id and wterm > 0 and wdeadline > now:
            # still ours and still live: refresh at the same term
            ok = self._propose_majority(key, node_id, wterm, now + lease)
            _m.ELECTION_CAMPAIGN.inc(key=key, outcome="renewed" if ok else "lost")
            _campaign_event("renewed" if ok else "lost", key, node_id, wterm)
            return ok
        if wowner is not None and wowner != node_id and wdeadline > now:
            _m.ELECTION_CAMPAIGN.inc(key=key, outcome="lost")
            _campaign_event("lost", key, node_id, wterm)
            return False  # live lease elsewhere: back off until it expires
        # vacant / expired / our own expired lease: the fencing token bumps.
        # (An expired lease we used to hold gets a NEW term too — same-term
        # re-grant past expiry is the split-brain window, see module doc.)
        ok = self._propose_majority(key, node_id, wterm + 1, now + lease)
        _m.ELECTION_CAMPAIGN.inc(key=key, outcome="won" if ok else "lost")
        _campaign_event("won" if ok else "lost", key, node_id, wterm + 1 if ok else wterm)
        if ok and wowner is not None and wowner != node_id:
            _m.ELECTION_FAILOVER.inc(key=key)
            lg = _ev.on(_ev.WARN)
            if lg is not None:
                lg.emit(
                    _ev.WARN,
                    "election",
                    "failover",
                    key=key,
                    node=node_id,
                    prev_owner=wowner,
                    term=wterm + 1,
                )
        return ok

    def owner(self, key: str) -> Optional[str]:
        term, owner, deadline = self._read_majority(key)
        if term == 0 or owner is None or deadline <= time.time():
            return None
        return owner

    def term(self, key: str) -> int:
        """The current fencing token for ``key`` (majority-resolved)."""
        return self._read_majority(key)[0]

    def resign(self, key: str, node_id: str) -> None:
        """Vacate the lease with a TOMBSTONE at ``term+1`` (owner recorded,
        deadline 0): the next campaigner grants immediately, no lease wait.
        The tombstone burns a term on purpose — a same-term vacate that
        reached only a minority of replicas would be invisible to majority
        reads (the same-term live record wins the highest-(term, deadline)
        resolution), leaving a ghost lease until expiry; the higher-term
        tombstone dominates every stale record the moment a majority has it,
        and read repair spreads it to the rest."""
        wterm, wowner, _ = self._read_majority(key)
        if wowner != node_id or wterm == 0:
            return
        try:
            self._propose_majority(key, node_id, wterm + 1, 0.0)
        except ConnectionError:
            pass  # below quorum: the lease will expire on its own

    def snapshot(self) -> dict:
        """Observability: {key: {owner, term, lease_remaining_s}} for every
        key this client has campaigned or resolved (status server surface)."""
        with self._mu:
            keys = list(self._seen_terms)
        out = {}
        now = time.time()
        for key in keys:
            try:
                term, owner, deadline = self._read_majority(key)
            except ConnectionError as e:
                out[key] = {"error": str(e)}
                continue
            live = deadline > now
            out[key] = {
                "owner": owner if live else None,
                "term": term,
                "lease_remaining_s": round(max(0.0, deadline - now), 3) if live else 0.0,
            }
        return out
