"""Owner election (ref: pkg/owner/manager.go:49 — etcd campaign-based
singleton election for DDL/stats owners).

In the embedded single-process deployment the election is trivially local,
but the seam matters: every would-be owner (DDL worker, stats owner, TTL
coordinator) campaigns through this interface, so a multi-process build
swaps the backend (etcd/raft lease) without touching the callers — exactly
how the reference keeps `owner.Manager` pluggable."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class _Election:
    owner_id: Optional[str] = None
    lease_deadline: float = 0.0
    term: int = 0
    lease_s: Optional[float] = None  # per-election override of the default


class OwnerManager:
    """Campaign/resign/retire API compatible with the reference's usage."""

    def __init__(self, lease_s: float = 10.0):
        self._mu = threading.Lock()
        self._elections: dict[str, _Election] = {}
        self.lease_s = lease_s

    def campaign(
        self,
        key: str,
        node_id: str,
        lease_s: Optional[float] = None,
        term: Optional[int] = None,
    ) -> bool:
        """Try to become the owner of ``key``; re-campaigning refreshes the
        lease. ``lease_s`` overrides the lease duration for THIS election
        only (other keys keep the manager default). Returns True when
        ``node_id`` is (now) the owner.

        With ``term`` given this is a FENCED RENEWAL (the term-checked grant
        path): it refreshes only while ``node_id`` still owns the key at
        exactly that term — after a failover bumped the term, the deposed
        owner's renewals are rejected even once the new lease expires, so a
        stale owner can never silently resume (kv/election.py runs the same
        rule against the quorum keyspace)."""
        now = time.monotonic()
        with self._mu:
            el = self._elections.setdefault(key, _Election())
            if lease_s is not None:
                el.lease_s = lease_s
            if term is not None:
                if el.owner_id != node_id or el.term != term or now > el.lease_deadline:
                    return False
                el.lease_deadline = now + (el.lease_s if el.lease_s is not None else self.lease_s)
                return True
            if el.owner_id is None or el.owner_id == node_id or now > el.lease_deadline:
                if el.owner_id != node_id:
                    el.term += 1
                el.owner_id = node_id
                el.lease_deadline = now + (el.lease_s if el.lease_s is not None else self.lease_s)
                return True
            return False

    def is_owner(self, key: str, node_id: str) -> bool:
        with self._mu:
            el = self._elections.get(key)
            return (
                el is not None
                and el.owner_id == node_id
                and time.monotonic() <= el.lease_deadline
            )

    def owner(self, key: str) -> Optional[str]:
        with self._mu:
            el = self._elections.get(key)
            if el is None or time.monotonic() > el.lease_deadline:
                return None
            return el.owner_id

    def resign(self, key: str, node_id: str) -> None:
        with self._mu:
            el = self._elections.get(key)
            if el is not None and el.owner_id == node_id:
                el.owner_id = None
                el.lease_deadline = 0.0

    def term(self, key: str) -> int:
        with self._mu:
            el = self._elections.get(key)
            return el.term if el else 0

    def snapshot(self) -> dict:
        """Observability: {key: {owner, term, lease_remaining_s}} (the same
        shape QuorumElection.snapshot() serves on the status port)."""
        now = time.monotonic()
        with self._mu:
            out = {}
            for key, el in self._elections.items():
                live = el.owner_id is not None and now <= el.lease_deadline
                out[key] = {
                    "owner": el.owner_id if live else None,
                    "term": el.term,
                    "lease_remaining_s": round(max(0.0, el.lease_deadline - now), 3) if live else 0.0,
                }
            return out
