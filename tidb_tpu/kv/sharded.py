"""Multi-store-server topology: N storage processes, one kv.Storage.

Reference parity: the region-sharded TiKV fleet behind one SQL layer — PD
maps key ranges to store owners (pkg/store/copr/coprocessor.go:334 splits
cop tasks per region and the region cache routes each to its store), 2PC
spans stores with a single TSO authority, and MPP tasks are scheduled onto
the engine nodes that own the data (pkg/planner/core/fragment.go:116).

Placement here is TABLE-granular: every key routes by its table id (meta /
non-table keys live on shard 0, the PD-analog authority), so one query's cop
fan-out crosses store processes while each range still has exactly one
owner. Timestamps come from shard 0's wall-clock TSO; the other shards'
oracles run on the same physical-time layout ((ms << 18) | logical,
kv/kv.py:87), so same-host shards are mutually consistent to clock skew —
the deployment assumption is documented PD behavior, not an accident.

MPP placement rule: a gather is dispatched to the ONE store owning every
table it reads; a gather spanning owners raises MPPRetryExhausted and the
session re-plans without MPP (cop scans + host join), mirroring the
reference's fallback when no engine can serve the fragment set.

Percolator across shards: prewrite/commit/rollback group keys by owner; a
stuck lock resolves by consulting the PRIMARY key's owner (check_txn_status
there) and then committing/rolling back the lock on its own owner — the
cross-store resolve path of pkg/store/mockstore/unistore/tikv/mvcc.go.

Meta replication: the "m"/system keyspace (catalog, DDL jobs, sysvars)
REPLICATES to every shard on write and reads authoritatively from shard 0 —
the storage processes resolve MPP gathers against their own catalog copy,
exactly how TiFlash keeps a synced schema snapshot per engine node (ref:
the schema-sync the coprocessor's schema-version check relies on).
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

from tidb_tpu.kv import tablecodec
from tidb_tpu.kv.kv import (
    KeyRange,
    RegionError,
    Request,
    RequestType,
    TxnAbortedError,
    UndeterminedError,
)
from tidb_tpu.kv.memstore import Lock, Mutation
from tidb_tpu.utils.backoff import Backoffer, BackoffExhausted, boRegionMiss, boStoreDown


class _FailoverTSO:
    """TSO authority with owner re-resolution: timestamps come from the
    current authority shard and fail over with it (the shards' oracles share
    the (ms << 18) | logical wall-clock layout — see the module docstring's
    deployment assumption, which is what makes the handoff safe)."""

    def __init__(self, store: "ShardedStore"):
        self._store = store

    def ts(self) -> int:
        return self._store._monotonic_ts(lambda st: st.tso.ts(), kind="tso")


class _FailoverDetector:
    def __init__(self, store: "ShardedStore"):
        self._store = store

    def clean_up(self, start_ts: int) -> None:
        self._store._authority_call(lambda st: st.detector.clean_up(start_ts), kind="detector")


class _ShardedPD:
    """Region lookup across shards: each owner answers for its own ranges;
    region ids are namespaced by shard AND the table's placement epoch so
    two stores' region 1s never collide — and a MIGRATED region's id never
    collides with the old owner's cached copy of it (fresh ids minted from
    the epoch, not just bit-packed shard indices: a consumer keying caches
    or routing state off the namespaced id sees a new identity after every
    move, ref: PD bumping RegionEpoch.version on transfer)."""

    _SHARD_BITS = 48
    _EPOCH_BITS = 56

    def __init__(self, store: "ShardedStore"):
        self._store = store

    def _mint(self, region_id: int, si: int, krs) -> int:
        epoch = 0
        if krs:
            k = krs[0].start
            if ShardedStore.is_table_key(k):
                from tidb_tpu.utils import codec

                epoch = self._store.placement_epoch(codec.decode_int_raw(k, 1))
        return region_id | (si << self._SHARD_BITS) | (epoch << self._EPOCH_BITS)

    def regions_in_ranges(self, ranges: Sequence[KeyRange]):
        import copy as _copy

        out = []
        for si, sub in self._store.group_ranges(ranges):
            for region, krs in self._store.stores[si].pd.regions_in_ranges(sub):
                # namespace on a COPY: in-process stores hand out their live
                # Region objects, and mutating those would corrupt the
                # store's own metadata (cache keys, plan-cache versions)
                r2 = _copy.copy(region)
                r2.region_id = self._mint(region.region_id, si, krs)
                out.append((r2, krs))
        return out


class _ShardedSnapshot:
    def __init__(self, store: "ShardedStore", ts: int):
        self._store = store
        self.read_ts = ts

    def get(self, key: bytes) -> Optional[bytes]:
        if not ShardedStore.is_table_key(key):
            # meta keyspace: any live replica can answer (replicated catalog)
            return self._store._authority_call(
                lambda st: st.get_snapshot(self.read_ts).get(key)
            )
        # placement-routed read: a fenced ex-owner (the region moved) answers
        # RegionError → re-resolve placement and retry at the new owner
        return self._store._routed(
            "snap_get",
            lambda: self._store.store_for_key(key).get_snapshot(self.read_ts).get(key),
        )

    def scan(self, kr: KeyRange, limit: int = 2**63, reverse: bool = False):
        if not ShardedStore.is_table_key(kr.start):
            # meta keyspace reads come from the authority, failing over to a
            # surviving replica on store-down
            return self._store._authority_call(
                lambda st: st.get_snapshot(self.read_ts).scan(kr, limit=limit, reverse=reverse)
            )

        def run():
            one = self._store.single_owner(kr)
            if one is not None:
                # the whole range lives on one owner (the common per-table
                # scan): no reason to pay N-1 always-empty fan-out RPCs
                return self._store.stores[one].get_snapshot(self.read_ts).scan(
                    kr, limit=limit, reverse=reverse
                )
            outs = []
            for s in self._store.stores:
                outs.extend(s.get_snapshot(self.read_ts).scan(kr, limit=limit, reverse=reverse))
            outs.sort(key=lambda kv: kv[0], reverse=reverse)
            return outs[:limit] if limit < 2**62 else outs

        return self._store._routed("snap_scan", run)

    def scan_record_rows(self, kr: KeyRange):
        """Record scan feeding the coordinator's columnar cache — the hybrid
        shards × devices MPP path reads every owner from the SQL layer. A
        region's range lives on exactly one owner, so this routes (no
        fan-out); in-process members answer natively, wire members fall back
        to a visible-pairs scan packed into BulkRows (their stable rows ride
        the scan, and :meth:`ShardedStore.stable_parts` reports none for
        them, so nothing double-counts)."""

        def run():
            si = self._store.shard_of_key(kr.start)
            snap = self._store.stores[si].get_snapshot(self.read_ts)
            native = getattr(snap, "scan_record_rows", None)
            if native is not None:
                return native(kr)
            import numpy as np

            from tidb_tpu.kv import tablecodec
            from tidb_tpu.kv.memstore import BulkRows

            handles, chunks, starts, ends = [], [], [], []
            off = 0
            for k, v in snap.scan(kr):
                if not tablecodec.is_record_key(k):
                    continue
                handles.append(tablecodec.decode_record_key(k)[1])
                chunks.append(v)
                starts.append(off)
                off += len(v)
                ends.append(off)
            n = len(handles)
            return BulkRows(
                np.asarray(handles, dtype=np.int64),
                np.asarray(starts, dtype=np.int64),
                np.asarray(ends, dtype=np.int64),
                b"".join(chunks),
                put_ts=np.full(n, self.read_ts, dtype=np.int64),
            )

        return self._store._routed("snap_scan_rows", run)


class _ShardedCopClient:
    """Cop fan-out per range OWNER: consecutive same-owner ranges form one
    sub-request served by that store's own cop client; segment results are
    emitted in range order so keep-order semantics survive the split.

    Placement-aware: a RegionError (the fenced ex-owner of a MOVED table
    refusing the scan) or a dead owner re-resolves placement and
    re-dispatches the segment's ranges to whoever owns them now — the cop
    half of the boRegionMiss re-route. Both clients raise the fence verdict
    EAGERLY in send() (region resolution runs before any task), so the
    re-route fires before a single result streams; the rare mid-stream move
    (results already yielded when the error lands) surfaces typed instead —
    a silent retry there would duplicate rows. The happy path keeps the
    pre-placement streaming + cancel semantics (a satisfied LIMIT still
    cancels pending region tasks)."""

    def __init__(self, store: "ShardedStore"):
        self.store = store

    def _dispatch(self, req: Request, si: int, sub, subs: list):
        """Start one segment's sub-request; a synchronous refusal (the
        eager fence verdict) comes back as the exception VALUE so the
        consumer's re-route handler deals with it at consumption time."""
        try:
            resp = self.store.stores[si].get_client().send(self._sub(req, sub))
            subs.append(resp)
            return resp
        except (RegionError, ConnectionError) as e:
            return e

    def _consume(self, req: Request, si: int, sub, attempt, bo: Backoffer, subs: list):
        """Drain one segment's CopResults (a generator), re-routing on
        placement moves while nothing has streamed yet."""
        from tidb_tpu.utils import metrics as _m

        while True:
            yielded = False
            try:
                if isinstance(attempt, Exception):
                    raise attempt
                for res in attempt:
                    yielded = True
                    yield res
                return
            except (RegionError, ConnectionError) as e:
                if yielded:
                    raise  # mid-stream move: typed, never silently re-read
                moved = self.store.placement_refresh()
                if isinstance(e, ConnectionError) and not moved:
                    raise  # dead owner and the region did not move: typed
                try:
                    bo.backoff(boRegionMiss, e)
                except BackoffExhausted:
                    raise e from None
                _m.PLACEMENT_REROUTE.inc(verb="cop")
                regrouped = self.store.group_ranges(sub, consecutive=True)
                if len(regrouped) == 1:
                    si, sub = regrouped[0]
                    attempt = self._dispatch(req, si, sub, subs)
                    continue
                # the refresh split this segment across owners
                for si2, sub2 in regrouped:
                    yield from self._consume(
                        req, si2, sub2, self._dispatch(req, si2, sub2, subs), bo, subs
                    )
                return

    def send(self, req: Request):
        from tidb_tpu.copr.client import CopResponse

        if req.tp != RequestType.DAG:
            raise ValueError(f"sharded cop client handles DAG requests only, got {req.tp}")
        segments = self.store.group_ranges(req.ranges, consecutive=True)
        bo = Backoffer(budget_ms=2000)
        subs: list = []  # live sub-responses, for early-exit cancellation

        def cancel():
            for r in subs:
                r.close()

        # every segment dispatches EAGERLY (the stores start their cop work
        # concurrently, as before placement); results drain in range order
        started = [(si, sub, self._dispatch(req, si, sub, subs)) for si, sub in segments]

        def gen():
            try:
                for si, sub, attempt in started:
                    yield from self._consume(req, si, sub, attempt, bo, subs)
            finally:
                cancel()

        return CopResponse(gen(), cancel)

    @staticmethod
    def _sub(req: Request, ranges) -> Request:
        import copy as _copy

        sub = _copy.copy(req)
        sub.ranges = list(ranges)
        return sub


class ShardedStore:
    """kv.Storage over N store servers with table-granular placement."""

    def __init__(self, stores: list, placement: Optional[dict] = None):
        if not stores:
            raise ValueError("ShardedStore needs at least one store")
        self.stores = list(stores)
        # explicit table_id → shard index; unlisted tables hash by id
        self.placement = dict(placement or {})
        self.nonce = "sharded(" + ",".join(s.nonce for s in self.stores) + ")"
        # per-store cop-digest rings for IN-PROCESS members: wire members
        # record cop tasks into their server's StmtSummary, but embedded
        # MemStores share one process registry, so the balancer's hot-table
        # boost had no per-store signal. Each member gets its own ring; the
        # embedded cop client records into it and sys_report ships it in the
        # "statements" section exactly like a store server would.
        from tidb_tpu.utils.stmtsummary import StmtSummary as _SS

        for st in self.stores:
            if not hasattr(st, "host") and getattr(st, "cop_ring", None) is None:
                try:
                    st.cop_ring = _SS(capacity=128, slow_capacity=64)
                except AttributeError:  # slotted/duck store: ring stays off
                    pass
        # single authority (the PD TSO role) with store-down failover: the
        # authority index advances to the next live shard when the current
        # one is unreachable, and meta reads follow it (every shard carries a
        # replicated meta keyspace, so any live replica can answer)
        self._auth_idx = 0
        # high-water mark over every timestamp this fleet has handed out:
        # failover moves the TSO stream to another shard whose oracle may sit
        # behind within the same millisecond (logical counter restarts) —
        # percolator's conflict checks assume ONE monotonic stream, so a
        # post-failover ts is never released until it clears this mark
        self._ts_hwm = 0
        self.tso = _FailoverTSO(self)
        self.detector = _FailoverDetector(self)
        self.pd = _ShardedPD(self)
        self._mu = threading.Lock()
        # owner election: lease/term state replicates to a MAJORITY of the
        # shards (kv/election.py), so losing any single store — including
        # shard 0 — neither halts the control plane nor risks split-brain
        from tidb_tpu import config as _config
        from tidb_tpu.kv.election import QuorumElection

        self.election = QuorumElection(self.stores, lease_s=_config.current().owner_lease_s)
        # elastic placement (kv/placement.py): epoch-versioned movable
        # table→shard bindings, quorum-replicated like the election keyspace.
        # The cached map serves the hot routing path; a RegionError from a
        # fenced ex-owner triggers placement_refresh — the boRegionMiss
        # re-resolve. Explicit constructor placement seeds at epoch 0.
        from tidb_tpu.kv.placement import PlacementClient

        self.placement_cache = PlacementClient(self.stores, explicit=self.placement)
        # returning-replica anti-entropy: a shard that answers after being
        # marked down gets the majority's meta/election/placement records
        # replayed onto it BEFORE its votes count again (PR-2's carried gap)
        self.election.catchup_fn = self._replica_catchup

    @property
    def quorum(self) -> int:
        """Majority size — what replicated meta writes and election verbs
        need to succeed (minority shard loss is tolerated, minority
        partitions are refused)."""
        return len(self.stores) // 2 + 1

    def _authority_call(self, fn, kind: str = "meta"):
        """Run ``fn(store)`` against the authority shard, re-resolving the
        authority to the next live shard on store-down. Paced by a typed
        Backoffer (boStoreDown) so a flapping shard doesn't spin; when every
        replica is down the LAST ConnectionError surfaces — a typed error,
        not a hang."""
        from tidb_tpu.utils import metrics as _m

        bo = Backoffer(budget_ms=2000)
        last: Exception | None = None
        start = self._auth_idx
        swept_ms = 0.0
        while True:
            t0 = time.monotonic()
            for i in range(len(self.stores)):
                j = (start + i) % len(self.stores)
                try:
                    out = fn(self.stores[j])
                except ConnectionError as e:
                    last = e
                    continue
                if j != self._auth_idx:
                    with self._mu:
                        self._auth_idx = j
                    _m.STORE_FAILOVER.inc(kind=kind)
                return out
            # a FULL sweep failed — every replica looked down this pass. The
            # backoff paces the next sweep, never the first attempt against
            # an untried shard (an alternative live replica costs nothing to
            # try immediately; sleeping before it is pure failover latency).
            # Sweep wall time charges the budget CUMULATIVELY: each dead
            # REMOTE shard burns its internal boRPC reconnect budget before
            # surfacing ConnectionError, so without the charge the nested
            # budgets would multiply into tens of seconds per call (total
            # block time here is bounded by ~budget + one sweep)
            swept_ms += (time.monotonic() - t0) * 1000.0
            if swept_ms >= bo.remaining_ms():
                raise last  # type: ignore[misc]
            try:
                bo.backoff(boStoreDown, last)
            except BackoffExhausted:
                raise last  # type: ignore[misc]

    def _monotonic_ts(self, fn, kind: str = "tso") -> int:
        """An authority timestamp that never regresses across failover: spin
        past the high-water mark when the new authority's oracle is behind
        (normally the same-millisecond logical overlap). The spin is
        BOUNDED: skew beyond the deployment assumption (same-host clocks)
        surfaces a typed error instead of issuing a regressed timestamp or
        hanging — the one thing this layer may never do is either."""
        deadline: Optional[float] = None
        while True:
            ts = self._authority_call(fn, kind=kind)
            with self._mu:
                if ts > self._ts_hwm:
                    self._ts_hwm = ts
                    return ts
                hwm = self._ts_hwm
            if deadline is None:
                deadline = time.monotonic() + 2.0
            elif time.monotonic() > deadline:
                raise ConnectionError(
                    f"TSO authority clock behind the fleet high-water mark "
                    f"({ts} <= {hwm}) beyond skew tolerance; refusing to issue "
                    "a regressed timestamp"
                )
            time.sleep(0.0005)

    # -- placement ----------------------------------------------------------
    def shard_of_table(self, table_id: int) -> int:
        """Owner shard for a table: the cached placement map (quorum
        bindings + explicit constructor pins) first, the stable hash for
        tables no migration ever touched."""
        got = self.placement_cache.shard_of(table_id)
        if got is not None:
            return got % len(self.stores)
        return table_id % len(self.stores)

    # the PD-client naming twin (routing callers say "owner", admin says
    # "shard"); one implementation
    owner_for = shard_of_table

    def placement_epoch(self, table_id: int) -> int:
        """The table's current placement epoch as this client has observed
        it (0 = never moved)."""
        return self.placement_cache.epoch_of(table_id)

    def placement_refresh(self) -> bool:
        """Re-resolve the placement map from a majority — what a routing
        caller runs after RegionError (fenced ex-owner) or after a dead
        owner (did the region move away before the store died?). False when
        nothing changed or the keyspace is below quorum (the stale cache
        keeps serving — it may still be right)."""
        try:
            return self.placement_cache.refresh()
        except ConnectionError:
            return False

    def placement_snapshot(self) -> dict:
        """Bindings + epochs + in-flight moves for the cluster_placement
        memtable; refreshes from the fleet first (best-effort) so the rows
        show quorum truth, not just this client's cache."""
        self.placement_refresh()
        return self.placement_cache.snapshot()

    def migrate_table(self, table_id: int, dst: int, **kw) -> dict:
        """Move one table's region to shard ``dst`` (kv/placement.py
        migrate_table): snapshot copy + change catch-up + fenced epoch-bump
        cutover; in-flight 2PC locks move with the region."""
        from tidb_tpu.kv.placement import migrate_table as _migrate

        return _migrate(self, table_id, dst, **kw)

    def _routed(self, verb: str, fn, conn_reroute: bool = True):
        """Run a placement-routed operation with epoch-mismatch recovery:
        ``fn`` recomputes its routing from the cached map on every attempt,
        so after a RegionError (the fenced ex-owner's refusal) a
        placement_refresh re-routes the retry to the new owner — the
        boRegionMiss loop, applied to DATA verbs, which is what lets 2PC
        re-route mid-txn when a region moves between prewrite and commit.
        A ConnectionError (dead owner) retries only when the refresh
        actually moved something (``conn_reroute``; commit keeps its
        undetermined-result semantics and never re-routes on a dead wire)."""
        from tidb_tpu.utils import metrics as _m

        bo = Backoffer(budget_ms=2000)
        while True:
            try:
                return fn()
            except RegionError as e:
                self.placement_refresh()
                try:
                    bo.backoff(boRegionMiss, e)
                except BackoffExhausted:
                    raise e from None
                _m.PLACEMENT_REROUTE.inc(verb=verb)
            except ConnectionError as e:
                if not conn_reroute or not self.placement_refresh():
                    raise
                try:
                    bo.backoff(boRegionMiss, e)
                except BackoffExhausted:
                    raise
                _m.PLACEMENT_REROUTE.inc(verb=verb)

    def _replica_catchup(self, si: int) -> None:
        """Anti-entropy for a RETURNING replica (killed → restarted empty):
        replay the meta keyspace from a healthy peer plus the majority's
        election and placement records onto shard ``si`` before its votes
        count toward quorum again. Best-effort — a failure here leaves the
        shard to lazy read-repair, exactly the pre-catchup behavior."""
        from tidb_tpu.utils import metrics as _m

        st = self.stores[si]
        # 1. meta keyspace (catalog / DDL jobs / sysvars replicate to every
        #    shard): scan from the first healthy peer that is NOT the
        #    returner — its own blank copy must not be the source. Replay
        #    ONLY the keys the returner is MISSING: a shard that merely
        #    flapped (data intact, possibly NEWER than the source peer,
        #    which may itself have missed a tolerated-minority write) must
        #    not have stale values re-stamped over it at fresh timestamps —
        #    divergence on present keys stays with the lazy read-repair
        #    path, exactly as before this hook existed.
        pairs = None
        for j in range(len(self.stores)):
            if j == si:
                continue
            try:
                pairs = self.stores[j].raw_scan(KeyRange(b"", tablecodec.TABLE_PREFIX))
                break
            except ConnectionError:
                continue
        if pairs is not None:
            for k, v in pairs:
                if st.raw_get(k) is None:
                    st.raw_put(k, v)
        # 2. election records: the majority-resolved record per seen key
        #    (the replica accept rule keeps the higher term)
        with self.election._mu:
            keys = list(self.election._seen_terms)
        for key in keys:
            try:
                term, owner, deadline = self.election._read_majority(key)
            except ConnectionError:
                break
            if term > 0 and owner is not None:
                st.election_propose(key, owner, term, deadline)
        # 3. placement bindings (epoch accept rule keeps the higher epoch)
        self.placement_cache.repair_replica(si)
        _m.META_CATCHUP.inc()

    @staticmethod
    def is_table_key(key: bytes) -> bool:
        return key[:1] == tablecodec.TABLE_PREFIX and len(key) >= 9

    def shard_of_key(self, key: bytes) -> int:
        """Owner shard for reads: table keys by placement, meta keys by the
        authority (shard 0 holds the authoritative replica)."""
        if self.is_table_key(key):
            from tidb_tpu.utils import codec

            return self.shard_of_table(codec.decode_int_raw(key, 1))
        return 0  # meta / system keyspace: authoritative copy on shard 0

    def write_shards(self, key: bytes) -> list[int]:
        """Shards a WRITE of ``key`` lands on: one owner for table keys,
        EVERY shard for meta keys (replicated catalog)."""
        if self.is_table_key(key):
            return [self.shard_of_key(key)]
        return list(range(len(self.stores)))

    def store_for_key(self, key: bytes):
        return self.stores[self.shard_of_key(key)]

    def single_owner(self, kr: KeyRange) -> Optional[int]:
        """The one shard owning the WHOLE range, or None when it spans
        tables on different owners (fan-out required)."""
        if not self.is_table_key(kr.start):
            return None
        from tidb_tpu.utils import codec

        t0 = codec.decode_int_raw(kr.start, 1)
        if self.is_table_key(kr.end):
            t1 = codec.decode_int_raw(kr.end, 1)
            # the end bound may be the exclusive prefix of the NEXT table
            if t1 not in (t0, t0 + 1) and kr.end > tablecodec.table_prefix(t0 + 1):
                return None
        return self.shard_of_table(t0)

    def group_ranges(self, ranges: Sequence[KeyRange], consecutive: bool = False):
        """[(shard, [ranges])] — grouped by owner; with ``consecutive`` the
        original range order is preserved as same-owner runs (keep-order)."""
        out: list = []
        for kr in ranges:
            si = self.shard_of_key(kr.start)
            if out and out[-1][0] == si:
                out[-1][1].append(kr)
            elif not consecutive:
                for entry in out:
                    if entry[0] == si:
                        entry[1].append(kr)
                        break
                else:
                    out.append((si, [kr]))
            else:
                out.append((si, [kr]))
        return out

    # -- kv.Storage surface -------------------------------------------------
    def current_ts(self) -> int:
        return self._monotonic_ts(lambda st: st.current_ts(), kind="tso")

    def raw_get(self, key: bytes):
        if not self.is_table_key(key):
            return self._authority_call(lambda st: st.raw_get(key))
        return self._routed("raw_get", lambda: self.store_for_key(key).raw_get(key))

    def _meta_quorum_check(self, errs: list) -> None:
        """Replicated meta writes need a MAJORITY of replicas, not all of
        them: a dead minority is skipped (it re-bootstraps on return — a
        killed store process restarts empty) and counted, so the control
        plane keeps moving when any single shard dies. Below quorum the last
        ConnectionError surfaces — a minority partition must not believe it
        persisted cluster state it can no longer read back. Tolerable
        batches only exist for keys that fan to EVERY shard, so the quorum
        base is always the fleet size."""
        if not errs:
            return
        from tidb_tpu.utils import metrics as _m

        if len(self.stores) - len(errs) < self.quorum:
            raise errs[-1]
        _m.STORE_FAILOVER.inc(n=len(errs), kind="meta_write")

    def _fanout_tolerant(self, items, call, tolerable) -> None:
        """Run ``call(si, payload)`` for each ``(si, payload)``; a
        ConnectionError from a batch where ``tolerable(payload)`` holds
        (every key replicated on other shards) is collected and judged by
        the meta quorum rule, anything else propagates (a table key has
        exactly one owner — its loss cannot be masked)."""
        errs: list = []
        for si, payload in items:
            try:
                call(si, payload)
            except ConnectionError as e:
                if not tolerable(payload):
                    raise
                errs.append(e)
        self._meta_quorum_check(errs)

    def raw_put(self, key: bytes, value: bytes) -> None:
        shards = self.write_shards(key)
        if len(shards) == 1:
            self._routed("raw_put", lambda: self.store_for_key(key).raw_put(key, value))
            return
        self._fanout_tolerant(
            [(si, None) for si in shards],
            lambda si, _: self.stores[si].raw_put(key, value),
            lambda _: True,
        )

    def raw_delete(self, key: bytes) -> None:
        shards = self.write_shards(key)
        if len(shards) == 1:
            self._routed("raw_delete", lambda: self.store_for_key(key).raw_delete(key))
            return
        self._fanout_tolerant(
            [(si, None) for si in shards],
            lambda si, _: self.stores[si].raw_delete(key),
            lambda _: True,
        )

    def raw_cas(self, key: bytes, expected, value: bytes) -> bool:
        # the authority decides; replicas follow on success (meta keys only).
        # The deciding replica follows the authority-failover order, so a
        # dead shard 0 no longer wedges catalog version bumps.
        shards = self.write_shards(key)
        if len(shards) == 1:
            return self._routed(
                "raw_cas", lambda: self.store_for_key(key).raw_cas(key, expected, value),
                conn_reroute=False,  # CAS shares commit's replay hazard
            )
        ok = self._authority_call(lambda st: st.raw_cas(key, expected, value))
        if ok:
            decider = self._auth_idx
            self._fanout_tolerant(
                [(si, None) for si in shards if si != decider],
                lambda si, _: self.stores[si].raw_put(key, value),
                lambda _: True,
            )
        return ok

    def raw_scan(self, kr: KeyRange, limit: int = 2**62):
        if not self.is_table_key(kr.start):
            # meta keyspace: one replica only (fanning would surface every
            # shard's copy of the same row); the authority first, survivors
            # on store-down
            return self._authority_call(lambda st: st.raw_scan(kr, limit=limit))
        def run():
            one = self.single_owner(kr)
            if one is not None:
                return self.stores[one].raw_scan(kr, limit=limit)
            outs = []
            for s in self.stores:
                outs.extend(s.raw_scan(kr, limit=limit))
            outs.sort(key=lambda kv: kv[0])
            return outs[:limit]

        return self._routed("raw_scan", run)

    def run_gc(self, safe_point=None, life_ms: int = 600_000):
        pruned = 0
        sp = None
        for s in self.stores:
            p, spt = s.run_gc(safe_point, life_ms)
            pruned += p
            sp = spt if sp is None else min(sp, spt)
        return pruned, sp or 0

    def get_snapshot(self, ts: int) -> _ShardedSnapshot:
        return _ShardedSnapshot(self, ts)

    def snap_batch_get(self, pairs) -> list:
        """Batched snapshot point reads across the fleet — placement-routed:
        a RegionError from a fenced ex-owner re-resolves and re-dispatches
        the whole (idempotent) batch at the new owners."""
        return self._routed("snap_batch_get", lambda: self._snap_batch_get_once(pairs))

    def _snap_batch_get_once(self, pairs) -> list:
        """One batched dispatch: table keys group by their owner shard and
        ride that shard's own batched verb (one RPC per remote shard per
        flush), outcomes scatter back in request order. Failures stay
        per-key/per-shard OUTCOMES — a dead shard or a locked key fails
        only its own sessions' reads, never the strangers coalesced into
        the same batch."""
        from tidb_tpu.kv.kv import KeyLockedError

        out: list = [None] * len(pairs)
        groups: dict = {}
        for i, (ts, k) in enumerate(pairs):
            if not self.is_table_key(k):
                # meta keyspace: authority read with replica failover
                try:
                    out[i] = self._authority_call(
                        lambda st, ts=ts, k=k: st.get_snapshot(ts).get(k)
                    )
                except (KeyLockedError, ConnectionError, OSError) as e:
                    out[i] = e
                continue
            st = self.store_for_key(k)
            groups.setdefault(id(st), (st, []))[1].append((i, ts, k))
        for st, items in groups.values():
            sub = [(ts, k) for _, ts, k in items]
            try:
                bg = getattr(st, "snap_batch_get", None)
                if bg is not None:
                    vals = bg(sub)
                else:
                    vals = []
                    for ts, k in sub:
                        try:
                            vals.append(st.get_snapshot(ts).get(k))
                        except KeyLockedError as e:
                            vals.append(e)
            except (ConnectionError, OSError) as e:
                vals = [e] * len(sub)
            for (i, _, _), v in zip(items, vals):
                out[i] = v
        return out

    def begin(self):
        from tidb_tpu.kv.txn import Txn

        return Txn(self)

    def get_client(self) -> _ShardedCopClient:
        return _ShardedCopClient(self)

    # -- percolator verbs, grouped by owner (meta writes fan to every
    # replica; the lock/commit state converges via the shared primary) ------
    def _group_keys(self, keys: Sequence[bytes]):
        by: dict[int, list] = {}
        for k in keys:
            for si in self.write_shards(k):
                by.setdefault(si, []).append(k)
        return by.items()

    def prewrite(self, mutations: Sequence[Mutation], primary: bytes, start_ts: int) -> dict:
        # placement-routed: the grouping recomputes per attempt, so a
        # region that moved between two attempts re-routes (prewrite is
        # idempotent under one start_ts — re-sending to the new owner is
        # safe even when an earlier shard already holds its locks)
        def once():
            by: dict[int, list] = {}
            for m in mutations:
                for si in self.write_shards(m.key):
                    by.setdefault(si, []).append(m)
            self._fanout_tolerant(
                by.items(),
                lambda si, muts: self.stores[si].prewrite(muts, primary, start_ts),
                lambda muts: all(not self.is_table_key(m.key) for m in muts),
            )
            # write accounting computed from the UNIQUE mutation list, not the
            # per-store replies: meta keys fan to every replica and would
            # otherwise count once per shard
            return {
                "keys": len(mutations),
                "bytes": sum(len(m.key) + len(m.value) for m in mutations),
            }

        return self._routed("prewrite", once)

    def commit(self, keys: Sequence[bytes], start_ts: int, commit_ts: int) -> None:
        # placement-routed on the TYPED refusal only: a fenced ex-owner
        # rejects the commit before touching state (its locks moved with
        # the region), so re-routing to the new owner — where the migrated
        # lock waits — is safe, and an idempotent re-commit of shards that
        # already applied is a no-op. A dead wire keeps the undetermined-
        # result semantics (conn_reroute=False): re-sending a commit whose
        # fate is unknown could double-decide.
        self._routed(
            "commit",
            lambda: self._commit_once(keys, start_ts, commit_ts),
            conn_reroute=False,
        )

    def _commit_once(self, keys: Sequence[bytes], start_ts: int, commit_ts: int) -> None:
        committed: list[int] = []
        meta_errs: list = []
        groups = list(self._group_keys(keys))
        for si, ks in groups:
            try:
                self.stores[si].commit(ks, start_ts, commit_ts)
            except UndeterminedError as e:
                # cross-shard 2PC: an ambiguous commit on ANY owner makes the
                # round undetermined — annotate the shard and surface (never
                # retried, never downgraded to abort)
                raise UndeterminedError(f"shard {si}: {e}") from e
            except TxnAbortedError as e:
                if all(not self.is_table_key(k) for k in ks):
                    # a meta REPLICA with no lock at commit time is a replica
                    # that missed the prewrite (down then, possibly restarted
                    # empty since — the tolerated-minority recovery model),
                    # not a verdict on the transaction: the quorum decides
                    # below. A genuine abort raises this from EVERY replica
                    # and still surfaces through the below-quorum path.
                    meta_errs.append(e)
                    continue
                raise
            except ConnectionError as e:
                if all(not self.is_table_key(k) for k in ks):
                    # pure-meta replica batch: a dead minority is tolerable —
                    # the round is decided once a MAJORITY of replicas commit
                    # (checked below); the straggler re-bootstraps on return
                    meta_errs.append(e)
                    continue
                if committed:
                    # an earlier shard already durably committed this round
                    # (replicated meta keys fan one commit over every shard):
                    # the round's outcome is decided, only this replica is
                    # unacked — reporting a plain failure would invite a
                    # blind re-run of a committed transaction
                    raise UndeterminedError(
                        f"shard {si}: commit unreachable after shard(s) "
                        f"{committed} committed: {e}"
                    ) from e
                raise
            committed.append(si)
        if meta_errs:
            if len(self.stores) - len(meta_errs) < self.quorum:
                if committed:
                    raise UndeterminedError(
                        f"meta commit below quorum after shard(s) {committed} "
                        f"committed: {meta_errs[-1]}"
                    ) from meta_errs[-1]
                raise meta_errs[-1]
            from tidb_tpu.utils import metrics as _m

            _m.STORE_FAILOVER.inc(n=len(meta_errs), kind="meta_write")

    def rollback(self, keys: Sequence[bytes], start_ts: int) -> None:
        self._routed(
            "rollback",
            lambda: self._fanout_tolerant(
                self._group_keys(keys),
                lambda si, ks: self.stores[si].rollback(ks, start_ts),
                lambda ks: all(not self.is_table_key(k) for k in ks),
            ),
        )

    def check_txn_status(self, primary: bytes, start_ts: int):
        if not self.is_table_key(primary):
            # meta primaries are replicated: any live replica answers, the
            # authority order picks it (a dead shard 0 must not wedge
            # cross-shard lock resolution)
            return self._authority_call(lambda st: st.check_txn_status(primary, start_ts))
        # placement-routed: a fenced ex-owner must not answer "rolled_back"
        # from its stale copy — the truth (the migrated lock or the applied
        # commit) lives at the new owner
        return self._routed(
            "check_txn_status",
            lambda: self.store_for_key(primary).check_txn_status(primary, start_ts),
        )

    def resolve_lock(self, key: bytes, lock: Lock) -> None:
        def once():
            key_shard = self.shard_of_key(key)
            primary_shard = self.shard_of_key(lock.primary)
            if key_shard == primary_shard and self.is_table_key(key):
                self.stores[key_shard].resolve_lock(key, lock)
                return
            # cross-shard (or replicated meta): the primary's owner is the
            # source of truth; commit/rollback route back through the
            # quorum-aware verbs
            status, commit_ts = self.check_txn_status(lock.primary, lock.start_ts)
            if status == "committed":
                self.commit([key], lock.start_ts, commit_ts)
            elif status == "rolled_back":
                self.rollback([key], lock.start_ts)
            # "locked": primary still alive → caller backs off and retries

        self._routed("resolve_lock", once)

    def acquire_pessimistic_lock(self, keys, primary, start_ts, for_update_ts, wait_timeout_ms=3000):
        def once():
            by: dict[int, list] = {}
            for k in keys:
                by.setdefault(self.shard_of_key(k), []).append(k)
            for si, ks in by.items():
                self.stores[si].acquire_pessimistic_lock(
                    ks, primary, start_ts, for_update_ts, wait_timeout_ms
                )

        self._routed("acquire_lock", once)

    def pessimistic_rollback(self, keys: Sequence[bytes], start_ts: int) -> None:
        self._routed(
            "pessimistic_rollback",
            lambda: self._fanout_tolerant(
                self._group_keys(keys),
                lambda si, ks: self.stores[si].pessimistic_rollback(ks, start_ts),
                lambda ks: all(not self.is_table_key(k) for k in ks),
            ),
        )

    # -- bulk ingest --------------------------------------------------------
    def ingest(self, keys: Sequence[bytes], values: Sequence[bytes]) -> int:
        # NOT re-routed on ConnectionError: ingest mints a fresh commit_ts
        # per call, so a replay could double rows (same rule as the wire
        # layer's NON_REPLAYABLE); a typed RegionError still re-routes —
        # the fenced store refused before ingesting anything
        def once():
            by: dict[int, tuple[list, list]] = {}
            for k, v in zip(keys, values):
                e = by.setdefault(self.shard_of_key(k), ([], []))
                e[0].append(k)
                e[1].append(v)
            ts = 0
            for si, (ks, vs) in by.items():
                ts = max(ts, self.stores[si].ingest(ks, vs))
            return ts

        return self._routed("ingest", once, conn_reroute=False)

    def ingest_columnar(self, table_id: int, handles, cols, schema, dicts=None, on_existing=None) -> int:
        return self._routed(
            "ingest_columnar",
            lambda: self.stores[self.shard_of_table(table_id)].ingest_columnar(
                table_id, handles, cols, schema, dicts, on_existing
            ),
            conn_reroute=False,
        )

    def drop_stable(self, table_id: int) -> None:
        self._routed(
            "drop_stable",
            lambda: self.stores[self.shard_of_table(table_id)].drop_stable(table_id),
        )

    # -- owner election: quorum-replicated with fenced leases (kv/election.py,
    # the PD/etcd analog). campaign/renew/resign are majority writes carrying
    # the fencing token (term); owner reads resolve from a majority with
    # highest-term-wins; a minority partition can neither grant nor refresh a
    # lease (ConnectionError — owners keep their last verdict until the lease
    # runs out, then self-fence; ref: etcd quorum loss). Dead shards are
    # skipped under each store's own Backoffer and read-repaired on return. --
    def owner_campaign(
        self, key: str, node_id: str, lease_s: Optional[float] = None, term: Optional[int] = None
    ) -> bool:
        return self.election.campaign(key, node_id, lease_s, term=term)

    def owner_of(self, key: str):
        return self.election.owner(key)

    def owner_resign(self, key: str, node_id: str) -> None:
        self.election.resign(key, node_id)

    def owner_term(self, key: str) -> int:
        return self.election.term(key)

    def owner_granted_term(self, key: str, node_id: str):
        """Locally cached fencing token of ``node_id``'s last grant — spares
        a freshly granted owner the second majority sweep owner_term pays."""
        return self.election.granted_term(key, node_id)

    # -- fleet introspection (the sys_snapshot fan-out behind
    # information_schema.cluster_* and the StoreHealthRegistry) --------------
    @staticmethod
    def instance_name(st) -> str:
        """Stable display identity of one store: the wire address for remote
        stores, a nonce-derived tag for in-process MemStores."""
        if hasattr(st, "host") and hasattr(st, "port"):
            return f"{st.host}:{st.port}"
        return f"mem:{getattr(st, 'nonce', 'embedded')[:8]}"

    def sys_snapshot_all(self, hist=None, sections=None) -> list[dict]:
        """Fan the sys_snapshot introspection verb out to EVERY shard with
        dead-store tolerance: each remote call retries under that store's
        own boRPC Backoffer (RemoteStore._call), and a store that stays dead
        past its budget contributes a per-store failure OUTCOME — one dead
        instance must never fail the whole sweep (TiDB's cluster-memtable
        partial-result semantics). The probes run CONCURRENTLY (one short-
        lived thread per shard, joined before return), so a sweep over N
        dead stores stalls for max(budget), not the sum of N budgets.
        → [{"instance", "shard", "ok", "report" | "error"}] in shard
        order."""

        def probe(si: int, st) -> dict:
            addr = self.instance_name(st)
            fn = getattr(st, "sys_snapshot", None)
            try:
                if fn is not None:
                    rep = fn(hist=hist, sections=sections)
                else:
                    from tidb_tpu.kv.remote import sys_report

                    rep = sys_report(store=st, hist=hist, sections=sections)
                return {"instance": addr, "shard": si, "ok": True, "report": rep}
            except (ConnectionError, OSError) as e:
                return {"instance": addr, "shard": si, "ok": False, "error": str(e)}

        if len(self.stores) == 1:
            return [probe(0, self.stores[0])]
        out: list = [None] * len(self.stores)

        def run(si: int, st) -> None:
            out[si] = probe(si, st)

        threads = [
            threading.Thread(target=run, args=(si, st), daemon=True, name=f"syssnap-{si}")
            for si, st in enumerate(self.stores)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return out

    def log_search_all(
        self,
        since=None,
        until=None,
        min_level: int = 0,
        component=None,
        pattern=None,
        limit: int = 256,
        instances=None,
    ) -> list[dict]:
        """Fan the ``log_search`` verb out to every WIRE shard with the same
        concurrent dead-store-tolerant sweep as :meth:`sys_snapshot_all` —
        filters (time/level/component/regex/limit) apply server-side, and a
        dead store contributes a per-store failure outcome, never a failed
        sweep. In-process shards report zero rows with ``"local": True``:
        their events land in THIS process's ring, which the caller already
        reads directly (fanning out would duplicate every row per shard).
        ``instances`` (a set of instance names) restricts the sweep — the
        cluster_log INSTANCE-predicate pushdown.
        → [{"instance", "shard", "ok", "rows" | "error"}] in shard order."""

        def probe(si: int, st) -> dict:
            addr = self.instance_name(st)
            fn = getattr(st, "log_search", None)
            if fn is None:
                return {"instance": addr, "shard": si, "ok": True, "rows": [], "local": True}
            try:
                rows = fn(
                    since=since, until=until, min_level=min_level,
                    component=component, pattern=pattern, limit=limit,
                )
                return {"instance": addr, "shard": si, "ok": True, "rows": rows}
            except (ConnectionError, OSError) as e:
                return {"instance": addr, "shard": si, "ok": False, "error": str(e)}

        targets = [
            (si, st)
            for si, st in enumerate(self.stores)
            if instances is None or self.instance_name(st) in instances
        ]
        if len(targets) <= 1:
            return [probe(si, st) for si, st in targets]
        out: list = [None] * len(targets)

        def run(oi: int, si: int, st) -> None:
            out[oi] = probe(si, st)

        threads = [
            threading.Thread(
                target=run, args=(oi, si, st), daemon=True, name=f"logsearch-{si}"
            )
            for oi, (si, st) in enumerate(targets)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return out

    # -- columnar-cache verbs for the hybrid shards × devices path ----------
    def stable_parts(self, table_id: int, kr, read_ts: int) -> list:
        """Stable-block slices from the range's owner (the coordinator's
        columnar cache merges them like an embedded store's). Wire members
        keep their blocks server-side and report none — their rows arrive
        via the scan fallback instead."""

        def run():
            st = self.store_for_key(kr.start)
            fn = getattr(st, "stable_parts", None)
            return fn(table_id, kr, read_ts) if fn is not None else []

        return self._routed("stable_parts", run)

    def note_region_read(self, region_id: int, table_id: int, keys: int, nbytes: int) -> None:
        """Cop-serve traffic (copr/colcache.get_split) lands on the range
        owner's rings — the store that answers for the table is the one
        whose heatmap should show it hot. Embedded members take the note
        directly; wire members note server-side when their cop verbs run,
        so nothing ships here. Advisory: a mid-move owner flip just
        attributes the serve to whichever store owns the table NOW."""
        try:
            st = self.stores[self.shard_of_table(table_id)]
        except Exception:  # graftcheck: off=except-swallow
            return
        fn = getattr(st, "note_region_read", None)
        if fn is not None:
            fn(region_id, table_id, keys, nbytes)

    def col_changes_since(self, region_id: int, table_id: int, after_ts: int):
        # coordinator-side region ids are minted (shard/epoch-namespaced), so
        # member change logs cannot be consulted by id — "span" tells the
        # cache to MERGE (full routed re-scan) and never delta-read: always
        # correct, merely conservative after writes
        return ("span", (0, 2**63 - 1))

    def col_changes_prune(self, region_id: int, table_id: int, upto_ts: int) -> None:
        return None  # nothing itemized coordinator-side, nothing to prune

    # -- MPP: single-owner placement ----------------------------------------
    def mpp_ndev(self) -> int:
        fn = getattr(self.stores[0], "mpp_ndev", None)
        if fn is None:
            # embedded fleet: the coordinator process owns the (one) mesh
            from tidb_tpu.parallel import make_mesh

            return int(make_mesh().devices.size)
        return fn()

    def _mpp_owner(self, spec: dict) -> int:
        def tids_of(r: dict) -> list[int]:
            # subplan readers nest their table reader under "sub"; a staged
            # chain subplan reads EVERY chain table — all must co-locate,
            # or the serving store would see empty regions for the rest
            if "sub" in r:
                sp = r["sub"]
                if sp.get("chain"):
                    return [crp["tid"] for crp in sp["chain"]["readers"]]
                return [sp["reader"]["tid"]]
            return [r["tid"]]

        def owners() -> set[int]:
            return {
                self.shard_of_table(tid)
                for r in spec.get("readers", [])
                for tid in tids_of(r)
            }

        got = owners()
        if len(got) != 1 and self.placement_refresh():
            # a stale map can claim a straddle right after a co-locating
            # migration — re-resolve once before giving up on MPP
            got = owners()
        if len(got) != 1:
            from tidb_tpu.parallel.probe import MPPStraddleError

            raise MPPStraddleError(
                f"MPP gather reads tables on {len(got)} store shards; "
                "single-owner placement unavailable (hybrid mesh or host join)"
            )
        return got.pop()

    def mpp_dispatch(self, spec: dict, read_ts: int, **kw) -> str:
        owner = self._mpp_owner(spec)
        fn = getattr(self.stores[owner], "mpp_dispatch", None)
        if fn is None:
            # embedded members run no task manager — the coordinator's own
            # mesh serves the gather (same hybrid path a straddle takes)
            from tidb_tpu.parallel.probe import MPPStraddleError

            raise MPPStraddleError(
                "embedded fleet members dispatch no MPP tasks; "
                "coordinator mesh serves the gather"
            )
        return f"{owner}:{fn(spec, read_ts, **kw)}"

    def mpp_conn(self, task_id: str, check_killed=None, warn=None, **kw):
        owner, _, tid = task_id.partition(":")
        return self.stores[int(owner)].mpp_conn(tid, check_killed=check_killed, warn=warn, **kw)

    def mpp_cancel(self, task_id: str) -> None:
        owner, _, tid = task_id.partition(":")
        self.stores[int(owner)].mpp_cancel(tid)
