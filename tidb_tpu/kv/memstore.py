"""Embedded MVCC store — the in-process engine host (unistore analog).

Reference parity: pkg/store/mockstore/unistore/tikv/mvcc.go (MVCCStore,
Prewrite :768, Commit :1240), region.go (region management), pd.go (mock PD).
Badger-LSM is replaced by an in-memory hash map + lazily-sorted key index:
bulk loads append O(1) per key and the sorted view rebuilds once per scan
epoch, which matches the analytics-heavy profile of the TPU engine.

Percolator semantics (server side):
- ``prewrite``: lock check → write-conflict check → stage lock+value.
- ``commit``: move staged value into the write column at commit_ts.
- ``rollback`` / ``resolve_locks`` / ``check_txn_status``: crash recovery.

Regions: half-open key ranges with a data_version bumped on every committed
write batch — the TPU engine's columnar cache keys off (region_id,
data_version) to reuse device-resident columns across queries (TiFlash's
delta/stable analog, rebuilt rather than merged).
"""

from __future__ import annotations

import bisect
import threading
from dataclasses import dataclass, field
from typing import Iterator, Optional, Sequence

import numpy as np

from tidb_tpu.kv.kv import (
    KeyLockedError,
    KeyRange,
    LockWaitTimeoutError,
    StoreType,
    TimestampOracle,
    TxnAbortedError,
    WriteConflictError,
)
from tidb_tpu.kv.detector import DeadlockDetector
from tidb_tpu.kv import tablecodec

OP_PUT = "P"
OP_DEL = "D"
OP_PESSIMISTIC_LOCK = "L"  # lock-only; carries no data, invisible to readers


@dataclass(frozen=True)
class Write:
    """One committed version. Chains in MemStore._writes are strictly
    ascending by commit_ts — every append site must preserve this, it is what
    prewrite's conflict check, Snapshot._visible and gc() rely on. Rollback
    tombstones live out-of-band in MemStore._rollbacks."""

    commit_ts: int
    start_ts: int
    op: str
    value: bytes = b""


@dataclass
class Lock:
    primary: bytes
    start_ts: int
    op: str
    value: bytes
    ttl_ms: int = 3000
    created_ms: float = 0.0  # wall-clock at prewrite; TTL expiry base

    def expired(self) -> bool:
        import time

        return (time.time() * 1000 - self.created_ms) >= self.ttl_ms


@dataclass
class Mutation:
    op: str  # OP_PUT / OP_DEL
    key: bytes
    value: bytes = b""


@dataclass
class Region:
    """ref: unistore/tikv/region.go; metadata served by the embedded PD."""

    region_id: int
    start: bytes
    end: bytes  # b"" == +inf
    data_version: int = 0
    max_commit_ts: int = 0
    key_count: int = 0

    def contains(self, key: bytes) -> bool:
        return self.start <= key and (self.end == b"" or key < self.end)

    def range(self) -> KeyRange:
        return KeyRange(self.start, self.end if self.end else b"\xff" * 32)


class PlacementDriver:
    """Embedded PD: region metadata + id allocation (ref: unistore/pd.go).
    Region→node placement for MPP lives in tidb_tpu.parallel."""

    def __init__(self, store: "MemStore"):
        self._store = store

    def regions_in_ranges(self, ranges: Sequence[KeyRange]) -> list[tuple[Region, list[KeyRange]]]:
        """Split key ranges by region boundary (ref: copr/coprocessor.go:334
        buildCopTasks / region_cache.SplitKeyRangesByBuckets)."""
        out: list[tuple[Region, list[KeyRange]]] = []
        for region in self._store.regions():
            rr = region.range()
            pieces = [p for kr in ranges if (p := kr.intersect(rr)) is not None]
            if pieces:
                out.append((region, pieces))
        return out


class BulkRows:
    """Zero-loop handoff of a record scan: concatenated row values + offsets,
    ready for rowcodec.decode_fixed_bulk."""

    __slots__ = ("handles", "starts", "ends", "buf")

    def __init__(self, handles: np.ndarray, starts: np.ndarray, ends: np.ndarray, buf: bytes):
        self.handles, self.starts, self.ends, self.buf = handles, starts, ends, buf

    def __len__(self) -> int:
        return len(self.handles)


class Snapshot:
    """Consistent read view at read_ts (ref: kv.Snapshot; unistore mvcc
    reader)."""

    def __init__(self, store: "MemStore", read_ts: int):
        self._store = store
        self.read_ts = read_ts

    def _visible(self, writes: list[Write]) -> Optional[Write]:
        # writes ascend by commit_ts; walk from the end
        for w in reversed(writes):
            if w.commit_ts <= self.read_ts:
                return w
        return None

    def get(self, key: bytes) -> Optional[bytes]:
        with self._store._mu:
            self._store._check_lock(key, self.read_ts)
            writes = self._store._writes.get(key)
            if not writes:
                return None
            w = self._visible(writes)
            if w is None or w.op == OP_DEL:
                return None
            return w.value

    def scan(self, kr: KeyRange, limit: int = 2**63, reverse: bool = False) -> list[tuple[bytes, bytes]]:
        """Eager scan — materializes under the store lock, never holds it
        across caller iterations."""
        out: list[tuple[bytes, bytes]] = []
        with self._store._mu:
            keys = self._store._sorted_slice(kr)
            if reverse:
                keys = keys[::-1]
            for k in keys:
                self._store._check_lock(k, self.read_ts)
                w = self._visible(self._store._writes[k])
                if w is not None and w.op == OP_PUT:
                    out.append((k, w.value))
                    if len(out) >= limit:
                        break
        return out

    def scan_record_rows(self, kr: KeyRange) -> BulkRows:
        """Scan record keys in [kr) and pack visible row values contiguously
        — the hot path feeding the columnar cache."""
        handles: list[int] = []
        chunks: list[bytes] = []
        starts: list[int] = []
        ends: list[int] = []
        off = 0
        with self._store._mu:
            keys = self._store._sorted_slice(kr)
            writes_map = self._store._writes
            locks = self._store._locks
            read_ts = self.read_ts
            for k in keys:
                if locks and k in locks:
                    self._store._check_lock(k, read_ts)
                w = self._visible(writes_map[k])
                if w is None or w.op != OP_PUT:
                    continue
                if not tablecodec.is_record_key(k):
                    continue
                handles.append(tablecodec.decode_record_key(k)[1])
                chunks.append(w.value)
                starts.append(off)
                off += len(w.value)
                ends.append(off)
        return BulkRows(
            np.asarray(handles, dtype=np.int64),
            np.asarray(starts, dtype=np.int64),
            np.asarray(ends, dtype=np.int64),
            b"".join(chunks),
        )


class MemStore:
    """The storage node. One process can host several (multi-"node" tests)."""

    def __init__(self, region_split_keys: int = 500_000, lock_ttl_ms: int = 3000):
        import uuid

        self.lock_ttl_ms = lock_ttl_ms
        # distinguishes this store in process-global caches (device arrays):
        # region/table ids restart per store and would otherwise collide
        self.nonce = uuid.uuid4().hex
        self._mu = threading.RLock()
        self._writes: dict[bytes, list[Write]] = {}
        # key → start_ts set of rolled-back txns (out-of-band so write chains
        # stay strictly ascending by commit_ts)
        self._rollbacks: dict[bytes, set[int]] = {}
        self._locks: dict[bytes, Lock] = {}
        self._sorted: list[bytes] | None = []
        self.tso = TimestampOracle()
        self._region_split_keys = region_split_keys
        self._regions: list[Region] = [Region(region_id=1, start=b"", end=b"")]
        self._next_region_id = 2
        self.pd = PlacementDriver(self)
        self._client = None  # installed by copr.CopClient wiring
        self.detector = DeadlockDetector()

    # -- kv.Storage surface ------------------------------------------------
    def current_ts(self) -> int:
        return self.tso.ts()

    def get_snapshot(self, ts: int) -> Snapshot:
        return Snapshot(self, ts)

    def begin(self):
        from tidb_tpu.kv.txn import Txn

        return Txn(self)

    def get_client(self):
        if self._client is None:
            from tidb_tpu.copr.client import CopClient

            self._client = CopClient(self)
        return self._client

    # -- sorted key index --------------------------------------------------
    def _ensure_sorted(self) -> list[bytes]:
        if self._sorted is None:
            self._sorted = sorted(self._writes.keys())
        return self._sorted

    def _sorted_slice(self, kr: KeyRange) -> list[bytes]:
        keys = self._ensure_sorted()
        lo = bisect.bisect_left(keys, kr.start)
        hi = bisect.bisect_left(keys, kr.end)
        return keys[lo:hi]

    # -- region management -------------------------------------------------
    def regions(self) -> list[Region]:
        with self._mu:
            return list(self._regions)

    def region_for_key(self, key: bytes) -> Region:
        with self._mu:
            for r in self._regions:
                if r.contains(key):
                    return r
            raise KeyError(f"no region for {key!r}")

    def split_region(self, split_key: bytes) -> None:
        """Manual split (ref: failpoint-forced splits in tests)."""
        with self._mu:
            for i, r in enumerate(self._regions):
                if r.contains(split_key) and split_key > r.start:
                    new = Region(
                        region_id=self._next_region_id,
                        start=split_key,
                        end=r.end,
                        data_version=r.data_version,
                        max_commit_ts=r.max_commit_ts,
                    )
                    self._next_region_id += 1
                    r.end = split_key
                    self._regions.insert(i + 1, new)
                    self._recount_region(r)
                    self._recount_region(new)
                    return

    def _recount_region(self, r: Region) -> None:
        r.key_count = len(self._sorted_slice(r.range()))

    def _maybe_auto_split(self, r: Region) -> None:
        if r.key_count <= self._region_split_keys:
            return
        keys = self._sorted_slice(r.range())
        if len(keys) < 2:
            return
        self.split_region(keys[len(keys) // 2])

    # -- percolator (server side; ref: mvcc.go:768 Prewrite, :1240 Commit) --
    def _check_lock(self, key: bytes, read_ts: int) -> None:
        lock = self._locks.get(key)
        if lock is not None and lock.start_ts <= read_ts and lock.op != OP_PESSIMISTIC_LOCK:
            # pessimistic (lock-only) locks carry no data → readers pass
            raise KeyLockedError(key, lock)

    def prewrite(self, mutations: Sequence[Mutation], primary: bytes, start_ts: int) -> None:
        with self._mu:
            for m in mutations:
                lock = self._locks.get(m.key)
                if lock is not None and lock.start_ts != start_ts:
                    raise KeyLockedError(m.key, lock)
                if lock is not None and lock.op == OP_PESSIMISTIC_LOCK:
                    # upgrading our own pessimistic lock: the conflict window
                    # was already checked against for_update_ts at lock time
                    continue
                writes = self._writes.get(m.key)
                if writes and writes[-1].commit_ts > start_ts:
                    raise WriteConflictError(m.key, writes[-1].commit_ts, start_ts)
                if start_ts in self._rollbacks.get(m.key, ()):
                    raise TxnAbortedError(f"txn {start_ts} already rolled back at {m.key!r}")
            import time

            now_ms = time.time() * 1000
            for m in mutations:
                self._locks[m.key] = Lock(
                    primary=primary,
                    start_ts=start_ts,
                    op=m.op,
                    value=m.value,
                    ttl_ms=self.lock_ttl_ms,
                    created_ms=now_ms,
                )

    def acquire_pessimistic_lock(
        self,
        keys: Sequence[bytes],
        primary: bytes,
        start_ts: int,
        for_update_ts: int,
        wait_timeout_ms: int = 3000,
    ) -> None:
        """Statement-time lock acquisition (ref: unistore mvcc.go
        PessimisticLock). Blocks (polling) on foreign locks until timeout;
        wait edges feed the deadlock detector, whose victim is the requester
        that closes a cycle. Write-conflict check runs against for_update_ts,
        not start_ts — that is what lets pessimistic txns proceed where
        optimistic ones must restart."""
        import time

        deadline = time.time() * 1000 + wait_timeout_ms
        placed: list[bytes] = []  # locks created by THIS call, for unwind
        try:
            for key in keys:
                while True:
                    with self._mu:
                        lock = self._locks.get(key)
                        if lock is None or lock.start_ts == start_ts:
                            writes = self._writes.get(key)
                            if writes and writes[-1].commit_ts > for_update_ts:
                                raise WriteConflictError(key, writes[-1].commit_ts, start_ts)
                            if start_ts in self._rollbacks.get(key, ()):
                                raise TxnAbortedError(f"txn {start_ts} already rolled back at {key!r}")
                            if lock is None:  # keep prewrite-upgraded locks as-is
                                self._locks[key] = Lock(
                                    primary=primary,
                                    start_ts=start_ts,
                                    op=OP_PESSIMISTIC_LOCK,
                                    value=b"",
                                    ttl_ms=self.lock_ttl_ms,
                                    created_ms=time.time() * 1000,
                                )
                                placed.append(key)
                            self.detector.unregister(start_ts)
                            break
                        holder = lock.start_ts
                        expired = lock.expired()
                    # outside the store lock: deadlock check, resolution, backoff
                    self.detector.register(start_ts, holder, key)
                    if expired:
                        self.resolve_lock(key, lock)
                        continue
                    if time.time() * 1000 >= deadline:
                        self.detector.unregister(start_ts)
                        raise LockWaitTimeoutError(key)
                    time.sleep(0.002)
        except Exception:
            # a failed statement must not leave locks the caller doesn't
            # know about (it only records keys on full success)
            self.pessimistic_rollback(placed, start_ts)
            raise

    def pessimistic_rollback(self, keys: Sequence[bytes], start_ts: int) -> None:
        """Release lock-only locks without leaving rollback tombstones (the
        txn may still commit other keys)."""
        with self._mu:
            for k in keys:
                lock = self._locks.get(k)
                if lock is not None and lock.start_ts == start_ts and lock.op == OP_PESSIMISTIC_LOCK:
                    del self._locks[k]
        self.detector.clean_up(start_ts)

    def commit(self, keys: Sequence[bytes], start_ts: int, commit_ts: int) -> None:
        with self._mu:
            touched: set[int] = set()
            for k in keys:
                lock = self._locks.get(k)
                if lock is None or lock.start_ts != start_ts:
                    # idempotent re-commit or lost lock
                    if any(w.start_ts == start_ts for w in self._writes.get(k, [])):
                        continue  # already committed
                    raise TxnAbortedError(f"commit of {k!r}@{start_ts}: lock not found")
                del self._locks[k]
                chain = self._writes.setdefault(k, [])
                is_new = not chain
                chain.append(Write(commit_ts, start_ts, OP_PUT if lock.op == OP_PUT else OP_DEL, lock.value))
                if is_new and self._sorted is not None:
                    # cheap append keeps sortedness only if appending at tail
                    if self._sorted and self._sorted[-1] < k:
                        self._sorted.append(k)
                    else:
                        self._sorted = None
                region = self.region_for_key(k)
                region.max_commit_ts = max(region.max_commit_ts, commit_ts)
                if is_new:
                    region.key_count += 1
                touched.add(id(region))
            for r in self._regions:
                if id(r) in touched:
                    r.data_version += 1
                    self._maybe_auto_split(r)

    def ingest(self, keys: Sequence[bytes], values: Sequence[bytes]) -> int:
        """Bulk ingest of pre-encoded committed rows at one fresh commit ts —
        the local-SST-ingest path (ref: lightning local backend + unistore's
        IngestSST): bypasses prewrite/commit per key. Refuses when any
        ingested key holds a lock (writers would race the ingest)."""
        with self._mu:
            start_ts = self.tso.ts()
            commit_ts = self.tso.ts()
            if self._locks:
                for k in keys:
                    if k in self._locks:
                        raise KeyLockedError(k, self._locks[k])
            writes = self._writes
            lo: bytes | None = None
            hi: bytes | None = None
            for k, v in zip(keys, values):
                chain = writes.get(k)
                if chain is None:
                    writes[k] = [Write(commit_ts, start_ts, OP_PUT, v)]
                else:
                    chain.append(Write(commit_ts, start_ts, OP_PUT, v))
                if lo is None or k < lo:
                    lo = k
                if hi is None or k > hi:
                    hi = k
            if lo is None:
                return commit_ts
            # region bookkeeping in one sweep over the regions the ingested
            # span touches (per-key region lookup is the slow path the txn
            # commit pays); untouched regions keep their data_version so
            # their columnar/device caches stay warm
            self._sorted = None
            touched = [
                r
                for r in self._regions
                if (not r.end or lo < r.end) and (not r.start or hi >= r.start)
            ]
            for r in touched:
                self._recount_region(r)
                r.max_commit_ts = max(r.max_commit_ts, commit_ts)
                r.data_version += 1
            for r in touched:
                self._maybe_auto_split(r)
            return commit_ts

    def rollback(self, keys: Sequence[bytes], start_ts: int) -> None:
        with self._mu:
            for k in keys:
                lock = self._locks.get(k)
                if lock is not None and lock.start_ts == start_ts:
                    del self._locks[k]
                self._rollbacks.setdefault(k, set()).add(start_ts)

    def check_txn_status(self, primary: bytes, start_ts: int) -> tuple[str, int]:
        """→ ("committed", commit_ts) | ("rolled_back", 0) | ("locked", 0).
        (ref: unistore CheckTxnStatus; TTL expiry handled by caller policy)"""
        with self._mu:
            lock = self._locks.get(primary)
            if lock is not None and lock.start_ts == start_ts:
                if lock.expired():
                    # dead txn: roll back its primary so the decision is durable
                    del self._locks[primary]
                    self._rollbacks.setdefault(primary, set()).add(start_ts)
                    return "rolled_back", 0
                return "locked", 0
            for w in self._writes.get(primary, []):
                if w.start_ts == start_ts:
                    return "committed", w.commit_ts
            return "rolled_back", 0  # no lock, no write → treat as rolled back

    def resolve_lock(self, key: bytes, lock: Lock) -> None:
        """Resolve one stuck lock by consulting its primary."""
        status, commit_ts = self.check_txn_status(lock.primary, lock.start_ts)
        if status == "committed":
            self.commit([key], lock.start_ts, commit_ts)
        elif status == "rolled_back":
            self.rollback([key], lock.start_ts)
        # "locked": primary still alive → caller backs off and retries

    # -- GC (ref: pkg/store/gcworker) ---------------------------------------
    def gc(self, safe_ts: int) -> int:
        """Drop versions no snapshot at ts ≥ safe_ts can see. Returns number
        of pruned version records."""
        pruned = 0
        with self._mu:
            dead_keys = []
            for k, writes in self._writes.items():
                # find newest write with commit_ts <= safe_ts; keep it (unless DEL), drop older
                keep_from = 0
                for i in range(len(writes) - 1, -1, -1):
                    if writes[i].commit_ts <= safe_ts:
                        keep_from = i
                        if writes[i].op == OP_DEL:
                            keep_from = i + 1
                        break
                if keep_from > 0:
                    pruned += keep_from
                    del writes[:keep_from]
                if not writes:
                    dead_keys.append(k)
            for k in dead_keys:
                del self._writes[k]
            # rollback tombstones older than the GC horizon can never matter
            # to a future prewrite (its start_ts would conflict anyway)
            for k in list(self._rollbacks):
                self._rollbacks[k] = {ts for ts in self._rollbacks[k] if ts > safe_ts}
                if not self._rollbacks[k]:
                    del self._rollbacks[k]
            if dead_keys:
                self._sorted = None
                for r in self._regions:
                    self._recount_region(r)
        return pruned

    # -- raw ops (catalog/meta convenience; single-key autocommit) ----------
    def raw_put(self, key: bytes, value: bytes) -> None:
        with self._mu:  # ts drawn under the lock keeps chains ascending
            ts = self.tso.ts()
            chain = self._writes.setdefault(key, [])
            if not chain and self._sorted is not None:
                if self._sorted and self._sorted[-1] < key:
                    self._sorted.append(key)
                else:
                    self._sorted = None
            chain.append(Write(ts, ts, OP_PUT, value))
            r = self.region_for_key(key)
            r.max_commit_ts = max(r.max_commit_ts, ts)
            r.data_version += 1

    def raw_get(self, key: bytes) -> Optional[bytes]:
        return Snapshot(self, self.tso.ts()).get(key)

    def raw_delete(self, key: bytes) -> None:
        with self._mu:
            ts = self.tso.ts()
            self._writes.setdefault(key, []).append(Write(ts, ts, OP_DEL))
            self.region_for_key(key).data_version += 1

    def raw_scan(self, kr: KeyRange, limit: int = 2**63) -> list[tuple[bytes, bytes]]:
        return Snapshot(self, self.tso.ts()).scan(kr, limit)
