"""Embedded MVCC store — the in-process engine host (unistore analog).

Reference parity: pkg/store/mockstore/unistore/tikv/mvcc.go (MVCCStore,
Prewrite :768, Commit :1240), region.go (region management), pd.go (mock PD).
Badger-LSM is replaced by an in-memory hash map + lazily-sorted key index:
bulk loads append O(1) per key and the sorted view rebuilds once per scan
epoch, which matches the analytics-heavy profile of the TPU engine.

Percolator semantics (server side):
- ``prewrite``: lock check → write-conflict check → stage lock+value.
- ``commit``: move staged value into the write column at commit_ts.
- ``rollback`` / ``resolve_locks`` / ``check_txn_status``: crash recovery.

Regions: half-open key ranges with a data_version bumped on every committed
write batch — the TPU engine's columnar cache keys off (region_id,
data_version) to reuse device-resident columns across queries (TiFlash's
delta/stable analog, rebuilt rather than merged).
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Iterator, Optional, Sequence

import numpy as np

from tidb_tpu.kv.kv import (
    KeyLockedError,
    KeyRange,
    LockWaitTimeoutError,
    RegionError,
    StoreType,
    TimestampOracle,
    TxnAbortedError,
    WriteConflictError,
)
from tidb_tpu.kv.detector import DeadlockDetector
from tidb_tpu.kv import tablecodec
from tidb_tpu.utils import execdetails as _ed

OP_PUT = "P"
OP_DEL = "D"
OP_PESSIMISTIC_LOCK = "L"  # lock-only; carries no data, invisible to readers

# per-(region, table) change-log itemization bound: past this many pending
# record changes the log degrades to a handle-span watermark (the columnar
# delta path then falls back to a merge instead of a delta read)
_CHANGE_ITEMS_CAP = 65536


class _ChangeLog:
    """Committed record-key changes for one (region, table) since the last
    columnar merge — the write→delta notification seam the device column
    cache (copr/colcache.py) feeds from, the in-process analog of TiFlash's
    raft-learner change stream. Guarded by the owning store's ``_mu``.

    Two fidelity levels: itemized ``(commit_ts, handle, op)`` tuples while
    small, degrading to a handle-span watermark (``lo``/``hi`` + ``lost``)
    past the cap — watermarks still bound which device blocks a merge must
    re-upload even when individual changes can no longer be enumerated."""

    __slots__ = ("items", "lost", "lost_max_ts", "lo", "hi")

    def __init__(self):
        self.items: list[tuple[int, int, str]] = []  # (commit_ts, handle, op)
        self.lost = False
        self.lost_max_ts = 0
        self.lo: int | None = None  # handle watermark over ALL unpruned changes
        self.hi: int | None = None

    def note(self, ts: int, handle: int, op: str) -> None:
        self.lo = handle if self.lo is None else min(self.lo, handle)
        self.hi = handle if self.hi is None else max(self.hi, handle)
        if self.lost:
            self.lost_max_ts = max(self.lost_max_ts, ts)
            return
        if len(self.items) >= _CHANGE_ITEMS_CAP:
            self.items.clear()
            self.lost = True
            self.lost_max_ts = ts
            return
        self.items.append((ts, handle, op))

    def note_span(self, ts: int, lo: int, hi: int) -> None:
        """Bulk change too large to itemize: watermark only."""
        self.lo = lo if self.lo is None else min(self.lo, lo)
        self.hi = hi if self.hi is None else max(self.hi, hi)
        self.items.clear()
        self.lost = True
        self.lost_max_ts = max(self.lost_max_ts, ts)


# heatmap bound: past this many live (region, table) pairs, NEW pairs are
# dropped (existing rings keep accumulating) — the retention math stays exact
# and a pathological keyspace cannot balloon the store's memory
_TRAFFIC_RINGS_CAP = 4096


class TrafficStats:
    """Per-(region, table) keyspace traffic rings — the Key Visualizer
    substrate (ref: the Dashboard heatmap fed by per-region read/write
    statistics). Read and write keys+bytes are bucketed by the
    ``[observability] keyviz-interval-s`` knob with bounded retention
    (``keyviz-retention-s``), sampled at the snapshot/scan/cop/commit seams
    and shipped fleet-wide via the ``sys_snapshot`` "heatmap" section.

    Lockless on purpose (the eventlog discipline): notes ride the hottest
    read path of the store, so they rely on GIL-atomic dict/deque ops
    instead of a mutex — a lock here costs more than the accounting,
    especially under the tier-1 lock-order detector. Counter bumps are
    plain read-modify-writes, so a racing pair can drop a count into a
    just-rolled bucket or lose one — the heatmap is advisory traffic
    telemetry, not billing; ``enabled`` is the first check on every note
    so a disabled recorder (interval <= 0) costs one attribute read."""

    __slots__ = ("interval_s", "retention_s", "enabled", "_rings")

    def __init__(self, interval_s: float | None = None, retention_s: float | None = None):
        from tidb_tpu import config as _config

        cfg = _config.current()
        self.interval_s = cfg.keyviz_interval_s if interval_s is None else interval_s
        self.retention_s = cfg.keyviz_retention_s if retention_s is None else retention_s
        self.enabled = self.interval_s > 0
        # (region_id, table_id) → deque of mutable rows
        # [bucket_ts, read_keys, read_bytes, write_keys, write_bytes]
        self._rings: dict[tuple[int, int], deque] = {}

    def _note(self, region_id: int, table_id: int, ki: int, bi: int, keys: int, nbytes: int) -> None:
        now = time.time()
        bts = now - (now % self.interval_s)
        ring = self._rings.get((region_id, table_id))
        if ring is None:
            if len(self._rings) >= _TRAFFIC_RINGS_CAP:
                return
            depth = max(1, int(self.retention_s / self.interval_s))
            # setdefault: a racing creator's ring wins, ours is discarded
            ring = self._rings.setdefault((region_id, table_id), deque(maxlen=depth))
        row = ring[-1] if ring else None
        if row is None or row[0] != bts:
            row = [bts, 0, 0, 0, 0]
            ring.append(row)
        row[ki] += keys
        row[bi] += nbytes

    def note_read(self, region_id: int, table_id: int, keys: int, nbytes: int) -> None:
        if self.enabled and keys > 0:
            self._note(region_id, table_id, 1, 2, int(keys), int(nbytes))

    def note_write(self, region_id: int, table_id: int, keys: int, nbytes: int) -> None:
        if self.enabled and keys > 0:
            self._note(region_id, table_id, 3, 4, int(keys), int(nbytes))

    def drop_table(self, table_id: int) -> None:
        """Migration purge / DDL drop forgets the table's rings — post-
        cutover traffic belongs to the new owner's store."""
        for k in [k for k in self._rings if k[1] == table_id]:
            self._rings.pop(k, None)

    def snapshot(self, since: float = 0.0) -> list[dict]:
        """JSON-able ring dump (buckets at or after ``since``): the
        sys_snapshot "heatmap" section / GET /keyviz payload."""
        out: list[dict] = []
        for (rid, tid), ring in list(self._rings.items()):
            buckets = [list(r) for r in list(ring) if r[0] >= since]
            if buckets:
                out.append({"region_id": rid, "table_id": tid, "buckets": buckets})
        return out


@dataclass(frozen=True)
class Write:
    """One committed version. Chains in MemStore._writes are strictly
    ascending by commit_ts — every append site must preserve this, it is what
    prewrite's conflict check, Snapshot._visible and gc() rely on. Rollback
    tombstones live out-of-band in MemStore._rollbacks."""

    commit_ts: int
    start_ts: int
    op: str
    value: bytes = b""


@dataclass
class Lock:
    primary: bytes
    start_ts: int
    op: str
    value: bytes
    ttl_ms: int = 3000
    created_ms: float = 0.0  # wall-clock at prewrite; TTL expiry base

    def expired(self) -> bool:
        import time

        return (time.time() * 1000 - self.created_ms) >= self.ttl_ms


@dataclass
class Mutation:
    op: str  # OP_PUT / OP_DEL
    key: bytes
    value: bytes = b""


@dataclass
class Region:
    """ref: unistore/tikv/region.go; metadata served by the embedded PD."""

    region_id: int
    start: bytes
    end: bytes  # b"" == +inf
    data_version: int = 0
    max_commit_ts: int = 0
    key_count: int = 0

    def contains(self, key: bytes) -> bool:
        return self.start <= key and (self.end == b"" or key < self.end)

    def range(self) -> KeyRange:
        return KeyRange(self.start, self.end if self.end else b"\xff" * 32)


class PlacementDriver:
    """Embedded PD: region metadata + id allocation (ref: unistore/pd.go).
    Region→node placement for MPP lives in tidb_tpu.parallel."""

    def __init__(self, store: "MemStore"):
        self._store = store

    def regions_in_ranges(self, ranges: Sequence[KeyRange]) -> list[tuple[Region, list[KeyRange]]]:
        """Split key ranges by region boundary (ref: copr/coprocessor.go:334
        buildCopTasks / region_cache.SplitKeyRangesByBuckets). A range whose
        table is placement-FENCED here (its region moved to another store)
        raises RegionError instead of splitting — the routing caller
        re-resolves placement under boRegionMiss; silently returning no
        tasks would read as an empty table."""
        for kr in ranges:
            self._store._check_fence_range(kr)
        out: list[tuple[Region, list[KeyRange]]] = []
        for region in self._store.regions():
            rr = region.range()
            pieces = [p for kr in ranges if (p := kr.intersect(rr)) is not None]
            if pieces:
                out.append((region, pieces))
        return out


class BulkRows:
    """Zero-loop handoff of a record scan: concatenated row values + offsets,
    ready for rowcodec.decode_fixed_bulk. ``tombstones`` are handles whose
    visible version is a delete — the columnar merge masks stable rows with
    them (PUT handles mask implicitly via ``handles``)."""

    __slots__ = ("handles", "starts", "ends", "buf", "tombstones", "put_ts", "tomb_ts")

    def __init__(
        self,
        handles: np.ndarray,
        starts: np.ndarray,
        ends: np.ndarray,
        buf: bytes,
        tombstones: np.ndarray | None = None,
        put_ts: np.ndarray | None = None,
        tomb_ts: np.ndarray | None = None,
    ):
        self.handles, self.starts, self.ends, self.buf = handles, starts, ends, buf
        self.tombstones = tombstones if tombstones is not None else np.empty(0, np.int64)
        # commit_ts of each PUT / tombstone verdict: the stable merge is
        # newest-version-wins PER HANDLE, so a delta verdict only overrides
        # stable rows from blocks committed before it (and vice versa)
        self.put_ts = put_ts if put_ts is not None else np.empty(0, np.int64)
        self.tomb_ts = tomb_ts if tomb_ts is not None else np.empty(0, np.int64)

    def __len__(self) -> int:
        return len(self.handles)


class StableBlock:
    """One columnar ingest: decoded, device-ready columns for a handle span
    of one table — the TiFlash *stable layer* analog. Row-delta writes after
    ingest live in the MVCC dict and override by handle at read time.

    ``cols``: column position → (data, valid); STRING columns hold int32
    dictionary codes against the shared per-(table, column) dictionary (the
    ``dicts`` mapping), so the columnar cache can hand slices straight to the
    device. ``schema`` lets point reads re-encode a row on demand.
    """

    __slots__ = ("table_id", "handles", "cols", "schema", "dicts", "commit_ts")

    def __init__(self, table_id: int, handles: np.ndarray, cols: dict, schema, dicts: dict, commit_ts: int):
        self.table_id = table_id
        self.handles = handles  # ascending int64
        self.cols = cols
        self.schema = schema
        self.dicts = dicts
        self.commit_ts = commit_ts

    def __len__(self) -> int:
        return len(self.handles)

    def row_values(self, idx: int) -> list:
        """Logical-physical values of one row (for encode-on-demand reads)."""
        out = []
        for pos in range(self.schema.n):
            data, valid = self.cols[pos]
            if not valid[idx]:
                out.append(None)
            elif data.dtype == np.int32:  # dictionary code
                out.append(self.dicts[pos].decode(int(data[idx])))
            elif data.dtype == np.float64:
                out.append(float(data[idx]))
            else:
                out.append(int(data[idx]))
        return out


class Snapshot:
    """Consistent read view at read_ts (ref: kv.Snapshot; unistore mvcc
    reader)."""

    def __init__(self, store: "MemStore", read_ts: int):
        self._store = store
        self.read_ts = read_ts

    def _visible(self, writes: list[Write]) -> Optional[Write]:
        # writes ascend by commit_ts; walk from the end
        for w in reversed(writes):
            if w.commit_ts <= self.read_ts:
                return w
        return None

    def _get_locked(self, key: bytes) -> Optional[bytes]:
        """One key's read under the store mutex (caller holds it)."""
        self._store._check_fence_key(key)
        self._store._check_lock(key, self.read_ts)
        writes = self._store._writes.get(key)
        w = self._visible(writes) if writes else None
        # newest-version-wins across layers: a dict verdict only hides a
        # stable row committed before it
        floor_ts = w.commit_ts if w is not None else 0
        stable = self._store._stable_get(key, self.read_ts, after_ts=floor_ts)
        if stable is not None:
            return stable
        if w is not None:
            return None if w.op == OP_DEL else w.value
        return None

    def get(self, key: bytes) -> Optional[bytes]:
        with self._store._mu:
            v = self._get_locked(key)
        self._store._note_read_traffic(key, 1, len(v) if v is not None else 0)
        return v

    def get_many(self, keys) -> list:
        """Vectorized multi-key read: ONE lock acquisition for the whole
        batch (the embedded analog of a batched store RPC). Per-key lock
        conflicts come back as ``KeyLockedError`` OUTCOMES in the result
        list — one session's locked key must never fail the other sessions'
        reads coalesced into the same batch."""
        out: list = []
        first = None
        nb = 0
        with self._store._mu:
            for k in keys:
                if first is None:
                    first = k
                try:
                    v = self._get_locked(k)
                    if v is not None:
                        nb += len(v)
                    out.append(v)
                except KeyLockedError as e:
                    out.append(e)
        if first is not None:
            self._store._note_read_traffic(first, len(out), nb)
        return out

    def scan(self, kr: KeyRange, limit: int = 2**63, reverse: bool = False) -> list[tuple[bytes, bytes]]:
        """Eager scan — materializes under the store lock, never holds it
        across caller iterations. Merges the row-delta dict with stable
        columnar blocks via a limit-aware k-way merge: newest version per key
        wins, stable rows encode lazily only when yielded (a LIMIT-k scan of
        a bulk-loaded table touches k rows, not the whole suffix)."""
        import heapq

        from tidb_tpu.kv.rowcodec import encode_row

        store = self._store
        store._check_fence_range(kr)
        out: list[tuple[bytes, bytes]] = []
        with store._mu:
            keys = store._sorted_slice(kr)
            if reverse:
                keys = keys[::-1]

            def dict_iter():
                for k in keys:
                    store._check_lock(k, self.read_ts)
                    w = self._visible(store._writes[k])
                    if w is not None:
                        yield (k, w.commit_ts, None if w.op == OP_DEL else w.value)

            streams = [dict_iter()]
            for table_id, blocks in store._stable.items():
                hlo, hhi = tablecodec.range_to_handles(kr, table_id)
                if hlo >= hhi:
                    continue
                for block in blocks:
                    if block.commit_ts > self.read_ts:
                        continue
                    lo = int(np.searchsorted(block.handles, hlo, side="left"))
                    hi = int(np.searchsorted(block.handles, hhi, side="left"))
                    if lo >= hi:
                        continue

                    def block_iter(b=block, lo=lo, hi=hi):
                        rng = range(hi - 1, lo - 1, -1) if reverse else range(lo, hi)
                        for i in rng:
                            yield (tablecodec.record_key(b.table_id, int(b.handles[i])), b.commit_ts, (b, i))
                    streams.append(block_iter())

            merged = heapq.merge(*streams, key=lambda e: e[0], reverse=reverse)
            cur_key: bytes | None = None
            cur_ts = -1
            cur_val = None
            for k, ts, v in merged:
                if k != cur_key:
                    if cur_key is not None and cur_val is not None:
                        b, i = cur_val if isinstance(cur_val, tuple) else (None, None)
                        out.append((cur_key, encode_row(b.schema, b.row_values(i)) if b is not None else cur_val))
                        if len(out) >= limit:
                            cur_key = None
                            break
                    cur_key, cur_ts, cur_val = k, ts, v
                elif ts > cur_ts:
                    cur_ts, cur_val = ts, v
            if cur_key is not None and cur_val is not None and len(out) < limit:
                b, i = cur_val if isinstance(cur_val, tuple) else (None, None)
                out.append((cur_key, encode_row(b.schema, b.row_values(i)) if b is not None else cur_val))
        if out:
            store._note_read_traffic(out[0][0], len(out), sum(len(v) for _, v in out))
        return out

    def scan_record_rows(self, kr: KeyRange) -> BulkRows:
        """Scan record keys in [kr) from the row-delta dict and pack visible
        row values contiguously — the hot path feeding the columnar cache.
        Stable columnar blocks are NOT included (the cache merges them via
        :meth:`MemStore.stable_parts`); visible deletes come back as
        ``tombstones`` so the merge can mask stable rows."""
        handles: list[int] = []
        chunks: list[bytes] = []
        starts: list[int] = []
        ends: list[int] = []
        put_ts: list[int] = []
        tombs: list[int] = []
        tomb_ts: list[int] = []
        off = 0
        self._store._check_fence_range(kr)
        with self._store._mu:
            keys = self._store._sorted_slice(kr)
            writes_map = self._store._writes
            locks = self._store._locks
            read_ts = self.read_ts
            for k in keys:
                if locks and k in locks:
                    self._store._check_lock(k, read_ts)
                w = self._visible(writes_map[k])
                if w is None:
                    continue
                if not tablecodec.is_record_key(k):
                    continue
                if w.op != OP_PUT:
                    tombs.append(tablecodec.decode_record_key(k)[1])
                    tomb_ts.append(w.commit_ts)
                    continue
                handles.append(tablecodec.decode_record_key(k)[1])
                put_ts.append(w.commit_ts)
                chunks.append(w.value)
                starts.append(off)
                off += len(w.value)
                ends.append(off)
        if handles or tombs:
            self._store._note_read_traffic(kr.start, len(handles) + len(tombs), off)
        return BulkRows(
            np.asarray(handles, dtype=np.int64),
            np.asarray(starts, dtype=np.int64),
            np.asarray(ends, dtype=np.int64),
            b"".join(chunks),
            np.asarray(tombs, dtype=np.int64),
            np.asarray(put_ts, dtype=np.int64),
            np.asarray(tomb_ts, dtype=np.int64),
        )


class MemStore:
    """The storage node. One process can host several (multi-"node" tests)."""

    def __init__(self, region_split_keys: int = 500_000, lock_ttl_ms: int = 3000):
        import uuid

        self.lock_ttl_ms = lock_ttl_ms
        # distinguishes this store in process-global caches (device arrays):
        # region/table ids restart per store and would otherwise collide
        self.nonce = uuid.uuid4().hex
        self._mu = threading.RLock()
        self._writes: dict[bytes, list[Write]] = {}
        # stable columnar layer: table_id → ingest-ordered StableBlocks
        # (later blocks override earlier ones on handle collision)
        self._stable: dict[int, list[StableBlock]] = {}
        # key → start_ts set of rolled-back txns (out-of-band so write chains
        # stay strictly ascending by commit_ts)
        self._rollbacks: dict[bytes, set[int]] = {}
        self._locks: dict[bytes, Lock] = {}
        # GC pins from services (log backup checkpoints): name → ts
        self._service_safepoints: dict[str, int] = {}
        # columnar change logs: (region_id, table_id) → pending record-key
        # changes since the last delta merge (see _ChangeLog)
        self._changes: dict[tuple[int, int], _ChangeLog] = {}
        self._sorted: list[bytes] | None = []
        self.tso = TimestampOracle()
        self._region_split_keys = region_split_keys
        self._regions: list[Region] = [Region(region_id=1, start=b"", end=b"")]
        self._next_region_id = 2
        self.pd = PlacementDriver(self)
        self._client = None  # installed by copr.CopClient wiring
        self.detector = DeadlockDetector()
        # cluster-singleton election lives WITH the data (ref: etcd-backed
        # owner.Manager — here the store process is the etcd analog, so N
        # SQL layers sharing this store elect exactly one TTL/stats/GC/DDL
        # owner; kv/owner.py holds the lease machinery)
        from tidb_tpu.kv.election import ElectionReplica
        from tidb_tpu.kv.owner import OwnerManager

        self.owner_mgr = OwnerManager()
        # this store's share of the QUORUM election keyspace: a sharded
        # fleet (kv/sharded.py) replicates lease/term state to a majority of
        # these replicas instead of using the local OwnerManager above
        # (kv/election.py — the PD/etcd-member role)
        self.election_replica = ElectionReplica()
        # this store's share of the quorum PLACEMENT keyspace: epoch-
        # versioned table→shard bindings the elastic-placement driver
        # (kv/placement.py) replicates to a majority — the PD region-epoch
        # analog that makes ownership movable at runtime
        from tidb_tpu.kv.placement import PlacementReplica

        self.placement_replica = PlacementReplica()
        # placement fences: table_id → expiry (monotonic seconds; None =
        # permanent). A fenced table's reads AND writes raise RegionError —
        # the cutover signal stale routing clients re-resolve on. TTL
        # fences self-heal when a migration driver dies mid-move.
        self._fences: dict[int, float | None] = {}
        # keyspace traffic heatmap rings (Key Visualizer substrate) — fed by
        # the read/write seams below, served via sys_snapshot "heatmap"
        self.traffic = TrafficStats()
        # one-entry (table-prefix, region-range) resolution memo for the
        # lockless read seam: (key9, start, end, region_id, table_id) —
        # invalidated on region splits and table purges
        self._traffic_memo: tuple | None = None

    # -- owner election (ref: pkg/owner/manager.go:49) ----------------------
    def owner_campaign(
        self, key: str, node_id: str, lease_s: float | None = None, term: int | None = None
    ) -> bool:
        return self.owner_mgr.campaign(key, node_id, lease_s, term=term)

    def owner_of(self, key: str):
        return self.owner_mgr.owner(key)

    def owner_resign(self, key: str, node_id: str) -> None:
        self.owner_mgr.resign(key, node_id)

    def owner_term(self, key: str) -> int:
        """The key's current fencing token (ref: the etcd campaign's lease
        revision — owners carry it so stale renewals are rejectable)."""
        return self.owner_mgr.term(key)

    def owner_granted_term(self, key: str, node_id: str):
        """Fencing token for a node that just won ``key`` (local lookup; the
        quorum backend caches this to avoid a post-grant majority sweep)."""
        return self.owner_mgr.term(key) if self.owner_mgr.owner(key) == node_id else None

    # -- election replica verbs (quorum keyspace; see kv/election.py) -------
    def election_propose(self, key: str, node_id: str, term: int, deadline: float):
        return self.election_replica.propose(key, node_id, term, deadline)

    def election_read(self, key: str):
        return self.election_replica.read(key)

    # -- placement replica verbs (quorum keyspace; see kv/placement.py) ------
    def placement_propose(self, table_id: int, shard: int, epoch: int):
        return self.placement_replica.propose(table_id, shard, epoch)

    def placement_read(self, table_id: int | None = None):
        if table_id is None:
            return self.placement_replica.read_all()
        return self.placement_replica.read(table_id)

    # -- placement fences (the cutover write/read barrier) -------------------
    def fence_table(self, table_id: int, ttl_s: float | None = None) -> None:
        """Fence one table's keyspace: reads and writes raise RegionError
        until unfenced. ``ttl_s`` bounds a migration's cutover blackout (a
        dead driver's fence expires on its own); ``None`` is permanent —
        the post-move state of the OLD owner, so a stale client always gets
        a typed re-route signal instead of a silently empty table."""
        import time as _time

        with self._mu:
            self._fences[table_id] = None if ttl_s is None else _time.monotonic() + ttl_s

    def unfence_table(self, table_id: int) -> None:
        with self._mu:
            self._fences.pop(table_id, None)

    def _fence_live(self, table_id: int) -> bool:
        import time as _time

        ent = self._fences.get(table_id, False)
        if ent is False:
            return False
        if ent is not None and _time.monotonic() >= ent:
            with self._mu:  # expired TTL fence: migration aborted, reopen
                cur = self._fences.get(table_id)
                if cur is not None and _time.monotonic() >= cur:
                    self._fences.pop(table_id, None)
            return False
        return True

    def _check_fence_table(self, table_id: int) -> None:
        """The one home of the fence verdict (clients may match its text)."""
        if self._fences and self._fence_live(table_id):
            raise RegionError(
                table_id, f"table {table_id} placement moved (fenced on this store)"
            )

    def _check_fence_key(self, key: bytes) -> None:
        if not self._fences or key[:1] != tablecodec.TABLE_PREFIX or len(key) < 9:
            return
        from tidb_tpu.utils import codec

        self._check_fence_table(codec.decode_int_raw(key, 1))

    def _check_fence_range(self, kr: KeyRange) -> None:
        """Raise when ``kr`` lies WITHIN one fenced table's keyspace (the
        per-table scan every data path issues). Broader multi-table ranges
        pass — after the purge there is nothing left to return, and during
        the ms-scale cutover blackout the source's copy is still exact."""
        if not self._fences or kr.start[:1] != tablecodec.TABLE_PREFIX or len(kr.start) < 9:
            return
        from tidb_tpu.utils import codec

        tid = codec.decode_int_raw(kr.start, 1)
        if kr.end <= tablecodec.table_prefix(tid + 1):
            self._check_fence_table(tid)

    # -- region migration verbs (kv/placement.py migrate_table) --------------
    def migrate_export(self, table_id: int, after_ts: int = 0, upto_ts: int | None = None,
                       cursor=None, limit: int = 4096, include_locks: bool = False) -> dict:
        """One page of ``table_id``'s committed state for a region move:
        ``(key, op, value, commit_ts, start_ts)`` items carrying their
        ORIGINAL timestamps (concurrent snapshots must read identically
        from either side, and check_txn_status must stay truthful at the
        destination). Pages walk the row-delta dict first, then the stable
        columnar blocks (encoded as row puts at the block's commit ts);
        the FINAL page of a fenced window additionally ships the in-flight
        prewrite locks, so a 2PC commit that re-routes finds them waiting.
        Pure read — replay-safe over the wire. ``cursor`` is opaque:
        ``None`` starts, the returned cursor continues, ``None`` back means
        done."""
        hi_ts = upto_ts if upto_ts is not None else 2**63
        lo_key = tablecodec.table_prefix(table_id)
        hi_key = tablecodec.table_prefix(table_id + 1)
        phase, pos = ("dict", lo_key) if cursor is None else (cursor[0], cursor[1:])
        items: list = []
        next_cur = None
        stable_jobs: list = []
        with self._mu:
            if phase == "dict":
                start = pos if isinstance(pos, bytes) else pos[0]
                for k in self._sorted_slice(KeyRange(max(lo_key, start), hi_key)):
                    if len(items) >= limit:
                        next_cur = ("dict", k)
                        break
                    for w in self._writes.get(k, ()):
                        if after_ts < w.commit_ts <= hi_ts:
                            items.append((k, w.op, w.value, w.commit_ts, w.start_ts))
                else:
                    next_cur = ("stable", 0, 0)
            else:
                bi, ri = int(pos[0]), int(pos[1])
                blocks = self._stable.get(table_id, [])
                budget = limit
                while bi < len(blocks) and budget > 0:
                    b = blocks[bi]
                    if not (after_ts < b.commit_ts <= hi_ts):
                        bi, ri = bi + 1, 0
                        continue
                    take = min(budget, len(b.handles) - ri)
                    stable_jobs.append((b, ri, ri + take))
                    budget -= take
                    ri += take
                    if ri >= len(b.handles):
                        bi, ri = bi + 1, 0
                if bi < len(blocks):
                    next_cur = ("stable", bi, ri)
        # stable blocks are immutable once ingested: encode OUTSIDE the lock
        if stable_jobs:
            from tidb_tpu.kv.rowcodec import encode_row

            for b, lo, hi in stable_jobs:
                for i in range(lo, hi):
                    items.append(
                        (
                            tablecodec.record_key(table_id, int(b.handles[i])),
                            OP_PUT,
                            encode_row(b.schema, b.row_values(i)),
                            b.commit_ts,
                            b.commit_ts,
                        )
                    )
        locks: list = []
        if include_locks and next_cur is None:
            with self._mu:
                for k, l in self._locks.items():
                    if lo_key <= k < hi_key:
                        locks.append((k, l))
        return {"items": items, "locks": locks, "cursor": next_cur}

    def migrate_apply(self, items, locks=()) -> int:
        """Install migrated versions (and in-flight locks) preserving their
        original timestamps. Idempotent: a (key, commit_ts) already present
        is skipped, so the wire verb is replay-safe. Region bookkeeping
        mirrors commit — data_version bumps, change logs note the rows, so
        the destination's device column cache revalidates."""
        applied = 0
        with self._mu:
            touched: dict[int, Region] = {}
            for k, op, v, cts, sts in items:
                chain = self._writes.get(k)
                is_new = chain is None
                if is_new:
                    chain = self._writes[k] = []
                    if self._sorted is not None:
                        if self._sorted and self._sorted[-1] < k:
                            self._sorted.append(k)
                        else:
                            self._sorted = None
                elif any(w.commit_ts == cts for w in chain):
                    continue
                chain.insert(
                    bisect.bisect_left([w.commit_ts for w in chain], cts),
                    Write(cts, sts, op, v),
                )
                applied += 1
                r = self.region_for_key(k)
                r.max_commit_ts = max(r.max_commit_ts, cts)
                if is_new:
                    r.key_count += 1
                touched[id(r)] = r
                self._note_change(r.region_id, k, op, cts)
            for k, lock in locks:
                cur = self._locks.get(k)
                if cur is not None and cur.start_ts != lock.start_ts:
                    continue  # a newer txn holds the key here: never clobber
                if any(w.start_ts == lock.start_ts for w in self._writes.get(k, ())):
                    # the lock's txn already COMMITTED on this store (a
                    # post-cutover sweep re-shipping the source's stale copy
                    # of a lock the client resolved here): re-installing it
                    # would re-lock a decided key
                    continue
                if lock.start_ts in self._rollbacks.get(k, ()):
                    continue  # likewise a decided rollback
                self._locks[k] = lock
            for r in touched.values():
                r.data_version += 1
                self._maybe_auto_split(r)
        return applied

    def purge_table(self, table_id: int) -> None:
        """Drop every version/lock/stable block of ``table_id`` — post-
        cutover hygiene on the OLD owner. Callers must keep the permanent
        fence: without it a stale client would read a silently EMPTY table
        instead of getting the typed re-route signal."""
        lo, hi = tablecodec.table_prefix(table_id), tablecodec.table_prefix(table_id + 1)
        with self._mu:
            doomed = self._sorted_slice(KeyRange(lo, hi))
            for k in doomed:
                self._writes.pop(k, None)
            for k in [k for k in self._locks if lo <= k < hi]:
                del self._locks[k]
            for k in [k for k in self._rollbacks if lo <= k < hi]:
                del self._rollbacks[k]
            self._stable.pop(table_id, None)
            for ck in [ck for ck in self._changes if ck[1] == table_id]:
                del self._changes[ck]
            if doomed:
                self._sorted = None
            for r in self._regions:
                rr = r.range()
                if rr.start < hi and rr.end > lo:
                    self._recount_region(r)
                    r.data_version += 1
        self.traffic.drop_table(table_id)
        self._traffic_memo = None

    # -- workload attribution (read seam) ------------------------------------
    def _note_read_traffic(self, key: bytes, keys: int, nbytes: int) -> None:
        """Attribute a read at ``key``'s region/table into the traffic rings
        AND the active cop-task sidecar (the keys/bytes-scanned RU inputs).
        Rides the hottest read path of the store, so it is lockless end to
        end: a one-entry (table-prefix, region-range) memo resolves the
        repeat-key / scan-locality case with a slice compare and two bytes
        compares, and memo misses walk ``_regions`` WITHOUT the store mutex
        (GIL-snapshot iteration — re-acquiring ``_mu`` here doubled the
        per-get cost under the tier-1 lock-order detector, and a racing
        split at worst misattributes a few advisory counts)."""
        det = _ed.current_cop()
        if det is not None:
            det.keys_scanned += keys
            det.bytes_scanned += nbytes
        tr = self.traffic
        if not tr.enabled or keys <= 0:
            return
        memo = self._traffic_memo
        if (
            memo is not None
            and memo[1] <= key
            and key[:9] == memo[0]
            and (memo[2] == b"" or key < memo[2])
        ):
            tr._note(memo[3], memo[4], 1, 2, keys, nbytes)
            return
        tid = tablecodec.table_id_of(key)
        if tid < 0:
            return
        rid = -1
        for r in self._regions:
            if r.start <= key and (r.end == b"" or key < r.end):
                rid = r.region_id
                self._traffic_memo = (key[:9], r.start, r.end, rid, tid)
                break
        tr._note(rid, tid, 1, 2, keys, nbytes)

    def note_region_read(self, region_id: int, table_id: int, keys: int, nbytes: int) -> None:
        """Logical read traffic with region/table already resolved — the
        cop-serve seam (copr/colcache.get_split). Device-cache hits never
        touch the MVCC seams above, yet a hammered-but-cached region IS hot:
        the heatmap (and the balancer reading it) must see every serve, not
        just the physical builds."""
        tr = self.traffic
        if tr.enabled:
            tr.note_read(region_id, table_id, keys, nbytes)

    # -- columnar change log (write→delta notification seam) ----------------
    def _note_change(self, region_id: int, key: bytes, op: str, ts: int) -> None:
        """Record one committed record-key change (caller holds ``_mu``)."""
        if not tablecodec.is_record_key(key):
            return
        tid, h = tablecodec.decode_record_key(key)
        self._changes.setdefault((region_id, tid), _ChangeLog()).note(ts, h, op)

    def _note_bulk(self, table_id: int, handles: np.ndarray, regions, ts: int) -> None:
        """Record a bulk ingest's handle set per touched region (caller holds
        ``_mu``; ``handles`` sorted ascending). Small slices itemize (they can
        serve the delta read path); big ones degrade to span watermarks."""
        for r in regions:
            hlo, hhi = tablecodec.range_to_handles(r.range(), table_id)
            if hlo >= hhi:
                continue
            lo = int(np.searchsorted(handles, hlo, side="left"))
            hi = int(np.searchsorted(handles, hhi, side="left"))
            if lo >= hi:
                continue
            log = self._changes.setdefault((r.region_id, table_id), _ChangeLog())
            if hi - lo > _CHANGE_ITEMS_CAP:
                log.note_span(ts, int(handles[lo]), int(handles[hi - 1]))
            else:
                for h in handles[lo:hi]:
                    log.note(ts, int(h), OP_PUT)

    def col_changes_since(self, region_id: int, table_id: int, after_ts: int):
        """Changes with commit_ts > after_ts for one (region, table):
        ``("none", None)`` | ``("items", [(ts, handle, op), ...])`` |
        ``("span", (lo, hi))`` — span means itemization was lost; only the
        handle watermark is reliable (merge, don't delta-read)."""
        with self._mu:
            log = self._changes.get((region_id, table_id))
            if log is None or log.lo is None:
                return ("none", None)
            if log.lost and log.lost_max_ts > after_ts:
                return ("span", (log.lo, log.hi))
            items = [it for it in log.items if it[0] > after_ts]
            if not items:
                return ("none", None)
            return ("items", items)

    def col_changes_prune(self, region_id: int, table_id: int, upto_ts: int) -> None:
        """Forget changes at or below ``upto_ts`` — they were folded into a
        freshly merged columnar base."""
        with self._mu:
            log = self._changes.get((region_id, table_id))
            if log is None:
                return
            if log.lost:
                if log.lost_max_ts > upto_ts:
                    return  # cannot prune what we cannot itemize
                log.lost = False
                log.lost_max_ts = 0
                log.items = []
                log.lo = log.hi = None
                return
            log.items = [it for it in log.items if it[0] > upto_ts]
            if log.items:
                hs = [it[1] for it in log.items]
                log.lo, log.hi = min(hs), max(hs)
            else:
                log.lo = log.hi = None

    def col_changes_drop(self, table_id: int) -> None:
        """DDL (drop/truncate) discards the table's change logs."""
        with self._mu:
            for k in [k for k in self._changes if k[1] == table_id]:
                del self._changes[k]

    # -- kv.Storage surface ------------------------------------------------
    def current_ts(self) -> int:
        return self.tso.ts()

    def get_snapshot(self, ts: int) -> Snapshot:
        return Snapshot(self, ts)

    def snap_batch_get(self, pairs) -> list:
        """Batched snapshot point reads: ``[(read_ts, key)]`` →
        ``[bytes | None | KeyLockedError]`` in request order. Same-ts keys
        share one snapshot and one lock acquisition (Snapshot.get_many) —
        the vectorized multi-key lookup the cross-session point-get batcher
        (copr/client.py) amortizes N sessions' reads onto."""
        out: list = [None] * len(pairs)
        by_ts: dict = {}
        for i, (ts, k) in enumerate(pairs):
            by_ts.setdefault(ts, []).append((i, k))
        for ts, items in by_ts.items():
            vals = self.get_snapshot(ts).get_many([k for _, k in items])
            for (i, _), v in zip(items, vals):
                out[i] = v
        return out

    def begin(self):
        from tidb_tpu.kv.txn import Txn

        return Txn(self)

    def get_client(self):
        if self._client is None:
            from tidb_tpu.copr.client import CopClient

            self._client = CopClient(self)
        return self._client

    # -- sorted key index --------------------------------------------------
    def _ensure_sorted(self) -> list[bytes]:
        if self._sorted is None:
            self._sorted = sorted(self._writes.keys())
        return self._sorted

    def _sorted_slice(self, kr: KeyRange) -> list[bytes]:
        keys = self._ensure_sorted()
        lo = bisect.bisect_left(keys, kr.start)
        hi = bisect.bisect_left(keys, kr.end)
        return keys[lo:hi]

    # -- region management -------------------------------------------------
    def regions(self) -> list[Region]:
        with self._mu:
            return list(self._regions)

    def region_for_key(self, key: bytes) -> Region:
        with self._mu:
            for r in self._regions:
                if r.contains(key):
                    return r
            raise KeyError(f"no region for {key!r}")

    def split_region(self, split_key: bytes) -> None:
        """Manual split (ref: failpoint-forced splits in tests)."""
        with self._mu:
            for i, r in enumerate(self._regions):
                if r.contains(split_key) and split_key > r.start:
                    new = Region(
                        region_id=self._next_region_id,
                        start=split_key,
                        end=r.end,
                        data_version=r.data_version,
                        max_commit_ts=r.max_commit_ts,
                    )
                    self._next_region_id += 1
                    r.end = split_key
                    self._regions.insert(i + 1, new)
                    self._traffic_memo = None
                    self._recount_region(r)
                    self._recount_region(new)
                    return

    def _recount_region(self, r: Region) -> None:
        # approximate: a handle present in both the delta dict and a stable
        # block counts twice. key_count only drives the auto-split heuristic,
        # where a ≤2× overestimate just splits a little early.
        n = len(self._sorted_slice(r.range()))
        rr = r.range()
        for tid, blocks in self._stable.items():
            hlo, hhi = tablecodec.range_to_handles(rr, tid)
            if hlo >= hhi:
                continue
            for b in blocks:
                n += int(np.searchsorted(b.handles, hhi)) - int(np.searchsorted(b.handles, hlo))
        r.key_count = n

    def _stable_handles_in(self, r: Region) -> tuple[int | None, np.ndarray | None]:
        """(table_id, handles) of the most-populous stable table inside r."""
        best_tid, best_cnt, best = None, 0, None
        rr = r.range()
        for tid, blocks in self._stable.items():
            hlo, hhi = tablecodec.range_to_handles(rr, tid)
            if hlo >= hhi:
                continue
            parts = []
            for b in blocks:
                lo = int(np.searchsorted(b.handles, hlo))
                hi = int(np.searchsorted(b.handles, hhi))
                if lo < hi:
                    parts.append(b.handles[lo:hi])
            cnt = sum(len(p) for p in parts)
            if cnt > best_cnt:
                best_tid, best_cnt, best = tid, cnt, parts
        if best is None:
            return None, None
        return best_tid, np.sort(np.concatenate(best))

    def _maybe_auto_split(self, r: Region) -> None:
        if r.key_count <= self._region_split_keys:
            return
        keys = self._sorted_slice(r.range())
        tid, stable_handles = self._stable_handles_in(r)
        if stable_handles is not None and len(stable_handles) > len(keys):
            # columnar-dominant region: split at the median stable handle
            split = tablecodec.record_key(tid, int(stable_handles[len(stable_handles) // 2]))
            if r.contains(split) and split > r.start:
                self.split_region(split)
            return
        if len(keys) < 2:
            return
        self.split_region(keys[len(keys) // 2])

    # -- percolator (server side; ref: mvcc.go:768 Prewrite, :1240 Commit) --
    def _check_lock(self, key: bytes, read_ts: int) -> None:
        lock = self._locks.get(key)
        if lock is not None and lock.start_ts <= read_ts and lock.op != OP_PESSIMISTIC_LOCK:
            # pessimistic (lock-only) locks carry no data → readers pass
            raise KeyLockedError(key, lock)

    def prewrite(self, mutations: Sequence[Mutation], primary: bytes, start_ts: int) -> dict:
        """Stage locks; returns write-side accounting (``keys``/``bytes``
        staged) — the counts ride the response headers so the txn layer can
        attribute write RUs without a second pass over the mutations."""
        nbytes = 0
        with self._mu:
            for m in mutations:
                self._check_fence_key(m.key)
                lock = self._locks.get(m.key)
                if lock is not None and lock.start_ts != start_ts:
                    raise KeyLockedError(m.key, lock)
                if lock is not None and lock.op == OP_PESSIMISTIC_LOCK:
                    # upgrading our own pessimistic lock: the conflict window
                    # was already checked against for_update_ts at lock time
                    continue
                writes = self._writes.get(m.key)
                if writes and writes[-1].commit_ts > start_ts:
                    raise WriteConflictError(m.key, writes[-1].commit_ts, start_ts)
                if start_ts in self._rollbacks.get(m.key, ()):
                    raise TxnAbortedError(f"txn {start_ts} already rolled back at {m.key!r}")
            now_ms = time.time() * 1000
            for m in mutations:
                nbytes += len(m.key) + len(m.value)
                self._locks[m.key] = Lock(
                    primary=primary,
                    start_ts=start_ts,
                    op=m.op,
                    value=m.value,
                    ttl_ms=self.lock_ttl_ms,
                    created_ms=now_ms,
                )
        return {"keys": len(mutations), "bytes": nbytes}

    def acquire_pessimistic_lock(
        self,
        keys: Sequence[bytes],
        primary: bytes,
        start_ts: int,
        for_update_ts: int,
        wait_timeout_ms: int = 3000,
    ) -> None:
        """Statement-time lock acquisition (ref: unistore mvcc.go
        PessimisticLock). Blocks (polling) on foreign locks until timeout;
        wait edges feed the deadlock detector, whose victim is the requester
        that closes a cycle. Write-conflict check runs against for_update_ts,
        not start_ts — that is what lets pessimistic txns proceed where
        optimistic ones must restart."""
        import time

        deadline = time.time() * 1000 + wait_timeout_ms
        placed: list[bytes] = []  # locks created by THIS call, for unwind
        try:
            for key in keys:
                while True:
                    with self._mu:
                        self._check_fence_key(key)
                        lock = self._locks.get(key)
                        if lock is None or lock.start_ts == start_ts:
                            writes = self._writes.get(key)
                            if writes and writes[-1].commit_ts > for_update_ts:
                                raise WriteConflictError(key, writes[-1].commit_ts, start_ts)
                            if start_ts in self._rollbacks.get(key, ()):
                                raise TxnAbortedError(f"txn {start_ts} already rolled back at {key!r}")
                            if lock is None:  # keep prewrite-upgraded locks as-is
                                self._locks[key] = Lock(
                                    primary=primary,
                                    start_ts=start_ts,
                                    op=OP_PESSIMISTIC_LOCK,
                                    value=b"",
                                    ttl_ms=self.lock_ttl_ms,
                                    created_ms=time.time() * 1000,
                                )
                                placed.append(key)
                            self.detector.unregister(start_ts)
                            break
                        holder = lock.start_ts
                        expired = lock.expired()
                    # outside the store lock: deadlock check, resolution, backoff
                    self.detector.register(start_ts, holder, key)
                    if expired:
                        self.resolve_lock(key, lock)
                        continue
                    if time.time() * 1000 >= deadline:
                        self.detector.unregister(start_ts)
                        raise LockWaitTimeoutError(key)
                    time.sleep(0.002)
        except Exception:
            # a failed statement must not leave locks the caller doesn't
            # know about (it only records keys on full success)
            self.pessimistic_rollback(placed, start_ts)
            raise

    def pessimistic_rollback(self, keys: Sequence[bytes], start_ts: int) -> None:
        """Release lock-only locks without leaving rollback tombstones (the
        txn may still commit other keys)."""
        with self._mu:
            for k in keys:
                self._check_fence_key(k)
                lock = self._locks.get(k)
                if lock is not None and lock.start_ts == start_ts and lock.op == OP_PESSIMISTIC_LOCK:
                    del self._locks[k]
        self.detector.clean_up(start_ts)

    def commit(self, keys: Sequence[bytes], start_ts: int, commit_ts: int) -> dict:
        """Move staged values into the write column. Returns write-side
        accounting of keys NEWLY committed by THIS call — the idempotent
        re-commit path contributes nothing, so a boRegionMiss re-routed
        commit never double-counts in RU metering or the traffic rings."""
        committed = 0
        committed_bytes = 0
        # (region_id, table_id) → [keys, bytes] for the heatmap write seam
        wtraf: dict[tuple[int, int], list[int]] = {}
        with self._mu:
            touched: set[int] = set()
            for k in keys:
                # fenced table: this region moved (its locks moved WITH it,
                # see migrate_export) — the typed refusal makes the client
                # re-resolve placement and commit at the new owner
                self._check_fence_key(k)
                lock = self._locks.get(k)
                if lock is None or lock.start_ts != start_ts:
                    # idempotent re-commit or lost lock
                    if any(w.start_ts == start_ts for w in self._writes.get(k, [])):
                        continue  # already committed
                    raise TxnAbortedError(f"commit of {k!r}@{start_ts}: lock not found")
                del self._locks[k]
                chain = self._writes.setdefault(k, [])
                is_new = not chain
                op = OP_PUT if lock.op == OP_PUT else OP_DEL
                chain.append(Write(commit_ts, start_ts, op, lock.value))
                if is_new and self._sorted is not None:
                    # cheap append keeps sortedness only if appending at tail
                    if self._sorted and self._sorted[-1] < k:
                        self._sorted.append(k)
                    else:
                        self._sorted = None
                region = self.region_for_key(k)
                region.max_commit_ts = max(region.max_commit_ts, commit_ts)
                if is_new:
                    region.key_count += 1
                touched.add(id(region))
                self._note_change(region.region_id, k, op, commit_ts)
                nb = len(k) + len(lock.value)
                committed += 1
                committed_bytes += nb
                if self.traffic.enabled:
                    tid = tablecodec.table_id_of(k)
                    if tid >= 0:
                        acc = wtraf.setdefault((region.region_id, tid), [0, 0])
                        acc[0] += 1
                        acc[1] += nb
            for r in self._regions:
                if id(r) in touched:
                    r.data_version += 1
                    self._maybe_auto_split(r)
        for (rid, tid), (nk, nb) in wtraf.items():
            self.traffic.note_write(rid, tid, nk, nb)
        return {"keys": committed, "bytes": committed_bytes}

    def ingest(self, keys: Sequence[bytes], values: Sequence[bytes]) -> int:
        """Bulk ingest of pre-encoded committed rows at one fresh commit ts —
        the local-SST-ingest path (ref: lightning local backend + unistore's
        IngestSST): bypasses prewrite/commit per key. Refuses when any
        ingested key holds a lock (writers would race the ingest)."""
        with self._mu:
            start_ts = self.tso.ts()
            commit_ts = self.tso.ts()
            if self._fences:
                for k in keys:
                    self._check_fence_key(k)
            if self._locks:
                for k in keys:
                    if k in self._locks:
                        raise KeyLockedError(k, self._locks[k])
            writes = self._writes
            lo: bytes | None = None
            hi: bytes | None = None
            for k, v in zip(keys, values):
                chain = writes.get(k)
                if chain is None:
                    writes[k] = [Write(commit_ts, start_ts, OP_PUT, v)]
                else:
                    chain.append(Write(commit_ts, start_ts, OP_PUT, v))
                if lo is None or k < lo:
                    lo = k
                if hi is None or k > hi:
                    hi = k
            if lo is None:
                return commit_ts
            # region bookkeeping in one sweep over the regions the ingested
            # span touches (per-key region lookup is the slow path the txn
            # commit pays); untouched regions keep their data_version so
            # their columnar/device caches stay warm
            self._sorted = None
            touched = [
                r
                for r in self._regions
                if (not r.end or lo < r.end) and (not r.start or hi >= r.start)
            ]
            for r in touched:
                self._recount_region(r)
                r.max_commit_ts = max(r.max_commit_ts, commit_ts)
                r.data_version += 1
            # change-log the ingested record keys per (region, table)
            by_table: dict[int, list[int]] = {}
            for k in keys:
                if tablecodec.is_record_key(k):
                    tid, h = tablecodec.decode_record_key(k)
                    by_table.setdefault(tid, []).append(h)
            per_key_bytes = (
                sum(len(k) + len(v) for k, v in zip(keys, values)) / max(1, len(keys))
                if self.traffic.enabled
                else 0.0
            )
            for tid, hs in by_table.items():
                arr = np.sort(np.asarray(hs, dtype=np.int64))
                self._note_bulk(tid, arr, touched, commit_ts)
                if self.traffic.enabled:
                    for r in touched:
                        hlo, hhi = tablecodec.range_to_handles(r.range(), tid)
                        if hlo >= hhi:
                            continue
                        blo = int(np.searchsorted(arr, hlo, side="left"))
                        bhi = int(np.searchsorted(arr, hhi, side="left"))
                        if bhi > blo:
                            self.traffic.note_write(
                                r.region_id, tid, bhi - blo, int((bhi - blo) * per_key_bytes)
                            )
            for r in touched:
                self._maybe_auto_split(r)
            return commit_ts

    def ingest_columnar(self, table_id: int, handles: np.ndarray, cols: dict, schema, dicts: dict | None = None, on_existing: str | None = None) -> int:
        """Bulk ingest of decoded columns as a stable block at one fresh
        commit ts — the columnar twin of :meth:`ingest` (TiFlash stable layer;
        ref: lightning local backend writing SSTs below the LSM). Rows never
        take the per-key dict path: reads overlay the MVCC row-delta dict on
        top of the block. Handles must be unique; they are sorted here.

        ``on_existing`` governs handles already in a stable block:

        - ``'skip'``: drop them from THIS ingest (first-writer-wins). Safe
          only for task-reserved handle ranges, where presence proves the
          same subtask already wrote the identical row — a restarted import
          subtask becomes idempotent WITHOUT rewriting committed history, so
          in-flight snapshots stay consistent (ref: lightning re-importing a
          failed engine's deterministic keys).
        - ``'verify'``: skip rows whose stored values match this ingest
          row-for-row; raise on any mismatch — the duplicate-PK conflict
          surface for user-keyed tables (ref: lightning duplicate detection).
        - ``None``: append blindly."""
        handles = np.asarray(handles, dtype=np.int64)
        if len(handles) == 0:
            return self.tso.ts()
        if not np.all(handles[:-1] < handles[1:]):
            order = np.argsort(handles, kind="stable")
            handles = handles[order]
            cols = {s: (d[order], v[order]) for s, (d, v) in cols.items()}
            if np.any(handles[:-1] == handles[1:]):
                raise ValueError("ingest_columnar: duplicate handles")
        with self._mu:
            self._check_fence_table(table_id)
            if on_existing is not None:
                present = self._stable_present_locked(
                    table_id, handles, cols if on_existing == "verify" else None
                )
                if present.all():
                    return self.tso.ts()  # full duplicate: nothing to do
                if present.any():
                    keep = ~present
                    handles = handles[keep]
                    cols = {s: (d[keep], v[keep]) for s, (d, v) in cols.items()}
            self.tso.ts()  # burn a start_ts to mirror the txn path
            commit_ts = self.tso.ts()
            lo_key = tablecodec.record_key(table_id, int(handles[0]))
            hi_key = tablecodec.record_key(table_id, int(handles[-1]))
            if self._locks:
                for k in self._locks:
                    if lo_key <= k <= hi_key:
                        raise KeyLockedError(k, self._locks[k])
            block = StableBlock(table_id, handles, cols, schema, dicts or {}, commit_ts)
            self._stable.setdefault(table_id, []).append(block)
            touched = [
                r
                for r in self._regions
                if (not r.end or lo_key < r.end) and (not r.start or hi_key >= r.start)
            ]
            for r in touched:
                self._recount_region(r)
                r.max_commit_ts = max(r.max_commit_ts, commit_ts)
                r.data_version += 1
            self._note_bulk(table_id, handles, touched, commit_ts)
            if self.traffic.enabled:
                ncols = max(1, len(cols))
                for r in touched:
                    hlo, hhi = tablecodec.range_to_handles(r.range(), table_id)
                    if hlo >= hhi:
                        continue
                    blo = int(np.searchsorted(handles, hlo, side="left"))
                    bhi = int(np.searchsorted(handles, hhi, side="left"))
                    if bhi > blo:
                        # decoded columns: ~8 data bytes per cell
                        self.traffic.note_write(
                            r.region_id, table_id, bhi - blo, (bhi - blo) * 8 * ncols
                        )
            for r in touched:
                self._maybe_auto_split(r)
            return commit_ts

    def _stable_present_locked(self, table_id: int, handles: np.ndarray, verify_cols: dict | None = None) -> np.ndarray:
        """Bool mask: which of these (sorted) handles already sit in a stable
        block. Span-disjoint blocks (the common first-run case — subtasks
        write disjoint reserved ranges) skip in O(1). With ``verify_cols``,
        every present handle's stored values must equal this ingest's values
        (string codes share the per-table dictionary, so codes compare) —
        a mismatch raises the duplicate-key conflict."""
        present = np.zeros(len(handles), dtype=bool)
        lo, hi = int(handles[0]), int(handles[-1])
        for b in self._stable.get(table_id, ()):
            if not len(b.handles) or int(b.handles[-1]) < lo or int(b.handles[0]) > hi:
                continue
            i = np.searchsorted(b.handles, handles)
            i = np.minimum(i, len(b.handles) - 1)
            hit = b.handles[i] == handles
            if verify_cols is not None and hit.any():
                new_idx = np.nonzero(hit)[0]
                blk_idx = i[hit]
                for slot, (nd, nv) in verify_cols.items():
                    bd, bv = b.cols[slot]
                    same_valid = bv[blk_idx] == nv[new_idx]
                    both = bv[blk_idx] & nv[new_idx]
                    same_val = ~both | (bd[blk_idx] == nd[new_idx])
                    bad = ~(same_valid & same_val)
                    if bad.any():
                        k = int(handles[new_idx[np.nonzero(bad)[0][0]]])
                        raise ValueError(
                            f"duplicate key conflict on handle {k}: existing row differs"
                        )
            present |= hit
        return present

    def stable_parts(self, table_id: int, kr: KeyRange, read_ts: int) -> list[tuple["StableBlock", int, int]]:
        """[(block, lo, hi)] index slices of stable rows with record keys in
        [kr) visible at ``read_ts``, in ingest order."""
        self._check_fence_table(table_id)
        hlo, hhi = tablecodec.range_to_handles(kr, table_id)
        out = []
        with self._mu:
            for block in self._stable.get(table_id, ()):
                if block.commit_ts > read_ts:
                    continue
                lo = int(np.searchsorted(block.handles, hlo, side="left"))
                hi = int(np.searchsorted(block.handles, hhi, side="left"))
                if lo < hi:
                    out.append((block, lo, hi))
        if out:
            nk = sum(hi - lo for _, lo, hi in out)
            # decoded columns: ~8 data bytes per cell
            nb = sum((hi - lo) * 8 * max(1, len(b.cols)) for b, lo, hi in out)
            self._note_read_traffic(kr.start, nk, nb)
        return out

    def stable_row_count(self, table_id: int) -> int:
        with self._mu:
            return sum(len(b) for b in self._stable.get(table_id, ()))

    def drop_stable(self, table_id: int) -> None:
        """DDL (drop/truncate) discards the table's stable blocks."""
        with self._mu:
            if self._stable.pop(table_id, None) is not None:
                for r in self._regions:
                    self._recount_region(r)
                    r.data_version += 1
        self.col_changes_drop(table_id)

    def _stable_holds(self, key: bytes) -> bool:
        """Does ANY stable block contain this record key's handle?"""
        if not self._stable or not tablecodec.is_record_key(key):
            return False
        table_id, handle = tablecodec.decode_record_key(key)
        for block in self._stable.get(table_id, ()):
            i = int(np.searchsorted(block.handles, handle))
            if i < len(block.handles) and int(block.handles[i]) == handle:
                return True
        return False

    def _stable_get(self, key: bytes, read_ts: int, after_ts: int = 0) -> Optional[bytes]:
        """Point read from the stable layer (encode-on-demand). Latest visible
        block wins; blocks at or before ``after_ts`` lose to the caller's dict
        verdict (newest-version-wins across layers)."""
        if not self._stable or not tablecodec.is_record_key(key):
            return None
        table_id, handle = tablecodec.decode_record_key(key)
        from tidb_tpu.kv.rowcodec import encode_row

        for block in reversed(self._stable.get(table_id, ())):
            if block.commit_ts > read_ts or block.commit_ts <= after_ts:
                continue
            i = int(np.searchsorted(block.handles, handle))
            if i < len(block.handles) and int(block.handles[i]) == handle:
                return encode_row(block.schema, block.row_values(i))
        return None

    def rollback(self, keys: Sequence[bytes], start_ts: int) -> None:
        with self._mu:
            for k in keys:
                self._check_fence_key(k)
                lock = self._locks.get(k)
                if lock is not None and lock.start_ts == start_ts:
                    del self._locks[k]
                self._rollbacks.setdefault(k, set()).add(start_ts)

    def check_txn_status(self, primary: bytes, start_ts: int) -> tuple[str, int]:
        """→ ("committed", commit_ts) | ("rolled_back", 0) | ("locked", 0).
        (ref: unistore CheckTxnStatus; TTL expiry handled by caller policy)"""
        with self._mu:
            # fenced primary: its lock/write state moved with the region —
            # answering "rolled_back" from the stale copy could erase a
            # commit that landed at the new owner; force the re-route
            self._check_fence_key(primary)
            lock = self._locks.get(primary)
            if lock is not None and lock.start_ts == start_ts:
                if lock.expired():
                    # dead txn: roll back its primary so the decision is durable
                    del self._locks[primary]
                    self._rollbacks.setdefault(primary, set()).add(start_ts)
                    return "rolled_back", 0
                return "locked", 0
            for w in self._writes.get(primary, []):
                if w.start_ts == start_ts:
                    return "committed", w.commit_ts
            return "rolled_back", 0  # no lock, no write → treat as rolled back

    def resolve_lock(self, key: bytes, lock: Lock) -> None:
        """Resolve one stuck lock by consulting its primary."""
        status, commit_ts = self.check_txn_status(lock.primary, lock.start_ts)
        if status == "committed":
            self.commit([key], lock.start_ts, commit_ts)
        elif status == "rolled_back":
            self.rollback([key], lock.start_ts)
        # "locked": primary still alive → caller backs off and retries

    # -- GC (ref: pkg/store/gcworker) ---------------------------------------
    def gc(self, safe_ts: int) -> int:
        """Drop versions no snapshot at ts ≥ safe_ts can see. Returns number
        of pruned version records."""
        pruned = 0
        with self._mu:
            dead_keys = []
            for k, writes in self._writes.items():
                # find newest write with commit_ts <= safe_ts; keep it (unless DEL), drop older
                keep_from = 0
                for i in range(len(writes) - 1, -1, -1):
                    if writes[i].commit_ts <= safe_ts:
                        keep_from = i
                        if writes[i].op == OP_DEL and not self._stable_holds(k):
                            # a tombstone masking a stable row must survive GC
                            # or the deleted row would resurrect from the block
                            keep_from = i + 1
                        break
                if keep_from > 0:
                    pruned += keep_from
                    del writes[:keep_from]
                if not writes:
                    dead_keys.append(k)
            for k in dead_keys:
                del self._writes[k]
            # rollback tombstones older than the GC horizon can never matter
            # to a future prewrite (its start_ts would conflict anyway)
            for k in list(self._rollbacks):
                self._rollbacks[k] = {ts for ts in self._rollbacks[k] if ts > safe_ts}
                if not self._rollbacks[k]:
                    del self._rollbacks[k]
            if dead_keys:
                self._sorted = None
                for r in self._regions:
                    self._recount_region(r)
        return pruned

    # -- raw ops (catalog/meta convenience; single-key autocommit) ----------
    def resolved_ts(self) -> int:
        """A ts every commit at or below which has fully APPLIED (ref: the
        resolved-ts concept in TiKV). Percolator draws commit_ts after
        prewrite locks are placed, so any drawn-but-unapplied commit still
        holds locks — the minimum live lock start_ts bounds it."""
        with self._mu:
            if self._locks:
                return min(l.start_ts for l in self._locks.values()) - 1
            return self.tso.ts()

    def register_service_safepoint(self, name: str, ts: int) -> None:
        """Pin GC: versions newer than ``ts`` stay until the service (e.g. a
        log-backup task's checkpoint) advances (ref: PD service safepoints
        that br registers for log backup)."""
        with self._mu:
            self._service_safepoints[name] = ts

    def remove_service_safepoint(self, name: str) -> None:
        with self._mu:
            self._service_safepoints.pop(name, None)

    def min_service_safepoint(self) -> Optional[int]:
        with self._mu:
            return min(self._service_safepoints.values()) if self._service_safepoints else None

    def changes_since(self, after_ts: int, upto_ts: int, record_only: bool = True):
        """Committed versions with after_ts < commit_ts <= upto_ts, commit-ts
        ordered — the log-backup change feed (ref: br log backup observing
        the KV change stream). Stable-block ingests emit as row puts at the
        block's commit ts. ``record_only`` filters to table record keys (the
        PITR replay recomputes index entries from rows)."""
        out: list[tuple[bytes, str, bytes, int]] = []
        in_window: list[tuple[int, "StableBlock"]] = []
        with self._mu:
            for key, chain in self._writes.items():
                if record_only and not tablecodec.is_record_key(key):
                    continue
                for w in chain:
                    if after_ts < w.commit_ts <= upto_ts:
                        out.append((key, w.op, w.value, w.commit_ts))
            for tid, blocks in self._stable.items():
                for b in blocks:
                    if after_ts < b.commit_ts <= upto_ts:
                        in_window.append((tid, b))
        # blocks are immutable once ingested: encode OUTSIDE the store lock
        from tidb_tpu.kv.rowcodec import encode_row

        for tid, b in in_window:
            for i in range(len(b.handles)):
                out.append(
                    (
                        tablecodec.record_key(tid, int(b.handles[i])),
                        OP_PUT,
                        encode_row(b.schema, b.row_values(i)),
                        b.commit_ts,
                    )
                )
        out.sort(key=lambda e: e[3])
        return out

    def raw_put(self, key: bytes, value: bytes) -> None:
        with self._mu:  # ts drawn under the lock keeps chains ascending
            self._check_fence_key(key)
            ts = self.tso.ts()
            chain = self._writes.setdefault(key, [])
            if not chain and self._sorted is not None:
                if self._sorted and self._sorted[-1] < key:
                    self._sorted.append(key)
                else:
                    self._sorted = None
            chain.append(Write(ts, ts, OP_PUT, value))
            r = self.region_for_key(key)
            r.max_commit_ts = max(r.max_commit_ts, ts)
            r.data_version += 1
            self._note_change(r.region_id, key, OP_PUT, ts)

    def raw_get(self, key: bytes) -> Optional[bytes]:
        return Snapshot(self, self.tso.ts()).get(key)

    def raw_cas(self, key: bytes, expected: Optional[bytes], value: bytes) -> bool:
        """Atomic compare-and-swap on a raw key (``expected`` None = key must
        be absent). The catalog's cross-process DDL guard hangs off this —
        two read-then-write RPCs cannot serialize schema rewrites."""
        with self._mu:
            self._check_fence_key(key)
            ts = self.tso.ts()
            cur = None
            chain = self._writes.get(key)
            if chain:
                for w in reversed(chain):
                    if w.commit_ts <= ts:
                        cur = None if w.op == OP_DEL else w.value
                        break
            if cur != expected:
                return False
            chain = self._writes.setdefault(key, [])
            if not chain and self._sorted is not None:
                if self._sorted and self._sorted[-1] < key:
                    self._sorted.append(key)
                else:
                    self._sorted = None
            chain.append(Write(ts, ts, OP_PUT, value))
            r = self.region_for_key(key)
            r.max_commit_ts = max(r.max_commit_ts, ts)
            r.data_version += 1
            self._note_change(r.region_id, key, OP_PUT, ts)
            return True

    def raw_delete(self, key: bytes) -> None:
        with self._mu:
            self._check_fence_key(key)
            ts = self.tso.ts()
            self._writes.setdefault(key, []).append(Write(ts, ts, OP_DEL))
            r = self.region_for_key(key)
            r.data_version += 1
            self._note_change(r.region_id, key, OP_DEL, ts)

    def raw_scan(self, kr: KeyRange, limit: int = 2**63) -> list[tuple[bytes, bytes]]:
        return Snapshot(self, self.tso.ts()).scan(kr, limit)
