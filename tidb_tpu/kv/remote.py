"""The process boundary: a storage server and its remote store client.

Reference parity: the TiDB↔TiKV seam — `kv.Storage` backed by gRPC
(pkg/store/driver/tikv_driver.go) with coprocessor DAGs executed store-side
(pkg/store/copr/coprocessor.go:87 CopClient.Send → gRPC Cop; MPP dispatch
pkg/kv/mpp.go:189-199). Here the wire is a length-framed JSON+blob protocol
over TCP, and the payloads are the SAME contracts the in-process path uses:
`dagpb.DAGRequest.to_pb()` travels out, `utils.chunk.encode_chunk` travels
back, percolator verbs (prewrite/commit/rollback/resolve) ship mutation
lists. A SQL-layer process built on :class:`RemoteStore` plans and runs the
Volcano tree locally while every byte of data — and the device engine —
lives in the server process, exactly the TiKV-serves-the-region role.

Frame layout: 8-byte little-endian total length, then 4-byte header length,
the JSON header, and the blobs (each 8-byte length + bytes) the header's
``nblobs`` declares. Short keys ride the header base64; row payloads ride
blobs.
"""

from __future__ import annotations

import base64
import json
import socket
import struct
import threading
import time
from typing import Optional, Sequence

from tidb_tpu.kv.kv import (
    KeyLockedError,
    KeyRange,
    RegionError,
    Request,
    RequestType,
    StoreType,
    TxnAbortedError,
    UndeterminedError,
    WriteConflictError,
)
from tidb_tpu.kv.memstore import OP_DEL, OP_PUT, Lock, MemStore, Mutation, Region
from tidb_tpu.utils import eventlog as _ev
from tidb_tpu.utils import execdetails as _ed
from tidb_tpu.utils import failpoint
from tidb_tpu.utils import tracing as _tracing
from tidb_tpu.utils.backoff import Backoffer, BackoffExhausted, boRPC


def _b(x: bytes) -> str:
    return base64.b64encode(x).decode()


def _ub(s: str) -> bytes:
    return base64.b64decode(s)


def _send_frame(sock: socket.socket, header: dict, blobs: Sequence[bytes] = ()) -> None:
    h = json.dumps({**header, "nblobs": len(blobs)}).encode()
    parts = [struct.pack("<I", len(h)), h]
    for b in blobs:
        parts.append(struct.pack("<Q", len(b)))
        parts.append(b)
    payload = b"".join(parts)
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    out = bytearray()
    while len(out) < n:
        got = sock.recv(n - len(out))
        if not got:
            raise ConnectionError("peer closed")
        out.extend(got)
    return bytes(out)


def _recv_frame(sock: socket.socket) -> tuple[dict, list[bytes]]:
    (total,) = struct.unpack("<Q", _recv_exact(sock, 8))
    payload = _recv_exact(sock, total)
    (hlen,) = struct.unpack_from("<I", payload, 0)
    header = json.loads(payload[4 : 4 + hlen])
    blobs = []
    off = 4 + hlen
    for _ in range(header.get("nblobs", 0)):
        (blen,) = struct.unpack_from("<Q", payload, off)
        off += 8
        blobs.append(payload[off : off + blen])
        off += blen
    return header, blobs


def _lock_pb(lock: Lock) -> dict:
    return {
        "primary": _b(lock.primary),
        "start_ts": lock.start_ts,
        "op": lock.op,
        "value": _b(lock.value),
        "ttl_ms": lock.ttl_ms,
        "created_ms": lock.created_ms,
    }


def _lock_from_pb(pb: dict) -> Lock:
    return Lock(_ub(pb["primary"]), pb["start_ts"], pb["op"], _ub(pb["value"]), pb["ttl_ms"], pb["created_ms"])


def _migrate_items_blob(items) -> bytes:
    """Pack migrate_export items: per item 1B op (0=put 1=del), 4B klen,
    key, 8B vlen, value, 8B commit_ts, 8B start_ts."""
    buf = bytearray()
    for k, op, v, cts, sts in items:
        buf += bytes([0 if op == OP_PUT else 1])
        buf += struct.pack("<I", len(k)) + k
        buf += struct.pack("<Q", len(v)) + v
        buf += struct.pack("<QQ", cts, sts)
    return bytes(buf)


def _migrate_items_unpack(buf: bytes) -> list:
    items = []
    off = 0
    while off < len(buf):
        op = OP_PUT if buf[off] == 0 else OP_DEL
        off += 1
        (klen,) = struct.unpack_from("<I", buf, off)
        off += 4
        k = buf[off : off + klen]
        off += klen
        (vlen,) = struct.unpack_from("<Q", buf, off)
        off += 8
        v = buf[off : off + vlen]
        off += vlen
        cts, sts = struct.unpack_from("<QQ", buf, off)
        off += 16
        items.append((k, op, v, cts, sts))
    return items


def _cursor_pb(cur):
    """Migration cursor → JSON-able (dict-phase cursors carry a raw key)."""
    if cur is None:
        return None
    if cur[0] == "dict":
        return ["dict", _b(cur[1])]
    return ["stable", cur[1], cur[2]]


def _cursor_from_pb(pb):
    if pb is None:
        return None
    if pb[0] == "dict":
        return ("dict", _ub(pb[1]))
    return ("stable", int(pb[1]), int(pb[2]))


# every section name sys_report's request side may select — the graftcheck
# sys-sections rule asserts each _want("...") literal below is declared here,
# so a new heavy section cannot silently ship to load probes that asked for
# nothing (the sections=() discipline)
SYS_SECTIONS = frozenset({"metrics", "statements", "slow", "heatmap"})


def sys_report(store=None, server=None, hist=None, sections=None) -> dict:
    """One process's introspection report — what the replay-safe
    ``sys_snapshot`` verb ships fleet-wide (ref: the gRPC coprocessor
    endpoint for memory tables serving ``information_schema.cluster_*``,
    rpc_server.go:96). Walks the process-global metrics registry, the
    store-side StmtSummary ring (``server`` given), cop-pool depth,
    device-cache residency, uptime, and process info into one JSON-able
    dict; ``hist`` additionally attaches the metrics-history rings (True =
    every series, a string = that metric only). ``sections`` selects the
    HEAVY parts (any of "metrics"/"statements"/"slow"): None ships them
    all, an iterable ships only those named — cluster_info/cluster_load
    sweeps and GET /cluster request ``sections=()`` so a load probe never
    serializes whole slow rings over the wire."""
    import os as _os

    from tidb_tpu.utils import metrics as _m
    from tidb_tpu.utils import metricshist as _mh

    want = None if sections is None else set(sections)

    def _want(k: str) -> bool:
        return want is None or k in want

    now = time.time()
    rec = _mh.recorder()
    rep: dict = {
        "pid": _os.getpid(),
        "version": "8.0.11-tidb-tpu",
        "start_time": _mh.PROC_START,
        "uptime_s": round(now - _mh.PROC_START, 3),
        "stmts": _m.STMT_TOTAL.total(),
        "cop_tasks": _m.COP_TASKS.total(),
        "conns": int(_m.SERVER_CONNS.get()),
        # recent rates need the history recorder running (default on for
        # server processes); 0.0 with no samples — never an error
        "qps": round(rec.rate("tidb_tpu_executor_statement_total"), 3),
        "cop_qps": round(rec.rate("tidb_tpu_copr_task_total"), 3),
        "delta_rows": _m.DEVICE_DELTA_ROWS.get(),
    }
    if _want("metrics"):
        rep["metrics"] = _m.REGISTRY.snapshot()
    from tidb_tpu.copr.client import cop_pool_stats

    rep["cop_pool"], rep["cop_queue"] = cop_pool_stats()
    if store is not None and isinstance(store, MemStore):
        from tidb_tpu.copr.colcache import cache_for

        rep["device_cache_bytes"] = cache_for(store).resident_bytes()
        ring = getattr(store, "cop_ring", None)
        if ring is not None and _want("statements"):
            # embedded fleet member: its per-store cop-digest ring ships in
            # the same section a store server's StmtSummary would, so the
            # balancer's hot-table boost works in-process too
            rep["statements"] = [st.to_pb() for st in ring.stats()[-64:]]
        if _want("heatmap"):
            # keyspace traffic rings (Key Visualizer substrate) — heavy like
            # statements/slow, so only shipped when asked for
            rep["heatmap"] = store.traffic.snapshot()
    if server is not None:
        rep["addr"] = f"{server.host}:{server.port}"
        with server._conns_mu:
            rep["conns"] = len(server._conns)
        if _want("statements"):
            rep["statements"] = [st.to_pb() for st in server.stmt_summary.stats()[-64:]]
        if _want("slow"):
            rep["slow"] = [e.to_pb() for e in server.stmt_summary.slow_queries()[-128:]]
    if hist:
        rep["history"] = [
            list(r) for r in rec.series(name=hist if isinstance(hist, str) else None)
        ]
    return rep


class StoreServer:
    """Serves one MemStore (and its engines) to remote SQL-layer processes."""

    def __init__(self, store: MemStore, host: str = "127.0.0.1", port: int = 0):
        self.store = store
        self._sock = socket.create_server((host, port))
        self.host, self.port = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop, daemon=True, name="store-server")
        self._mpp = None  # lazy MPPTaskManager (first dispatch pays SQL-context open)
        self._mpp_mu = threading.Lock()
        # live client connections, so shutdown() behaves like process death:
        # in-flight requests see a reset, not a silent hang (chaos tests kill
        # and resurrect in-process servers this way)
        self._conns: set[socket.socket] = set()
        self._conns_mu = threading.Lock()
        # store-side cop slow log (the TiKV-slow-log analog): every cop task
        # records into this ring; tasks over [observability] store-slow-cop-ms
        # pin a SlowEntry. Served fleet-wide via the sys_snapshot verb.
        from tidb_tpu.utils.stmtsummary import StmtSummary

        self.stmt_summary = StmtSummary(capacity=64, slow_capacity=128)

    def _mpp_mgr(self):
        with self._mpp_mu:
            if self._mpp is None:
                from tidb_tpu.parallel.mpptask import MPPTaskManager

                self._mpp = MPPTaskManager(self.store)
            return self._mpp

    def start(self) -> int:
        # the in-process metrics history rides along (default on, refcounted
        # — shared with any embedded DB's background loops in this process)
        from tidb_tpu.utils.metricshist import recorder

        recorder().start()
        self._rec_started = True
        self._thread.start()
        # background delta-merge sweep (the embedded DB's owner-gated
        # 'colmerge' timer mirrored onto the storage tier): this server is
        # the single owner of its store's column cache by construction, so
        # the gate is just the server's own stop event — without it a store
        # only folds deltas when a query crosses the merge threshold
        from tidb_tpu import config as _config

        interval = _config.current().store_colmerge_interval_s
        if interval > 0:
            self._colmerge = threading.Thread(
                target=self._colmerge_loop, args=(interval,), daemon=True,
                name="store-colmerge",
            )
            self._colmerge.start()
        return self.port

    def _colmerge_loop(self, interval: float) -> None:
        from tidb_tpu.copr.colcache import cache_for

        while not self._stop.wait(interval):
            try:
                cache_for(self.store).merge_pending(should_stop=self._stop.is_set)
            # a failed sweep retries next tick; queries still merge on the
            # query-path threshold, so nothing is lost — only deferred
            except Exception:  # graftcheck: off=except-swallow
                pass

    def shutdown(self) -> None:
        if getattr(self, "_rec_started", False) and not self._stop.is_set():
            from tidb_tpu.utils.metricshist import recorder

            recorder().stop()
        self._stop.set()
        cm = getattr(self, "_colmerge", None)
        if cm is not None and cm is not threading.current_thread():
            cm.join(timeout=5)  # a mid-sweep merge stops at the next region
        try:
            # wake the blocked accept() (it holds the listener's file
            # description, so close() alone would leave the port accepting)
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_mu:
            conns, self._conns = list(self._conns), set()
        for c in conns:
            try:
                # SHUT_RDWR, not just close(): a serve thread blocked in
                # recv holds the open file description, so close() alone
                # neither wakes it nor sends the peer a FIN
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            # the stop re-check must happen INSIDE the registry lock:
            # shutdown() sets _stop before draining _conns, so either it
            # drains this conn or we observe _stop here — an unlocked check
            # lets a conn accepted pre-shutdown slip into the fresh set and
            # keep a "dead" server answering one client
            with self._conns_mu:
                if self._stop.is_set():  # raced shutdown: refuse, don't serve
                    try:
                        conn.close()
                    except OSError:
                        pass
                    return
                self._conns.add(conn)
            lg = _ev.on(_ev.DEBUG)
            if lg is not None:
                lg.emit(_ev.DEBUG, "store", "conn_open", port=self.port)
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True, name="store-conn"
            ).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            while True:
                header, blobs = _recv_frame(conn)
                try:
                    reply, rblobs = self._dispatch(header, blobs)
                except RegionError as e:
                    # typed for EVERY verb (not just cop): a placement-fenced
                    # table refuses reads/writes/commits with RegionMiss and
                    # the client re-resolves routing under boRegionMiss —
                    # never a Generic error, never an UndeterminedError
                    reply, rblobs = {"err": "RegionMiss", "region_id": getattr(e, "region_id", -1)}, []
                except KeyLockedError as e:
                    reply, rblobs = {"err": "KeyLocked", "key": _b(e.key), "lock": _lock_pb(e.lock)}, []
                except WriteConflictError as e:
                    reply, rblobs = {
                        "err": "WriteConflict",
                        "key": _b(e.key),
                        "conflict_ts": e.conflict_ts,
                        "start_ts": e.start_ts,
                    }, []
                except TxnAbortedError as e:
                    reply, rblobs = {"err": "TxnAborted", "msg": str(e)}, []
                except Exception as e:  # surfaced to the caller, not the server log
                    # the kind travels with the message so the client can
                    # re-type semantically load-bearing errors (a KILL/OOM
                    # verdict must never be mistaken for an engine failure
                    # and re-run on another engine — see run_task_resilient)
                    reply, rblobs = {
                        "err": "Generic",
                        "kind": type(e).__name__,
                        "msg": f"{type(e).__name__}: {e}",
                    }, []
                _send_frame(conn, reply, rblobs)
        except (ConnectionError, OSError):
            pass
        finally:
            with self._conns_mu:
                self._conns.discard(conn)
            lg = _ev.on(_ev.DEBUG)
            if lg is not None:
                lg.emit(_ev.DEBUG, "store", "conn_close", port=self.port)
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, h: dict, blobs: list[bytes]):
        st = self.store
        cmd = h["cmd"]
        if cmd == "ping":
            return {"ok": 1}, []
        if cmd == "sys_snapshot":
            # the store-introspection verb (replay-safe: a pure read of
            # process state) — one JSON-able health/load report per store,
            # the substrate of information_schema.cluster_* and the
            # SQL layer's StoreHealthRegistry
            return {
                "report": sys_report(
                    store=st, server=self, hist=h.get("hist"),
                    sections=h.get("sections"),
                )
            }, []
        if cmd == "log_search":
            # fleet log search (replay-safe: a pure read of the process's
            # event rings) — ALL filtering happens server-side so a ring
            # never ships whole: time range, min level, component, regex,
            # and the row cap travel in the header
            from tidb_tpu.utils import eventlog as _evlog

            lim = h.get("limit", 256)
            rows = _evlog.get().search(
                since=h.get("since"),
                until=h.get("until"),
                min_level=int(h.get("min_level", _evlog.DEBUG)),
                component=h.get("component"),
                pattern=h.get("pattern"),
                limit=int(lim) if lim is not None else None,
            )
            return {"rows": [list(r) for r in rows]}, []
        if cmd == "current_ts":
            return {"ts": st.current_ts()}, []
        if cmd == "tso":
            return {"ts": st.tso.ts()}, []
        if cmd == "raw_get":
            v = st.raw_get(_ub(h["key"]))
            return ({"hit": v is not None}, [v] if v is not None else [])
        if cmd == "raw_put":
            st.raw_put(_ub(h["key"]), blobs[0])
            return {"ok": 1}, []
        if cmd == "raw_delete":
            st.raw_delete(_ub(h["key"]))
            return {"ok": 1}, []
        if cmd == "raw_cas":
            expected = blobs[0] if h["has_expected"] else None
            ok = st.raw_cas(_ub(h["key"]), expected, blobs[-1])
            return {"ok": int(ok)}, []
        if cmd == "raw_scan":
            pairs = st.raw_scan(KeyRange(_ub(h["start"]), _ub(h["end"])), limit=h.get("limit", 2**62))
            out = bytearray()
            for k, v in pairs:
                out += struct.pack("<II", len(k), len(v)) + k + v
            return {"n": len(pairs)}, [bytes(out)]
        if cmd == "run_gc":
            from tidb_tpu.kv.gcworker import GCWorker

            w = GCWorker(st, life_ms=h.get("life_ms", 600_000))
            pruned = w.run_once(h.get("safe_point"))
            return {"pruned": pruned, "safe_point": w.safe_point}, []
        if cmd == "snap_get":
            v = st.get_snapshot(h["ts"]).get(_ub(h["key"]))
            return ({"hit": v is not None}, [v] if v is not None else [])
        if cmd == "snap_batch_get":
            # batched point reads (TiKV batch-commands idiom): N keys, one
            # RPC, one vectorized store lookup. Per-key lock conflicts ship
            # as per-key verdicts — one locked key must not fail the batch.
            outs = st.snap_batch_get([(ts, _ub(kb)) for ts, kb in h["gets"]])
            results = []
            vals = []
            for v in outs:
                if isinstance(v, KeyLockedError):
                    results.append({"err": "KeyLocked", "key": _b(v.key), "lock": _lock_pb(v.lock)})
                elif v is None:
                    results.append({"hit": 0})
                else:
                    results.append({"hit": 1})
                    vals.append(v)
            return {"gets": results}, vals
        if cmd == "snap_scan":
            kr = KeyRange(_ub(h["start"]), _ub(h["end"]))
            pairs = st.get_snapshot(h["ts"]).scan(kr, limit=h.get("limit", 2**63), reverse=h.get("reverse", False))
            out = bytearray()
            for k, v in pairs:
                out += struct.pack("<II", len(k), len(v)) + k + v
            return {"n": len(pairs)}, [bytes(out)]
        if cmd == "prewrite":
            # muts blob: per mutation 1B op (0=put 1=del) + 4B klen + key + 8B vlen + value
            muts = []
            buf = blobs[0]
            off = 0
            while off < len(buf):
                op = buf[off]
                off += 1
                (klen,) = struct.unpack_from("<I", buf, off)
                off += 4
                key = buf[off : off + klen]
                off += klen
                (vlen,) = struct.unpack_from("<Q", buf, off)
                off += 8
                val = buf[off : off + vlen]
                off += vlen
                muts.append(Mutation(OP_PUT if op == 0 else OP_DEL, key, val))
            counts = st.prewrite(muts, _ub(h["primary"]), h["start_ts"])
            # write-side accounting rides the reply headers (RU metering)
            return {"ok": 1, **(counts or {})}, []
        if cmd == "commit":
            counts = st.commit([_ub(k) for k in h["keys"]], h["start_ts"], h["commit_ts"])
            return {"ok": 1, **(counts or {})}, []
        if cmd == "rollback":
            st.rollback([_ub(k) for k in h["keys"]], h["start_ts"])
            return {"ok": 1}, []
        if cmd == "drop_stable":
            st.drop_stable(h["table_id"])
            return {"ok": 1}, []
        if cmd == "owner_campaign":
            # the fencing token ("term") rides the wire so a renewal by a
            # deposed owner is rejected server-side (kv/owner.py term check)
            ok = st.owner_campaign(h["key"], h["node_id"], h.get("lease_s"), term=h.get("term"))
            return {"ok": int(ok)}, []
        if cmd == "owner_of":
            return {"owner": st.owner_of(h["key"])}, []
        if cmd == "owner_resign":
            st.owner_resign(h["key"], h["node_id"])
            return {"ok": 1}, []
        if cmd == "owner_term":
            return {"term": st.owner_term(h["key"])}, []
        if cmd == "placement_propose":
            # quorum placement replica verb (kv/placement.py): idempotent —
            # re-proposing an accepted binding re-accepts, so replay-safe
            ok, epoch = st.placement_propose(h["tid"], h["shard"], h["epoch"])
            return {"ok": int(ok), "epoch": epoch}, []
        if cmd == "placement_read":
            if h.get("tid") is None:
                recs = st.placement_read(None)
                return {"recs": [[tid, e, s] for tid, e, s in recs]}, []
            epoch, shard = st.placement_read(h["tid"])
            return {"epoch": epoch, "shard": shard}, []
        if cmd == "fence_table":
            # placement cutover fence (idempotent → replay-safe): reads and
            # writes of the table now answer RegionMiss until unfenced
            st.fence_table(h["tid"], h.get("ttl_s"))
            return {"ok": 1}, []
        if cmd == "unfence_table":
            st.unfence_table(h["tid"])
            return {"ok": 1}, []
        if cmd == "migrate_export":
            # region-move page read (pure read → replay-safe)
            page = st.migrate_export(
                h["tid"], after_ts=h.get("after_ts", 0), upto_ts=h.get("upto_ts"),
                cursor=_cursor_from_pb(h.get("cursor")), limit=h.get("limit", 4096),
                include_locks=bool(h.get("locks")),
            )
            return {
                "cursor": _cursor_pb(page["cursor"]),
                "locks": [[_b(k), _lock_pb(l)] for k, l in page["locks"]],
            }, [_migrate_items_blob(page["items"])]
        if cmd == "migrate_region":
            # region-move apply (idempotent per (key, commit_ts) → replay-
            # safe): installs migrated versions + in-flight prewrite locks
            n = st.migrate_apply(
                _migrate_items_unpack(blobs[0]) if blobs else [],
                [(_ub(k), _lock_from_pb(l)) for k, l in h.get("locks", ())],
            )
            return {"applied": n}, []
        if cmd == "purge_table":
            st.purge_table(h["tid"])
            return {"ok": 1}, []
        if cmd == "election_propose":
            # quorum election replica verb (kv/election.py): idempotent —
            # re-proposing an accepted record re-accepts, so replay-safe
            ok, term = st.election_propose(h["key"], h["node_id"], h["term"], h["deadline"])
            return {"ok": int(ok), "term": term}, []
        if cmd == "election_read":
            term, owner, deadline = st.election_read(h["key"])
            return {"term": term, "owner": owner, "deadline": deadline}, []
        if cmd == "check_txn_status":
            status, commit_ts = st.check_txn_status(_ub(h["primary"]), h["start_ts"])
            return {"status": status, "commit_ts": commit_ts}, []
        if cmd == "pessimistic_rollback":
            st.pessimistic_rollback([_ub(k) for k in h["keys"]], h["start_ts"])
            return {"ok": 1}, []
        if cmd == "acquire_lock":
            st.acquire_pessimistic_lock(
                [_ub(k) for k in h["keys"]], _ub(h["primary"]), h["start_ts"], h["for_update_ts"], h["wait_ms"]
            )
            return {"ok": 1}, []
        if cmd == "resolve_lock":
            st.resolve_lock(_ub(h["key"]), _lock_from_pb(h["lock"]))
            return {"ok": 1}, []
        if cmd == "detector_cleanup":
            st.detector.clean_up(h["start_ts"])
            return {"ok": 1}, []
        if cmd == "regions_in_ranges":
            ranges = [KeyRange(_ub(a), _ub(b)) for a, b in h["ranges"]]
            out = []
            for region, krs in st.pd.regions_in_ranges(ranges):
                out.append(
                    {
                        "id": region.region_id,
                        "start": _b(region.start),
                        "end": _b(region.end),
                        "ver": region.data_version,
                        "krs": [[_b(kr.start), _b(kr.end)] for kr in krs],
                    }
                )
            return {"regions": out}, []
        if cmd == "ingest":
            # bulk committed-row ingest (restore path): pairs ride one blob
            buf = blobs[0]
            keys, vals = [], []
            off = 0
            for _ in range(h["n"]):
                klen, vlen = struct.unpack_from("<IQ", buf, off)
                off += 12
                keys.append(buf[off : off + klen])
                off += klen
                vals.append(buf[off : off + vlen])
                off += vlen
            ts = st.ingest(keys, vals)
            return {"ts": ts}, []
        if cmd == "ingest_columnar":
            # the lightning-style columnar ingest crossing the process
            # boundary (ref: lightning local backend writing into TiKV)
            import numpy as _np

            from tidb_tpu.expression.expr import _ft_from_pb
            from tidb_tpu.kv.rowcodec import RowSchema
            from tidb_tpu.utils.chunk import Dictionary

            n = h["n"]
            handles = _np.frombuffer(blobs[0], dtype=_np.int64).copy()
            cols = {}
            bi = 1
            for slot, dt in h["slots"]:
                data = _np.frombuffer(blobs[bi], dtype=_np.dtype(dt)).copy()
                valid = _np.frombuffer(blobs[bi + 1], dtype=_np.bool_).copy()
                cols[slot] = (data, valid)
                bi += 2
            dicts = {}
            for slot in h["dict_slots"]:
                buf = blobs[bi]
                bi += 1
                vals = []
                off = 0
                while off < len(buf):
                    (ln,) = struct.unpack_from("<I", buf, off)
                    off += 4
                    vals.append(buf[off : off + ln])
                    off += ln
                dicts[slot] = Dictionary(vals)
            schema = RowSchema([_ft_from_pb(f) for f in h["schema"]])
            ts = st.ingest_columnar(
                h["table_id"], handles[:n], cols, schema, dicts,
                on_existing=h.get("on_existing"),
            )
            return {"ts": ts}, []
        if cmd == "mpp_ndev":
            return {"ndev": self._mpp_mgr().ndev()}, []
        if cmd == "mpp_dispatch":
            # DispatchMPPTask analog (ref: kv/mpp.go:189): the gather spec
            # arrives as table ids + expression pbs; execution starts on a
            # worker thread against the LOCAL store + mesh. An incoming
            # trace context makes the task session record real spans that
            # ship home with the result (Dapper-style propagation).
            task_id = self._mpp_mgr().dispatch(h["spec"], h["read_ts"], trace=h.get("trace"))
            return {"task_id": task_id}, []
        if cmd == "mpp_conn":
            # EstablishMPPConns analog: long-poll for the merged result frame
            done, blob, kind, msg, warns, exec_pb, spans = self._mpp_mgr().conn(
                h["task_id"], h.get("wait_s", 1.0)
            )
            if not done:
                return {"done": 0}, []
            if kind:
                return {"done": 1, "err_kind": kind, "msg": msg}, []
            reply = {"done": 1, "warnings": warns}
            if exec_pb:
                reply["exec"] = exec_pb
            if spans:
                reply["spans"] = spans
            return reply, [blob]
        if cmd == "mpp_cancel":
            self._mpp_mgr().cancel(h["task_id"])
            return {"ok": 1}, []
        if cmd == "cop":
            # the coprocessor boundary: DAG in, chunk out (ref: Cop gRPC)
            from tidb_tpu.copr import dagpb
            from tidb_tpu.copr.client import _engines
            from tidb_tpu.utils.chunk import encode_chunk

            dag = dagpb.DAGRequest.from_pb(h["dag"])
            region = next((r for r in st.regions() if r.region_id == h["region_id"]), None)
            if region is None:
                # typed region error, not Generic: the client re-resolves
                # routing and re-splits the task (ref: errorpb.RegionNotFound)
                return {"err": "RegionMiss", "region_id": h["region_id"]}, []
            ranges = [KeyRange(_ub(a), _ub(b)) for a, b in h["ranges"]]
            engine = _engines()[StoreType(h["store_type"])]
            # engine warnings ride the response header, the per-
            # SelectResponse warning carriage of the reference (tipb)
            warns: list = []
            # ExecDetails sidecar (ref: tipb ExecDetails inside every cop
            # response): store-side processing wall + the engines' device/
            # host/compile/transfer attribution, shipped home in the header.
            # A propagated trace context additionally opens REAL spans here
            # that travel back for the caller to graft into its trace.
            det = _ed.CopExecDetails(region_id=h["region_id"])
            tracer = None
            tctx = None
            if h.get("trace"):
                from tidb_tpu.utils.tracing import TraceContext, Tracer

                tctx = TraceContext.from_pb(h["trace"])
            if tctx is not None and tctx.sampled:
                tracer = Tracer(trace_id=tctx.trace_id)
            t0 = time.perf_counter()
            with _ed.collecting(det, tracer=tracer):
                with _ed.trace_span(f"cop.r{h['region_id']}"):
                    chunk = engine(
                        st, dag, region, ranges, h["read_ts"],
                        warn=lambda lv, code, msg: len(warns) < 64 and warns.append([lv, code, msg]),
                    )
            det.proc_ms = (time.perf_counter() - t0) * 1000.0
            # store-side cop slow log: record the task into THIS process's
            # ring (digest per TABLE so repeats aggregate across regions and
            # shapes; the fleet reads it via sys_snapshot → cluster_slow_query)
            from tidb_tpu import config as _config

            tid = dag.executors[0].table_id if dag.executors else 0
            text = f"cop table={tid} region={h['region_id']}"
            self.stmt_summary.record(
                text,
                det.proc_ms / 1000.0,
                len(chunk),
                user="store",
                slow_threshold_s=_config.current().store_slow_cop_ms / 1000.0,
                digest_val=f"cop:{tid}|cop table={tid}",
            )
            reply = {"ok": 1, "warnings": warns, "exec": det.to_pb()}
            if tracer is not None:
                reply["spans"] = tracer.to_pb()
            return reply, [encode_chunk(chunk)]
        raise ValueError(f"unknown command {cmd!r}")


class _RemoteTSO:
    def __init__(self, store: "RemoteStore"):
        self._store = store

    def ts(self) -> int:
        return self._store._call({"cmd": "tso"})[0]["ts"]


class _RemoteDetector:
    def __init__(self, store: "RemoteStore"):
        self._store = store

    def clean_up(self, start_ts: int) -> None:
        self._store._call({"cmd": "detector_cleanup", "start_ts": start_ts})


class _RemotePD:
    def __init__(self, store: "RemoteStore"):
        self._store = store

    def regions_in_ranges(self, ranges: Sequence[KeyRange]):
        h, _ = self._store._call(
            {"cmd": "regions_in_ranges", "ranges": [[_b(r.start), _b(r.end)] for r in ranges]}
        )
        out = []
        for r in h["regions"]:
            region = Region(r["id"], _ub(r["start"]), _ub(r["end"]))
            region.data_version = r["ver"]
            out.append((region, [KeyRange(_ub(a), _ub(b)) for a, b in r["krs"]]))
        return out


class _RemoteSnapshot:
    def __init__(self, store: "RemoteStore", ts: int):
        self._store = store
        self.read_ts = ts

    def get(self, key: bytes) -> Optional[bytes]:
        h, blobs = self._store._call({"cmd": "snap_get", "ts": self.read_ts, "key": _b(key)})
        return blobs[0] if h["hit"] else None

    def scan(self, kr: KeyRange, limit: int = 2**63, reverse: bool = False):
        h, blobs = self._store._call(
            {
                "cmd": "snap_scan",
                "ts": self.read_ts,
                "start": _b(kr.start),
                "end": _b(kr.end),
                "limit": min(limit, 2**62),
                "reverse": reverse,
            }
        )
        buf = blobs[0] if blobs else b""
        out = []
        off = 0
        for _ in range(h["n"]):
            klen, vlen = struct.unpack_from("<II", buf, off)
            off += 8
            out.append((buf[off : off + klen], buf[off + klen : off + klen + vlen]))
            off += klen + vlen
        return out


class _RemoteCopClient:
    """kv.Client over the wire: region split via the remote PD, one cop RPC
    per region task on a worker pool (ref: copr worker fan-out)."""

    def __init__(self, store: "RemoteStore"):
        self.store = store

    def send(self, req: Request):
        from tidb_tpu.copr.client import CopResponse, CopResult, run_task_resilient
        from tidb_tpu.utils.chunk import decode_chunk

        if req.tp != RequestType.DAG:
            raise ValueError(f"remote cop client handles DAG requests only, got {req.tp}")
        read_ts = req.start_ts or self.store.current_ts()
        tasks = list(self.store.pd.regions_in_ranges(req.ranges))
        if req.desc:
            tasks.reverse()
        if not tasks:
            return CopResponse(iter(()), None)
        dag_pb = req.data.to_pb()
        # per-region responses decode into fresh dictionaries; the gather
        # concatenates chunks, which requires SHARED dictionary objects —
        # unify codes per output column across this request's tasks
        from tidb_tpu.types import TypeKind
        from tidb_tpu.utils.chunk import Chunk, Column, Dictionary

        shared: dict[int, Dictionary] = {}
        share_mu = threading.Lock()

        def unify(chunk: Chunk) -> Chunk:
            import numpy as np

            cols = []
            for i, col in enumerate(chunk.columns):
                if col.ftype.kind == TypeKind.STRING and col.dictionary is not None:
                    with share_mu:
                        dic = shared.setdefault(i, Dictionary())
                        vals = col.dictionary.decode_many(col.data)
                        codes = np.fromiter(
                            (dic.encode(v) for v in vals), dtype=np.int32, count=len(vals)
                        )
                    cols.append(Column(codes, col.validity, col.ftype, dic))
                else:
                    cols.append(col)
            return Chunk(cols)

        # one retry budget for the whole fan-out (ref: copIterator handling
        # region errors under the request's Backoffer)
        bo = Backoffer(budget_ms=self.store._retry_budget_ms, seed=self.store._backoff_seed)
        store_addr = f"{self.store.host}:{self.store.port}"
        # the sampled=0 case: the id may exist for correlation but neither
        # side records spans (nor ships the header) — one rule, one home
        tracer = _tracing.effective(req.tracer)
        parent_span = tracer.current() if tracer is not None else None
        t_submit = time.perf_counter()

        def one_call(region_id, krs, store_type):
            hdr = {
                "cmd": "cop",
                "dag": dag_pb,
                "region_id": region_id,
                "ranges": [[_b(kr.start), _b(kr.end)] for kr in krs],
                "read_ts": read_ts,
                "store_type": store_type.value,
            }
            if tracer is not None:
                # trace-context propagation: the id travels out, the store
                # records spans under it and ships them back (see the server
                # cop handler); merge grafts them under this RPC's span
                hdr["trace"] = tracer.context().to_pb()
                with tracer.span(f"cop-rpc.r{region_id}", parent=parent_span) as sp:
                    h, blobs = self.store._call(hdr)
                if h.get("spans"):
                    tracer.merge_remote(
                        h["spans"], base_s=sp.start_s, node=store_addr, depth=sp.depth + 1
                    )
            else:
                h, blobs = self.store._call(hdr)
            d = _ed.current_cop()
            if d is not None and h.get("exec"):
                d.merge_pb(h["exec"])
            if req.warn is not None:
                for lv, code, msg in h.get("warnings", ()):
                    req.warn(lv, code, msg)
            return unify(decode_chunk(blobs[0]))

        def run_one(st, region, krs):
            return one_call(region.region_id, krs, st)

        from tidb_tpu.utils.memory import QueryKilledError, QueryOOMError

        def run(item):
            ti, (region, krs) = item
            det = _ed.CopExecDetails(region.region_id, store=store_addr)
            det.queue_ms = (time.perf_counter() - t_submit) * 1000.0
            t0 = time.perf_counter()
            # server-side engine failures arrive as RuntimeError ("remote
            # store error: ..."); kill/quota verdicts arrive re-typed by
            # _call (the server ships the error kind) and must pass through
            with _ed.collecting(det, tracer=tracer):
                chunk = run_task_resilient(
                    bo,
                    run_one,
                    self.store.pd.regions_in_ranges,
                    region,
                    krs,
                    req.store_type,
                    warn=req.warn,
                    degrade_reason="remote",
                    degrade_on=(RuntimeError,),
                    never_degrade=(QueryKilledError, QueryOOMError),
                    detail=det,
                    trace_id=tracer.trace_id if tracer is not None else None,
                )
            # proc_ms arrived from the server's sidecar; what remains of the
            # client-observed wall is wire + (de)serialization time
            wall = (time.perf_counter() - t0) * 1000.0
            det.wire_ms = max(wall - det.proc_ms - det.backoff_ms, 0.0)
            return CopResult(chunk, ti, region.region_id, det)

        items = list(enumerate(tasks))
        if req.concurrency <= 1 or len(items) == 1:
            return CopResponse((run(it) for it in items), None)
        # the process-wide cop pool (copr/client.py): worker threads and
        # their pooled per-thread sockets outlive individual queries; the
        # window caps THIS request at its own concurrency
        from tidb_tpu.copr.client import shared_cop_pool, windowed_fanout

        window = min(max(req.concurrency, 1), len(items))
        it, cancel = windowed_fanout(shared_cop_pool(window), run, items, window)
        return CopResponse(it, cancel)


# The wire-verb replay registry. EVERY verb must appear in exactly one of
# these two sets — graftcheck's replay-registry rule cross-checks them
# against the server dispatcher and every client header, and the replay
# gate in RemoteStore._call is fail-closed (``cmd in REPLAYABLE``), so a
# new verb CANNOT silently default to replay-on-reconnect (the PR 1
# mpp_dispatch bug class: replaying a lost reply double-executed a gather).
#
# REPLAYABLE — safe to re-send after the server may have executed it:
# reads are pure; percolator prewrite/rollback/pessimistic_rollback/
# acquire_lock are idempotent under the same start_ts (re-prewrite rewrites
# the same lock); raw_put/raw_delete write the same value; owner/election/
# placement proposes re-assert the same record under the same fencing
# token; fence/unfence/purge/drop_stable are absorbing; migrate_region
# re-installs the same (key, commit_ts) versions; mpp_conn retains the
# final frame server-side precisely so a lost reply can be re-asked;
# mpp_cancel is the idempotent ack.
REPLAYABLE = frozenset(
    {
        "ping", "sys_snapshot", "log_search", "current_ts", "tso",
        "raw_get", "raw_put", "raw_delete", "raw_scan",
        "run_gc", "snap_get", "snap_batch_get", "snap_scan",
        "prewrite", "rollback", "pessimistic_rollback", "acquire_lock",
        "check_txn_status", "resolve_lock", "detector_cleanup",
        "drop_stable", "purge_table",
        "owner_campaign", "owner_of", "owner_resign", "owner_term",
        "election_propose", "election_read",
        "placement_propose", "placement_read",
        "fence_table", "unfence_table", "migrate_export", "migrate_region",
        "regions_in_ranges", "cop",
        "mpp_ndev", "mpp_conn", "mpp_cancel",
    }
)
# NON_REPLAYABLE — a replay after an unacked send could double-apply:
# ``commit`` is the 2PC safety case (UndeterminedError); ``raw_cas``
# replayed after a successful-but-unacked swap would misreport failure;
# the ingest verbs mint a fresh commit_ts per call, so a replay doubles
# the rows; ``mpp_dispatch`` mints a fresh task_id per call — replaying a
# lost reply would double-execute the gather and orphan the first task
# (retry belongs at the gather layer, which can cancel).
NON_REPLAYABLE = frozenset({"commit", "raw_cas", "ingest", "ingest_columnar", "mpp_dispatch"})


class RemoteStore:
    """kv.Storage whose every byte lives in a StoreServer process.

    Per-thread pooled connections (cop fan-out runs parallel region tasks).
    Transient wire failures are retried under a typed Backoffer: the
    connection re-dials with backoff and replay-safe verbs are re-sent
    transparently (ref: client-go Backoffer + RegionRequestSender retry).
    A commit that fails after it may have reached the store surfaces
    :class:`UndeterminedError` — the 2PC undetermined-result rule. A server
    that stays dead past the retry budget surfaces ConnectionError, which
    the session layers report like any region error."""

    def __init__(
        self,
        host: str,
        port: int,
        connect_timeout: Optional[float] = None,
        read_timeout: Optional[float] = None,
        retry_budget_ms: Optional[float] = None,
        backoff_seed: Optional[int] = None,
    ):
        from tidb_tpu import config as _config

        dflt = _config.current()
        self.host, self.port = host, port
        self._timeout = connect_timeout if connect_timeout is not None else dflt.connect_timeout_s
        self._read_timeout = read_timeout if read_timeout is not None else dflt.read_timeout_s
        self._retry_budget_ms = retry_budget_ms if retry_budget_ms is not None else dflt.rpc_retry_budget_ms
        self._backoff_seed = backoff_seed
        self._local = threading.local()
        self.nonce = f"remote:{host}:{port}"
        self.tso = _RemoteTSO(self)
        self.detector = _RemoteDetector(self)
        self.pd = _RemotePD(self)
        # cop fan-out runs on the process-wide shared pool (copr/client.py):
        # its threads (and their pooled per-thread sockets) outlive both
        # individual queries and individual RemoteStore handles
        self._mpp_ndev: Optional[int] = None
        # fail fast on a bad endpoint: zero retry budget, so a dead/refused
        # address raises on the FIRST dial instead of looping out the full
        # boRPC budget (fleet assembly and liveness probes construct these)
        self._call({"cmd": "ping"}, budget_ms=0)

    # -- plumbing ----------------------------------------------------------
    def _conn(self) -> socket.socket:
        c = getattr(self._local, "conn", None)
        if c is None:
            c = socket.create_connection((self.host, self.port), timeout=self._timeout)
            # long deadline: first-query jit compiles + big scans legitimately
            # run minutes; a genuinely dead server still fails fast on connect
            c.settimeout(self._read_timeout)
            self._local.conn = c
        return c

    def _drop_conn(self) -> None:
        """Close the pooled connection so the next attempt re-dials. Closing
        matters even for INJECTED faults: the server may have executed the
        command and its reply is sitting in the socket — reusing the
        connection would desynchronize the frame stream."""
        c = getattr(self._local, "conn", None)
        self._local.conn = None
        if c is not None:
            try:
                c.close()
            except OSError:
                pass

    def _call(self, header: dict, blobs: Sequence[bytes] = (), *, budget_ms: Optional[float] = None):
        """One RPC with reconnect-and-replay under a per-request Backoffer.
        ``budget_ms`` overrides the store's retry budget for THIS call
        (0 = no retries, fail on the first wire error).

        Chaos failpoints (see kv/fault_injection.py wire helpers):
          - ``remote_send(cmd)`` fires BEFORE any byte hits the wire — a
            raised ConnectionError here is retriable for every verb.
          - ``remote_recv(cmd)`` fires after the request went out — raising
            simulates a lost reply: the server executed the command, the
            client never heard. Replay-safe verbs replay; commit surfaces
            UndeterminedError.
        """
        cmd = header["cmd"]
        # fail-closed: replay is an earned property — an undeclared verb is
        # treated as non-replayable (and fails the graftcheck registry scan)
        replayable = cmd in REPLAYABLE
        bo: Optional[Backoffer] = None
        while True:
            maybe_sent = False
            try:
                c = self._conn()
                failpoint.inject("remote_send", cmd)
                maybe_sent = True
                _send_frame(c, header, blobs)
                failpoint.inject("remote_recv", cmd)
                h, rblobs = _recv_frame(c)
                break
            except (ConnectionError, OSError) as e:
                self._drop_conn()
                if not replayable and maybe_sent:
                    if cmd == "commit":
                        raise UndeterminedError(
                            f"commit to store {self.host}:{self.port} failed after send "
                            f"({type(e).__name__}: {e}); transaction outcome UNDETERMINED — "
                            "not retried, not reported as aborted"
                        ) from e
                    raise ConnectionError(
                        f"non-replayable {cmd!r} to {self.host}:{self.port} failed after send: {e}"
                    ) from e
                if bo is None:
                    bo = Backoffer(
                        budget_ms=self._retry_budget_ms if budget_ms is None else budget_ms,
                        seed=self._backoff_seed,
                    )
                try:
                    slept = bo.backoff(boRPC, e)
                except BackoffExhausted as be:
                    raise ConnectionError(
                        f"store server {self.host}:{self.port} unreachable "
                        f"(gave up after {be.attempts} retries / {be.slept_ms:.0f}ms: {e})"
                    ) from e
                # wire-level retries charge the active cop task's sidecar
                # (one thread-local read when nothing is collecting)
                d = _ed.current_cop()
                if d is not None:
                    d.retries += 1
                    d.backoff_ms += slept
        err = h.get("err")
        if err == "KeyLocked":
            raise KeyLockedError(_ub(h["key"]), _lock_from_pb(h["lock"]))
        if err == "WriteConflict":
            raise WriteConflictError(_ub(h["key"]), h["conflict_ts"], h["start_ts"])
        if err == "TxnAborted":
            raise TxnAbortedError(h["msg"])
        if err == "RegionMiss":
            raise RegionError(h.get("region_id", -1))
        if err:
            kind = h.get("kind")
            if kind in ("QueryKilledError", "QueryOOMError"):
                # re-type the kill/quota verdicts (ref: mpp_conn's err_kind
                # mapping): the cop degrade path must see them typed, never
                # as a retriable-looking RuntimeError
                from tidb_tpu.utils.memory import QueryKilledError, QueryOOMError

                cls = QueryKilledError if kind == "QueryKilledError" else QueryOOMError
                raise cls(f"remote store error: {h.get('msg', err)}")
            raise RuntimeError(f"remote store error: {h.get('msg', err)}")
        return h, rblobs

    # -- kv.Storage surface -------------------------------------------------
    def current_ts(self) -> int:
        return self._call({"cmd": "current_ts"})[0]["ts"]

    def raw_get(self, key: bytes) -> Optional[bytes]:
        h, blobs = self._call({"cmd": "raw_get", "key": _b(key)})
        return blobs[0] if h["hit"] else None

    def raw_put(self, key: bytes, value: bytes) -> None:
        self._call({"cmd": "raw_put", "key": _b(key)}, [value])

    def raw_delete(self, key: bytes) -> None:
        self._call({"cmd": "raw_delete", "key": _b(key)})

    def raw_cas(self, key: bytes, expected, value: bytes) -> bool:
        blobs = ([expected] if expected is not None else []) + [value]
        h, _ = self._call(
            {"cmd": "raw_cas", "key": _b(key), "has_expected": expected is not None}, blobs
        )
        return bool(h["ok"])

    def raw_scan(self, kr: KeyRange, limit: int = 2**62):
        h, blobs = self._call(
            {"cmd": "raw_scan", "start": _b(kr.start), "end": _b(kr.end), "limit": min(limit, 2**62)}
        )
        buf = blobs[0] if blobs else b""
        out = []
        off = 0
        for _ in range(h["n"]):
            klen, vlen = struct.unpack_from("<II", buf, off)
            off += 8
            out.append((buf[off : off + klen], buf[off + klen : off + klen + vlen]))
            off += klen + vlen
        return out

    def sys_snapshot(self, hist=None, sections=None) -> dict:
        """The store's introspection report (see ``sys_report``): one
        replay-safe RPC under the usual boRPC Backoffer. ``hist`` attaches
        the store's metrics-history rings (True = all, str = one metric);
        ``sections`` selects the heavy report parts (None = all)."""
        h, _ = self._call(
            {
                "cmd": "sys_snapshot",
                "hist": hist if isinstance(hist, str) else (1 if hist else 0),
                "sections": None if sections is None else list(sections),
            }
        )
        return h["report"]

    def log_search(
        self,
        since=None,
        until=None,
        min_level: int = 0,
        component=None,
        pattern=None,
        limit: int = 256,
    ) -> list:
        """Search the SERVER process's structured event log — filters ship
        in the header and apply store-side, so at most ``limit`` rows cross
        the wire. Replay-safe (a pure read). → [[ts, level, component,
        event, fields, trace_id], ...] oldest-first."""
        h, _ = self._call(
            {
                "cmd": "log_search",
                "since": since,
                "until": until,
                "min_level": min_level,
                "component": component,
                "pattern": pattern,
                "limit": limit,
            }
        )
        return h["rows"]

    def run_gc(self, safe_point=None, life_ms: int = 600_000):
        """MVCC GC runs where the data lives — proxied to the server.
        Returns (pruned, safe_point) so callers can expire recoverables."""
        h, _ = self._call({"cmd": "run_gc", "safe_point": safe_point, "life_ms": life_ms})
        return h["pruned"], h.get("safe_point", 0)

    def get_snapshot(self, ts: int) -> _RemoteSnapshot:
        return _RemoteSnapshot(self, ts)

    def snap_batch_get(self, pairs) -> list:
        """Batched snapshot point reads: ``[(read_ts, key)]`` →
        ``[bytes | None | KeyLockedError]``. ONE replay-safe RPC instead of
        one per key — the wire half of the cross-session point-get batcher
        (N sessions pay one round trip + one store dispatch)."""
        if not pairs:
            return []
        h, blobs = self._call(
            {"cmd": "snap_batch_get", "gets": [[ts, _b(k)] for ts, k in pairs]}
        )
        out: list = []
        bi = 0
        for r in h["gets"]:
            if r.get("err") == "KeyLocked":
                out.append(KeyLockedError(_ub(r["key"]), _lock_from_pb(r["lock"])))
            elif r.get("hit"):
                out.append(blobs[bi])
                bi += 1
            else:
                out.append(None)
        return out

    def begin(self):
        from tidb_tpu.kv.txn import Txn

        return Txn(self)

    def get_client(self) -> _RemoteCopClient:
        return _RemoteCopClient(self)

    # -- bulk ingest (ref: lightning local backend → TiKV ingest RPCs) -----
    def ingest(self, keys: Sequence[bytes], values: Sequence[bytes]) -> int:
        buf = bytearray()
        for k, v in zip(keys, values):
            buf += struct.pack("<IQ", len(k), len(v)) + k + v
        h, _ = self._call({"cmd": "ingest", "n": len(keys)}, [bytes(buf)])
        return h["ts"]

    def ingest_columnar(self, table_id: int, handles, cols: dict, schema, dicts=None, on_existing: str | None = None) -> int:
        import numpy as np

        from tidb_tpu.expression.expr import _ft_pb

        handles = np.ascontiguousarray(np.asarray(handles, dtype=np.int64))
        blobs = [handles.tobytes()]
        slots = []
        for slot, (data, valid) in cols.items():
            data = np.ascontiguousarray(data)
            slots.append([slot, data.dtype.str])
            blobs.append(data.tobytes())
            blobs.append(np.ascontiguousarray(valid, dtype=np.bool_).tobytes())
        dict_slots = []
        for slot, dic in (dicts or {}).items():
            dict_slots.append(slot)
            buf = bytearray()
            for v in dic._values:
                buf += struct.pack("<I", len(v)) + v
            blobs.append(bytes(buf))
        h, _ = self._call(
            {
                "cmd": "ingest_columnar",
                "table_id": table_id,
                "on_existing": on_existing,
                "n": len(handles),
                "slots": slots,
                "dict_slots": dict_slots,
                "schema": [_ft_pb(f) for f in schema.ftypes],
            },
            blobs,
        )
        return h["ts"]

    # -- MPP dispatch (ref: kv/mpp.go DispatchMPPTask/EstablishMPPConns) ----
    def mpp_ndev(self) -> int:
        """Mesh size of the server's device mesh — the remote planner's
        exchange-cost model needs the REAL ndev, not this process's."""
        if self._mpp_ndev is None:
            self._mpp_ndev = int(self._call({"cmd": "mpp_ndev"})[0]["ndev"])
        return self._mpp_ndev

    def mpp_dispatch(self, spec: dict, read_ts: int, trace: Optional[dict] = None) -> str:
        hdr = {"cmd": "mpp_dispatch", "spec": spec, "read_ts": read_ts}
        if trace:
            hdr["trace"] = trace
        h, _ = self._call(hdr)
        return h["task_id"]

    def mpp_conn(self, task_id: str, check_killed=None, warn=None, on_exec=None):
        """Block until the task's merged chunk arrives (long-poll loop so a
        client-side KILL propagates as mpp_cancel). Raises the task's error
        with its original kind when the server reports one. ``on_exec(exec,
        spans)`` receives the server's MPP exec-details sidecar + any spans
        it recorded under a propagated trace context."""
        while True:
            h, blobs = self._call({"cmd": "mpp_conn", "task_id": task_id, "wait_s": 1.0})
            if h["done"]:
                break
            if check_killed is not None:
                try:
                    check_killed()
                except BaseException:
                    try:
                        self._call({"cmd": "mpp_cancel", "task_id": task_id})
                    except ConnectionError:
                        pass
                    raise
        # ack: the final frame is safely client-side — release the server's
        # retained copy now (it is kept after collection only so a LOST
        # final frame can be replayed; mpp_cancel is the idempotent ack)
        try:
            self._call({"cmd": "mpp_cancel", "task_id": task_id})
        except ConnectionError:
            pass  # the server's dispatch-time sweep reclaims it
        if h.get("err_kind"):
            from tidb_tpu.parallel.probe import MPPRetryExhausted, MPPTaskLostError
            from tidb_tpu.utils.memory import QueryKilledError, QueryOOMError

            if h["err_kind"] == "RegionError":
                # the server's gather hit a placement fence (the table moved
                # mid-dispatch): typed so the gather re-resolves placement
                # and re-dispatches to the new owner (kv/placement.py)
                raise RegionError(-1, f"remote mpp task failed: {h['msg']}")
            kinds = {
                "MPPRetryExhausted": MPPRetryExhausted,
                # the server no longer knows this task (it restarted between
                # dispatch and conn): the gather re-dispatches — the
                # client-go mpp_probe lost-task recovery idiom
                "MPPTaskLost": MPPTaskLostError,
                "QueryKilledError": QueryKilledError,
                "QueryOOMError": QueryOOMError,
            }
            raise kinds.get(h["err_kind"], RuntimeError)(
                f"remote mpp task failed: {h['msg']}"
            )
        from tidb_tpu.utils.chunk import decode_chunk

        if warn is not None:
            for lv, code, msg in h.get("warnings", ()):
                warn(lv, code, msg)
        if on_exec is not None:
            on_exec(h.get("exec"), h.get("spans"))
        return decode_chunk(blobs[0])

    def mpp_cancel(self, task_id: str) -> None:
        self._call({"cmd": "mpp_cancel", "task_id": task_id})

    def drop_stable(self, table_id: int) -> None:
        """Discard a table's stable columnar blocks (reorg DDL rewrote the
        rows into the delta layer server-side)."""
        self._call({"cmd": "drop_stable", "table_id": table_id})

    # -- owner election: the store process is the etcd analog ----------------
    def owner_campaign(
        self, key: str, node_id: str, lease_s: Optional[float] = None, term: Optional[int] = None
    ) -> bool:
        h, _ = self._call(
            {"cmd": "owner_campaign", "key": key, "node_id": node_id, "lease_s": lease_s, "term": term}
        )
        return bool(h["ok"])

    def owner_of(self, key: str):
        return self._call({"cmd": "owner_of", "key": key})[0]["owner"]

    def owner_resign(self, key: str, node_id: str) -> None:
        self._call({"cmd": "owner_resign", "key": key, "node_id": node_id})

    def owner_term(self, key: str) -> int:
        return self._call({"cmd": "owner_term", "key": key})[0]["term"]

    # -- quorum placement replica verbs + region-move verbs (kv/placement.py:
    # this server hosts one replica of the fleet's placement keyspace and
    # serves region migration; every verb here is replay-safe — proposes and
    # applies are idempotent, exports and fences are pure/absorbing) --------
    def placement_propose(self, table_id: int, shard: int, epoch: int):
        h, _ = self._call(
            {"cmd": "placement_propose", "tid": table_id, "shard": shard, "epoch": epoch}
        )
        return bool(h["ok"]), h["epoch"]

    def placement_read(self, table_id: Optional[int] = None):
        if table_id is None:
            h, _ = self._call({"cmd": "placement_read", "tid": None})
            return [(tid, e, s) for tid, e, s in h["recs"]]
        h, _ = self._call({"cmd": "placement_read", "tid": table_id})
        return h["epoch"], h["shard"]

    def fence_table(self, table_id: int, ttl_s: Optional[float] = None) -> None:
        self._call({"cmd": "fence_table", "tid": table_id, "ttl_s": ttl_s})

    def unfence_table(self, table_id: int) -> None:
        self._call({"cmd": "unfence_table", "tid": table_id})

    def migrate_export(self, table_id: int, after_ts: int = 0, upto_ts: Optional[int] = None,
                       cursor=None, limit: int = 4096, include_locks: bool = False) -> dict:
        h, blobs = self._call(
            {
                "cmd": "migrate_export", "tid": table_id, "after_ts": after_ts,
                "upto_ts": upto_ts, "cursor": _cursor_pb(cursor), "limit": limit,
                "locks": int(include_locks),
            }
        )
        return {
            "items": _migrate_items_unpack(blobs[0]) if blobs else [],
            "locks": [(_ub(k), _lock_from_pb(l)) for k, l in h.get("locks", ())],
            "cursor": _cursor_from_pb(h.get("cursor")),
        }

    def migrate_apply(self, items, locks=()) -> int:
        h, _ = self._call(
            {"cmd": "migrate_region", "locks": [[_b(k), _lock_pb(l)] for k, l in locks]},
            [_migrate_items_blob(items)],
        )
        return h["applied"]

    def purge_table(self, table_id: int) -> None:
        self._call({"cmd": "purge_table", "tid": table_id})

    # -- quorum election replica verbs (kv/election.py: this server hosts one
    # replica of the fleet's election keyspace; both verbs are replay-safe) --
    def election_propose(self, key: str, node_id: str, term: int, deadline: float):
        h, _ = self._call(
            {"cmd": "election_propose", "key": key, "node_id": node_id, "term": term, "deadline": deadline}
        )
        return bool(h["ok"]), h["term"]

    def election_read(self, key: str):
        h, _ = self._call({"cmd": "election_read", "key": key})
        return h["term"], h["owner"], h["deadline"]

    # -- percolator verbs (ref: unistore mvcc server surface) ---------------
    def check_txn_status(self, primary: bytes, start_ts: int):
        """→ ("committed"|"rolled_back"|"locked", commit_ts) — the cross-
        store lock-resolution primitive (ref: kvproto CheckTxnStatus)."""
        h, _ = self._call({"cmd": "check_txn_status", "primary": _b(primary), "start_ts": start_ts})
        return h["status"], h["commit_ts"]

    def prewrite(self, mutations: Sequence[Mutation], primary: bytes, start_ts: int) -> dict:
        buf = bytearray()
        for m in mutations:
            buf += bytes([0 if m.op == OP_PUT else 1])
            buf += struct.pack("<I", len(m.key)) + m.key
            buf += struct.pack("<Q", len(m.value)) + m.value
        h, _ = self._call({"cmd": "prewrite", "primary": _b(primary), "start_ts": start_ts}, [bytes(buf)])
        return {"keys": int(h.get("keys", 0)), "bytes": int(h.get("bytes", 0))}

    def commit(self, keys: Sequence[bytes], start_ts: int, commit_ts: int) -> dict:
        h, _ = self._call({"cmd": "commit", "keys": [_b(k) for k in keys], "start_ts": start_ts, "commit_ts": commit_ts})
        return {"keys": int(h.get("keys", 0)), "bytes": int(h.get("bytes", 0))}

    def rollback(self, keys: Sequence[bytes], start_ts: int) -> None:
        self._call({"cmd": "rollback", "keys": [_b(k) for k in keys], "start_ts": start_ts})

    def pessimistic_rollback(self, keys: Sequence[bytes], start_ts: int) -> None:
        self._call({"cmd": "pessimistic_rollback", "keys": [_b(k) for k in keys], "start_ts": start_ts})

    def acquire_pessimistic_lock(
        self, keys: Sequence[bytes], primary: bytes, start_ts: int, for_update_ts: int, wait_timeout_ms: int = 3000
    ) -> None:
        self._call(
            {
                "cmd": "acquire_lock",
                "keys": [_b(k) for k in keys],
                "primary": _b(primary),
                "start_ts": start_ts,
                "for_update_ts": for_update_ts,
                "wait_ms": wait_timeout_ms,
            }
        )

    def resolve_lock(self, key: bytes, lock: Lock) -> None:
        self._call({"cmd": "resolve_lock", "key": _b(key), "lock": _lock_pb(lock)})
