"""Engine-neutral KV contracts.

Reference parity: pkg/kv (kv.go:316 Client, kv.go:533 Request, kv.go:353
StoreType, kv.go:648 Response; mpp.go MPP contracts). The rebuild keeps the
same seam: the planner/executor speak ``Request``/``Response`` and an engine
registry; which silicon executes a DAG fragment is a late-bound config choice.
"""

from tidb_tpu.kv.kv import (
    Client,
    KeyRange,
    Request,
    RequestType,
    Response,
    StoreType,
    Storage,
    TimestampOracle,
)

__all__ = [
    "Client",
    "KeyRange",
    "Request",
    "RequestType",
    "Response",
    "StoreType",
    "Storage",
    "TimestampOracle",
]
