"""Fault-injection store wrapper (ref: pkg/kv/fault_injection.go
InjectedStore/InjectedTransaction): wraps a MemStore so tests force
configurable errors on get/scan/commit without failpoint rewrites."""

from __future__ import annotations

import threading
from typing import Optional


class InjectionConfig:
    def __init__(self):
        self._mu = threading.Lock()
        self.get_error: Optional[Exception] = None
        self.commit_error: Optional[Exception] = None

    def set_get_error(self, err: Optional[Exception]) -> None:
        with self._mu:
            self.get_error = err

    def set_commit_error(self, err: Optional[Exception]) -> None:
        with self._mu:
            self.commit_error = err


class InjectedSnapshot:
    def __init__(self, snap, cfg: InjectionConfig):
        self._snap = snap
        self._cfg = cfg

    def get(self, key):
        if self._cfg.get_error is not None:
            raise self._cfg.get_error
        return self._snap.get(key)

    def __getattr__(self, name):
        return getattr(self._snap, name)


class InjectedTxn:
    def __init__(self, txn, cfg: InjectionConfig):
        self._txn = txn
        self._cfg = cfg

    def get(self, key):
        if self._cfg.get_error is not None:
            raise self._cfg.get_error
        return self._txn.get(key)

    def commit(self):
        if self._cfg.commit_error is not None:
            raise self._cfg.commit_error
        return self._txn.commit()

    def __getattr__(self, name):
        return getattr(self._txn, name)


class InjectedStore:
    """kv.Storage wrapper; pass the real store everywhere else."""

    def __init__(self, store, cfg: Optional[InjectionConfig] = None):
        self._store = store
        self.cfg = cfg or InjectionConfig()

    def get_snapshot(self, ts):
        return InjectedSnapshot(self._store.get_snapshot(ts), self.cfg)

    def begin(self):
        return InjectedTxn(self._store.begin(), self.cfg)

    def __getattr__(self, name):
        return getattr(self._store, name)
