"""Fault injection: the store wrapper and the deterministic chaos toolkit.

Reference parity: ``pkg/kv/fault_injection.go`` (InjectedStore /
InjectedTransaction — configurable errors on get/scan/commit/prewrite
without failpoint rewrites) plus the failpoint *scheduling* idioms the
reference's 238 failpoint call sites rely on (``N*return(x)`` one-shot
counts, ``x%return`` probabilities — pingcap/failpoint term grammar).

Two layers live here:

1. :class:`InjectedStore` + :class:`InjectionConfig` — wrap a kv.Storage so
   tests force typed errors on get/scan/prewrite/commit, permanently or for
   exactly ``n_times`` calls (one-shot semantics).
2. Chaos actions for :mod:`tidb_tpu.utils.failpoint` points — :class:`NShot`,
   :class:`Probabilistic` (seeded RNG → reproducible schedules), and
   :class:`Script` (exact per-call fault sequences). Combined with the wire
   failpoints in ``kv/remote.py`` (``remote_send`` / ``remote_recv``) they
   reach down to the frame level: drops, delays, and connection resets
   against real multi-process topologies.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional, Sequence

# -- failpoint registry ------------------------------------------------------
#
# The authoritative set of failpoint names the package defines (every
# ``failpoint.inject("<name>", ...)`` call site). Failpoints are armed by
# bare string name, so a typo'd name in a chaos test silently never fires
# and the test passes vacuously; graftcheck's ``failpoint-registry`` rule
# cross-checks every reference in package code AND tests/ against this set,
# and flags stale entries whose inject site was removed. Adding a new
# inject site means adding its name here in the same change.

FAILPOINTS = frozenset(
    {
        "colcache_merge",  # copr/colcache.py: mid-merge crash atomicity
        "cop_task_engine",  # copr/client.py: per-task engine fault/degrade
        "ddl/afterStateSwitch",  # catalog/ddl.py: crash between DDL states
        "ddl/beforeBackfillBatch",  # catalog/ddl.py: crash mid-backfill
        "disttask_local_worker_start",  # disttask/framework.py: slow worker
        "import_subtask_before_ingest",  # tools/importer.py: subtask restart
        "mpp_run_fragment",  # parallel/gather.py: fragment dispatch fault
        "mpp_shard_slow",  # parallel/gather.py: per-shard straggler delay
        "placement_cutover",  # kv/placement.py: hold the migration fence
        "placement_migrate_batch",  # kv/placement.py: slow copy batches
        "remote_send",  # kv/remote.py: wire frame drop/delay on send
        "remote_recv",  # kv/remote.py: wire frame drop/delay on receive
        "table_reader_begin",  # executor/executors.py: park a reader mid-stmt
    }
)


class InjectionConfig:
    """Configurable error hooks. Each hook is ``(exception, remaining)``:
    ``remaining is None`` fires forever (the original permanent semantics);
    an integer fires for exactly that many calls, then disarms itself."""

    _HOOKS = ("get", "scan", "commit", "prewrite")

    def __init__(self):
        self._mu = threading.Lock()
        self._errs: dict[str, tuple[Exception, Optional[int]]] = {}

    def _set(self, name: str, err: Optional[Exception], n_times: Optional[int]) -> None:
        if name not in self._HOOKS:
            raise KeyError(f"unknown injection hook {name!r}")
        with self._mu:
            if err is None:
                self._errs.pop(name, None)
            else:
                self._errs[name] = (err, n_times)

    def _take(self, name: str) -> Optional[Exception]:
        """The armed error for ``name`` (decrementing one-shot counts)."""
        with self._mu:
            ent = self._errs.get(name)
            if ent is None:
                return None
            err, n = ent
            if n is not None:
                if n <= 1:
                    del self._errs[name]
                else:
                    self._errs[name] = (err, n - 1)
            return err

    def set_get_error(self, err: Optional[Exception], n_times: Optional[int] = None) -> None:
        self._set("get", err, n_times)

    def set_scan_error(self, err: Optional[Exception], n_times: Optional[int] = None) -> None:
        self._set("scan", err, n_times)

    def set_commit_error(self, err: Optional[Exception], n_times: Optional[int] = None) -> None:
        self._set("commit", err, n_times)

    def set_prewrite_error(self, err: Optional[Exception], n_times: Optional[int] = None) -> None:
        self._set("prewrite", err, n_times)


class InjectedSnapshot:
    def __init__(self, snap, cfg: InjectionConfig):
        self._snap = snap
        self._cfg = cfg

    def get(self, key):
        err = self._cfg._take("get")
        if err is not None:
            raise err
        return self._snap.get(key)

    def scan(self, *args, **kwargs):
        err = self._cfg._take("scan")
        if err is not None:
            raise err
        return self._snap.scan(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._snap, name)


class InjectedTxn:
    def __init__(self, txn, cfg: InjectionConfig):
        self._txn = txn
        self._cfg = cfg

    def get(self, key):
        err = self._cfg._take("get")
        if err is not None:
            raise err
        return self._txn.get(key)

    def scan(self, *args, **kwargs):
        err = self._cfg._take("scan")
        if err is not None:
            raise err
        return self._txn.scan(*args, **kwargs)

    def commit(self):
        err = self._cfg._take("commit")
        if err is not None:
            raise err
        return self._txn.commit()

    def __getattr__(self, name):
        return getattr(self._txn, name)


class InjectedStore:
    """kv.Storage wrapper; pass the real store everywhere else."""

    def __init__(self, store, cfg: Optional[InjectionConfig] = None):
        self._store = store
        self.cfg = cfg or InjectionConfig()

    def get_snapshot(self, ts):
        return InjectedSnapshot(self._store.get_snapshot(ts), self.cfg)

    def begin(self):
        return InjectedTxn(self._store.begin(), self.cfg)

    def prewrite(self, mutations, primary, start_ts):
        err = self.cfg._take("prewrite")
        if err is not None:
            raise err
        return self._store.prewrite(mutations, primary, start_ts)

    def __getattr__(self, name):
        return getattr(self._store, name)


# -- chaos actions for failpoints ------------------------------------------
#
# These are *callables* for failpoint.enable(name, action): the point fires
# them with its site args. Raising simulates the fault; returning None lets
# the call proceed. All counters are thread-safe, and every random choice
# comes from a SEEDED rng (see the Probabilistic caveat on concurrency —
# exact schedules belong to Script/NShot).


class NShot:
    """Fire ``action`` for the first ``n_times`` *matching* calls, then pass
    (ref: failpoint ``N*return`` terms). ``match(*args)`` filters by site
    args — e.g. only ``cmd == "cop"`` frames of the wire point."""

    def __init__(self, action: Callable, n_times: int = 1, match: Optional[Callable] = None):
        self._action = action
        self._match = match
        self._mu = threading.Lock()
        self.remaining = n_times
        self.fired = 0
        self.calls = 0

    def __call__(self, *args):
        with self._mu:
            self.calls += 1
            if self._match is not None and not self._match(*args):
                return None
            if self.remaining <= 0:
                return None
            self.remaining -= 1
            self.fired += 1
        return self._action(*args)


class Probabilistic:
    """Fire ``action`` with probability ``p`` per matching call, from a
    SEEDED rng (ref: failpoint ``x%return`` terms). The DRAW sequence is
    reproducible; which call consumes which draw is not when the point is
    hit concurrently (the cop fan-out's worker pool races for the rng), so
    the same seed can fault different (task, verb) pairs run-to-run. Use
    :class:`Script`/:class:`NShot` with a ``match`` filter when a test must
    schedule exact faults; use this for soak-style randomized pressure."""

    def __init__(self, action: Callable, p: float, seed: int, match: Optional[Callable] = None):
        self._action = action
        self._p = p
        self._match = match
        self._mu = threading.Lock()
        self._rng = random.Random(seed)
        self.fired = 0

    def __call__(self, *args):
        if self._match is not None and not self._match(*args):
            return None
        with self._mu:
            fire = self._rng.random() < self._p
            if fire:
                self.fired += 1
        return self._action(*args) if fire else None


class Script:
    """Exact per-call fault sequence: step k of ``steps`` decides call k.
    A step is None (pass), an Exception instance (raised), a float (sleep
    seconds — injected latency), or a callable (run with the site args).
    Past the end of the script every call passes."""

    def __init__(self, steps: Sequence, match: Optional[Callable] = None):
        self._steps = list(steps)
        self._match = match
        self._mu = threading.Lock()
        self._idx = 0

    def __call__(self, *args):
        if self._match is not None and not self._match(*args):
            return None
        with self._mu:
            if self._idx >= len(self._steps):
                return None
            step = self._steps[self._idx]
            self._idx += 1
        if step is None:
            return None
        if isinstance(step, BaseException):
            raise step
        if isinstance(step, (int, float)):
            time.sleep(step)
            return None
        return step(*args)


def reset_wire(*_args):
    """Chaos action: sever the connection (frame drop / peer reset). The
    retry layer sees exactly what a killed store produces."""
    raise ConnectionResetError("chaos: injected connection reset")


def delay(seconds: float) -> Callable:
    """Chaos action factory: inject ``seconds`` of wire latency."""

    def _sleep(*_args):
        time.sleep(seconds)

    return _sleep
