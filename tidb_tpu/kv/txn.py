"""Client-side transaction: membuffer + two-phase commit driver.

Reference parity: pkg/session/txn.go (LazyTxn membuffer with per-statement
staging), tikv/client-go 2PC (prewrite primary-first → TSO commit_ts → commit
primary → commit secondaries), pkg/store/driver/txn. Single-process build
commits synchronously; the secondary-commit fan-out is where a multi-node
deployment parallelizes.
"""

from __future__ import annotations

import threading
from typing import Optional

from tidb_tpu.kv.kv import KeyLockedError, KeyRange, TxnAbortedError, WriteConflictError
from tidb_tpu.kv.memstore import MemStore, Mutation, OP_DEL, OP_PUT, Snapshot


class MemBuffer:
    """Uncommitted writes with statement staging (ref: LazyTxn staging,
    session/txn.go:128 flushStmtBuf)."""

    def __init__(self):
        self._buf: dict[bytes, tuple[str, bytes]] = {}
        self._stages: list[dict[bytes, tuple[str, bytes] | None]] = []

    def put(self, key: bytes, value: bytes) -> None:
        self._record(key)
        self._buf[key] = (OP_PUT, value)

    def delete(self, key: bytes) -> None:
        self._record(key)
        self._buf[key] = (OP_DEL, b"")

    def get(self, key: bytes):
        ent = self._buf.get(key)
        if ent is None:
            return None
        return None if ent[0] == OP_DEL else ent[1]

    def contains(self, key: bytes) -> bool:
        return key in self._buf

    def is_deleted(self, key: bytes) -> bool:
        ent = self._buf.get(key)
        return ent is not None and ent[0] == OP_DEL

    def _record(self, key: bytes) -> None:
        if self._stages:
            st = self._stages[-1]
            if key not in st:
                st[key] = self._buf.get(key)

    # statement staging: begin at stmt start, rollback on stmt error
    def stage(self) -> None:
        self._stages.append({})

    def release_stage(self) -> None:
        self._stages.pop()

    def rollback_stage(self) -> None:
        for key, old in self._stages.pop().items():
            if old is None:
                self._buf.pop(key, None)
            else:
                self._buf[key] = old

    def mutations(self) -> list[Mutation]:
        return [Mutation(op, k, v) for k, (op, v) in sorted(self._buf.items())]

    def __len__(self) -> int:
        return len(self._buf)


class Txn:
    """One transaction. Reads go to a start_ts snapshot overlaid with the
    membuffer; commit runs percolator 2PC against the store."""

    def __init__(self, store: MemStore, start_ts: Optional[int] = None):
        self.store = store
        self.start_ts = start_ts if start_ts is not None else store.tso.ts()
        self.snapshot = Snapshot(store, self.start_ts)
        self.membuf = MemBuffer()
        self.commit_ts: Optional[int] = None
        self._done = False

    # -- reads -------------------------------------------------------------
    def get(self, key: bytes) -> Optional[bytes]:
        if self.membuf.contains(key):
            return self.membuf.get(key)
        return self._retry_locked(lambda: self.snapshot.get(key))

    def scan(self, kr: KeyRange, limit: int = 2**63) -> list[tuple[bytes, bytes]]:
        base = dict(self._retry_locked(lambda: self.snapshot.scan(kr)))
        for k, (op, v) in self.membuf._buf.items():
            if kr.start <= k < kr.end:
                if op == OP_DEL:
                    base.pop(k, None)
                else:
                    base[k] = v
        return sorted(base.items())[:limit]

    def _retry_locked(self, fn, max_retries: int = 16):
        import time

        for i in range(max_retries):
            try:
                return fn()
            except KeyLockedError as e:
                self.store.resolve_lock(e.key, e.lock)
                if i > 0:
                    time.sleep(min(0.001 * (1 << i), 0.1))  # backoff while lock holder lives
        raise TxnAbortedError("lock resolution did not converge")

    # -- writes ------------------------------------------------------------
    def put(self, key: bytes, value: bytes) -> None:
        self.membuf.put(key, value)

    def delete(self, key: bytes) -> None:
        self.membuf.delete(key)

    # -- 2PC ---------------------------------------------------------------
    def commit(self) -> int:
        assert not self._done, "txn already finished"
        self._done = True
        muts = self.membuf.mutations()
        if not muts:
            self.commit_ts = self.start_ts
            return self.commit_ts
        primary = muts[0].key
        try:
            self.store.prewrite(muts, primary, self.start_ts)
        except KeyLockedError as e:
            self.store.resolve_lock(e.key, e.lock)
            # single retry after resolution; else surface the conflict
            self.store.prewrite(muts, primary, self.start_ts)
        self.commit_ts = self.store.tso.ts()
        # commit primary first — the txn is durably decided once this returns
        self.store.commit([primary], self.start_ts, self.commit_ts)
        secondaries = [m.key for m in muts if m.key != primary]
        if secondaries:
            self.store.commit(secondaries, self.start_ts, self.commit_ts)
        return self.commit_ts

    def rollback(self) -> None:
        if self._done:
            return
        self._done = True
        keys = [m.key for m in self.membuf.mutations()]
        if keys:
            self.store.rollback(keys, self.start_ts)
