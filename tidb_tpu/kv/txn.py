"""Client-side transaction: membuffer + two-phase commit driver.

Reference parity: pkg/session/txn.go (LazyTxn membuffer with per-statement
staging), tikv/client-go 2PC (prewrite primary-first → TSO commit_ts → commit
primary → commit secondaries), pkg/store/driver/txn. Single-process build
commits synchronously; the secondary-commit fan-out is where a multi-node
deployment parallelizes.
"""

from __future__ import annotations

import threading
from typing import Optional

from tidb_tpu.kv.kv import (
    KeyLockedError,
    KeyRange,
    TxnAbortedError,
    UndeterminedError,
    WriteConflictError,
)
from tidb_tpu.kv.memstore import MemStore, Mutation, OP_DEL, OP_PUT, Snapshot


class MemBuffer:
    """Uncommitted writes with statement staging (ref: LazyTxn staging,
    session/txn.go:128 flushStmtBuf)."""

    def __init__(self):
        self._buf: dict[bytes, tuple[str, bytes]] = {}
        self._stages: list[dict[bytes, tuple[str, bytes] | None]] = []

    def put(self, key: bytes, value: bytes) -> None:
        self._record(key)
        self._buf[key] = (OP_PUT, value)

    def delete(self, key: bytes) -> None:
        self._record(key)
        self._buf[key] = (OP_DEL, b"")

    def get(self, key: bytes):
        ent = self._buf.get(key)
        if ent is None:
            return None
        return None if ent[0] == OP_DEL else ent[1]

    def contains(self, key: bytes) -> bool:
        return key in self._buf

    def _record(self, key: bytes) -> None:
        if self._stages:
            st = self._stages[-1]
            if key not in st:
                st[key] = self._buf.get(key)

    # statement staging: begin at stmt start, rollback on stmt error
    def stage(self) -> None:
        self._stages.append({})

    def release_stage(self) -> None:
        self._stages.pop()

    def rollback_stage(self) -> None:
        for key, old in self._stages.pop().items():
            if old is None:
                self._buf.pop(key, None)
            else:
                self._buf[key] = old

    def mutations(self) -> list[Mutation]:
        return [Mutation(op, k, v) for k, (op, v) in sorted(self._buf.items())]

    def __len__(self) -> int:
        return len(self._buf)


def retry_locked(store, fn, max_retries: int = 16):
    """Run ``fn``, resolving any pending lock it trips over and backing off
    while the lock's holder is still alive — the reader-side
    Backoffer+ResolveLocks loop every kv read path needs (ref: client-go's
    snapshot reads under BoTxnLock; a reader surfacing KeyLocked raw would
    make every scan race concurrent writers)."""
    from tidb_tpu.utils.backoff import Backoffer, BackoffExhausted, boTxnLock

    bo = Backoffer(budget_ms=2000)
    for i in range(max_retries):
        try:
            return fn()
        except KeyLockedError as e:
            store.resolve_lock(e.key, e.lock)
            if i > 0:
                try:
                    bo.backoff(boTxnLock)  # holder still alive: wait it out
                except BackoffExhausted:
                    break
    raise TxnAbortedError("lock resolution did not converge")


class Txn:
    """One transaction. Reads go to a start_ts snapshot overlaid with the
    membuffer; commit runs percolator 2PC against the store. In pessimistic
    mode, lock_keys acquires statement-time locks (ref: client-go
    LockKeys + sessiontxn/isolation pessimistic provider)."""

    def __init__(self, store: MemStore, start_ts: Optional[int] = None, pessimistic: bool = False):
        self.store = store
        self.start_ts = start_ts if start_ts is not None else store.tso.ts()
        self.snapshot = store.get_snapshot(self.start_ts)
        self.membuf = MemBuffer()
        self.commit_ts: Optional[int] = None
        self._done = False
        self.pessimistic = pessimistic
        self.for_update_ts = self.start_ts
        self._locked_keys: set[bytes] = set()
        self._pess_primary: Optional[bytes] = None
        self._primary: Optional[bytes] = None  # recorded at commit for resolve_undetermined
        # write-side accounting set by commit() (WRU metering inputs): unique
        # keys/bytes this txn wrote, from the prewrite response headers when
        # the store reports them, else computed client-side
        self.write_keys = 0
        self.write_bytes = 0

    # -- pessimistic locking ------------------------------------------------
    def lock_keys(self, keys, wait_timeout_ms: int = 3000) -> None:
        """Acquire pessimistic locks at a fresh for_update_ts. No-op for
        optimistic txns (commit-time conflict detection covers them)."""
        if not self.pessimistic or not keys:
            return
        new = [k for k in keys if k not in self._locked_keys]
        if not new:
            return
        if self._pess_primary is None:
            self._pess_primary = new[0]
        # a conflicting commit can land while we wait on its lock; refresh
        # for_update_ts and retry (ref: pessimistic lock retry in
        # session/txn pessimistic mode — the statement, not the txn, restarts)
        last: Exception | None = None
        for _ in range(8):
            self.for_update_ts = self.store.tso.ts()
            try:
                self.store.acquire_pessimistic_lock(
                    new, self._pess_primary, self.start_ts, self.for_update_ts, wait_timeout_ms
                )
                self._locked_keys.update(new)
                return
            except WriteConflictError as e:
                last = e
        raise last  # type: ignore[misc]

    # -- reads -------------------------------------------------------------
    def get(self, key: bytes) -> Optional[bytes]:
        if self.membuf.contains(key):
            return self.membuf.get(key)
        return self._retry_locked(lambda: self.snapshot.get(key))

    def batch_get(self, keys) -> list:
        """Membuffer-overlaid batched point reads: snapshot misses coalesce
        through the store's cross-session point-get batcher (one batched
        dispatch instead of a per-key lookup — the dirty-txn gap PERF.md
        named). Values in key order; membuffer deletes come back as None."""
        out: list = [None] * len(keys)
        miss: list[tuple[int, bytes]] = []
        for i, k in enumerate(keys):
            if self.membuf.contains(k):
                out[i] = self.membuf.get(k)
            else:
                miss.append((i, k))
        if miss:
            from tidb_tpu.copr.client import batched_point_get

            vals = self._retry_locked(
                lambda: batched_point_get(self.store, self.start_ts, [k for _, k in miss])
            )
            for (i, _), v in zip(miss, vals):
                out[i] = v
        return out

    def scan(self, kr: KeyRange, limit: int = 2**63, read_ts: Optional[int] = None) -> list[tuple[bytes, bytes]]:
        snap = self.snapshot if read_ts is None else self.store.get_snapshot(read_ts)
        # membuf DELs can only shrink the snapshot result: limit+ndel snapshot
        # rows always cover the first `limit` merged rows (keeps LIMIT-k scans
        # of bulk-loaded tables O(k), e.g. the DDL backfill batches)
        ndel = 0
        if limit < 2**63:
            ndel = sum(
                1
                for k, (op, _) in self.membuf._buf.items()
                if op == OP_DEL and kr.start <= k < kr.end
            )
        base = dict(self._retry_locked(lambda: snap.scan(kr, limit=min(limit + ndel, 2**63))))
        for k, (op, v) in self.membuf._buf.items():
            if kr.start <= k < kr.end:
                if op == OP_DEL:
                    base.pop(k, None)
                else:
                    base[k] = v
        return sorted(base.items())[:limit]

    def _retry_locked(self, fn, max_retries: int = 16):
        return retry_locked(self.store, fn, max_retries)

    # -- writes ------------------------------------------------------------
    def put(self, key: bytes, value: bytes) -> None:
        self.membuf.put(key, value)

    def delete(self, key: bytes) -> None:
        self.membuf.delete(key)

    # -- 2PC ---------------------------------------------------------------
    def commit(self) -> int:
        if self._done:
            raise RuntimeError("txn already finished")
        self._done = True
        muts = self.membuf.mutations()
        if not muts:
            if self._locked_keys:
                self.store.pessimistic_rollback(list(self._locked_keys), self.start_ts)
            self.commit_ts = self.start_ts
            return self.commit_ts
        written = {m.key for m in muts}
        leftover = [k for k in self._locked_keys if k not in written]
        if leftover:  # locked but never written (e.g. FOR UPDATE only)
            self.store.pessimistic_rollback(leftover, self.start_ts)
        primary = muts[0].key
        if self.pessimistic and self._pess_primary is not None and self._pess_primary in written:
            primary = self._pess_primary  # keep lock primary stable across upgrade
        self._primary = primary
        try:
            counts = self.store.prewrite(muts, primary, self.start_ts)
        except KeyLockedError as e:
            self.store.resolve_lock(e.key, e.lock)
            # single retry after resolution; else surface the conflict
            counts = self.store.prewrite(muts, primary, self.start_ts)
        if isinstance(counts, dict) and "keys" in counts:
            self.write_keys = int(counts["keys"])
            self.write_bytes = int(counts.get("bytes", 0))
        else:  # store (or a wrapper) predates the accounting headers
            self.write_keys = len(muts)
            self.write_bytes = sum(len(m.key) + len(m.value) for m in muts)
        self.commit_ts = self.store.tso.ts()
        # commit primary first — the txn is durably decided once this returns.
        # An UndeterminedError here (commit sent, reply lost) propagates with
        # the resolver bound: retrying could misreport abort, rolling back
        # could erase a commit (ref: client-go undetermined-result rule), but
        # once the store answers again err.resolve() reports the truth.
        try:
            self.store.commit([primary], self.start_ts, self.commit_ts)
        except UndeterminedError as e:
            e.bind_resolver(self.resolve_undetermined)
            raise
        secondaries = [m.key for m in muts if m.key != primary]
        if secondaries:
            try:
                self.store.commit(secondaries, self.start_ts, self.commit_ts)
            except (ConnectionError, UndeterminedError):
                # the primary committed, so the txn IS committed; stranded
                # secondary locks roll forward lazily when a reader trips on
                # them (check_txn_status on the primary → resolve_lock), the
                # same path client-go relies on for async secondary commit
                pass
        try:
            self.store.detector.clean_up(self.start_ts)
        except ConnectionError:
            pass  # committed; detector hygiene must not fail the txn
        return self.commit_ts

    def resolve_undetermined(self):
        """Resolve an ambiguous commit after the store returns (ref: the
        ROADMAP "undetermined-commit resolution" gap; client-go resolves via
        CheckTxnStatus on the primary). Consults the PRIMARY key's owner:

        → ``("committed", commit_ts)`` — the commit landed; ``self.commit_ts``
          is updated to the store's truth.
        → ``("rolled_back", 0)`` — it did not land (the prewrite lock
          expired or was rolled back); safe to re-run the transaction.
        → ``("locked", 0)`` — still undecided: the prewrite lock is alive
          (its TTL has not expired). Back off and call again.

        Raises ConnectionError while the store is still unreachable."""
        if self._primary is None:
            raise RuntimeError("transaction never reached the commit phase; nothing to resolve")
        status, commit_ts = self.store.check_txn_status(self._primary, self.start_ts)
        if status == "committed":
            self.commit_ts = commit_ts
        return status, commit_ts

    def rollback(self) -> None:
        if self._done:
            return
        self._done = True
        if self._locked_keys:
            self.store.pessimistic_rollback(list(self._locked_keys), self.start_ts)
        keys = [m.key for m in self.membuf.mutations()]
        if keys:
            self.store.rollback(keys, self.start_ts)
        self.store.detector.clean_up(self.start_ts)
