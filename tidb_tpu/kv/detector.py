"""Wait-for-graph deadlock detector for pessimistic locking.

Reference parity: pkg/store/mockstore/unistore/tikv/detector.go — a digraph
of start_ts → start_ts wait edges; a lock request that would close a cycle is
rejected with DeadlockError (the requester is the victim, matching TiKV's
first-in-wins policy).
"""

from __future__ import annotations

import threading

from tidb_tpu.kv.kv import DeadlockError


class DeadlockDetector:
    def __init__(self):
        self._mu = threading.Lock()
        # waiter start_ts → {holder start_ts: key}
        self._edges: dict[int, dict[int, bytes]] = {}

    def register(self, waiter: int, holder: int, key: bytes) -> None:
        """Add a wait edge; raises DeadlockError if it closes a cycle."""
        with self._mu:
            # path holder →* waiter already? then waiter → holder closes it
            if self._reaches(holder, waiter):
                raise DeadlockError(waiter, holder, key)
            self._edges.setdefault(waiter, {})[holder] = key

    def _reaches(self, src: int, dst: int) -> bool:
        stack, seen = [src], set()
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(self._edges.get(n, ()))
        return False

    def unregister(self, waiter: int, holder: int | None = None) -> None:
        with self._mu:
            if holder is None:
                self._edges.pop(waiter, None)
            else:
                edges = self._edges.get(waiter)
                if edges is not None:
                    edges.pop(holder, None)
                    if not edges:
                        del self._edges[waiter]

    def clean_up(self, txn_ts: int) -> None:
        """Txn finished: drop all its edges (as waiter)."""
        self.unregister(txn_ts)
