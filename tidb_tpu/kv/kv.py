"""KV abstraction layer (ref: pkg/kv/kv.go)."""

from __future__ import annotations

import enum
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional, Protocol, Sequence


class StoreType(enum.Enum):
    """Which engine executes a pushed-down fragment (ref: kv.go:353
    StoreType{TiKV, TiFlash, TiDB}). HOST is the CPU reference engine
    (unistore-cophandler analog), TPU is the XLA engine (TiFlash analog),
    ROOT means "execute in the SQL layer" (TiDB memtables)."""

    HOST = "host"
    TPU = "tpu"
    ROOT = "root"


class RequestType(enum.IntEnum):
    DAG = 103  # mirrors kv.ReqTypeDAG
    ANALYZE = 104
    CHECKSUM = 105


@dataclass(frozen=True)
class KeyRange:
    """Half-open [start, end)."""

    start: bytes
    end: bytes

    def intersect(self, other: "KeyRange") -> Optional["KeyRange"]:
        s = max(self.start, other.start)
        e = min(self.end, other.end)
        return KeyRange(s, e) if s < e else None


@dataclass
class Request:
    """A pushdown request (ref: kv.Request kv.go:533)."""

    tp: RequestType
    data: Any  # dagpb.DAGRequest (tidb_tpu.copr.dagpb)
    ranges: list[KeyRange]
    store_type: StoreType = StoreType.HOST
    start_ts: int = 0
    concurrency: int = 8
    keep_order: bool = False
    desc: bool = False
    paging: bool = True
    # partition pushdown: list of (physical_table_id, ranges) like
    # kv.Request.PartitionIDAndRanges (kv.go:544)
    partition_ranges: list[tuple[int, list[KeyRange]]] = field(default_factory=list)
    # per-statement warning sink ``warn(level, code, msg)`` — engine-side
    # warnings (cast truncation, division by 0) travel back to the session
    # like the reference's per-SelectResponse warnings (tipb.SelectResponse)
    warn: Any = None
    # the statement's live Tracer when TRACE is on (None = tracing off,
    # strictly zero cost): cop clients open per-task spans under it, ship
    # the trace context over the wire, and merge remote-recorded spans back
    tracer: Any = None


class Response(Protocol):
    """Streaming response (ref: kv.Response kv.go:648). Yields
    copr.CopResult items; exhausted when the iterator ends."""

    def __iter__(self) -> Iterator[Any]: ...

    def close(self) -> None: ...


class Client(Protocol):
    """ref: kv.Client kv.go:316."""

    def send(self, req: Request) -> Response: ...


class Storage(Protocol):
    """ref: kv.Storage. Concrete impl: tidb_tpu.kv.memstore.MemStore."""

    def get_client(self) -> Client: ...

    def current_ts(self) -> int: ...

    def get_snapshot(self, ts: int): ...

    def begin(self): ...


class TimestampOracle:
    """TSO: (physical_ms << 18) | logical, globally unique and monotonic
    (ref: PD TSO; pkg/store/mockstore/unistore/pd.go)."""

    _PHYSICAL_SHIFT = 18

    def __init__(self):
        self._lock = threading.Lock()
        self._last = 0

    def ts(self) -> int:
        with self._lock:
            phys = int(time.time() * 1000) << self._PHYSICAL_SHIFT
            if phys <= self._last:
                self._last += 1
            else:
                self._last = phys
            return self._last

    @staticmethod
    def physical_ms(ts: int) -> int:
        return ts >> TimestampOracle._PHYSICAL_SHIFT


class KVError(Exception):
    pass


class RegionError(Exception):
    """Stale region routing: the store no longer serves the region this task
    named (split/merge bumped the epoch, or the region moved). Retriable
    after re-resolving regions from PD (ref: errorpb.EpochNotMatch /
    RegionNotFound — client-go re-splits the task under BoRegionMiss).

    Deliberately NOT a KVError: the taxonomy (utils/backoff.classify) treats
    KVError subclasses as statement verdicts (fatal to the retry layer),
    while a region miss is pure routing staleness."""

    def __init__(self, region_id: int, msg: str = ""):
        super().__init__(msg or f"region {region_id} not served here (epoch changed?)")
        self.region_id = region_id


class UndeterminedError(KVError):
    """A commit request failed AFTER it may have reached the store: the
    transaction may be durably committed or not, and nothing client-side can
    tell which. Never blind-retry (a re-commit can hit 'lock not found' and
    misreport abort), never report abort (the write may be visible). Surface
    to the client, who must check (ref: client-go ErrResultUndetermined,
    terror CodeResultUndetermined — the 2PC safety rule).

    "Who must check" is automated: the transaction layer binds a
    ``check_txn_status``-driven resolver (``Txn.resolve_undetermined``), so
    once the store is reachable again ``err.resolve()`` reports which way
    the ambiguous commit actually went."""

    def __init__(self, msg: str):
        super().__init__(msg)
        self._resolver = None

    def bind_resolver(self, fn) -> "UndeterminedError":
        """Attach the layer-appropriate resolver (the txn that owns the
        primary key binds ``Txn.resolve_undetermined``)."""
        self._resolver = fn
        return self

    def resolve(self):
        """→ ("committed", commit_ts) | ("rolled_back", 0) | ("locked", 0).
        Consults the primary key's owner via check_txn_status once the store
        answers again; raises ConnectionError while it is still down, and
        RuntimeError when no resolver was bound (the error surfaced below
        the transaction layer)."""
        if self._resolver is None:
            raise RuntimeError(
                "no resolver bound to this UndeterminedError (it surfaced "
                "below the transaction layer); call check_txn_status on the "
                "transaction's primary key directly"
            )
        return self._resolver()


class WriteConflictError(KVError):
    def __init__(self, key: bytes, conflict_ts: int, start_ts: int):
        super().__init__(f"write conflict on {key!r}: commit_ts {conflict_ts} > start_ts {start_ts}")
        self.key, self.conflict_ts, self.start_ts = key, conflict_ts, start_ts


class KeyLockedError(KVError):
    def __init__(self, key: bytes, lock):
        super().__init__(f"key {key!r} locked by txn {lock.start_ts}")
        self.key, self.lock = key, lock


class TxnAbortedError(KVError):
    pass


class DeadlockError(KVError):
    """Raised to the waiter whose lock request closes a wait-for cycle
    (ref: unistore/tikv/detector.go, kvproto Deadlock)."""

    def __init__(self, waiter_ts: int, holder_ts: int, key: bytes):
        super().__init__(f"deadlock: txn {waiter_ts} waiting for txn {holder_ts} on {key!r}")
        self.waiter_ts, self.holder_ts, self.key = waiter_ts, holder_ts, key


class LockWaitTimeoutError(KVError):
    def __init__(self, key: bytes):
        super().__init__(f"lock wait timeout on {key!r}")
        self.key = key
