"""Elastic data placement: the PD-analog placement driver.

Reference parity: PD's region scheduler — the component that makes TiKV
placement *elastic*: every region binding carries a placement epoch
(``metapb.RegionEpoch``), routing clients cache the map and treat an epoch
mismatch as a region error (re-resolve under ``boRegionMiss``), and the
balance-region/balance-hot-region schedulers move peers between stores on
load skew. This module is that control plane for the table-granular sharded
fleet (kv/sharded.py), layered on the same quorum-replica machinery the
election keyspace uses (kv/election.py):

- Each store shard hosts a :class:`PlacementReplica`: per table id it
  records ``(epoch, shard)``. The **epoch is the fencing token** — a
  proposal is accepted iff its epoch is strictly higher than the local one
  (re-proposing the accepted record re-accepts, so the wire verb is
  replay-safe). Epochs therefore never regress, fleet-wide.
- :class:`PlacementClient` is the client half: majority reads resolve
  highest-epoch-wins with read-repair of stragglers, majority writes bump
  the epoch, and a locally cached map serves the hot routing path with
  zero quorum traffic. ``refresh()`` is what a routing caller runs after a
  ``RegionError`` — the ``boRegionMiss`` re-resolve.
- :func:`migrate_table` is the region-move primitive: snapshot copy (rows
  keep their ORIGINAL commit timestamps, so in-flight snapshots stay
  consistent across the move), bounded change catch-up rounds, then an
  epoch-bump cutover that **fences the old owner** (reads and writes of the
  moved table raise ``RegionError`` there) and carries in-flight prewrite
  locks to the destination — a 2PC commit that started before the move
  re-routes and finds its locks waiting (the "commit replay on region
  move" RESILIENCE.md gap, closed).
- :func:`balancer_sweep` is the scheduler: owner-gated (one mover per
  cluster), fed by ``DB.health`` store reports and per-table weights, it
  moves the heaviest movable table off the most loaded shard when the
  max/min skew crosses ``[cluster] balancer-skew-ratio``.

Crash safety: the cutover fence carries a TTL ([cluster]
placement-fence-ttl-s) — a migration driver that dies between fencing and
the epoch bump leaves a fence that expires on its own, and the table
returns to its old owner with nothing lost (the destination's partial copy
is unreachable until some later migration finishes the job; re-applying is
idempotent). A cutover whose epoch bump cannot reach a majority first
tries to re-assert the OLD owner at a higher epoch; failing that it leaves
the fence to expire and surfaces a typed ConnectionError — a minority
partition can never decide a move.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from tidb_tpu.utils import eventlog as _ev
from tidb_tpu.utils import failpoint


class PlacementLostRace(Exception):
    """Another driver's move won the epoch race for this table. The loser
    must ABORT cleanly: leave its TTL fence to expire and touch neither the
    winner's fences nor the epoch — re-asserting the old owner here would
    outbid the winner and route the fleet at a purged copy."""


class PlacementReplica:
    """One shard's share of the placement keyspace (the PD-member role).

    Deliberately dumb, like :class:`~tidb_tpu.kv.election.ElectionReplica`:
    it enforces only the epoch accept rule and stores what it accepted —
    all move reasoning lives client-side, so a majority of ANY replicas
    reconstructs the truth."""

    def __init__(self):
        self._mu = threading.Lock()
        self._recs: dict[int, tuple[int, int]] = {}  # table_id → (epoch, shard)

    def propose(self, table_id: int, shard: int, epoch: int) -> tuple[bool, int]:
        """→ (accepted, replica's current epoch). Accept iff ``epoch`` beats
        the local epoch, or equals it with the SAME shard (idempotent
        replay of an accepted record — the wire verb is replay-safe)."""
        with self._mu:
            cur = self._recs.get(table_id, (0, -1))
            if epoch > cur[0] or (epoch == cur[0] and shard == cur[1]):
                self._recs[table_id] = (epoch, shard)
                return True, epoch
            return False, cur[0]

    def read(self, table_id: int) -> tuple[int, Optional[int]]:
        with self._mu:
            rec = self._recs.get(table_id)
            return (rec[0], rec[1]) if rec else (0, None)

    def read_all(self) -> list[tuple[int, int, int]]:
        """→ [(table_id, epoch, shard)] — the enumeration a fresh routing
        client bootstraps its cached map from."""
        with self._mu:
            return [(tid, e, s) for tid, (e, s) in self._recs.items()]


class PlacementClient:
    """Client half of the placement keyspace: majority reads/writes over
    the fleet's store list plus the locally cached routing map every data
    verb consults. Holds a REFERENCE to the fleet's store list (like
    QuorumElection), so store swaps in tests are visible immediately."""

    def __init__(self, stores: list, explicit: Optional[dict] = None):
        self.stores = stores
        self._mu = threading.Lock()
        # table_id → (epoch, shard): the cached routing map. Explicit
        # constructor placement seeds at epoch 0 (a static pin any real
        # quorum record outranks).
        self._map: dict[int, tuple[int, int]] = {
            tid: (0, si) for tid, si in (explicit or {}).items()
        }
        # epoch transitions this client has observed: table_id →
        # [(epoch, shard, wall_ts)] — the cluster_placement history surface
        self.history: dict[int, list[tuple[int, int, float]]] = {}
        # in-flight moves started by THIS process (cluster_placement rows)
        self.moving: dict[int, dict] = {}
        # bumped whenever the cached map changes — routing callers can use
        # it as a cheap "did anything move" witness
        self.version = 0

    @property
    def quorum(self) -> int:
        return len(self.stores) // 2 + 1

    # -- local cache --------------------------------------------------------
    def shard_of(self, table_id: int) -> Optional[int]:
        with self._mu:
            ent = self._map.get(table_id)
            return ent[1] if ent is not None else None

    def epoch_of(self, table_id: int) -> int:
        with self._mu:
            ent = self._map.get(table_id)
            return ent[0] if ent is not None else 0

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "tables": {tid: {"epoch": e, "shard": s} for tid, (e, s) in self._map.items()},
                "history": {tid: list(h) for tid, h in self.history.items()},
                "moving": {tid: dict(m) for tid, m in self.moving.items()},
            }

    def _adopt(self, table_id: int, epoch: int, shard: int) -> bool:
        """Install a resolved record into the local map — MONOTONE ONLY: a
        lower epoch can never displace a higher one (placement epochs never
        regress; a regression here would re-route writes to a fenced
        ex-owner)."""
        from tidb_tpu.utils import metrics as _m

        with self._mu:
            cur = self._map.get(table_id, (0, -1))
            if epoch < cur[0] or (epoch, shard) == cur:
                return False
            self._map[table_id] = (epoch, shard)
            self.version += 1
            self.history.setdefault(table_id, []).append((epoch, shard, time.time()))
        _m.PLACEMENT_EPOCH.set(epoch, table=str(table_id))
        return True

    # -- quorum plumbing ----------------------------------------------------
    def _sweep(self, call):
        """Run ``call(store)`` on every replica → (results, reached, last
        ConnectionError). Each store's own Backoffer already bounds the
        probe; a dead replica contributes only to ``last``."""
        out, last = [], None
        for i, st in enumerate(self.stores):
            try:
                out.append((i, call(st)))
            except ConnectionError as e:
                last = e
        return out, last

    def read_majority(self, table_id: int) -> tuple[int, Optional[int]]:
        """Resolve one table's binding from a majority (highest epoch wins)
        and read-repair stragglers. Raises ConnectionError below quorum."""
        reads, last = self._sweep(lambda st: st.placement_read(table_id))
        if len(reads) < self.quorum:
            raise ConnectionError(
                f"placement keyspace below quorum for table {table_id}: "
                f"{len(reads)}/{len(self.stores)} replicas reachable (need {self.quorum})"
            ) from last
        epoch, shard = max((rec for _, rec in reads), key=lambda r: r[0])
        if shard is not None:
            for i, (e, _) in reads:
                if e < epoch:
                    try:
                        self.stores[i].placement_propose(table_id, shard, epoch)
                    except ConnectionError:
                        pass
            self._adopt(table_id, epoch, shard)
        return epoch, shard

    def refresh(self) -> bool:
        """Re-resolve the WHOLE placement map from a majority — the
        ``boRegionMiss`` re-resolve a routing caller runs after a
        RegionError (or after a dead owner, to learn whether the region
        moved). Returns True iff the cached map changed. Below quorum the
        stale cache is kept (False) — routing on the last known map beats
        refusing reads the fleet can still serve."""
        from tidb_tpu.utils import metrics as _m

        reads, _last = self._sweep(lambda st: st.placement_read(None))
        if len(reads) < self.quorum:
            _m.PLACEMENT_REFRESH.inc(outcome="below_quorum")
            return False
        best: dict[int, tuple[int, int]] = {}
        for _, recs in reads:
            for tid, e, s in recs:
                if tid not in best or e > best[tid][0]:
                    best[tid] = (e, s)
        changed = False
        for tid, (e, s) in best.items():
            # read repair: push the resolved record at replicas behind it
            for i, recs in reads:
                seen = {t: ep for t, ep, _ in recs}
                if seen.get(tid, 0) < e:
                    try:
                        self.stores[i].placement_propose(tid, s, e)
                    except ConnectionError:
                        pass
            changed |= self._adopt(tid, e, s)
        _m.PLACEMENT_REFRESH.inc(outcome="changed" if changed else "clean")
        return changed

    def propose(self, table_id: int, shard: int, epoch: int) -> bool:
        """Majority write of a new binding; True iff a majority accepted.
        Below quorum raises — a minority partition must not believe it
        moved a region it cannot prove moved."""
        results, last = self._sweep(
            lambda st: st.placement_propose(table_id, shard, epoch)
        )
        if len(results) < self.quorum:
            raise ConnectionError(
                f"placement keyspace below quorum for table {table_id}: "
                f"{len(results)}/{len(self.stores)} replicas reachable (need {self.quorum})"
            ) from last
        acks = sum(1 for _, (ok, _e) in results if ok)
        if acks >= self.quorum:
            self._adopt(table_id, epoch, shard)
            return True
        return False

    def repair_replica(self, si: int) -> int:
        """Returning-replica anti-entropy for the placement keyspace: push
        every locally known binding onto shard ``si`` (its accept rule keeps
        the higher epoch). → number of records pushed."""
        with self._mu:
            recs = [(tid, e, s) for tid, (e, s) in self._map.items()]
        n = 0
        for tid, e, s in recs:
            try:
                self.stores[si].placement_propose(tid, s, e)
                n += 1
            except ConnectionError:
                break
        return n

    # -- move bookkeeping ---------------------------------------------------
    def note_moving(self, table_id: int, src: int, dst: int, epoch: int) -> None:
        with self._mu:
            self.moving[table_id] = {
                "src": src, "dst": dst, "epoch": epoch, "phase": "copy",
                "started": time.time(),
            }

    def note_phase(self, table_id: int, phase: str) -> None:
        with self._mu:
            if table_id in self.moving:
                self.moving[table_id]["phase"] = phase

    def note_move_done(self, table_id: int) -> None:
        with self._mu:
            self.moving.pop(table_id, None)


# -- the region-move primitive ------------------------------------------------


def _copy_rounds(src, dst, table_id: int, after_ts: int, upto_ts, batch: int,
                 include_locks: bool = False) -> int:
    """Stream one catch-up window of ``table_id`` from src to dst in pages:
    committed versions (original commit_ts preserved) plus, on the final
    page of a fenced window, the in-flight prewrite locks. → rows copied.
    The ``placement_migrate_batch`` failpoint fires per page — chaos tests
    widen the kill window here."""
    copied = 0
    cursor = None
    while True:
        failpoint.inject("placement_migrate_batch", table_id, cursor)
        page = src.migrate_export(
            table_id, after_ts=after_ts, upto_ts=upto_ts, cursor=cursor,
            limit=batch, include_locks=include_locks,
        )
        if page["items"] or page.get("locks"):
            dst.migrate_apply(page["items"], page.get("locks", ()))
            copied += len(page["items"])
        cursor = page.get("cursor")
        if cursor is None:
            return copied


def migrate_table(store, table_id: int, dst: int, *, batch_keys: Optional[int] = None,
                  fence_ttl_s: Optional[float] = None) -> dict:
    """Move one table's region from its current owner to shard ``dst``.

    Protocol (the PD region-move analog, collapsed to one leader-less
    driver because regions here have exactly one replica):

    1. **Snapshot copy** at a fleet timestamp — every visible version ships
       with its ORIGINAL (commit_ts, start_ts), so concurrent snapshots
       read identically from either side and ``check_txn_status`` stays
       truthful at the destination.
    2. **Catch-up rounds** — committed changes since the last window, until
       a round comes back small (the write rate bounds the blackout).
    3. **Fenced cutover** — the source fences the table (reads AND writes
       raise RegionError; the fence carries a TTL so a dead driver
       self-heals), the final window ships together with the in-flight
       prewrite LOCKS (a 2PC commit that re-routes finds them waiting),
       the destination is unfenced, and the placement epoch bumps via a
       majority write. Stale routing clients keep hitting the source,
       get RegionError, re-resolve under boRegionMiss, and land here.
    4. **Hygiene** — the source keeps a PERMANENT fence (a stale client
       must get a typed re-route signal, never a silently empty scan) and
       purges its copy.

    Returns ``{"moved", "src", "dst", "epoch", "rows", "wall_ms",
    "blackout_ms"}``; raises typed errors (ConnectionError below quorum or
    on a dead peer) and never leaves the fleet split-brained: an ambiguous
    epoch bump first tries to re-assert the old owner at a higher epoch,
    else leaves the TTL fence to expire.
    """
    from tidb_tpu import config as _config
    from tidb_tpu.utils import metrics as _m

    cfg = _config.current()
    batch = batch_keys if batch_keys is not None else cfg.migrate_batch_keys
    ttl = fence_ttl_s if fence_ttl_s is not None else cfg.placement_fence_ttl_s
    cache = store.placement_cache
    dst = dst % len(store.stores)
    src = store.shard_of_table(table_id)
    if src == dst:
        return {"moved": False, "src": src, "dst": dst, "reason": "already placed there"}
    # quorum-confirm the epoch we are about to outbid (our cache may lag a
    # move another driver finished)
    epoch, owner = cache.read_majority(table_id)
    if owner is not None and owner % len(store.stores) != src:
        src = owner % len(store.stores)
        if src == dst:
            return {"moved": False, "src": src, "dst": dst, "reason": "already placed there"}
    s_src, s_dst = store.stores[src], store.stores[dst]
    cache.note_moving(table_id, src, dst, epoch + 1)
    lg = _ev.on(_ev.INFO)
    if lg is not None:
        lg.emit(
            _ev.INFO, "placement", "migrate_begin",
            table=table_id, src=src, dst=dst, epoch=epoch + 1,
        )
    t0 = time.perf_counter()
    blackout_ms = 0.0
    rows = 0
    try:
        # 1+2: snapshot copy, then catch-up until a round comes back small
        last_ts = 0
        for _round in range(8):
            upto = store.current_ts()
            n = _copy_rounds(s_src, s_dst, table_id, last_ts, upto, batch)
            rows += n
            last_ts = upto
            if _round > 0 and n <= max(batch // 8, 64):
                break
        # 3: fenced cutover. The final window must PROVABLY complete inside
        # the fence TTL: a fence that lapsed mid-copy lets writes slip back
        # onto the source, and the purge below would silently erase them —
        # so the copy repeats under a fresh fence until a round finishes
        # with at least half the TTL remaining (re-copying the same window
        # is idempotent and picks up anything that slipped).
        cache.note_phase(table_id, "cutover")
        lg = _ev.on(_ev.INFO)
        if lg is not None:
            lg.emit(_ev.INFO, "placement", "fence", table=table_id, src=src, ttl_s=ttl)
        tb0 = time.perf_counter()
        try:
            for _attempt in range(4):
                s_src.fence_table(table_id, ttl)
                t_fence = time.monotonic()
                rows += _copy_rounds(
                    s_src, s_dst, table_id, last_ts, None, batch, include_locks=True
                )
                if time.monotonic() - t_fence < ttl * 0.5:
                    break
            else:
                raise ConnectionError(
                    f"cutover for table {table_id} could not finish its final "
                    f"catch-up inside the fence TTL ({ttl}s); aborting the move"
                )
            failpoint.inject("placement_cutover", table_id)
            s_dst.unfence_table(table_id)
            if not cache.propose(table_id, dst, epoch + 1):
                # lost an epoch race to another driver: re-resolve; if the
                # winner moved it where we wanted, that is still a success
                e2, o2 = cache.read_majority(table_id)
                if o2 is not None and o2 % len(store.stores) == dst:
                    epoch = e2 - 1
                else:
                    # the winner owns the table's state now (it may already
                    # have fenced+purged our src) — abort WITHOUT touching
                    # fences or the epoch; our TTL fence expires on its own
                    lg = _ev.on(_ev.WARN)
                    if lg is not None:
                        lg.emit(
                            _ev.WARN, "placement", "lost_race",
                            table=table_id, epoch=e2, winner_shard=o2,
                        )
                    raise PlacementLostRace(
                        f"placement epoch bump for table {table_id} lost the race "
                        f"(now epoch {e2} → shard {o2})"
                    )
            lg = _ev.on(_ev.INFO)
            if lg is not None:
                lg.emit(
                    _ev.INFO, "placement", "cutover",
                    table=table_id, src=src, dst=dst, epoch=epoch + 1,
                )
        except ConnectionError:
            # below quorum / dead peer mid-cutover: try to re-assert the OLD
            # owner at a higher epoch (a clean cancel); if even that cannot
            # reach a majority the TTL fence expires on its own. Only the
            # quorum-loss path may do this — a LOST RACE must not outbid the
            # winner (PlacementLostRace bypasses this handler).
            try:
                if cache.propose(table_id, src, epoch + 2):
                    s_src.unfence_table(table_id)
            except ConnectionError:
                pass
            raise
        except PlacementLostRace:
            raise
        except BaseException:
            try:
                s_src.unfence_table(table_id)  # pre-cutover abort: reopen src
            except ConnectionError:
                pass
            raise
        blackout_ms = (time.perf_counter() - tb0) * 1000.0
        # 4: permanent fence, then ONE more (normally empty) catch-up sweep
        # before the purge — if the TTL fence somehow lapsed in the ms
        # between the liveness check and the epoch bump, whatever slipped
        # onto the source is carried over instead of erased. Only then is
        # the purge provably loss-free. A stale client's read must
        # re-route, never see an empty table — hence the permanent fence.
        try:
            s_src.fence_table(table_id, None)
            rows += _copy_rounds(
                s_src, s_dst, table_id, last_ts, None, batch, include_locks=True
            )
            s_src.purge_table(table_id)
            lg = _ev.on(_ev.INFO)
            if lg is not None:
                lg.emit(_ev.INFO, "placement", "purge", table=table_id, src=src)
        except ConnectionError:
            pass  # src died right after cutover: nothing routes there anyway
    except BaseException:
        cache.note_move_done(table_id)
        _m.REGION_MIGRATE.inc(outcome="failed")
        raise
    cache.note_move_done(table_id)
    wall_ms = (time.perf_counter() - t0) * 1000.0
    _m.REGION_MIGRATE.inc(outcome="moved")
    _m.REGION_MIGRATE_SECONDS.observe(wall_ms / 1000.0)
    return {
        "moved": True, "src": src, "dst": dst, "epoch": epoch + 1,
        "rows": rows, "wall_ms": round(wall_ms, 3), "blackout_ms": round(blackout_ms, 3),
    }


# -- the balancer -------------------------------------------------------------


def _shard_weights(db, store):
    """Per-shard placement weight plus the movable tables behind it:
    → (weights list, [(weight, table_id, shard, name)]). Weight per table =
    stats row count (the durable skew signal) plus a hot boost from the
    stores' MEASURED per-(region, table) traffic rings (kv/memstore
    TrafficStats, swept as the ``heatmap`` sys_snapshot section) — keys
    touched over the retained window, reads and writes alike. This replaced
    the old cop-digest exec-count heuristic: the heatmap weighs actual keys
    moved, counts write traffic the cop ring never saw, and decays as the
    rings roll. Partitioned tables are immovable for now — their physical
    views would each need their own binding."""
    traffic: dict[int, int] = {}
    try:
        for o in db.health.sweep(sections=("heatmap",)):
            if not o["ok"]:
                continue
            for ent in o["report"].get("heatmap", ()):
                n = sum(b[1] + b[3] for b in ent["buckets"])  # read+write keys
                traffic[ent["table_id"]] = traffic.get(ent["table_id"], 0) + n
    # load probes are advisory: the balancer still sees row weights, and a
    # dead store's missing report must never abort the sweep
    except Exception:  # graftcheck: off=except-swallow
        pass
    weights = [0.0] * len(store.stores)
    tables = []
    for db_name in db.catalog.databases():
        for tname in db.catalog.tables(db_name):
            t = db.catalog.table(db_name, tname)
            st = db.stats.get(t.id)
            w = float(max(st.row_count if st is not None else 0, 1))
            w += float(traffic.get(t.id, 0))
            si = store.shard_of_table(t.id)
            weights[si] += w
            if t.partition is None:
                tables.append((w, t.id, si, f"{db_name}.{tname}"))
    return weights, tables


def balancer_sweep(db, max_moves: int = 1) -> dict:
    """One owner-gated balancer pass: when the max/min shard weight ratio
    crosses ``[cluster] balancer-skew-ratio``, move the heaviest movable
    table off the hottest shard onto the lightest LIVE shard — at most
    ``max_moves`` migrations per sweep (one region move per tick keeps the
    blackout windows disjoint, the PD store-limit idiom). Dead/stale shards
    are excluded as destinations (their data cannot be verified); sources
    must be live too — an unreplicated region on a dead store has nothing
    to stream from."""
    from tidb_tpu import config as _config
    from tidb_tpu.utils import metrics as _m

    store = db.store
    if not hasattr(store, "placement_cache") or len(getattr(store, "stores", ())) < 2:
        return {"skipped": "not a sharded fleet"}
    ratio = _config.current().balancer_skew_ratio
    # liveness per shard: one cheap sweep (sections=()) — a shard that
    # cannot answer a load probe is neither a source nor a destination
    live = [True] * len(store.stores)
    try:
        for o in db.health.sweep(sections=()):
            if 0 <= o.get("shard", -1) < len(live):
                live[o["shard"]] = bool(o["ok"])
    # health is advisory too: with no sweep every shard stays eligible,
    # which only risks a move the next tick would undo
    except Exception:  # graftcheck: off=except-swallow
        pass
    moves: list[dict] = []
    for _ in range(max_moves):
        weights, tables = _shard_weights(db, store)
        live_shards = [i for i in range(len(weights)) if live[i]]
        if len(live_shards) < 2:
            break
        hot = max(live_shards, key=lambda i: weights[i])
        cold = min(live_shards, key=lambda i: weights[i])
        if weights[hot] <= ratio * max(weights[cold], 1.0):
            break  # balanced
        movable = sorted(
            (e for e in tables if e[2] == hot), key=lambda e: e[0], reverse=True
        )
        picked = None
        for w, tid, _si, name in movable:
            # the move must IMPROVE the spread, not just swap the extremes
            if max(weights[hot] - w, weights[cold] + w) < weights[hot]:
                picked = (w, tid, name)
                break
        if picked is None:
            break
        w, tid, name = picked
        out = migrate_table(store, tid, cold)
        out["table"] = name
        moves.append(out)
        _m.BALANCER_MOVES.inc(reason="skew")
        lg = _ev.on(_ev.INFO)
        if lg is not None:
            lg.emit(
                _ev.INFO, "placement", "balancer_move",
                table=name, src=hot, dst=cold, reason="skew",
            )
    return {"moves": moves, "balanced": not moves or len(moves) < max_moves}
